#!/usr/bin/env python3
"""dwpa_tpu benchmark harness — prints ONE JSON line.

Tracks BASELINE.json's configs on the local accelerator:

  #1  single m22000 PMKID line x 1k-word dict slice (engine end-to-end)
  #2  single WPA2 4-way EAPOL line x dict (adds PRF-512 + MIC + NC search)
  #5  8-digit mask brute (?d x 8) — pure PBKDF2 throughput, no dict I/O

The headline metric is config #5's PMK/s on this chip.  North star
(BASELINE.json): >= 2x a hashcat-CUDA RTX 4090 (~2.5e6 PMK/s on m22000)
across a v5e-8, i.e. a per-chip share of 2 * 2.5e6 / 8 = 625k PMK/s;
``vs_baseline`` is the fraction of that per-chip share this run achieved.

Timing notes: every sample forces a device->host fetch of the result
(``np.asarray``) before the clock stops — on the axon-tunnelled TPU,
``block_until_ready`` returns before execution completes, so dispatch-only
timing overstates throughput by orders of magnitude.  Each repetition
feeds distinct inputs so no layer can serve a cached result.

Every timed region runs through the obs span tracer (dwpa_tpu.obs), so
the numbers in this JSON line and the live ``dwpa_span_seconds``
telemetry are the SAME measurement — they cannot disagree.  The spans
inherit the sync rule above: each region's body ends in an engine
``crack*`` call or an ``np.asarray`` fetch (lint rule DW106 checks
this file statically, as DW105 did for the raw perf_counter spans).
"""

import json
import os
import sys

import numpy as np

import jax

from dwpa_tpu import testing as T
from dwpa_tpu.analysis import watch_compiles
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.obs import SpanTracer, default_registry

TRACER = SpanTracer(default_registry())

RTX4090_PMKS = 2.5e6           # hashcat-CUDA m22000 on one RTX 4090
PER_CHIP_TARGET = 2 * RTX4090_PMKS / 8   # north-star share per v5e chip

ON_TPU = jax.devices()[0].platform == "tpu"


def tpu_selftest() -> dict:
    """Preflight: pin the production Pallas kernel against hashlib on the
    real chip, every round.

    The suite's conftest forces the CPU platform, so its full-4096
    bit-exactness test only runs when someone sets DWPA_TEST_TPU=1 —
    which recorded rounds never did.  This preflight closes that gap:
    the exact kernel configuration the headline number is measured on
    (hoisted prologue, default tile) is verified oracle-exact here, in
    the same driver-recorded run, or bench fails loudly (rc != 0).
    """
    if not ON_TPU:
        return {"label": "tpu_selftest", "status": "skipped_no_tpu"}
    import hashlib

    import jax.numpy as jnp

    from dwpa_tpu.models.m22000 import essid_salt_blocks
    from dwpa_tpu.ops.pbkdf2_pallas import pbkdf2_sha1_pmk_pallas
    from dwpa_tpu.utils import bytesops as bo

    essid = b"bench-selftest"
    s1, s2 = essid_salt_blocks(essid)
    # Lengths straddling both trimmed-width buckets and the 20-byte
    # SHA-1 block boundary, like the TPU-gated unit test.
    pws = [b"pw%06d" % i for i in range(32)]
    pws += [b"longpassphrase-%016d" % i for i in range(32)]
    out = np.asarray(
        pbkdf2_sha1_pmk_pallas(
            jnp.asarray(bo.pack_passwords_be(pws)), jnp.asarray(s1), jnp.asarray(s2)
        )
    )
    for i in range(0, len(pws), 7):
        ref = hashlib.pbkdf2_hmac("sha1", pws[i], essid, 4096, 32)
        got = bo.words_to_bytes_be(out[:, i])
        if got != ref:
            raise SystemExit(
                f"TPU SELFTEST FAILED: Pallas PBKDF2 not bit-exact for {pws[i]!r}"
            )
    return {"label": "tpu_selftest", "status": "pass",
            "check": "pallas_pbkdf2_4096_vs_hashlib", "words": len(pws)}


def bench_mask_pbkdf2(batch: int, batches: int = 8) -> dict:
    """Config #5: PBKDF2 throughput on the ?d x 8 keyspace, end to end.

    The real product path: ``M22000Engine.crack_mask`` generates
    candidates ON DEVICE (gen.mask.device_mask_words — iota→digits→pack;
    zero host packing, zero candidate H2D) and streams batches through
    the engine's pipelined crack loop, so per-batch dispatch and the
    hits-gate round trip hide behind compute.  Each batch covers a
    distinct keyspace slice (no layer can serve a cached result).
    """
    psk = b"not-in-keyspace"  # ?d keyspace can't contain letters: all-miss
    engine = M22000Engine(
        [T.make_pmkid_line(psk, b"bench-essid", seed="mask5")],
        batch_size=batch,
    )
    mask = "?d?d?d?d?d?d?d?d"
    n = batches * batch
    # Warmup (compile) on a keyspace slice disjoint from the timed run.
    # The sentinel proves the headline number measures steady state: a
    # nonzero ``recompiles`` means the timed run paid XLA compile time.
    engine.crack_mask(mask, skip=n, limit=batch)
    with watch_compiles() as comp:
        with TRACER.span("bench:mask_pbkdf2") as sp:
            engine.crack_mask(mask, skip=0, limit=n)
        dt = sp.seconds
    return {"pmk_per_s": n / dt, "batch": batch, "batches": batches,
            "seconds": dt, "candidate_gen": "on-device",
            "recompiles": comp.count}


def bench_engine_dict(line: str, psk: bytes, words: int, label: str,
                      batch: int = None) -> dict:
    """Configs #1/#2: engine end-to-end crack of a known-PSK hashline."""
    batch = batch or min(4096, words)
    dict_words = [b"candidate-%06d" % i for i in range(words - 1)] + [psk]
    engine = M22000Engine([line], batch_size=batch)
    # Warm the jit caches (PBKDF2 + verify kernels) on a no-match slice so
    # the timed run measures steady-state throughput, as hashcat reports it.
    engine.crack_batch([b"warmup-%06d" % i for i in range(batch)])
    with TRACER.span(f"bench:{label}") as sp:
        founds = engine.crack(dict_words)
    dt = sp.seconds
    assert founds and founds[0].psk == psk, f"{label}: engine missed the known PSK"
    return {"label": label, "words": words, "seconds": dt, "pmk_per_s": words / dt}


def bench_rules_dict(words: int) -> dict:
    """Config #3: a SMALL rules work unit through the client's pass-2
    path (engine.crack_rules — on-device mangling, the route
    client/main.py process_work takes since r5), overhead-dominated like
    the pmkid/eapol small-unit configs.

    A representative rule set (case/append/prepend/truncate families, the
    op classes bestWPA.rule uses); throughput counts expanded candidates.
    """
    from dwpa_tpu.rules import parse_rules

    rules = parse_rules([":", "u", "c", "$1", "^w", "r", "T0", "$1 $2 $3"])
    base = [b"benchword%04d" % i for i in range(words)]
    # The planted PSK is the LAST base word through the LAST rule — the
    # final expanded candidate — so the engine's early exit on the find
    # cannot shrink the work that the candidates/second figure counts.
    expanded_psk = b"benchword%04d123" % (words - 1)
    engine = M22000Engine(
        [T.make_pmkid_line(expanded_psk, b"bench-essid", seed="rules")],
        batch_size=min(4096, words),
    )
    engine.crack_rules([b"warm-%06d" % i for i in range(engine.batch_size)],
                       [rules[0], rules[-1]])
    with TRACER.span("bench:rules_dict") as sp:
        founds = engine.crack_rules(base, rules)
    dt = sp.seconds
    assert founds and founds[0].psk == expanded_psk, "rules config missed the PSK"
    n = words * len(rules)
    return {"label": "rules_dict", "candidates": n, "seconds": dt,
            "cand_per_s": n / dt}


def bench_rules_device(batch: int, n_rules: int = 8,
                       n_flush: int = 6) -> dict:
    """Rules attack with ON-DEVICE mangling (rules/device.py): each base
    batch uploads once and every rule expands on device, so candidate
    H2D amortizes over the rule count.  The proof point for VERDICT r3
    #3: a rules attack must sustain the dict-path rate (host expansion
    at ~1M cand/s can't feed even one chip at the kernel rate).

    ``n_flush`` base batches stream through the engine pipeline — the
    client's steady-state shape (a dictionary is many engine batches),
    where the next batch's host work (simulate_lens, pack, H2D) hides
    behind the previous chunk's device compute exactly like dict_steady's
    pipelined batches.  A single-flush run serializes that host work
    against an idle device and understates the attack by ~9%; at 6
    flushes the recorded rate (~264k cand/s) matches the MASK path —
    candidate H2D amortized to 1/n_rules per candidate is effectively
    free, which is the whole point of the on-device rule engine.
    """
    from dwpa_tpu.rules import parse_rules

    rules = parse_rules([":", "u", "c", "$1", "^w", "t", "T0", "$1 $2 $3"])
    assert len(rules) == n_rules
    base = [b"devrule%07d" % i for i in range(batch * n_flush)]
    # Planted PSK = LAST base word through the LAST rule, so the find
    # cannot shrink the counted work.
    psk = rules[-1].apply(base[-1])
    engine = M22000Engine(
        [T.make_pmkid_line(psk, b"bench-essid", seed="rulesdev")],
        batch_size=batch,
    )
    # Warm both interpreter step-buckets (1 and 4) + the crack step, so
    # the timed run measures steady state, not one-time XLA compiles.
    engine.crack_rules([b"warm%07d" % i for i in range(batch)],
                       [rules[0], rules[-1]])
    # Best of 2 (fresh engine per rep so the find doesn't shrink rep 2):
    # one transient ~20 s tunnel stall must not misrecord the steady rate
    # (see bench_dict_steady).
    dts = []
    for _ in range(2):
        eng = M22000Engine(
            [T.make_pmkid_line(psk, b"bench-essid", seed="rulesdev")],
            batch_size=batch,
        )
        founds = []
        dts.append(_timed(lambda: founds.extend(eng.crack_rules(base, rules)),
                          "bench:rules_device"))
        assert founds and founds[0].psk == psk, "rules_device missed the PSK"
    dt = min(dts)
    n = len(base) * len(rules)
    return {"label": "rules_device", "candidates": n, "rules": len(rules),
            "batches": n_flush, "seconds": dt, "cand_per_s": n / dt}


def bench_multi_bssid(words: int) -> dict:
    """Config #4: multi-BSSID work unit with ESSID-dedup amortization.

    5 nets share one ESSID (one PBKDF2 serves all five, the scheduler's
    grouping trick, get_work.php:96-109) plus 3 distinct-ESSID nets; the
    effective net-checks/s exceeds raw PMK/s by the sharing factor.
    """
    psk = b"benchpass4"
    lines = [T.make_eapol_line(psk, b"bench-shared", keyver=2, seed=f"mb{i}")
             for i in range(4)]
    lines.append(T.make_pmkid_line(psk, b"bench-shared", seed="mb4"))
    lines += [T.make_pmkid_line(psk, b"bench-solo-%d" % i, seed=f"ms{i}")
              for i in range(3)]
    n_nets, n_essids = len(lines), 4
    dict_words = [b"candidate-%06d" % i for i in range(words - 1)] + [psk]
    engine = M22000Engine(lines, batch_size=min(4096, words))
    engine.crack_batch([b"warm-%06d" % i for i in range(engine.batch_size)])
    with TRACER.span("bench:multi_bssid") as sp:
        founds = engine.crack(dict_words)
    dt = sp.seconds
    assert len(founds) == n_nets, f"multi-bssid: {len(founds)}/{n_nets} cracked"
    return {"label": "multi_bssid", "nets": n_nets, "essids": n_essids,
            "seconds": dt, "pmk_per_s": words * n_essids / dt,
            "net_checks_per_s": words * n_nets / dt}


def bench_dict_steady(batch: int, batches: int = 8) -> dict:
    """Engine product path at full batch: streaming dict crack with the
    three-deep pipeline (pack + H2D + hits-gate overlapped with compute).
    The gap to mask_pbkdf2 is the end-to-end overhead the engine fails
    to hide.  Best of 2: the tunnel occasionally stalls one transfer for
    ~20 s (measured: identical back-to-back runs of 24 s vs 45 s), and a
    steady-state figure must not record a one-off hiccup."""
    engine = M22000Engine(
        [T.make_pmkid_line(b"steadypass9", b"bench-steady", seed="st")],
        batch_size=batch,
    )
    engine.crack_batch([b"warm-%07d" % i for i in range(batch)])
    n = batches * batch
    with watch_compiles() as comp:
        dt = min(_timed(lambda: engine.crack(b"r%d-%08d" % (rep, i)
                                             for i in range(n)),
                        "bench:dict_steady")
                 for rep in range(2))
    return {"label": "dict_steady", "words": n, "seconds": dt,
            "pmk_per_s": n / dt, "recompiles": comp.count}


def bench_feed_overlap(batch: int, batches: int = 8) -> dict:
    """Candidate-feed pipeline overlap (dwpa_tpu/feed): the dict product
    path with host packing moved onto producer threads and H2D staged
    double-buffered — the input-pipeline shape ISSUE 3 built.

    Reports PMK/s next to the STARVE FRACTION: the share of the region's
    wall-clock the consumer spent blocked on an empty feed queue
    (``dwpa_feed_consumer_starve_seconds`` over the span).  ~0 means the
    host pipeline keeps the mesh fed (the feed's point); a fraction
    approaching the gap to mask_pbkdf2 means the host stages are the
    bottleneck — scale --feed-workers or the native packer, not the
    device.  The stall fraction is the mirror (producers blocked on a
    full queue = device-bound, the healthy state).  An isolated registry
    keeps this run's histograms out of the process-wide scrape numbers.
    """
    from dwpa_tpu.feed import CandidateFeed
    from dwpa_tpu.obs import MetricsRegistry

    engine = M22000Engine(
        [T.make_pmkid_line(b"feedpass77", b"bench-feed", seed="fo")],
        batch_size=batch,
    )
    engine.crack_batch([b"warm-%07d" % i for i in range(batch)])
    n = batches * batch
    reg = MetricsRegistry()
    feed = CandidateFeed((b"feed-%08d" % i for i in range(n)),
                         batch_size=batch, depth=2, producers=1,
                         prepack=engine.host_packer(), registry=reg,
                         name="bench")
    with watch_compiles() as comp:
        with TRACER.span("bench:feed_overlap") as sp:
            engine.crack_blocks(feed)
        dt = sp.seconds
    feed.close()
    snap = reg.snapshot()

    def _hist(nm):
        s = snap.get(nm, {}).get("samples") or [{}]
        return float(s[0].get("sum", 0.0))

    starve = _hist("dwpa_feed_consumer_starve_seconds")
    stall = _hist("dwpa_feed_producer_stall_seconds")
    return {"label": "feed_overlap", "words": n, "seconds": dt,
            "pmk_per_s": n / dt,
            "starve_fraction": starve / dt, "stall_fraction": stall / dt,
            "queue_depth": 2, "producers": 1, "recompiles": comp.count}


def bench_pmkstore(batch: int, batches: int = 4, overlap: float = 0.875) -> dict:
    """Persistent PMK store (dwpa_tpu/pmkstore): cold-vs-warm PMK/s on an
    overlapping dictionary pair.

    The cold pass cracks dictionary A with an empty store — every block
    is all-miss (plain-path shapes) and its PMKs write back after the
    device fetch.  The warm pass cracks dictionary B, which shares
    ``overlap`` of A's words SPREAD UNIFORMLY through the stream (every
    8th word is fresh at the default 7/8), so every block takes the
    mixed hit/miss path: PBKDF2 runs only on the compacted miss
    sub-batch (bucketed to <= 3 static widths — ``recompiles_warm``
    proves the bound holds) while cached PMKs are gathered in around it.
    The speedup ceiling is 1/(1-overlap); the measured ratio is how much
    of the skipped PBKDF2 the store actually returns.  ``hit_ratio``
    comes from the same isolated registry the store records to, so the
    headline and the live telemetry cannot disagree.
    """
    import tempfile

    from dwpa_tpu.feed import CandidateFeed
    from dwpa_tpu.obs import MetricsRegistry
    from dwpa_tpu.pmkstore import PMKStore

    n = batches * batch
    reg = MetricsRegistry()
    line = T.make_pmkid_line(b"not-in-either-dict", b"bench-store", seed="pks")
    # Warm the plain crack-step shapes first (18-char words, like the
    # dict below) so the COLD pass measures PBKDF2, not XLA compiles;
    # the store-specific shapes compile inside the warm pass, where the
    # sentinel counts them.
    warm_eng = M22000Engine([line], batch_size=batch)
    warm_eng.crack_batch([b"storewarm-%08d" % i for i in range(batch)])

    def run(words, label):
        eng = M22000Engine([line], batch_size=batch, pmk_store=store)
        feed = CandidateFeed(iter(words), batch_size=batch, depth=2,
                             producers=1, prepack=eng.host_packer(),
                             registry=MetricsRegistry(), name=label)
        with TRACER.span(f"bench:{label}") as sp:
            eng.crack_blocks(feed)
        feed.close()
        return sp.seconds

    with tempfile.TemporaryDirectory() as td:
        store = PMKStore(td, registry=reg)
        dict_a = [b"storeword-%08d" % i for i in range(n)]
        period = max(2, round(1 / (1 - overlap)))
        dict_b = [dict_a[i] if i % period else b"freshword-%08d" % i
                  for i in range(n)]
        # One-time mixed-shape warmup at the warm pass's hit ratio: one
        # block whose hits are seeded host-side (hashlib IS the oracle
        # PMK) compiles the bucketed miss-PBKDF2 + mix-gather shapes
        # outside the timed region; the sentinel around it records the
        # mixed path's bounded compile count (the <= 3 acceptance bound),
        # and the timed warm pass below must then add ZERO.
        import hashlib

        mixwarm = [b"mixwarm-%010d" % i for i in range(batch)]
        seeded = [w for i, w in enumerate(mixwarm) if i % period]
        store.put(b"bench-store", seeded,
                  [hashlib.pbkdf2_hmac("sha1", w, b"bench-store", 4096, 32)
                   for w in seeded])
        with watch_compiles() as mixed_comp:
            run(mixwarm, "pmkstore_mixwarm")
        cold_s = run(dict_a, "pmkstore_cold")
        with watch_compiles() as comp:
            warm_s = run(dict_b, "pmkstore_warm")
        hit_ratio = reg.value("dwpa_pmkstore_hit_ratio") or 0.0
    return {"label": "pmkstore", "words": n, "batch": batch,
            "overlap": 1 - 1 / period,
            "cold_seconds": cold_s, "warm_seconds": warm_s,
            "cold_pmk_per_s": n / cold_s, "warm_pmk_per_s": n / warm_s,
            "warm_speedup": cold_s / warm_s, "hit_ratio": hit_ratio,
            "mixed_compiles": mixed_comp.count, "recompiles_warm": comp.count}


def bench_dict_cache(batch: int, feed_words: int = 200_000,
                     batches: int = 2) -> dict:
    """bench:dict_cache — the packed-dict-cache acceptance measurement.

    Feed-only legs: one ~200k-word gz dict drained through
    ``DictFeedSource`` + ``CandidateFeed`` cold (gunzip + native pack +
    the cache write riding along) and then warm (mmap'd packed chunks,
    zero gunzip, zero per-word packing; the prep materialization memcpy
    IS counted — it is the warm path's real per-block cost).  The
    headline ``warm_speedup`` is warm/cold words/s: the host-side
    feed-rate multiplier an 8-chip mesh's repeat passes see.

    E2E legs: a planted-PSK dict cracked cold then warm through the
    engine's pre-packed bypass (``host_packer(pre=...)``) — the found
    list and per-batch consumed counts must be IDENTICAL, a mid-stream
    resume skip must account identically, and the warm pass must add
    zero XLA compiles (``recompiles_warm``).
    """
    import gzip
    import tempfile

    from dwpa_tpu.feed import CandidateFeed, DictCache, DictFeedSource
    from dwpa_tpu.gen.dicts import md5_file
    from dwpa_tpu.obs import MetricsRegistry

    def write_dict(td, ws, name):
        path = os.path.join(td, name + ".gz")
        with open(path, "wb") as f:
            f.write(gzip.compress(b"\n".join(ws) + b"\n"))
        return path, md5_file(path)

    def drain(units, cache, prepack=None, skip=0, engine=None,
              on_batch=None):
        src = DictFeedSource(units, batch_size=batch, cache=cache,
                             skip=skip, name="bench_dcache")
        feed = CandidateFeed(None, batch_size=batch, frames=src,
                             producers=2, prepack=prepack,
                             registry=MetricsRegistry(), name="bench_dcache")
        try:
            if engine is not None:
                return engine.crack_blocks(feed, on_batch=on_batch)
            n = 0
            for blk in feed:
                n += blk.count
            return n
        finally:
            feed.close()

    out = {"label": "dict_cache", "batch": batch, "feed_words": feed_words}
    with tempfile.TemporaryDirectory() as td:
        ws = [b"dcachebench-%09d" % i for i in range(feed_words)]
        fpath, fh = write_dict(td, ws, "feedleg")
        cache = DictCache(os.path.join(td, "dc"))
        # feed-only spans launch no device work — nothing to sync
        with TRACER.span("bench:dict_cache_cold") as sp:
            n = drain([(fpath, fh)], cache)
        out["cold_words_per_s"] = n / sp.seconds
        with TRACER.span("bench:dict_cache_warm") as sp:
            n = drain([(fpath, fh)], cache)
        out["warm_words_per_s"] = n / sp.seconds
        out["warm_speedup"] = (out["warm_words_per_s"]
                               / out["cold_words_per_s"])
        out["cache_bytes"] = cache._bytes_used()

        # -- e2e: the warm feed composing with the engine's pre-packed
        # bypass; plain crack shapes warm OUTSIDE the timed region
        psk = b"benchpass1"
        n2 = batches * batch
        ws2 = [b"dcache-e2e-%09d" % i for i in range(n2 - 1)] + [psk]
        epath, eh = write_dict(td, ws2, "e2eleg")
        line = T.make_pmkid_line(psk, b"bench-dcache")
        M22000Engine([line], batch_size=batch).crack_batch(
            [b"dcachewarm0-%07d" % i for i in range(batch)])
        ecache = DictCache(os.path.join(td, "dc2"))

        def crack(cache_, skip=0):
            consumed = []
            eng = M22000Engine([line], batch_size=batch)
            founds = drain([(epath, eh)], cache_,
                           prepack=eng.host_packer(), skip=skip,
                           engine=eng,
                           on_batch=lambda c, f: consumed.append(c))
            return [f.psk for f in founds], consumed

        with TRACER.span("bench:dict_cache_e2e_cold") as sp:
            cold_f, cold_c = crack(ecache)    # populates dc2
        e2e_cold = sp.seconds
        with watch_compiles() as comp:
            with TRACER.span("bench:dict_cache_e2e_warm") as sp:
                warm_f, warm_c = crack(ecache)
        e2e_warm = sp.seconds
        assert warm_f == cold_f == [psk], "cold/warm found-list parity"
        assert warm_c == cold_c, "cold/warm consumed parity"
        # resume parity: a mid-stream skip accounts identically whether
        # it replays the gzip prefix or seeks the block index
        skip = n2 // 3
        rf_cold, rc_cold = crack(None, skip=skip)
        rf_warm, rc_warm = crack(ecache, skip=skip)
        assert rf_cold == rf_warm == [psk] and rc_cold == rc_warm, \
            "cold/warm resume parity"
        out.update(e2e_words=n2, e2e_cold_pmk_per_s=n2 / e2e_cold,
                   e2e_warm_pmk_per_s=n2 / e2e_warm,
                   recompiles_warm=comp.count)
    return out


def bench_small_units(nunits: int = 8, words_per_unit: int = 1000,
                      batch: int = None) -> dict:
    """bench:small_units — the unit-fusion acceptance measurement.

    The structural gap this quantifies (see unit_overhead and the
    dict_steady-vs-pmkid_dict ratio): a stream of SMALL ESSID-group x
    dict work units runs each unit alone, padding its ~1k candidates to
    the full compiled batch width — per-unit fixed costs plus dead
    padding lanes, not the PBKDF2 kernel, bound aggregate PMK/s.

    Serial leg: one engine per unit (the client's per-unit loop), each
    cracking its own 1k-word dict at the configured batch.  Fused leg:
    ONE engine over all the units' lines, ``crack_fused`` packing the
    same candidates into one mixed-ESSID batch with per-lane salt
    gather (dwpa_tpu/sched).  Same candidates, same founds — the
    speedup is pure fill.  The compile sentinel around the fused leg
    must read 0: both legs run after same-shaped warmups, so the
    headline ratio is steady-state, not compile noise.
    """
    from dwpa_tpu.sched import fused_width

    batch = batch or (131072 if ON_TPU else 8192)
    nmesh = len(jax.devices())

    def make_units(tag):
        units = []
        for i in range(nunits):
            psk = ("fusedpass-%s-%03d" % (tag, i)).encode()
            essid = ("bench-small-%s-%d" % (tag, i)).encode()
            line = T.make_pmkid_line(psk, essid, seed=f"su-{tag}-{i}")
            words = [("su%s%d-%07d" % (tag, i, j)).encode()
                     for j in range(words_per_unit - 1)] + [psk]
            units.append((line, essid, words, psk))
        return units

    # Warm both legs' shapes outside the timed regions: the serial crack
    # step at the full batch, and the fused per-lane step + verify at
    # the width the timed unit mix lands on.
    for line, _, words, _ in make_units("warm-serial")[:1]:
        M22000Engine([line], batch_size=batch).crack(words)
    warm = make_units("warm-fused")
    M22000Engine([u[0] for u in warm], batch_size=batch).crack_fused(
        [(u[1], u[2]) for u in warm], max_units=nunits)

    units = make_units("run")
    n = nunits * words_per_unit
    expected = sorted((e, p) for _, e, _, p in units)

    serial_found = []
    with TRACER.span("bench:small_units_serial") as sp:
        for line, _, words, _ in units:
            for f in M22000Engine([line], batch_size=batch).crack(words):
                serial_found.append((f.line.essid, f.psk))
    serial_s = sp.seconds

    fused_eng = M22000Engine([u[0] for u in units], batch_size=batch)
    fb_stats = []
    with watch_compiles() as comp:
        with TRACER.span("bench:small_units_fused") as sp:
            fused = fused_eng.crack_fused(
                [(u[1], u[2]) for u in units], max_units=nunits,
                on_fused=lambda fb: fb_stats.append((len(fb.units), fb.fill)))
        fused_s = sp.seconds
    fused_found = [(f.line.essid, f.psk) for f in fused]
    assert sorted(serial_found) == expected, "serial leg missed a planted PSK"
    founds_identical = sorted(fused_found) == sorted(serial_found)
    assert founds_identical, "fused leg's founds differ from the serial leg"

    return {"label": "small_units", "units": nunits,
            "words_per_unit": words_per_unit, "batch": batch,
            "fused_width": fused_width(batch, nmesh, n),
            "serial_seconds": serial_s, "fused_seconds": fused_s,
            "serial_pmk_per_s": n / serial_s, "fused_pmk_per_s": n / fused_s,
            "aggregate_speedup": serial_s / fused_s,
            "units_per_batch": max(u for u, _ in fb_stats),
            "fill_fraction": max(f for _, f in fb_stats),
            "founds_identical": founds_identical,
            "recompiles": comp.count}


def bench_device_streams(batch: int = None, batches: int = 12) -> dict:
    """bench:device_streams — lockstep DP dispatch vs per-device streams.

    Leg 1 cracks a framed stream the lockstep way: every block split
    1/ndev across the ``shard_map`` mesh, a psum hits-gate barriering
    all devices per batch.  Leg 2 cracks the SAME stream with the
    device-stream executor (dwpa_tpu/parallel/streams.py): each device
    runs whole blocks on its own single-device engine, pulled from a
    shared queue — identical founds, no cross-device collective.  The
    compile sentinel wraps the warm streams leg at 0.

    The straggler pair quantifies the executor's headline property.
    Run A: all streams crack junk blocks at their natural rate.  Run B:
    stream 0's engine is wrapped to dawdle on every collect.  Because
    streams share nothing but the queue, the other streams' BUSY rate
    (blocks per second not spent waiting on the queue) must hold —
    ``min_retained`` is the worst non-straggler B/A busy-rate ratio and
    the acceptance floor is 0.9.  Under lockstep the same wrap would
    drag every device to the straggler's pace.
    """
    import time as _time

    from dwpa_tpu.feed import frame_blocks
    from dwpa_tpu.parallel import StreamExecutor, default_mesh

    batch = batch or (131072 if ON_TPU else 2048)
    # equal device width on both legs: lockstep splits each block over
    # the full mesh, streams give each of the same devices whole blocks
    devices = list(jax.devices())
    nstreams = len(devices)

    def make_lines(tag):
        # three ESSID groups: the forced-host CPU lockstep leg stalls
        # its AllReduce rendezvous when too many collective-bearing
        # steps are in flight (seen from ~7 groups); streams don't care
        return [T.make_pmkid_line(b"streampass-%d" % i,
                                  b"bench-stream-%s-%d" % (tag, i),
                                  seed=f"ds-{tag.decode()}-{i}")
                for i in range(3)]

    n = batch * batches
    words = [b"dsjunk-%08d" % i for i in range(n)]
    for i in range(3):              # plant each PSK in a different block
        words[batch * (i * batches // 3) + 17 + i] = b"streampass-%d" % i

    # Warm both legs' shapes outside the timed regions (junk words so
    # the warm engines never prune).
    warm_words = [b"dswarm-%07d" % i for i in range(batch)]
    M22000Engine(make_lines(b"wl"), batch_size=batch).crack(warm_words)
    M22000Engine(make_lines(b"ws"), batch_size=batch).crack_streams(
        frame_blocks(iter(warm_words * nstreams), batch), devices=devices)

    lock_eng = M22000Engine(make_lines(b"run"), batch_size=batch)
    with TRACER.span("bench:device_streams_lockstep") as sp:
        lock_founds = lock_eng.crack_blocks(
            frame_blocks(iter(words), lock_eng.batch_size))
    lock_s = sp.seconds

    st_eng = M22000Engine(make_lines(b"run"), batch_size=batch)
    with watch_compiles() as comp:
        with TRACER.span("bench:device_streams") as sp:
            st_founds = st_eng.crack_streams(
                frame_blocks(iter(words), st_eng.batch_size),
                devices=devices)
    streams_s = sp.seconds
    founds_identical = (
        sorted((f.line.essid, f.psk) for f in st_founds)
        == sorted((f.line.essid, f.psk) for f in lock_founds))
    assert founds_identical, "streams leg's founds differ from lockstep"
    assert len(st_founds) == 3, "a planted PSK was missed"

    # Straggler pair: same junk workload, run B wraps stream 0's engine.
    drag = max(0.02, lock_s / batches)
    sblocks = 4 * nstreams

    class _Dawdle:
        def __init__(self, eng):
            self._eng = eng

        def __getattr__(self, name):
            return getattr(self._eng, name)

        def _collect(self, disp):
            _time.sleep(drag)
            return self._eng._collect(disp)

    def busy_rates(straggle):
        def factory(device):
            eng = M22000Engine(make_lines(b"st"), batch_size=batch,
                               mesh=default_mesh(devices=[device]))
            if straggle and device is devices[0]:
                return _Dawdle(eng)
            return eng

        ex = StreamExecutor(factory, devices)
        t0 = _time.perf_counter()
        ex.run(frame_blocks(iter(b"stjunk-%08d" % i
                                 for i in range(batch * sblocks)), batch))
        wall = _time.perf_counter() - t0
        return [st.blocks_done / max(1e-9, wall - st.wait_s)
                for st in ex.streams]

    rates_a = busy_rates(False)
    rates_b = busy_rates(True)
    retained = [rates_b[i] / rates_a[i] for i in range(1, nstreams)]

    return {"label": "device_streams", "batch": batch, "batches": batches,
            "streams": nstreams,
            "lockstep_seconds": lock_s, "streams_seconds": streams_s,
            "lockstep_pmk_per_s": n / lock_s,
            "streams_pmk_per_s": n / streams_s,
            "aggregate_speedup": lock_s / streams_s,
            "founds_identical": founds_identical,
            "straggler_drag_s": drag,
            "min_retained": min(retained), "retained": retained,
            "recompiles_warm": comp.count}


def bench_mesh_aggregate(batch: int = None, n_flush: int = 4) -> dict:
    """bench:mesh_aggregate — the mesh-aggregate candidate pipeline
    acceptance measurement (on-device rule expansion as pass 2).

    Three legs over the SAME base-word stream and rule set:

    1. host-feed flat — the pre-mesh-aggregate regime: every (word,
       rule) pair interpreted on the host CPU, the EXPANDED candidates
       packed and shipped (H2D bytes x n_rules), cracked lockstep;
    2. lockstep rules — ``crack_rules_blocks`` on the full mesh: base
       blocks ship compact, expansion is on-device, but every block
       splits 1/ndev with a psum hits-gate barriering the mesh;
    3. mesh aggregate — ``crack_rules_streams``: each device pulls
       whole base blocks from the shared queue and expands rules
       directly ahead of its own PBKDF2 dispatch, no cross-device
       traffic at all.

    Founds must be identical across all three; the compile sentinel
    wraps the warm streams leg at 0.  ``aggregate_speedup`` is leg 2 /
    leg 3 and ``host_expand_ratio`` is leg 1 / leg 3 (how much the
    compact base feed buys over shipping expanded candidates).
    """
    from dwpa_tpu.feed import frame_blocks
    from dwpa_tpu.rules import parse_rules

    batch = batch or (131072 if ON_TPU else 2048)
    devices = list(jax.devices())
    rules = parse_rules([":", "u", "c", "$1", "^w", "t", "T0", "$1 $2 $3"])
    base = [b"meshagg%07d" % i for i in range(batch * n_flush)]
    # Planted PSK = LAST base word through the LAST rule, so the find
    # cannot shrink the counted work on any leg.
    psk = rules[-1].apply(base[-1])
    lines = [T.make_pmkid_line(psk, b"bench-essid", seed="meshagg")]
    n = len(base) * len(rules)

    def expanded():
        for w in base:
            for r in rules:
                out = r.apply(w)
                if out is not None:
                    yield out

    # Warm every shape outside the timed regions: the host-feed crack
    # step, the lockstep rules step, and each stream's single-device
    # rules step (junk words so no engine prunes).
    warm = [b"meshwarm%06d" % i for i in range(batch)]
    M22000Engine(lines, batch_size=batch).crack(list(warm))
    M22000Engine(lines, batch_size=batch).crack_rules(
        list(warm), [rules[0], rules[-1]])
    M22000Engine(lines, batch_size=batch).crack_rules_streams(
        frame_blocks(iter(warm * len(devices)), batch),
        [rules[0], rules[-1]], devices=devices)

    host_eng = M22000Engine(lines, batch_size=batch)
    with TRACER.span("bench:mesh_aggregate_hostfeed") as sp:
        host_founds = host_eng.crack(expanded())
    host_s = sp.seconds

    lock_eng = M22000Engine(lines, batch_size=batch)
    with TRACER.span("bench:mesh_aggregate_lockstep") as sp:
        lock_founds = lock_eng.crack_rules_blocks(
            frame_blocks(iter(base), batch), rules)
    lock_s = sp.seconds

    st_eng = M22000Engine(lines, batch_size=batch)
    with watch_compiles() as comp:
        with TRACER.span("bench:mesh_aggregate") as sp:
            st_founds = st_eng.crack_rules_streams(
                frame_blocks(iter(base), batch), rules, devices=devices)
    st_s = sp.seconds

    founds_identical = (
        sorted((f.line.essid, f.psk) for f in st_founds)
        == sorted((f.line.essid, f.psk) for f in lock_founds)
        == sorted((f.line.essid, f.psk) for f in host_founds))
    assert founds_identical, "mesh-aggregate legs disagree on founds"
    assert st_founds and st_founds[0].psk == psk, "planted PSK missed"

    return {"label": "mesh_aggregate", "batch": batch, "rules": len(rules),
            "candidates": n, "streams": len(devices),
            "hostfeed_seconds": host_s, "lockstep_seconds": lock_s,
            "aggregate_seconds": st_s,
            "hostfeed_pmk_per_s": n / host_s,
            "lockstep_pmk_per_s": n / lock_s,
            "aggregate_pmk_per_s": n / st_s,
            "aggregate_speedup": lock_s / st_s,
            "host_expand_ratio": host_s / st_s,
            "founds_identical": founds_identical,
            "recompiles_warm": comp.count}


def bench_resilience(batch: int = None, words: int = 20_000,
                     fault_rate: float = 0.10, seed: int = 10) -> dict:
    """Crack-loop throughput under transport faults (resilient transport
    + found outbox).

    Three loopback work units over the same dict geometry: a warmup leg
    (pays the compiles), a fault-free reference leg, and a leg under a
    seeded ``fault_rate`` schedule (drop/timeout/http_5xx/slow) plus a
    forced put_work reject redriven through the found outbox.  Backoff
    and circuit cooldowns run on the chaos VirtualClock, so the faulted
    leg's wall time is crack work plus fault *handling* only — the
    degraded loop must never park the devices behind a real backoff
    sleep.  Tracks ``retention`` (faulted PMK/s over clean PMK/s;
    acceptance floor 0.8) and ``recompiles_faulted`` (must stay 0:
    fault handling is host logic and must not perturb device shapes).
    """
    import gzip as _gzip
    import hashlib as _hashlib
    import random as _random
    import tempfile

    from dwpa_tpu.chaos import (ChaosTransport, FaultPlan, VirtualClock,
                                WsgiTransport)
    from dwpa_tpu.client.main import ClientConfig, TpuCrackClient
    from dwpa_tpu.client.protocol import CircuitBreaker, ServerAPI
    from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

    if batch is None:
        batch = 131072 if ON_TPU else 2048
    batch = min(batch, max(256, words // 4))
    psk = b"benchpass-res1"
    wordlist = [b"resword%07d" % i for i in range(words - 1)] + [psk]
    blob = _gzip.compress(b"\n".join(wordlist) + b"\n")
    dhash = _hashlib.md5(blob).hexdigest()

    def build_server(td):
        core = ServerCore(Database(":memory:"),
                          dictdir=os.path.join(td, "dicts"),
                          capdir=os.path.join(td, "caps"))
        core.add_hashlines([T.make_pmkid_line(psk, b"bench-res",
                                              seed="res1")])
        core.db.x("UPDATE nets SET algo = ''")
        os.makedirs(core.dictdir, exist_ok=True)
        with open(os.path.join(core.dictdir, "res.txt.gz"), "wb") as f:
            f.write(blob)
        core.add_dict("dict/res.txt.gz", "res.txt.gz", dhash,
                      len(wordlist), rules=None)
        return core

    def run_leg(td, plan, span):
        """One full work unit (get_work -> crack -> submit) under
        ``plan``; returns (result, seconds, client, clock)."""
        clock = VirtualClock()
        api = ServerAPI("http://loopback/", max_tries=0, backoff=2.0,
                        sleep=clock.sleep, rng=_random.Random(seed),
                        breaker=CircuitBreaker(threshold=5, cooldown=4.0,
                                               clock=clock.now))
        api.retry.clock = clock.now
        api._transport = ChaosTransport(
            WsgiTransport(make_wsgi_app(build_server(td))), plan,
            sleep=clock.sleep)
        cfg = ClientConfig(base_url="http://loopback/",
                           workdir=os.path.join(td, "work"),
                           batch_size=batch, dictcount=1,
                           device_streams="off")
        client = TpuCrackClient(cfg, api=api, log=lambda *a, **k: None)
        work = client.api.get_work(1)
        box = {}
        s = _timed(lambda: box.setdefault("res", client.process_work(work)),
                   span)
        return box["res"], s, client, clock

    with tempfile.TemporaryDirectory() as td:
        run_leg(os.path.join(td, "warm"), FaultPlan(seed),
                "bench:resilience_warmup")
        res0, clean_s, _, _ = run_leg(os.path.join(td, "clean"),
                                      FaultPlan(seed),
                                      "bench:resilience_clean")
        plan = FaultPlan(seed, rate=fault_rate,
                         kinds=("drop", "timeout", "http_5xx", "slow"))
        plan.force("put_work", "reject")
        with watch_compiles() as comp:
            res1, fault_s, client1, clock1 = run_leg(
                os.path.join(td, "chaos"), plan, "bench:resilience")
        # The rejected submission sits in the outbox; redrive until the
        # seeded schedule lets a clean exchange through.
        for _ in range(25):
            if not client1.outbox.pending_count():
                break
            clock1.sleep(client1.api.breaker.cooldown)
            try:
                client1._drain_outbox()
            except ConnectionError:
                continue

    n = res0.candidates_tried
    faults = [k for _, _, k in plan.schedule() if k is not None]
    return {"label": "resilience", "words": words, "batch": batch,
            "fault_rate": fault_rate,
            "clean_seconds": clean_s, "faulted_seconds": fault_s,
            "clean_pmk_per_s": n / clean_s,
            "faulted_pmk_per_s": res1.candidates_tried / fault_s,
            "retention": (res1.candidates_tried / fault_s) / (n / clean_s),
            "faults_injected": len(faults),
            "founds_delivered": bool(res0.founds) and bool(res1.founds)
            and client1.outbox.pending_count() == 0,
            "recompiles_faulted": comp.count}


def bench_server_load(sessions: int = 2000, threads: int = 16,
                      nets: int = 200, dicts: int = 20) -> dict:
    """Server core under a loopback client storm (epoch-leased scheduler
    + admission control, PR: crash-safe server core).

    ``sessions`` client sessions (each a get_work -> put_work release
    pair over ``chaos.WsgiTransport``, naps on a VirtualClock) are driven
    by ``threads`` workers against two same-geometry servers: the legacy
    per-request scheduling scan (``use_queue=False``) and the
    precomputed issuable-unit queue.  Reports issues/s, accepts/s and
    the server-side p99 request latency from the
    ``dwpa_http_request_seconds`` histogram; ``queue_speedup`` is the
    issues/s ratio (queue over scan — the pop path must win).
    """
    import json as _json
    import threading as _threading

    from dwpa_tpu.chaos import VirtualClock, WsgiTransport
    from dwpa_tpu.obs import MetricsRegistry
    from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

    # capacity: nets x dicts issuable units must cover the sessions
    assert nets * dicts >= 2 * sessions, "geometry too small for sessions"

    def build_server(use_queue):
        reg = MetricsRegistry()
        core = ServerCore(Database(":memory:"), registry=reg,
                          use_queue=use_queue, max_inflight=0)
        lines = [T.make_pmkid_line(b"load-psk-%04d" % i,
                                   b"LoadNet%04d" % i, seed=f"load{i}")
                 for i in range(nets)]
        core.add_hashlines(lines)
        core.db.x("UPDATE nets SET algo = ''")
        for i in range(dicts):
            core.add_dict(f"dict/load{i}.txt.gz", f"load{i}",
                          "0" * 32, 1000 + i)
        return core, make_wsgi_app(core)

    def p99(reg):
        fam = reg.histogram("dwpa_http_request_seconds")
        counts = [0] * (len(fam.bucket_bounds) + 1)
        total = 0
        for child in list(fam._children.values()):
            total += child.value
            for i, c in enumerate(child.buckets):
                counts[i] += c
        if not total:
            return 0.0
        need, acc = 0.99 * total, 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= need:
                return fam.bucket_bounds[i] if i < len(fam.bucket_bounds) \
                    else float("inf")
        return float("inf")

    def run_leg(use_queue, span):
        core, app = build_server(use_queue)
        issued = [0] * threads
        accepted = [0] * threads
        clock = VirtualClock()

        def worker(w):
            wsgi = WsgiTransport(app)
            body = _json.dumps({"dictcount": 1}).encode()
            for _ in range(sessions // threads):
                try:
                    raw = wsgi("http://loop/?get_work=2.2.0", body,
                               {"Content-Type": "application/json"})
                except Exception:
                    clock.sleep(0.01)  # 429/503: virtual nap, retry next
                    continue
                if raw in (b"No nets", b"Version"):
                    continue
                work = _json.loads(raw)
                issued[w] += 1
                sub = _json.dumps({"hkey": work["hkey"],
                                   "epoch": work["epoch"],
                                   "cand": []}).encode()
                try:
                    if wsgi("http://loop/?put_work", sub,
                            {"Content-Type": "application/json"}) == b"OK":
                        accepted[w] += 1
                except Exception:
                    clock.sleep(0.01)

        ts = [_threading.Thread(target=worker, args=(w,))
              for w in range(threads)]
        s = _timed(lambda: [[t.start() for t in ts],
                            [t.join() for t in ts]], span)
        return {"issued": sum(issued), "accepted": sum(accepted),
                "issues_per_s": sum(issued) / s,
                "accepts_per_s": sum(accepted) / s,
                "p99_request_s": p99(core.registry), "seconds": s}

    scan = run_leg(False, "bench:server_load_scan")
    queue = run_leg(True, "bench:server_load_queue")
    return {"label": "server_load", "sessions": sessions,
            "threads": threads, "nets": nets, "dicts": dicts,
            "scan": scan, "queue": queue,
            "queue_speedup": (queue["issues_per_s"]
                              / max(scan["issues_per_s"], 1e-9))}


def bench_server_precrack(nets: int = 48, group: int = 16,
                          vendor_words: int = 256, imei_words: int = 32,
                          batch: int = 2048) -> dict:
    """Batched server-side pre-crack vs the scalar per-candidate sweep
    (PR: batched pre-crack).

    ``nets`` synthetic PMKID nets in ``nets // group`` sibling groups
    share an ESSID, mirroring the war-driving capture shape the fused
    sweep exists for: the scalar loop pays one PBKDF2 per (net,
    candidate) while the fused wave dedups every shared (essid, word)
    pair to a single derivation.  Candidate mix per net: vendor pack +
    IMEI sweep + Single/Pattern mutations, plus replay/dict rows fed by
    one pre-cracked seed per group.  One net per group carries a
    last-vendor-word PSK so each leg must scan the full pack before its
    hit; the rest are misses (full sweep).  Reports candidates/s for
    both legs, whether they cracked the exact same free-found set, and
    the warm-path recompile count (must be 0).
    """
    from dwpa_tpu.models import hashline as hl
    from dwpa_tpu.obs import MetricsRegistry
    from dwpa_tpu.oracle import m22000 as oracle
    from dwpa_tpu.server import Database, ServerCore
    from dwpa_tpu.server.core import SERVER_NC
    from dwpa_tpu.server.db import long2mac
    from dwpa_tpu.server.precrack import PrecrackEngine

    groups = nets // group

    def essid_of(i):
        return b"PrecrackBench%02d" % (i % groups)

    def psk_of(i):
        if i % group == 0:  # group seed: cracked before either sweep
            return b"benchsecret-%02d!" % (i % groups)
        if i % group == 1:  # hit on the LAST vendor word: full pack scan
            return essid_of(i).lower() + b"-key-%03d" % (vendor_words - 1)
        return b"bench-miss-%04d" % i  # unmatchable: full sweep

    gens = [
        lambda bssid, ssid: [("BenchVendor",
                              ssid.lower() + b"-key-%03d" % k)
                             for k in range(vendor_words)],
        lambda bssid, ssid: [("IMEI", b"3526%011d" % k)
                             for k in range(imei_words)],
    ]

    def build_server():
        core = ServerCore(Database(":memory:"), registry=MetricsRegistry())
        core.add_hashlines([T.make_pmkid_line(psk_of(i), essid_of(i),
                                              seed=f"pcb{i}")
                            for i in range(nets)])
        rows = core.db.q("SELECT * FROM nets ORDER BY net_id")
        for i in range(0, nets, group):  # crack the group seeds
            core._try_accept(rows[i], psk_of(i))
        core.db.x("UPDATE nets SET algo = 'Manual' "
                  "WHERE n_state = 1 AND algo IS NULL")
        return core

    def scalar_sweep(core):
        # the per-candidate loop the engine supersedes (keygen_precompute
        # shape): same candidate stream, same per-net tx, but one full
        # PBKDF2 per check_key_m22000 call
        eng = PrecrackEngine(core, device="off", batch=batch,
                             generators=gens)
        db = core.db
        corpus = eng._dict_corpus()
        plan = []
        for net in db.q("SELECT * FROM nets WHERE algo IS NULL "
                        "AND n_state = 0 ORDER BY net_id"):
            h = hl.parse(net["struct"])
            plan.append((net, h, eng._collect(net, h,
                                              long2mac(net["bssid"]),
                                              corpus)))
        found = total = 0
        for net, h, cands in plan:
            total += len(cands)
            tried, hit = [], None
            for _, algo, cand in cands:
                tried.append((algo, cand))
                r = oracle.check_key_m22000(h, [cand], nc=SERVER_NC)
                if r:
                    hit = (algo, cand, r)
                    break
            with core._getwork_lock:
                with db.tx():
                    for algo, cand in tried:
                        db.x("INSERT INTO rkg(net_id, algo, pass) "
                             "VALUES (?, ?, ?)",
                             (net["net_id"], algo, cand))
                    if hit:
                        _, cand, r = hit
                        core._mark_cracked(net["net_id"], r[0], r[3],
                                           r[1] or 0, r[2] or "")
                        db.x("UPDATE rkg SET n_state = 1 "
                             "WHERE net_id = ? AND pass = ?",
                             (net["net_id"], cand))
                        found += 1
                    db.x("UPDATE nets SET algo = ? WHERE net_id = ?",
                         (hit[0] if hit else "", net["net_id"]))
        return {"cracked": found, "candidates": total}

    def founds(core):
        return {(r["ssid"], r["pass"]) for r in core.db.q(
            "SELECT ssid, pass FROM nets WHERE n_state = 1")}

    if ON_TPU:  # compile the fused widths off the clock
        PrecrackEngine(build_server(), device="auto", batch=batch,
                       generators=gens).run(limit=nets)

    sc, fc = build_server(), build_server()
    box = {}
    s_scalar = _timed(lambda: box.update(scalar=scalar_sweep(sc)),
                      "bench:server_precrack_scalar")
    feng = PrecrackEngine(fc, device="auto", batch=batch, generators=gens)
    with watch_compiles() as comp:
        s_fused = _timed(lambda: box.update(fused=feng.run(limit=nets)),
                         "bench:server_precrack_fused")
    cands = box["scalar"]["candidates"]
    out = {"label": "server_precrack", "nets": nets, "groups": groups,
           "candidates": cands,
           "scalar_seconds": s_scalar, "fused_seconds": s_fused,
           "scalar_cands_per_s": cands / max(s_scalar, 1e-9),
           "fused_cands_per_s": cands / max(s_fused, 1e-9),
           "speedup": s_scalar / max(s_fused, 1e-9),
           "free_founds": box["fused"]["cracked"],
           "found_parity": (founds(sc) == founds(fc)
                            and box["scalar"]["cracked"]
                            == box["fused"]["cracked"] == groups),
           "recompiles_warm": comp.count}
    if not ON_TPU:
        # device="on" off-accelerator would just re-time the jax CPU
        # backend; the device-path rate is only meaningful end-to-end
        out["device_leg"] = "skipped_no_tpu"
        return out
    # Attached-device leg: the recurring sweep as operators run it on a
    # TPU host — device derivations forced on, same candidate stream,
    # same found set, warm shapes already paid by the auto leg above.
    dc = build_server()
    deng = PrecrackEngine(dc, device="on", batch=batch, generators=gens)
    with watch_compiles() as dcomp:
        s_dev = _timed(lambda: box.update(dev=deng.run(limit=nets)),
                       "bench:server_precrack_device")
    out.update(device_seconds=s_dev,
               device_cands_per_s=cands / max(s_dev, 1e-9),
               device_found_parity=(founds(dc) == founds(fc)
                                    and box["dev"]["cracked"] == groups),
               device_recompiles_warm=dcomp.count)
    return out


def bench_mask_shards(batch: int = None, words: int = 20_000,
                      ceiling_pmk_per_s: float = None) -> dict:
    """bench:mask_shards — server-issued mask-shard unit vs the same
    keyspace pre-materialized as a dictionary (smart-keyspace vertical).

    Two loopback servers over the SAME 20k-word keyspace
    ``^benchm[01]\\d{4}$``: the mask leg holds only a ks row, so
    get_work hands the client a ``dicts: []`` unit whose candidates are
    generated ON DEVICE from ``(mask, custom, skip, limit)`` alone; the
    dict leg ships the identical words (odometer order) as a gzipped
    wordlist.  The PSK is the LAST keyspace word, so both legs sweep
    the full range before their hit.  Both legs run the full
    get_work -> crack -> put_work exchange through a byte-counting
    WSGI transport: ``mask_wire_bytes_per_cand`` must be ~0 (the
    unit's JSON framing only) while the dict leg pays the wordlist
    download.  Tracks found parity, the mask leg's rate against the
    dict leg and against the raw ``bench_mask_pbkdf2`` ceiling
    (``vs_mask_ceiling``; acceptance floor 0.9), and the warm-path
    recompile count (must be 0).
    """
    import gzip as _gzip
    import hashlib as _hashlib
    import tempfile

    from dwpa_tpu.chaos import WsgiTransport
    from dwpa_tpu.client.main import ClientConfig, TpuCrackClient
    from dwpa_tpu.client.protocol import ServerAPI
    from dwpa_tpu.gen.mask import mask_words
    from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

    if batch is None:
        batch = 131072 if ON_TPU else 2048
    # keyspace = 2 * 10^digits: snap ``words`` to the nearest such size
    digits = max(1, len(str(max(words, 20) // 2)) - 1)
    words = 2 * 10 ** digits
    batch = min(batch, max(256, words // 4))
    essid = b"bench-maskks"
    pass_re = r"^benchm[01]\d{%d}$" % digits
    # the dict leg's wordlist IS the compiled keyspace in odometer order
    wordlist = list(mask_words("benchm?1" + "?d" * digits, {"1": b"01"}))
    assert len(wordlist) == words
    psk = wordlist[-1]
    blob = _gzip.compress(b"\n".join(wordlist) + b"\n")
    dhash = _hashlib.md5(blob).hexdigest()

    def build_server(td, leg):
        core = ServerCore(Database(":memory:"),
                          dictdir=os.path.join(td, "dicts"),
                          capdir=os.path.join(td, "caps"))
        core.add_hashlines([T.make_pmkid_line(psk, essid, seed="maskks1")])
        core.db.x("UPDATE nets SET algo = ''")
        if leg == "mask":
            core.ks_add(r"^bench-maskks$", pass_re)
        else:
            os.makedirs(core.dictdir, exist_ok=True)
            with open(os.path.join(core.dictdir, "ks.txt.gz"), "wb") as f:
                f.write(blob)
            core.add_dict("dict/ks.txt.gz", "ks.txt.gz", dhash,
                          len(wordlist), rules=None)
        return core

    class CountingTransport(WsgiTransport):
        """WsgiTransport that meters both wire directions."""

        def __init__(self, app):
            super().__init__(app)
            self.wire_bytes = 0

        def __call__(self, url, body=None, headers=None):
            self.wire_bytes += len(url) + len(body or b"")
            data = super().__call__(url, body, headers)
            self.wire_bytes += len(data)
            return data

    def run_leg(td, leg, span):
        core = build_server(td, leg)
        api = ServerAPI("http://loopback/", max_tries=1,
                        sleep=lambda s: None)
        api._transport = transport = CountingTransport(make_wsgi_app(core))
        cfg = ClientConfig(base_url="http://loopback/",
                           workdir=os.path.join(td, "work"),
                           batch_size=batch, dictcount=1,
                           device_streams="off")
        client = TpuCrackClient(cfg, api=api, log=lambda *a, **k: None)
        work = client.api.get_work(1)
        assert (work["dicts"] == []) == (leg == "mask")
        box = {}
        s = _timed(lambda: box.setdefault("res", client.process_work(work)),
                   span)
        return box["res"], s, transport.wire_bytes, core

    with tempfile.TemporaryDirectory() as td:
        # warm both trace families off the clock: the on-device mask
        # generator and the host-packed dict feed
        run_leg(os.path.join(td, "wm"), "mask", "bench:mask_shards_warmup")
        run_leg(os.path.join(td, "wd"), "dict", "bench:mask_shards_warmup")
        with watch_compiles() as comp:
            mres, mask_s, mask_wire, mcore = run_leg(
                os.path.join(td, "mask"), "mask", "bench:mask_shards")
        dres, dict_s, dict_wire, dcore = run_leg(
            os.path.join(td, "dict"), "dict", "bench:mask_shards_dict")

    mask_rate = mres.candidates_tried / max(mask_s, 1e-9)
    # both legs also sweep the client's pass-1 SSID-targeted host
    # candidates (same ESSID -> same count), so tried is words + a few
    # dozen on each side; parity demands the counts MATCH, not == words
    parity = ([f.psk for f in mres.founds] == [f.psk for f in dres.founds]
              == [psk]
              and mres.candidates_tried == dres.candidates_tried >= words
              and mcore.db.q1("SELECT n_state FROM nets")["n_state"] == 1
              and dcore.db.q1("SELECT n_state FROM nets")["n_state"] == 1)
    out = {"label": "mask_shards", "words": words, "batch": batch,
           "mask_seconds": mask_s, "dict_seconds": dict_s,
           "mask_cands_per_s": mask_rate,
           "dict_cands_per_s": dres.candidates_tried / max(dict_s, 1e-9),
           "rate_vs_dict": dict_s / max(mask_s, 1e-9),
           "mask_wire_bytes": mask_wire, "dict_wire_bytes": dict_wire,
           "mask_wire_bytes_per_cand": mask_wire / words,
           "dict_wire_bytes_per_cand": dict_wire / words,
           "found_parity": parity,
           "recompiles_warm": comp.count}
    if ceiling_pmk_per_s:
        out["vs_mask_ceiling"] = mask_rate / ceiling_pmk_per_s
    return out


def _timed(fn, name: str = "bench:timed") -> float:
    """One rep as a span: the body must sync its own device work (every
    caller passes an engine crack* call, which does)."""
    with TRACER.span(name) as sp:
        fn()
    return sp.seconds


def bench_host_feed(words: int = 200_000) -> dict:
    """Host candidate pipeline (SURVEY §7.3.3 "keeping the device fed").

    Tracks the rates BASELINE.md's host-pipeline table quotes so they
    cannot rot invisibly: rule expansion (serial and pooled),
    the C++ candidate packer, and the gzip DictStream reader.
    """
    import gzip
    import os
    import tempfile

    from dwpa_tpu.gen import DictStream
    from dwpa_tpu.rules import apply_rules, parse_rules
    from dwpa_tpu.native import pack_candidates_fast

    rules = parse_rules([":", "u", "c", "$1", "^w", "r", "T0", "$1 $2 $3"])
    base = [b"feedword%07d" % i for i in range(words // len(rules))]
    out = {"label": "host_feed"}

    with TRACER.span("bench:host_feed.rules_serial") as sp:
        n = sum(1 for _ in apply_rules(rules, base))
    out["rules_serial_cand_per_s"] = n / sp.seconds

    # Warm the worker pool first: spawning 2 interpreters costs ~10 s
    # once per process, amortized over a whole work unit in production.
    # force_pool bypasses the few-cores guard — the point here is to
    # track the true pooled rate even on hosts where the guard trips.
    sum(1 for _ in apply_rules(rules, base[:64], workers=2, force_pool=True))
    with TRACER.span("bench:host_feed.rules_pooled2") as sp:
        n = sum(1 for _ in apply_rules(rules, base, workers=2,
                                       force_pool=True))
    out["rules_pooled2_cand_per_s"] = n / sp.seconds

    cands = [b"packword%07d" % i for i in range(words)]
    with TRACER.span("bench:host_feed.pack_fast") as sp:
        pack_candidates_fast(cands, 8, 63, words)
    out["pack_fast_cand_per_s"] = words / sp.seconds

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "feed.txt.gz")
        with open(path, "wb") as f:
            f.write(gzip.compress(b"\n".join(cands) + b"\n"))
        with TRACER.span("bench:host_feed.dictstream") as sp:
            n = sum(1 for _ in DictStream(path))
        out["dictstream_words_per_s"] = n / sp.seconds
    return out


def bench_unit_overhead(pmkid_small: dict) -> dict:
    """Decompose the fixed per-unit overhead configs #1/#2 are bound by.

    Two engine runs at the SAME batch size but different word counts
    give ``t = overhead + words / rate``; solving the pair isolates the
    constant (compile-cache hits, host pack, hits-gate sync) from the
    marginal per-word rate at that batch size — so a regression in
    either is visible.  (``rate`` here is the small-batch slope, NOT
    the full-batch kernel rate — see dict_steady for that.)
    """
    psk = b"benchpass1"
    w1 = pmkid_small["words"]
    cfg_big = bench_engine_dict(
        T.make_pmkid_line(psk, b"bench-essid"), psk, 16 * w1, "pmkid_big",
        batch=min(4096, w1),
    )
    t1 = pmkid_small["seconds"]
    w2, t2 = cfg_big["words"], cfg_big["seconds"]
    rate = (w2 - w1) / max(t2 - t1, 1e-9)
    # The two-point fit can come out negative (timing noise on two
    # sub-second runs); the clamp keeps the headline sane, but the RAW
    # value is reported alongside — a run where fixed_overhead_s reads
    # 0.0 exactly is a clamped fit, not a free engine, and a real
    # per-unit overhead regression must not hide behind the clamp.
    raw = t1 - w1 / rate
    return {"label": "unit_overhead", "small_words": w1, "big_words": w2,
            "batch": min(4096, w1),
            "smallbatch_pmk_per_s": rate, "fixed_overhead_s": max(0.0, raw),
            "fixed_overhead_raw_s": raw}


def _round(cfg: dict) -> dict:
    return {k: round(v, 4) if isinstance(v, float) else v for k, v in cfg.items()}


def main():
    # Persistent compilation cache: the ~20-40 s PBKDF2 first-compile is
    # paid once per machine, not once per bench run (mirrors the client's
    # cold-start wiring, client/main.py).
    from dwpa_tpu.utils.compcache import enable_compilation_cache

    enable_compilation_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".xla_cache")
    )
    batch = 131072 if ON_TPU else 2048
    words = 1000

    selftest = tpu_selftest()
    mask = bench_mask_pbkdf2(batch)
    psk = b"benchpass1"
    pmkid = bench_engine_dict(
        T.make_pmkid_line(psk, b"bench-essid"), psk, words, "pmkid_dict"
    )
    eapol = bench_engine_dict(
        T.make_eapol_line(psk, b"bench-essid", keyver=2), psk, words, "eapol_dict"
    )
    rules = bench_rules_dict(words)
    rules_dev = bench_rules_device(batch)
    multi = bench_multi_bssid(words)
    steady = bench_dict_steady(batch)
    feed = bench_host_feed()
    feed_ov = bench_feed_overlap(batch)
    pmkstore = bench_pmkstore(batch)
    dcache = bench_dict_cache(batch)
    small_units = bench_small_units()
    streams = bench_device_streams()
    mesh_agg = bench_mesh_aggregate()
    overhead = bench_unit_overhead(pmkid)
    resilience = bench_resilience(batch)
    server_load = bench_server_load()
    server_precrack = bench_server_precrack(batch=batch)
    mask_shards = bench_mask_shards(batch, ceiling_pmk_per_s=mask["pmk_per_s"])

    value = mask["pmk_per_s"]
    print(
        json.dumps(
            {
                "metric": "PMK/s per chip (m22000 PBKDF2, ?d x8 mask, config #5)",
                "value": round(value),
                "unit": "PMK/s",
                "vs_baseline": round(value / PER_CHIP_TARGET, 4),
                "platform": jax.devices()[0].device_kind,
                "configs": {
                    "tpu_selftest": _round(selftest),
                    "mask_pbkdf2": _round(mask),
                    "pmkid_dict": _round(pmkid),
                    "eapol_dict": _round(eapol),
                    "rules_dict": _round(rules),
                    "rules_device": _round(rules_dev),
                    "multi_bssid": _round(multi),
                    "dict_steady": _round(steady),
                    "host_feed": _round(feed),
                    "feed_overlap": _round(feed_ov),
                    "pmkstore": _round(pmkstore),
                    "dict_cache": _round(dcache),
                    "small_units": _round(small_units),
                    "device_streams": _round(streams),
                    "mesh_aggregate": _round(mesh_agg),
                    "unit_overhead": _round(overhead),
                    "resilience": _round(resilience),
                    "server_load": _round(server_load),
                    "server_precrack": _round(server_precrack),
                    "mask_shards": _round(mask_shards),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
