"""Mixed-ESSID batch fusion: pack several small work units into one
full device batch (pure host work).

BENCH_r05's ~25x steady-vs-small-unit gap is structural: the scalar-salt
PMK step takes ONE ESSID per dispatch, so every small ESSID-group x dict
unit pads its ~1k candidates up to the compiled batch width and runs
alone — per-unit fixed costs and dead padding lanes bound aggregate
throughput, not the PBKDF2 kernel.  Fusion is the serving-stack answer
(Orca-style iteration-level batching, vLLM-style heterogeneous packing,
PAPERS.md): lay the units' candidates out unit-major in ONE batch, ship
a 4-byte ``unit_id`` per lane, and let ``parallel.step.fused_pmk_step``
gather each lane's salt blocks from a replicated per-unit table on
device.

Shape discipline (lint rule DW109): the fused batch is padded to one of
at most THREE static widths (``fused_widths`` — the same geometric
~B/8, ~B/2, B table as ``pmkstore.stage.miss_widths``, mesh-multiple
rounded) and the salt table to the fixed ``max_units`` bucket, so the
fused PMK step compiles a bounded number of times however the unit mix
wanders.  A data-dependent width here would retrace per unit
combination — exactly the compile-per-work-unit failure the scalar
path was designed around.

PMK-store composition: the hit/miss split runs PER UNIT before fusion
(each unit's candidates are looked up under its own ESSID), the fused
compute batch carries only the misses, and the cached PMKs are gathered
around the computed ones by the engine through the same ``mix_step``
the single-unit mixed path uses.
"""

from dataclasses import dataclass, field

import numpy as np

from ..models.m22000 import (MAX_PSK_LEN, MIN_PSK_LEN, essid_salt_blocks,
                             essid_salt_lanes)
from ..oracle import m22000 as oracle
from ..pmkstore.store import word_digest
from ..utils import bytesops as bo


def fused_widths(batch: int, n: int) -> tuple:
    """The static fused-batch widths for device batch ``batch`` on an
    ``n``-device mesh: at most 3 distinct values, each a positive mesh
    multiple, the largest exactly ``batch``.

    Same geometric (~B/8, ~B/2, B) table as
    ``pmkstore.stage.miss_widths`` and for the same reason: PBKDF2 cost
    is proportional to the PADDED width, so the smallest bucket sets the
    speedup for a lone underfilled wave while three widths keep the
    compile count bounded (the recompile_sentinel proof)."""
    def up(x):
        return max(n, -(-x // n) * n)

    return tuple(sorted({up(batch // 8), up(batch // 2), batch}))


def fused_width(batch: int, n: int, total: int) -> int:
    """Smallest static fused width that holds ``total`` candidate lanes."""
    for w in fused_widths(batch, n):
        if total <= w:
            return w
    return batch


def pack_salted_lanes(pairs, batch_size: int, n: int):
    """Derive-only mixed-ESSID packing (the server pre-crack path).

    ``pairs``: list of ``(essid, word)`` with words already decoded and
    length-valid (8..63 bytes); at most ``batch_size`` of them.  Returns
    ``(rows uint32[W, 16], salt1 uint32[W, 16], salt2 uint32[W, 16],
    nvalid)`` padded to the static fused width, ready for the per-lane
    rank-2 salt mode of ``pmk_kernel``.  Unlike ``fuse_units`` there is
    no unit table and no store split — the caller demuxes lanes itself —
    so the same ESSID may occupy many lanes.  Dead padding lanes repeat
    lane 0 (word and salt), never introducing a new salt row.
    """
    if not pairs:
        raise ValueError("pack_salted_lanes needs at least one lane")
    if len(pairs) > batch_size:
        raise ValueError(
            f"{len(pairs)} lanes overflow fused batch {batch_size}")
    W = fused_width(batch_size, n, len(pairs))
    rows = np.zeros((W, 16), np.uint32)
    salt1 = np.zeros((W, 16), np.uint32)
    salt2 = np.zeros((W, 16), np.uint32)
    rows[:len(pairs)] = bo.pack_passwords_be(
        [w for _, w in pairs]).astype(np.uint32)
    salt1[:len(pairs)], salt2[:len(pairs)] = essid_salt_lanes(
        [e for e, _ in pairs])
    if len(pairs) < W:
        rows[len(pairs):] = rows[0]
        salt1[len(pairs):] = salt1[0]
        salt2[len(pairs):] = salt2[0]
    return rows, salt1, salt2, len(pairs)


@dataclass
class FusedUnit:
    """One unit's lane window inside a fused batch.

    Logical lanes ``[lo, lo + nvalid)`` hold the unit's candidates
    (unit-major layout); compute (miss) lanes ``[mlo, mlo + nmiss)``
    index the compacted PBKDF2 sub-batch — equal to the logical window
    when no PMK store split the unit.  ``words`` aligns decode and
    ``miss_words`` store write-back; ``count`` is the unit's GLOBAL
    candidate coverage for this batch (resume framing: checkpoints
    advance by ``count``, exactly like ``feed.framing.Block``)."""

    key: bytes
    lo: int
    nvalid: int
    words: list
    count: int
    mlo: int = 0
    nmiss: int = 0
    miss_words: list = field(default_factory=list)


@dataclass
class FusedBatch:
    """One packed mixed-ESSID device batch (host arrays only — staging
    is consumer-thread work, ``M22000Engine._dispatch_fused``)."""

    width: int             # logical fused width W (static table)
    miss_width: int        # compute width Wm (static table; == W sans store)
    nmiss: int             # real compute lanes
    total: int             # real logical lanes across units
    miss_rows: np.ndarray  # uint32[Wm, 16] packed PBKDF2 input
    miss_lens: np.ndarray  # uint8[nmiss] for column trimming
    unit_id: np.ndarray    # int32[Wm] per-lane salt-table row
    table1: np.ndarray     # uint32[U, 16] per-unit salt block 1
    table2: np.ndarray     # uint32[U, 16] per-unit salt block 2
    idx: np.ndarray = None     # int32[W] mix gather map (None: all-miss)
    cached: np.ndarray = None  # uint32[8, W] hit PMKs at their lanes
    units: list = field(default_factory=list)  # [FusedUnit]

    @property
    def fill(self) -> float:
        """Fraction of logical lanes holding real candidates."""
        return self.total / self.width if self.width else 0.0


def _pack_words(words):
    """Decode + length-filter + pack one unit's candidates (pure host).

    Returns ``(rows uint32[nvalid, 16], lens uint8[nvalid], decoded)``.
    Prefers the native fused pass; the Python fallback matches
    ``M22000Engine._prepare``'s semantics ($HEX decode, 8..63 filter).
    """
    from ..native import pack_candidates_fast

    fast = pack_candidates_fast(words, MIN_PSK_LEN, MAX_PSK_LEN)
    if fast is not None:
        packed, lens, nvalid = fast
        blob = np.ascontiguousarray(packed[:nvalid]).astype(">u4").tobytes()
        decoded = [blob[64 * i:64 * i + int(lens[i])] for i in range(nvalid)]
        return packed[:nvalid], lens[:nvalid], decoded
    decoded = [oracle.hc_unhex(w) for w in words]
    decoded = [w for w in decoded if MIN_PSK_LEN <= len(w) <= MAX_PSK_LEN]
    if not decoded:
        return (np.zeros((0, 16), np.uint32), np.zeros(0, np.uint8), [])
    rows = bo.pack_passwords_be(decoded).astype(np.uint32)
    lens = np.asarray([len(w) for w in decoded], np.uint8)
    return rows, lens, decoded


def fuse_units(parts, batch_size: int, n: int, max_units: int,
               store=None, salts=None):
    """Fuse per-unit candidate lists into one ``FusedBatch``.

    ``parts``: list of ``(key, words, count)`` — unit key (its ESSID),
    raw candidate bytes, and the block's global candidate coverage.
    Keys must be unique (the caller defers a colliding unit to the next
    wave).  ``salts``: optional ``{key: (salt1, salt2)}`` snapshot (the
    engine's ``_salts``); missing keys derive via ``essid_salt_blocks``.

    Pure host work: packing, store lookups (mmap/dict reads), numpy
    shuffling — producer-thread safe under the feed's DW107 discipline.
    """
    if not parts:
        raise ValueError("fuse_units needs at least one unit part")
    if len(parts) > max_units:
        raise ValueError(f"{len(parts)} units > fuse_max_units={max_units}")
    keys = [k for k, _, _ in parts]
    if len(set(keys)) != len(keys):
        raise ValueError(f"duplicate unit keys in one fused batch: {keys}")

    packed = [(k, *_pack_words(words), count) for k, words, count in parts]
    total = sum(len(words) for _, _, _, words, _ in packed)
    W = fused_width(batch_size, n, total)
    if total > W:
        raise ValueError(f"{total} candidates overflow fused batch {W}")

    # Per-unit hit/miss split BEFORE fusion: each unit's candidates are
    # looked up under its own ESSID; only misses reach the compute batch.
    units, miss_segs, miss_len_segs, uid_segs = [], [], [], []
    cached = np.zeros((8, W), np.uint32) if store is not None else None
    hit_lanes = []  # logical lanes whose PMK comes from the store
    lo = mlo = 0
    for uid, (key, rows, lens, words, count) in enumerate(packed):
        nv = len(words)
        if store is not None and nv:
            pmks = store.lookup_digests(key, [word_digest(w) for w in words])
        else:
            pmks = [None] * nv
        miss_cols = [i for i, p in enumerate(pmks) if p is None]
        for i, p in enumerate(pmks):
            if p is not None:
                cached[:, lo + i] = np.frombuffer(p, dtype=">u4")
                hit_lanes.append(lo + i)
        nm = len(miss_cols)
        if nm:
            cols = np.asarray(miss_cols, np.int64)
            miss_segs.append(rows[cols])
            miss_len_segs.append(np.asarray(lens)[cols])
            uid_segs.append(np.full(nm, uid, np.int32))
        units.append(FusedUnit(
            key=key, lo=lo, nvalid=nv, words=words, count=count,
            mlo=mlo, nmiss=nm,
            miss_words=[words[i] for i in miss_cols] if nm < nv else words))
        lo += nv
        mlo += nm

    nmiss = mlo
    all_miss = not hit_lanes
    # All-miss: the compacted layout IS the logical layout, so the
    # compute width is the logical width and no mix gather runs — the
    # plain fused path costs nothing when the store is cold or absent.
    Wm = W if all_miss else fused_width(batch_size, n, max(nmiss, 1))
    miss_rows = np.zeros((Wm, 16), np.uint32)
    if nmiss:
        miss_rows[:nmiss] = np.concatenate(miss_segs)
    miss_lens = (np.concatenate(miss_len_segs) if nmiss
                 else np.zeros(0, np.uint8))
    unit_id = np.zeros(Wm, np.int32)
    if nmiss:
        unit_id[:nmiss] = np.concatenate(uid_segs)

    idx = None
    if not all_miss:
        # Gather map over concat([pmk_miss, cached], axis=1): miss lanes
        # read their compacted compute slot, hit lanes AND padding read
        # the cached matrix at their own column (mix_step's contract).
        idx = Wm + np.arange(W, dtype=np.int32)
        hit = np.zeros(W, bool)
        hit[np.asarray(hit_lanes, np.int64)] = True
        m = 0
        for u in units:
            for i in range(u.nvalid):
                lane = u.lo + i
                if not hit[lane]:
                    idx[lane] = m
                    m += 1
        assert m == nmiss, (m, nmiss)

    # Per-unit salt tables, padded to the FIXED max_units bucket (repeat
    # row 0) so the fused step's jit signature never keys on the wave's
    # unit count — only on the (bounded) width table.
    s1_rows, s2_rows = [], []
    for key, *_rest in packed:
        s = (salts or {}).get(key) or essid_salt_blocks(key)
        s1_rows.append(np.asarray(s[0], np.uint32))
        s2_rows.append(np.asarray(s[1], np.uint32))
    pad = max_units - len(s1_rows)
    table1 = np.stack(s1_rows + [s1_rows[0]] * pad)
    table2 = np.stack(s2_rows + [s2_rows[0]] * pad)

    return FusedBatch(
        width=W, miss_width=Wm, nmiss=nmiss, total=total,
        miss_rows=miss_rows, miss_lens=miss_lens, unit_id=unit_id,
        table1=table1, table2=table2, idx=idx, cached=cached, units=units)
