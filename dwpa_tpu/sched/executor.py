"""Multi-unit executor: keep a queue of small work units ahead of the
device and crack them as fused mixed-ESSID batches.

The client's unit loop is strictly serial: fetch a unit, crack it, fetch
the next — so a stream of small ESSID-group x dict units leaves the
device idle between units AND underfilled within them.  This executor
is the scheduling half of the fusion tentpole (``sched.fuse`` is the
packing half): a producer thread materializes up to ``unit_queue``
units ahead (skip applied — deterministic resume framing carries over),
and the consumer drains them in WAVES of up to ``fuse_max_units``,
handing each wave to ``M22000Engine.crack_fused`` which packs the
units' candidates into full device batches with per-lane salt gather.

Failure containment (the in-process recovery contract the client's
``--unit-queue`` path relies on): a wave whose crack dispatch raises —
device error, XLA OOM on an oversized fused width — is retried ONCE at
half the batch size on a fresh engine; if it fails again its units are
requeued with exponential backoff, and a unit that keeps failing lands
in ``failed`` instead of wedging the stream.

Observability: ``dwpa_fused_units_per_batch`` (histogram),
``dwpa_fused_fill_fraction`` / ``dwpa_unit_queue_depth`` (gauges),
``dwpa_fused_retries_total`` (counter), plus the engine's
``sched:fuse`` / ``sched:demux`` spans when a tracer is wired.
"""

import queue
import threading
import time
from dataclasses import dataclass, field

from ..feed.framing import skip_stream
from ..models import hashline as hl

#: Fused-batch histogram buckets: unit counts, not seconds (the metrics
#: registry's DEFAULT_BUCKETS are latency-oriented).
UNITS_PER_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0)


@dataclass
class WorkUnit:
    """One fetchable work unit: a hashline set and a candidate stream.

    ``words`` may be any iterable; the producer thread materializes it
    (after dropping ``skip`` candidates — the resume contract: a unit
    retried or resumed at skip=k behaves exactly like the serial path's
    ``skip_stream``).  ``consumed`` is the unit's conservative resume
    floor: the minimum candidate coverage across its ESSID parts, so a
    checkpoint written from it never skips an uncracked candidate.
    """

    uid: object
    lines: list
    words: object
    skip: int = 0
    attempts: int = 0
    consumed: int = 0
    founds: list = field(default_factory=list)
    #: parsed rule list for a RULES unit: ``words`` then carries BASE
    #: words and the unit dispatches through the device rule-expansion
    #: seam (``M22000Engine.crack_rules_blocks``/``crack_rules_streams``)
    #: instead of ``crack_fused`` — and ``skip``/``consumed`` count
    #: EXPANDED (word x rule) candidates, the rules resume domain
    rules: list = None
    # -- producer/consumer internals --
    _materialized: list = None
    _essids: tuple = None
    _done: dict = None

    def essids(self) -> tuple:
        """The unit's distinct ESSIDs, parse-tolerant (unparseable
        lines are the engine's ``skipped`` concern, not a wave killer)."""
        if self._essids is None:
            seen = {}
            for line in self.lines:
                try:
                    h = line if isinstance(line, hl.Hashline) else hl.parse(line)
                except ValueError:
                    continue
                seen[h.essid] = True
            self._essids = tuple(seen)
        return self._essids


class MultiUnitExecutor:
    """Pack small work units into fused device batches (see module doc).

    ``units``: iterable of ``WorkUnit`` (a generator is fine — the
    producer thread pulls lazily, so fetch latency overlaps cracking).
    ``engine_factory(lines, batch_size)``: override for tests; defaults
    to building an ``M22000Engine`` with this executor's mesh/store.
    """

    def __init__(self, units, *, batch_size=4096, unit_queue=4,
                 fuse_max_units=8, nc=8, mesh="auto", pmk_store=None,
                 registry=None, tracer=None, max_retries=2,
                 backoff_s=1.0, sleep=time.sleep, engine_factory=None,
                 verify_with_oracle=True, streams="auto"):
        self.units = iter(units)
        self.batch_size = int(batch_size)
        self.unit_queue = max(1, int(unit_queue))
        self.fuse_max_units = max(1, int(fuse_max_units))
        self.nc = nc
        self.mesh = mesh
        self.pmk_store = pmk_store
        self.registry = registry
        self.tracer = tracer
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.sleep = sleep
        self.verify_with_oracle = verify_with_oracle
        #: "auto" resolves per-run via parallel.streams.streams_default():
        #: single-process multi-device waves scatter over device streams
        #: (one chip per bundle) instead of padding the whole mesh.
        self.streams = streams
        self._engine_factory = engine_factory or self._default_engine
        self.done = []     # units that completed (possibly after retry)
        self.failed = []   # units abandoned after max_retries
        self._q = queue.Queue(maxsize=self.unit_queue)
        self._deferred = []  # essid-collision holdovers, next-wave first
        self._producer_err = None
        if registry is not None:
            self._m_units = registry.histogram(
                "dwpa_fused_units_per_batch",
                "Work units packed into each fused device batch",
                buckets=UNITS_PER_BATCH_BUCKETS)
            self._m_fill = registry.gauge(
                "dwpa_fused_fill_fraction",
                "Real-candidate fraction of the last fused batch")
            self._m_depth = registry.gauge(
                "dwpa_unit_queue_depth",
                "Prefetched work units waiting in the executor queue")
            self._m_retries = registry.counter(
                "dwpa_fused_retries_total",
                "Fused wave crack attempts retried after an engine error")
        else:
            self._m_units = self._m_fill = self._m_depth = None
            self._m_retries = None

    # -- producer ----------------------------------------------------------

    def _produce(self):
        """Materialize units ahead of the consumer (bounded queue).

        Pure host work — candidate IO and skip framing — so it overlaps
        device compute exactly like the feed's producer threads."""
        try:
            for u in self.units:
                words = iter(u.words)
                if u.skip and u.rules is None:
                    # a rules unit's skip is EXPANDED pairs — the
                    # engine's O(1) block-drop applies it, not the
                    # base-word stream
                    skip_stream(words, u.skip)  # consumes in place
                u._materialized = list(words)
                self._q.put(u)
                self._gauge_depth()
        except BaseException as e:  # surfaced on the consumer side
            self._producer_err = e
        finally:
            self._q.put(None)

    def _gauge_depth(self):
        if self._m_depth is not None:
            self._m_depth.set(self._q.qsize())

    # -- consumer ----------------------------------------------------------

    def _default_engine(self, lines, batch_size, mesh=None):
        from ..models.m22000 import M22000Engine

        return M22000Engine(lines, nc=self.nc, batch_size=batch_size,
                            mesh=self.mesh if mesh is None else mesh,
                            pmk_store=self.pmk_store,
                            verify_with_oracle=self.verify_with_oracle)

    def _factory_takes_mesh(self) -> bool:
        """Whether the engine factory accepts a ``mesh`` kwarg.  Stream
        waves REQUIRE it: each bundle must run on a 1-device mesh, and
        a factory that silently ignores ``mesh`` would hand every
        stream thread a full-mesh engine — concurrent collective
        programs dispatched from different threads interleave their
        per-device enqueues and deadlock the AllReduce rendezvous, so
        such factories (the old two-arg test fakes) pin the executor
        to the lockstep path instead."""
        try:
            import inspect

            params = inspect.signature(self._engine_factory).parameters
            return "mesh" in params or any(
                p.kind is p.VAR_KEYWORD for p in params.values())
        except (TypeError, ValueError):
            return False

    def _make_engine(self, lines, batch_size, mesh=None):
        """Build a wave engine, passing ``mesh`` through only when the
        factory's signature takes it (two-arg factories only ever see
        lockstep waves — see ``_factory_takes_mesh``)."""
        if mesh is not None and self._factory_takes_mesh():
            return self._engine_factory(lines, batch_size, mesh=mesh)
        return self._engine_factory(lines, batch_size)

    def _next_wave(self, exhausted):
        """Assemble the next wave: deferred holdovers first, then fresh
        units from the queue, stopping at ``fuse_max_units`` or at an
        ESSID collision (two units sharing an ESSID cannot share a
        fused batch's salt table — the collider waits one wave)."""
        wave, taken = [], set()

        def try_add(u):
            if wave and (u.rules is not None or wave[0].rules is not None):
                # rules units run as singleton waves: their dispatch is
                # the device-expansion seam, not the fused salt table
                return False
            es = u.essids()
            if any(e in taken for e in es):
                return False
            wave.append(u)
            taken.update(es)
            return True

        held, self._deferred = self._deferred, []
        for u in held:
            if len(wave) >= self.fuse_max_units or not try_add(u):
                self._deferred.append(u)
        while len(wave) < self.fuse_max_units and not exhausted[0]:
            try:
                u = self._q.get(block=not wave and not self._deferred)
            except queue.Empty:
                break
            if u is None:
                exhausted[0] = True
                break
            self._gauge_depth()
            if not try_add(u):
                self._deferred.append(u)
                break  # keep wave assembly cheap; collider leads next wave
        return wave

    def _run_wave(self, wave, batch_size, mesh=None):
        """Crack one wave through a fresh engine's fused path."""
        if len(wave) == 1 and wave[0].rules is not None:
            return self._run_wave_rules(wave[0], batch_size, mesh)
        lines = [ln for u in wave for ln in u.lines]
        engine = self._make_engine(lines, batch_size, mesh)
        by_essid = {}
        for u in wave:
            u._done = {}
            for e in u.essids():
                by_essid[e] = u
        parts = [(e, u._materialized) for u in wave for e in u.essids()]

        def on_batch(essid, consumed, founds):
            u = by_essid.get(essid)
            if u is None:
                return
            u._done[essid] = u._done.get(essid, 0) + consumed
            # Conservative resume floor across the unit's ESSID parts.
            u.consumed = u.skip + min(u._done.values())
            for f in founds:
                if all(f.line is not g.line or f.psk != g.psk
                       for g in u.founds):
                    u.founds.append(f)

        def on_fused(fb):
            if self._m_units is not None:
                self._m_units.observe(len(fb.units))
                self._m_fill.set(fb.fill)

        engine.crack_fused(parts, on_batch=on_batch,
                           max_units=self.fuse_max_units,
                           tracer=self.tracer, on_fused=on_fused)

    def _run_wave_rules(self, u, batch_size, mesh=None):
        """Crack one RULES unit through the shared device-expansion
        seam — the executor's pass-2 dispatch is the same
        ``crack_rules_blocks``/``crack_rules_streams`` entry as the
        serial client path, not a fourth regime.  Streams engage under
        the same conditions as ``_execute_wave`` (enabled, single
        process, multiple local devices, mesh-capable factory);
        otherwise the engine's own lockstep mesh runs the blocks
        serially.  ``u.consumed`` advances in EXPANDED candidates."""
        import jax

        from ..feed.framing import frame_blocks

        engine = self._make_engine(u.lines, batch_size, mesh)
        u.consumed = u.skip

        def on_batch(consumed, founds):
            u.consumed += consumed
            for f in founds:
                if all(f.line is not g.line or f.psk != g.psk
                       for g in u.founds):
                    u.founds.append(f)

        blocks = frame_blocks(iter(u._materialized),
                              engine.batch_size * jax.process_count())
        if (mesh is None and self._streams_enabled()
                and self._factory_takes_mesh()
                and jax.process_count() == 1
                and jax.local_device_count() > 1
                and hasattr(engine, "crack_rules_streams")):
            engine.crack_rules_streams(
                blocks, u.rules, on_batch=on_batch, skip=u.skip,
                registry=self.registry, tracer=self.tracer)
        else:
            engine.crack_rules_blocks(
                blocks, u.rules, on_batch=on_batch, skip=u.skip,
                registry=self.registry, tracer=self.tracer)

    # -- device-stream wave scheduling (parallel/streams.py) ---------------

    def _streams_enabled(self) -> bool:
        from ..parallel.streams import streams_default

        if self.streams == "auto":
            return streams_default()
        return bool(self.streams)

    def _stream_bundles(self, wave, batch_size, ndev):
        """Partition one ESSID-disjoint wave into per-device bundles:
        big units (a whole device batch or more of candidates) get a
        chip to themselves; small units spread over free chips first,
        then pack greedily (lightest small bundle, ``fuse_max_units``
        cap).  Each bundle is itself a valid wave — ESSID disjointness
        is inherited from the wave it was cut from."""
        sized = sorted(wave, key=lambda u: -len(u._materialized or ()))
        bundles = []   # [units], small bundles may grow

        def small_open():
            return [b for b in bundles
                    if len(b) < self.fuse_max_units
                    and len(b[0]._materialized or ()) < batch_size]

        for u in sized:
            size = len(u._materialized or ())
            if size >= batch_size or len(bundles) < ndev:
                bundles.append([u])
                continue
            opened = small_open()
            if opened:
                min(opened, key=lambda b: sum(
                    len(x._materialized or ()) for x in b)).append(u)
            else:
                bundles.append([u])
        return bundles

    def _run_wave_streams(self, wave, batch_size):
        """Scatter one wave onto independent device streams: each
        bundle runs ``crack_fused`` on its own 1-device mesh engine, so
        a big mask/dict unit and a clutch of small fused units crack
        concurrently on different chips instead of padding the whole
        lockstep mesh.  The per-unit demux is untouched — each unit
        lives in exactly one bundle, so its ``on_batch`` state is
        single-threaded.  Any bundle failure re-raises as RuntimeError
        for ``run``'s existing retry/requeue containment."""
        import jax

        from ..parallel import default_mesh

        devices = jax.local_devices()
        bundles = self._stream_bundles(wave, batch_size, len(devices))
        work = queue.Queue()
        for b in bundles:
            work.put(b)
        errs = []

        def drain(device):
            mesh = default_mesh(devices=[device])
            while not errs:
                try:
                    b = work.get_nowait()
                except queue.Empty:
                    return
                try:
                    self._run_wave(b, batch_size, mesh=mesh)
                except BaseException as e:  # contained by run()'s retry
                    errs.append(e)

        threads = [
            threading.Thread(target=drain, args=(d,), daemon=True,
                             name=f"sched-stream-{i}")
            for i, d in enumerate(devices[:len(bundles)])
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            err = errs[0]
            if isinstance(err, RuntimeError):
                raise err
            raise RuntimeError(f"stream wave failed: {err!r}") from err

    def _execute_wave(self, wave, batch_size):
        """One wave, streams or lockstep: streams when enabled, more
        than one unit to spread, and more than one local device —
        otherwise the classic full-mesh fused path."""
        if (self._streams_enabled() and len(wave) > 1
                and self._factory_takes_mesh()):
            import jax

            if jax.local_device_count() > 1 and jax.process_count() == 1:
                self._run_wave_streams(wave, batch_size)
                return
        self._run_wave(wave, batch_size)

    def run(self) -> list:
        """Drain every unit; returns the completed units in finish order.

        Engine errors are contained per wave: one retry at half batch,
        then requeue-with-backoff, then ``failed`` (module doc)."""
        producer = threading.Thread(target=self._produce, daemon=True,
                                    name="sched-unit-producer")
        producer.start()
        exhausted = [False]
        while True:
            wave = self._next_wave(exhausted)
            if not wave:
                if exhausted[0] and not self._deferred:
                    break
                continue
            try:
                self._execute_wave(wave, self.batch_size)
            except RuntimeError:
                # Satellite recovery: one in-process retry at half batch
                # (an XLA OOM on the fused width usually fits at W/2;
                # a transient device error just needs the re-dispatch).
                if self._m_retries is not None:
                    self._m_retries.inc()
                try:
                    self._execute_wave(wave, max(1, self.batch_size // 2))
                except RuntimeError:
                    requeued = False
                    for u in wave:
                        u.attempts += 1
                        if u.attempts > self.max_retries:
                            self.failed.append(u)
                        else:
                            self._deferred.append(u)
                            requeued = True
                    if requeued:
                        self.sleep(self.backoff_s * 2 ** (wave[0].attempts - 1))
                    continue
            self.done.extend(wave)
        producer.join(timeout=5)
        if self._producer_err is not None:
            raise self._producer_err
        return self.done
