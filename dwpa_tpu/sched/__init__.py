"""Client-side scheduling: pack small work units into full device
batches (mixed-ESSID fusion — see ``sched.fuse`` and
``sched.executor``).
"""

from .executor import MultiUnitExecutor, WorkUnit
from .fuse import FusedBatch, FusedUnit, fuse_units, fused_width, fused_widths

__all__ = [
    "FusedBatch",
    "FusedUnit",
    "MultiUnitExecutor",
    "WorkUnit",
    "fuse_units",
    "fused_width",
    "fused_widths",
]
