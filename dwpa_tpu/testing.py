"""Synthetic m22000 fixture builders.

Generates valid hashlines with *known* PSKs entirely from our own oracle
(dwpa_tpu/oracle/m22000.py), so tests never depend on captured data: build
an EAPOL-Key frame, derive the real MIC for the chosen PSK, then serialize
the line the way a capture converter would (format spec documented in the
reference at web/common.php:114-155).

Also used by the server tests as the source of fake submissions, mirroring
how the reference's only correctness fixture works (the hardcoded known-PSK
challenge at help_crack/help_crack.py:690-725).
"""

import hashlib
import struct

from .models import hashline as hl
from .oracle import m22000 as oracle


def _rand(seed: str, n: int) -> bytes:
    """Deterministic pseudo-random bytes (stable fixtures, no RNG state)."""
    out = b""
    i = 0
    while len(out) < n:
        out += hashlib.sha256(f"{seed}:{i}".encode()).digest()
        i += 1
    return out[:n]


def make_pmkid_line(psk: bytes, essid: bytes, seed: str = "pmkid") -> str:
    """A PMKID hashline whose PSK is ``psk``."""
    mac_ap = _rand(seed + "ap", 6)
    mac_sta = _rand(seed + "sta", 6)
    pmk = oracle.pmk_from_psk(psk, essid)
    pmkid = oracle.compute_pmkid(pmk, mac_ap, mac_sta)
    return hl.serialize(hl.TYPE_PMKID, pmkid, mac_ap, mac_sta, essid, message_pair=1)


def build_eapol_m2(key_information: int, snonce: bytes, key_data: bytes = b"") -> bytes:
    """A structurally-valid EAPOL-Key (message 2) frame with a zeroed MIC.

    Layout per IEEE 802.1X / 802.11i: version, type=3 (Key), BE length,
    descriptor type, key_information at offset 5 (where the verifier reads
    it), snonce at 17:49, zero MIC at 81:97.
    """
    body = struct.pack(
        ">BHH8s32s16s8s8s16sH",
        2,                      # descriptor type (RSN)
        key_information,
        0,                      # key length (0 in M2)
        b"\x00" * 7 + b"\x01",  # replay counter
        snonce,
        b"\x00" * 16,           # key IV
        b"\x00" * 8,            # key RSC
        b"\x00" * 8,            # key ID
        b"\x00" * 16,           # MIC (zeroed for MIC computation/storage)
        len(key_data),
    ) + key_data
    return struct.pack(">BBH", 2, 3, len(body)) + body


def make_eapol_line(
    psk: bytes,
    essid: bytes,
    keyver: int = 2,
    nc_delta: int = 0,
    endian: str = "LE",
    message_pair: int = 0x00,
    seed: str = "eapol",
    key_data: bytes = None,
) -> str:
    """An EAPOL hashline whose PSK is ``psk``.

    ``nc_delta``/``endian`` simulate a nonce-incrementing router: the MIC is
    derived from the *corrected* AP nonce while the line stores the captured
    one, so a verifier must apply +nc_delta (re-packed per ``endian``) to
    match — exercising the reference's NC search semantics
    (web/common.php:234-300).
    """
    mac_ap = _rand(seed + "ap", 6)
    mac_sta = _rand(seed + "sta", 6)
    anonce_rec = _rand(seed + "anonce", 32)
    snonce = _rand(seed + "snonce", 32)
    if key_data is None:
        key_data = _rand(seed + "rsnie", 22)

    key_information = {1: 0x0109, 2: 0x010A, 3: 0x010B}[keyver]
    eapol = build_eapol_m2(key_information, snonce, key_data)

    # The nonce the router actually used (what the MIC is computed over).
    anonce_real = anonce_rec
    if nc_delta:
        fmt = "<I" if endian == "LE" else ">I"
        last = struct.unpack_from(fmt, anonce_rec, 28)[0]
        anonce_real = anonce_rec[:28] + struct.pack(fmt, (last + nc_delta) & 0xFFFFFFFF)
        message_pair |= hl.MP_NC_NEEDED

    pmk = oracle.pmk_from_psk(psk, essid)
    if mac_ap < mac_sta:
        m = mac_ap + mac_sta
    else:
        m = mac_sta + mac_ap
    if snonce[:6] < anonce_real[:6]:
        n = snonce + anonce_real
    else:
        n = anonce_real + snonce
    mic = oracle.compute_mic(pmk, keyver, m, n, eapol)

    return hl.serialize(
        hl.TYPE_EAPOL, mic, mac_ap, mac_sta, essid, anonce_rec, eapol, message_pair
    )
