"""Synthetic m22000 fixture builders.

Generates valid hashlines with *known* PSKs entirely from our own oracle
(dwpa_tpu/oracle/m22000.py), so tests never depend on captured data: build
an EAPOL-Key frame, derive the real MIC for the chosen PSK, then serialize
the line the way a capture converter would (format spec documented in the
reference at web/common.php:114-155).

Also used by the server tests as the source of fake submissions, mirroring
how the reference's only correctness fixture works (the hardcoded known-PSK
challenge at help_crack/help_crack.py:690-725).
"""

import hashlib
import struct

from .models import hashline as hl
from .oracle import m22000 as oracle


def _rand(seed: str, n: int) -> bytes:
    """Deterministic pseudo-random bytes (stable fixtures, no RNG state)."""
    out = b""
    i = 0
    while len(out) < n:
        out += hashlib.sha256(f"{seed}:{i}".encode()).digest()
        i += 1
    return out[:n]


def make_pmkid_line(psk: bytes, essid: bytes, seed: str = "pmkid",
                    mac_ap: bytes = None, mac_sta: bytes = None) -> str:
    """A PMKID hashline whose PSK is ``psk``."""
    mac_ap = mac_ap or _rand(seed + "ap", 6)
    mac_sta = mac_sta or _rand(seed + "sta", 6)
    pmk = oracle.pmk_from_psk(psk, essid)
    pmkid = oracle.compute_pmkid(pmk, mac_ap, mac_sta)
    return hl.serialize(hl.TYPE_PMKID, pmkid, mac_ap, mac_sta, essid, message_pair=1)


def build_eapol_m2(key_information: int, snonce: bytes, key_data: bytes = b"") -> bytes:
    """A structurally-valid EAPOL-Key (message 2) frame with a zeroed MIC.

    Layout per IEEE 802.1X / 802.11i: version, type=3 (Key), BE length,
    descriptor type, key_information at offset 5 (where the verifier reads
    it), snonce at 17:49, zero MIC at 81:97.
    """
    body = struct.pack(
        ">BHH8s32s16s8s8s16sH",
        2,                      # descriptor type (RSN)
        key_information,
        0,                      # key length (0 in M2)
        b"\x00" * 7 + b"\x01",  # replay counter
        snonce,
        b"\x00" * 16,           # key IV
        b"\x00" * 8,            # key RSC
        b"\x00" * 8,            # key ID
        b"\x00" * 16,           # MIC (zeroed for MIC computation/storage)
        len(key_data),
    ) + key_data
    return struct.pack(">BBH", 2, 3, len(body)) + body


def make_eapol_line(
    psk: bytes,
    essid: bytes,
    keyver: int = 2,
    nc_delta: int = 0,
    endian: str = "LE",
    message_pair: int = 0x00,
    seed: str = "eapol",
    key_data: bytes = None,
    mac_ap: bytes = None,
    mac_sta: bytes = None,
) -> str:
    """An EAPOL hashline whose PSK is ``psk``.

    ``nc_delta``/``endian`` simulate a nonce-incrementing router: the MIC is
    derived from the *corrected* AP nonce while the line stores the captured
    one, so a verifier must apply +nc_delta (re-packed per ``endian``) to
    match — exercising the reference's NC search semantics
    (web/common.php:234-300).
    """
    mac_ap = mac_ap or _rand(seed + "ap", 6)
    mac_sta = mac_sta or _rand(seed + "sta", 6)
    anonce_rec = _rand(seed + "anonce", 32)
    snonce = _rand(seed + "snonce", 32)
    if key_data is None:
        key_data = _rand(seed + "rsnie", 22)

    key_information = {1: 0x0109, 2: 0x010A, 3: 0x010B}[keyver]
    eapol = build_eapol_m2(key_information, snonce, key_data)

    # The nonce the router actually used (what the MIC is computed over).
    anonce_real = anonce_rec
    if nc_delta:
        fmt = "<I" if endian == "LE" else ">I"
        last = struct.unpack_from(fmt, anonce_rec, 28)[0]
        anonce_real = anonce_rec[:28] + struct.pack(fmt, (last + nc_delta) & 0xFFFFFFFF)
        message_pair |= hl.MP_NC_NEEDED

    pmk = oracle.pmk_from_psk(psk, essid)
    if mac_ap < mac_sta:
        m = mac_ap + mac_sta
    else:
        m = mac_sta + mac_ap
    if snonce[:6] < anonce_real[:6]:
        n = snonce + anonce_real
    else:
        n = anonce_real + snonce
    mic = oracle.compute_mic(pmk, keyver, m, n, eapol)

    return hl.serialize(
        hl.TYPE_EAPOL, mic, mac_ap, mac_sta, essid, anonce_rec, eapol, message_pair
    )


# ---------------------------------------------------------------------------
# Synthetic captures (for testing the hcxpcapngtool-equivalent parser)
# ---------------------------------------------------------------------------


def _dot11_mgmt(subtype: int, dst: bytes, src: bytes, bssid: bytes, body: bytes):
    fc = (subtype << 4) | 0x00
    return struct.pack("<HH", fc, 0) + dst + src + bssid + struct.pack("<H", 0) + body


def _dot11_data_eapol(src: bytes, dst: bytes, bssid: bytes, eapol: bytes,
                      from_ds: bool):
    fc = 0x0008 | (0x0200 if from_ds else 0x0100)  # data frame, FromDS/ToDS
    if from_ds:
        a1, a2, a3 = dst, bssid, src
    else:
        a1, a2, a3 = bssid, src, dst
    hdr = struct.pack("<HH", fc, 0) + a1 + a2 + a3 + struct.pack("<H", 0)
    llc = b"\xaa\xaa\x03\x00\x00\x00\x88\x8e"
    return hdr + llc + eapol


def build_eapol_key_frame(key_information: int, replay: int, nonce: bytes,
                          mic: bytes = b"\x00" * 16, key_data: bytes = b"") -> bytes:
    """A full EAPOL-Key frame (802.1X header + key descriptor)."""
    body = struct.pack(
        ">BHH8s32s16s8s8s16sH",
        2, key_information, 0,
        replay.to_bytes(8, "big"), nonce,
        b"\x00" * 16, b"\x00" * 8, b"\x00" * 8, mic, len(key_data),
    ) + key_data
    return struct.pack(">BBH", 2, 3, len(body)) + body


def beacon_frame(bssid: bytes, essid: bytes) -> bytes:
    body = b"\x00" * 12 + bytes([0, len(essid)]) + essid
    return _dot11_mgmt(8, b"\xff" * 6, bssid, bssid, body)


def probe_request_frame(sta: bytes, essid: bytes) -> bytes:
    body = bytes([0, len(essid)]) + essid
    return _dot11_mgmt(4, b"\xff" * 6, sta, b"\xff" * 6, body)


def pcap_bytes(frames, linktype: int = 105, endian: str = "<",
               nsec: bool = False, times=None) -> bytes:
    """Wrap raw 802.11 frames in a classic pcap container.

    ``endian``: '<' (the common case) or '>' (big-endian writer);
    ``nsec``: use the nanosecond-resolution magic.  ``times``: per-frame
    epoch seconds (float ok; default: 1 s apart) — the knob for
    exercising the --eapoltimeout pairing gate.  Exercises every
    container variant server/capture.py accepts.
    """
    magic = 0xA1B23C4D if nsec else 0xA1B2C3D4
    res = 1e9 if nsec else 1e6
    out = struct.pack(endian + "IHHiIII", magic, 2, 4, 0, 0, 65535, linktype)
    for i, fr in enumerate(frames):
        t = (1700000000 + i) if times is None else times[i]
        sec = int(t)
        sub = round((t - sec) * res)
        out += struct.pack(endian + "IIII", sec, sub, len(fr), len(fr)) + fr
    return out


def pcapng_bytes(frames, linktype: int = 105, endian: str = "<",
                 simple: bool = False, times=None) -> bytes:
    """Wrap frames in a pcapng container (SHB + IDB + EPB/SPB blocks).

    ``times``: per-frame epoch seconds for EPBs (default 1 s apart,
    microsecond units — the pcapng default resolution); SPBs carry no
    timestamp by design."""
    def block(btype: int, body: bytes) -> bytes:
        pad = (-len(body)) % 4
        total = 12 + len(body) + pad
        return (struct.pack(endian + "II", btype, total) + body + b"\x00" * pad
                + struct.pack(endian + "I", total))

    bom = struct.pack(endian + "I", 0x1A2B3C4D)
    shb = block(0x0A0D0D0A, bom + struct.pack(endian + "HHq", 1, 0, -1))
    idb = block(0x00000001, struct.pack(endian + "HHI", linktype, 0, 65535))
    out = shb + idb
    for i, fr in enumerate(frames):
        if simple:
            out += block(0x00000003, struct.pack(endian + "I", len(fr)) + fr)
        else:
            t = (1700000000 + i) if times is None else times[i]
            units = round(t * 1e6)
            body = struct.pack(endian + "IIIII", 0, (units >> 32) & 0xFFFFFFFF,
                               units & 0xFFFFFFFF, len(fr), len(fr)) + fr
            out += block(0x00000006, body)
    return out


def radiotap_wrap(frames, rt_len: int = 8):
    """Prepend a minimal radiotap header (DLT 127) to each frame."""
    hdr = struct.pack("<BBHI", 0, 0, rt_len, 0).ljust(rt_len, b"\x00")
    return [hdr + fr for fr in frames]


def ppi_wrap(frames, ppi_len: int = 8):
    """Prepend a minimal PPI header (DLT 192) to each frame."""
    hdr = struct.pack("<BBHI", 0, 0, ppi_len, 105).ljust(ppi_len, b"\x00")
    return [hdr + fr for fr in frames]


def make_handshake_frames(psk: bytes, essid: bytes, seed: str = "cap",
                          with_pmkid: bool = True, probes=()) -> tuple:
    """Raw 802.11 frames (beacon + probes + M1 + M2) for a known PSK.

    Returns (frames, expected_hashline_count); wrap with ``pcap_bytes`` /
    ``pcapng_bytes`` / ``radiotap_wrap`` to exercise a container path.
    The M2 MIC is real (derived from the PSK via the oracle) so
    end-to-end ingest->crack tests can recover ``psk``.
    """
    mac_ap = _rand(seed + "ap", 6)
    mac_sta = _rand(seed + "sta", 6)
    anonce = _rand(seed + "anonce", 32)
    snonce = _rand(seed + "snonce", 32)
    pmk = oracle.pmk_from_psk(psk, essid)

    key_data_m1 = b""
    expected = 1
    if with_pmkid:
        pmkid = oracle.compute_pmkid(pmk, mac_ap, mac_sta)
        key_data_m1 = b"\xdd\x14\x00\x0f\xac\x04" + pmkid
        expected = 2

    m1 = build_eapol_key_frame(0x008A, 1, anonce, key_data=key_data_m1)
    m2_zero = build_eapol_key_frame(0x010A, 1, snonce, key_data=_rand(seed + "rsn", 22))
    m = min(mac_ap, mac_sta) + max(mac_ap, mac_sta)
    n = snonce + anonce if snonce[:6] < anonce[:6] else anonce + snonce
    mic = oracle.compute_mic(pmk, 2, m, n, m2_zero)
    m2 = m2_zero[:81] + mic + m2_zero[97:]

    frames = [beacon_frame(mac_ap, essid)]
    frames += [probe_request_frame(_rand(seed + "p", 6), p) for p in probes]
    frames += [
        _dot11_data_eapol(mac_ap, mac_sta, mac_ap, m1, from_ds=True),
        _dot11_data_eapol(mac_sta, mac_ap, mac_ap, m2, from_ds=False),
    ]
    return frames, expected


def make_handshake_capture(psk: bytes, essid: bytes, seed: str = "cap",
                           with_pmkid: bool = True, probes=()) -> tuple:
    """``make_handshake_frames`` in a classic LE pcap container."""
    frames, expected = make_handshake_frames(
        psk, essid, seed=seed, with_pmkid=with_pmkid, probes=probes
    )
    return pcap_bytes(frames), expected
