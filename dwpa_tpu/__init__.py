"""dwpa_tpu — a TPU-native distributed WPA-PSK audit framework.

A from-scratch reimplementation of the capabilities of `dwpa`
(reference: DarioAlejandroW/dwpa), replacing the hashcat/John GPU compute
path with JAX/XLA kernels designed for TPU hardware:

- ``dwpa_tpu.ops``      — uint32-lane crypto primitives (SHA-1, MD5,
  SHA-256, AES-128-CMAC, HMAC, PBKDF2) written as batched JAX ops.
- ``dwpa_tpu.models``   — hash-mode engines; ``m22000`` (WPA PMKID/EAPOL)
  is the flagship: PBKDF2->PMK -> PMKID-HMAC / PRF+MIC verification with
  nonce-error-correction, one jitted step over a candidate batch.
- ``dwpa_tpu.parallel`` — device-mesh data-parallel sharding of the
  candidate axis (jax.sharding / shard_map).
- ``dwpa_tpu.oracle``   — pure-Python (hashlib) oracle with the exact
  semantics of the reference server verifier (web/common.php:157-307),
  used for differential tests and host-side wide-NC re-verification.
- ``dwpa_tpu.rules``    — hashcat-rule-subset candidate mangler.
- ``dwpa_tpu.gen``      — candidate generators (dict streams, masks,
  IMEI/PSK pattern generators).
- ``dwpa_tpu.client``   — dwpa get_work/put_work protocol client.
- ``dwpa_tpu.server``   — work server (scheduler, ingestion, verification,
  maintenance) re-implemented on sqlite.
"""

__version__ = "0.1.0"
