"""Device-stream executor: N independent single-device crack streams.

Lockstep SPMD (``parallel/step.py``) runs the whole mesh as ONE
program: every batch splits 1/ndev per device, a global ``psum``
hits-gate barriers every step, and a single consumer thread feeds the
whole mesh — so one slow device (or a starved feed) stalls all of
them.  Once per-device compute is saturated, independent per-device
work streams beat global lockstep (hashcat's multi-GPU model, and the
reference dwpa's own per-client work units): each stream here owns one
device outright, crunches WHOLE feed blocks, gates on its own scalar
hit count (over a 1-device mesh the reduction is a plain ``jnp.sum`` —
no cross-device collective exists anywhere in a stream's dispatch),
stages prepare-ahead exactly like the double-buffered ``DeviceStager``
(async H2D + async dispatch overlap the previous block's device time),
and pulls prepacked blocks from a shared work queue — so a straggler
only slows its own stream and the feed fans out across
``default_feed_workers()`` producers instead of starving behind one.

Resume framing is unchanged: blocks keep their global
``frame_blocks`` offsets, and completed blocks are demuxed and
reported strictly in stream (sequence) order — the same per-unit demux
``sched/executor.py`` does — so the client's skip-by-count checkpoint
sees exactly the sequence the lockstep path would produce.

Failure containment mirrors the fused executor's excluded-style retry:
a stream that raises mid-block requeues its unfinished blocks with
itself excluded, another stream picks them up, and a block that fails
on every stream (or past ``max_attempts``) surfaces as a
``StreamError`` carrying the block's global offset.  No orphan
threads: workers exit only when the queue is closed, drained, and
nothing is in flight.

The lockstep ``shard_map`` path remains the multi-host fallback: with
``jax.process_count() > 1`` a global gate is genuinely needed (every
host must agree a batch is done), so ``streams_default()`` enables
streams only on single-process multi-device topologies — the v5e-8
case, and the forced-8-CPU-device test mesh.

Discipline (lint rule DW110, scoped to this file): no cross-device
collectives, no blocking fetch inside the per-stream dispatch loop
(the only sync is the engine's own hits-gate inside ``_collect``), and
any ``jax.device_put`` must carry an explicit device/sharding.
"""

import collections
import contextlib
import threading
import time

#: Returned by a non-blocking queue probe: nothing takeable right now,
#: but more may arrive — the stream should drain its own pipeline and
#: retry instead of parking while it still holds unfinished blocks.
_STALL = object()


def streams_default() -> bool:
    """True when device streams should replace lockstep dispatch: a
    single-process topology with more than one local device."""
    import jax

    return jax.process_count() == 1 and jax.local_device_count() > 1


def default_feed_workers() -> int:
    """Default candidate-feed producer count: one per local device, so
    an N-stream mesh doesn't starve behind a single producer (the
    ``--feed-workers`` flag overrides)."""
    import jax

    return max(1, jax.local_device_count())


def device_label(device) -> str:
    """Stable ``platform:id`` metric label for one device."""
    return f"{getattr(device, 'platform', 'dev')}:{getattr(device, 'id', 0)}"


class StreamError(RuntimeError):
    """A block failed on every eligible stream (or past the retry
    budget); ``offset`` is the block's global candidate offset."""

    def __init__(self, offset: int, cause: BaseException):
        super().__init__(
            f"stream block at offset {offset} failed: {cause!r}")
        self.offset = offset
        self.cause = cause


class _Item:
    """One queued block plus its retry state (``excluded`` mirrors the
    fused executor's requeue contract: streams that already failed this
    block don't get it back)."""

    __slots__ = ("seq", "block", "excluded", "attempts")

    def __init__(self, seq, block):
        self.seq = seq
        self.block = block
        self.excluded = frozenset()
        self.attempts = 0


class _WorkQueue:
    """Bounded shared block queue with excluded-stream routing.

    ``get`` returns the oldest item the calling stream may take, or
    None exactly when no such item can ever arrive: the queue is
    closed AND (it is empty with nothing in flight, or every remaining
    item excludes this stream while nothing is in flight that could be
    requeued its way).  Waiting while anything is in flight is what
    makes crash requeue orphan-free — an idle stream stays parked until
    the crashing stream's blocks come back to the queue.
    """

    def __init__(self, maxsize: int):
        self._dq = collections.deque()
        self._cond = threading.Condition()
        self._maxsize = max(1, int(maxsize))
        self._open = True
        self._inflight = 0

    def put(self, item, requeue: bool = False):
        with self._cond:
            if requeue:
                self._inflight -= 1
            else:
                while self._open and len(self._dq) >= self._maxsize:
                    self._cond.wait()
            if not self._open and not requeue:
                return  # aborted mid-feed: drop instead of growing a dead queue
            self._dq.append(item)
            self._cond.notify_all()

    def get(self, stream_index: int, block: bool = True):
        """Oldest item this stream may take; ``None`` when no such item
        can ever arrive; ``_STALL`` (non-blocking mode only) when
        nothing is takeable right now.  A stream must only call with
        ``block=True`` while it holds NO unfinished blocks of its own —
        parked streams hold zero inflight, so a positive count always
        belongs to an active stream that will resolve, requeue or
        abort, and the wait can't cycle."""
        with self._cond:
            while True:
                for i, item in enumerate(self._dq):
                    if stream_index not in item.excluded:
                        del self._dq[i]
                        self._inflight += 1
                        self._cond.notify_all()
                        return item
                done = not self._open and self._inflight == 0
                if done and (not self._dq or all(
                        stream_index in it.excluded for it in self._dq)):
                    return None
                if not block:
                    return _STALL
                self._cond.wait()

    def resolve(self):
        """An item handed out by ``get`` reached a final state."""
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()

    def close(self):
        with self._cond:
            self._open = False
            self._cond.notify_all()

    def abort(self):
        with self._cond:
            self._dq.clear()
            self._open = False
            self._cond.notify_all()

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._dq)


class DeviceStream:
    """One crack stream pinned to one device.

    Wraps a single-device engine (a 1-device mesh — ``shard_candidates``
    over it is an explicit ``jax.device_put`` onto exactly this device)
    plus the stream's telemetry: ``dwpa_stream_blocks_total`` /
    ``dwpa_stream_busy_fraction`` / ``dwpa_stream_queue_depth``, all
    labeled ``device=platform:id``, and ``stream:dispatch`` /
    ``stream:collect`` spans.
    """

    def __init__(self, index, device, engine, registry=None, tracer=None):
        self.index = index
        self.device = device
        self.engine = engine
        self.tracer = tracer
        self.label = device_label(device)
        self.wait_s = 0.0        # time blocked on the shared queue
        self.blocks_done = 0
        self.inflight = collections.deque()   # _Items fed, FIFO
        self.prune = collections.deque()      # cross-stream found removals
        if registry is not None:
            lbl = {"device": self.label}
            self._m_blocks = registry.counter(
                "dwpa_stream_blocks_total",
                "Feed blocks completed per device stream").labels(**lbl)
            self._m_busy = registry.gauge(
                "dwpa_stream_busy_fraction",
                "Per-stream fraction of wall time spent in "
                "prepare/dispatch/collect (1 - shared-queue wait)"
            ).labels(**lbl)
            self._m_qdepth = registry.gauge(
                "dwpa_stream_queue_depth",
                "Shared work-queue depth at this stream's last pull"
            ).labels(**lbl)
        else:
            self._m_blocks = self._m_busy = self._m_qdepth = None

    def _span(self, name):
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name)

    def run_blocks(self, next_item, on_result=None) -> list:
        """Crack framed blocks pulled from ``next_item`` on this
        stream's device.

        The single-device body of ``M22000Engine.crack_blocks``: the
        same prepare-ahead staging (``_prepare_block`` starts the
        async H2D copy, ``_dispatch`` launches compute without
        waiting, so block N+1's host work overlaps block N's device
        time) and the same ``PIPELINE_DEPTH`` dispatch/sync window,
        but the hits gate is this device's own scalar (a 1-device mesh
        reduces it without any collective) and every completed block
        is reported through ``on_result(block, founds)`` so a demux
        above can reassemble global stream order.

        ``next_item(block_ok)`` returns the next framed block, ``None``
        when the feed is exhausted, or ``_STALL`` (only when
        ``block_ok`` is false) when nothing is takeable yet.  The loop
        passes ``block_ok=True`` only once its pipeline is empty —
        never parking on the shared queue while it holds unfinished
        blocks, which is what keeps the executor's inflight accounting
        deadlock-free.  Dispatch is async; the only device sync is the
        engine's hits-gate fetch inside ``_collect`` — which is also
        what stops the ``stream:collect`` span's clock, satisfying the
        device-sync rule.  Returns the stream's Found list.
        """
        eng = self.engine
        pending = collections.deque()  # (block, dispatched | None)
        founds = []
        t_run = time.perf_counter()

        def finish_one():
            block, disp = pending.popleft()
            if disp is None:
                new = []
            else:
                with self._span("stream:collect"):
                    # the hits-gate fetch inside _collect is the sync
                    new = eng._collect(disp)
            founds.extend(new)
            self.blocks_done += 1
            if self._m_blocks is not None:
                self._m_blocks.inc()
                wall = time.perf_counter() - t_run
                if wall > 0:
                    self._m_busy.set(max(0.0, 1.0 - self.wait_s / wall))
            if on_result is not None:
                on_result(block, new)

        while True:
            block = next_item(not pending)
            if block is _STALL:
                finish_one()   # use the queue gap to sync our oldest
                continue
            if block is None:
                break
            if eng.groups:
                prep = eng._prepare_block(block)   # async H2D
                with self._span("stream:dispatch"):
                    disp = eng._dispatch(prep)     # async compute
            else:
                disp = None                        # all nets cracked: skip
            pending.append((block, disp))
            if len(pending) > eng.PIPELINE_DEPTH:
                finish_one()
        while pending:
            finish_one()
        return founds


class StreamExecutor:
    """Fan framed blocks out over independent per-device streams.

    ``engine_factory(device)`` builds each stream's single-device
    engine; every engine must be constructed from the SAME hashline
    objects so a find on one stream prunes the same net on every other
    (``M22000Engine.remove`` matches by line identity).  ``run`` feeds
    the shared queue, demuxes per-block results back into global
    sequence order, dedups founds across streams (first block wins,
    exactly like the lockstep live-set), and lazily prunes cracked nets
    from every stream's engine at that stream's next block boundary —
    the prune is advisory (a racing stream may still compute a cracked
    net's batch) but the ordered dedup keeps the reported found list
    identical to lockstep's.
    """

    def __init__(self, engine_factory, devices, registry=None, tracer=None,
                 queue_depth=None, max_attempts: int = 2):
        devices = list(devices)
        if not devices:
            raise ValueError("StreamExecutor needs at least one device")
        self.max_attempts = int(max_attempts)
        self.streams = [
            DeviceStream(i, d, engine_factory(d), registry=registry,
                         tracer=tracer)
            for i, d in enumerate(devices)
        ]
        self._q = _WorkQueue(queue_depth or 2 * len(self.streams))
        self._cond = threading.Condition()
        self._results = {}          # seq -> (block, founds, stream index)
        self._alive = set(range(len(self.streams)))
        self._fault = None
        self._total = None          # block count, set once the feed ends
        self._stop = False          # emitter saw every net cracked
        self._dead = set()          # id(line) of nets already reported
        nets = self.streams[0].engine.nets
        self._nlines = len({id(n.line) for n in nets})
        self.block_streams = []     # seq-ordered winning stream index

    # -- feeder --------------------------------------------------------------

    def _feed(self, blocks):
        try:
            seq = 0
            for block in blocks:
                if self._stop or self._fault is not None:
                    break
                self._q.put(_Item(seq, block))
                seq += 1
            with self._cond:
                self._total = seq
                self._cond.notify_all()
            self._q.close()
        except BaseException as e:   # surfaced to the caller (FeedError &co)
            self._abort(e)

    # -- stream workers ------------------------------------------------------

    def _pull(self, st, block_ok):
        """One stream's ``next_item``: pull from the shared queue,
        applying pending cross-stream prunes at block boundaries (the
        stream's own thread — never racing its dispatch).  Blocks only
        when ``block_ok`` (the stream's pipeline is empty), else
        returns ``_STALL`` so the stream drains instead of parking."""
        while st.prune:
            st.engine.remove(st.prune.popleft())
        t0 = time.perf_counter()
        item = self._q.get(st.index, block=block_ok)
        st.wait_s += time.perf_counter() - t0
        if st._m_qdepth is not None:
            st._m_qdepth.set(self._q.depth)
        if item is None or item is _STALL:
            return item
        st.inflight.append(item)
        return item.block

    def _record(self, st, block, founds):
        item = st.inflight.popleft()
        with self._cond:
            self._results[item.seq] = (item.block, founds, st.index)
            self._cond.notify_all()
        self._q.resolve()

    def _work(self, st):
        try:
            st.run_blocks(lambda ok: self._pull(st, ok),
                          on_result=lambda b, f: self._record(st, b, f))
        except BaseException as e:
            self._stream_failed(st, e)

    def _stream_failed(self, st, err):
        """Excluded-style retry (sched/executor.py's requeue contract):
        the dead stream's unfinished blocks go back to the queue with
        this stream excluded; a block out of eligible streams or past
        ``max_attempts`` aborts the run with a ``StreamError``."""
        with self._cond:
            self._alive.discard(st.index)
            alive = set(self._alive)
        fatal = None
        while st.inflight:
            item = st.inflight.popleft()
            item.attempts += 1
            item.excluded = item.excluded | {st.index}
            ok = (item.attempts <= self.max_attempts
                  and bool(alive - item.excluded))
            if fatal is None and ok:
                self._q.put(item, requeue=True)
            else:
                # Resolve even the unretryable blocks so the queue's
                # inflight count drains to zero and surviving workers
                # wake up (to observe the abort) instead of parking.
                if fatal is None:
                    fatal = StreamError(item.block.offset, err)
                self._q.resolve()
        if fatal is not None:
            self._abort(fatal)
        elif not alive:
            self._abort(StreamError(-1, err))

    def _abort(self, err):
        with self._cond:
            if self._fault is None:
                self._fault = err
            self._cond.notify_all()
        self._q.abort()

    # -- ordered demux -------------------------------------------------------

    def run(self, blocks, on_batch=None) -> list:
        """Drain ``blocks`` across every stream; returns the merged
        Found list.  ``on_batch(consumed, founds)`` fires once per
        block in global sequence order — the ``crack_blocks`` resume
        contract, so checkpoints written from it are identical to the
        lockstep path's."""
        feeder = threading.Thread(target=self._feed, args=(iter(blocks),),
                                  name="stream-feeder", daemon=True)
        workers = [threading.Thread(target=self._work, args=(st,),
                                    name=f"stream-{st.label}", daemon=True)
                   for st in self.streams]
        feeder.start()
        for w in workers:
            w.start()
        all_founds = []
        next_seq = 0
        fault = None
        while True:
            with self._cond:
                while True:
                    if self._fault is not None:
                        fault = self._fault
                        break
                    if next_seq in self._results:
                        break
                    if self._total is not None and next_seq >= self._total:
                        break
                    self._cond.wait()
                if fault is not None:
                    break
                if next_seq not in self._results:
                    break  # every block emitted
                block, founds, si = self._results.pop(next_seq)
            kept = []
            for f in founds:
                if id(f.line) in self._dead:
                    continue  # an earlier block already cracked this net
                self._dead.add(id(f.line))
                kept.append(f)
                for st in self.streams:
                    st.prune.append(f)
            self.block_streams.append(si)
            all_founds.extend(kept)
            if on_batch is not None:
                on_batch(block.count, kept)
            next_seq += 1
            if len(self._dead) >= self._nlines and not self._stop:
                # every net cracked: stop feeding, drain what's queued
                # (queued blocks still report their counts, as skips)
                self._stop = True
        if fault is not None:
            self._q.abort()
        feeder.join(timeout=10)
        for w in workers:
            w.join(timeout=10)
        if fault is not None:
            raise fault
        return all_founds
