"""Mesh construction and candidate-batch sharding.

The reference system's parallelism is volunteer data-parallelism over the
candidate keyspace (SURVEY.md §2.10: independent clients, dictionary
shards, coverage matrix).  On a TPU pod slice the same axis — candidates —
is the natural shard dimension: PBKDF2 is embarrassingly parallel per
candidate, so the hot loop needs *zero* cross-device traffic and only the
tiny found-flags tensor is ever reduced over ICI (psum in parallel/step.py).

One 1-D mesh axis ("dp") is therefore the whole story intra-pod; scaling
further mirrors the reference's WAN layer (many independent clients each
owning a pod slice), not a second mesh axis.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"


def default_mesh(devices=None, n: int = None) -> Mesh:
    """A 1-D data-parallel mesh over ``devices`` (default: all present)."""
    if devices is None:
        devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return Mesh(np.asarray(devices), (DP_AXIS,))


def shard_candidates(mesh: Mesh, pw_words):
    """Place a packed [B, 16] candidate batch with B split over the mesh."""
    return jax.device_put(pw_words, NamedSharding(mesh, P(DP_AXIS, None)))
