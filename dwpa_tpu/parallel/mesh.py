"""Mesh construction and candidate-batch sharding.

The reference system's parallelism is volunteer data-parallelism over the
candidate keyspace (SURVEY.md §2.10: independent clients, dictionary
shards, coverage matrix).  On a TPU pod slice the same axis — candidates —
is the natural shard dimension: PBKDF2 is embarrassingly parallel per
candidate, so the hot loop needs *zero* cross-device traffic and only the
tiny found-flags tensor is ever reduced over ICI (psum in parallel/step.py).

One 1-D mesh axis ("dp") is therefore the whole story intra-pod; scaling
further mirrors the reference's WAN layer (many independent clients each
owning a pod slice), not a second mesh axis.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"


def default_mesh(devices=None, n: int = None) -> Mesh:
    """A 1-D data-parallel mesh over ``devices`` (default: all present)."""
    if devices is None:
        devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return Mesh(np.asarray(devices), (DP_AXIS,))


def _shard_batch_axis(mesh: Mesh, x, spec: P):
    """Place ``x`` with its leading axis split over the dp mesh axis.

    Single-process: ``x`` is the whole batch, placed under the sharding.
    Multi-process (a ``multihost_mesh`` spanning hosts): ``x`` is this
    host's *local* shard, assembled into the global array with
    ``jax.make_array_from_process_local_data`` — device_put cannot
    express "local slice of a global array" across non-addressable
    devices.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))
    return jax.device_put(x, sharding)


def shard_candidates(mesh: Mesh, pw_words):
    """Place a packed [B, 16] candidate batch with B split over the mesh
    (see ``_shard_batch_axis`` for the single-/multi-process contract)."""
    return _shard_batch_axis(mesh, pw_words, P(DP_AXIS, None))


def shard_vector(mesh: Mesh, v):
    """The [B]-shaped companion of ``shard_candidates`` (e.g. word
    lengths), same contract."""
    return _shard_batch_axis(mesh, v, P(DP_AXIS))


def multihost_mesh(coordinator: str = None, num_processes: int = None,
                   process_id: int = None, auto_init: bool = False) -> Mesh:
    """A 1-D dp mesh spanning every chip of a multi-host slice.

    The distributed backend analog of the reference's NCCL/MPI role
    (SURVEY.md §5.8): ``jax.distributed.initialize`` wires the hosts
    (args default to the TPU environment's auto-detection), and the mesh
    covers ``jax.devices()`` — the *global* device list — so the same
    shard_map crack step scales from one chip to a full slice unchanged.
    Because the candidate axis is the only sharded axis and the hot loop
    is traffic-free, the lone collective (the psum hits-gate) rides ICI
    intra-host and DCN across hosts; its payload is one scalar per batch,
    so DCN latency is irrelevant to throughput.

    Each host feeds its local shard via ``shard_candidates`` (which
    assembles host-local slices into the global array with
    ``jax.make_array_from_process_local_data``); work-unit distribution
    stays on the reference's HTTP/JSON WAN protocol — a multi-host slice
    is simply one very large volunteer.
    """
    kw = {}
    if coordinator is not None:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    # ``auto_init``: join with zero args, letting jax auto-detect the
    # cluster from the managed environment (TPU pod slices) — the one
    # blessed slice-join path for callers with no explicit topology
    # (client CLI --multihost).  Either way the init must run before
    # anything touches the XLA backend (even jax.process_count() would
    # initialise it), hence the check against the distributed-service
    # state rather than device APIs.
    if (auto_init or kw) and not _distributed_initialized():
        try:
            # Multi-process computations on the CPU backend need an
            # explicit collectives implementation on the jax 0.4/0.5
            # line (later versions default to gloo); harmless on TPU,
            # where collectives ride ICI/DCN regardless.  Must be set
            # before the backend initializes, i.e. exactly here.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # flag retired upstream
            pass
        jax.distributed.initialize(**kw)
    return Mesh(np.asarray(jax.devices()), (DP_AXIS,))


def _distributed_initialized() -> bool:
    """jax.distributed.is_initialized arrived after the 0.4 line; fall
    back to the distributed-service client state it reads (still no
    device APIs — touching those would initialise the XLA backend and
    break the init-ordering contract above)."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    from jax._src.distributed import global_state

    return global_state.client is not None
