"""The sharded full crack step: PBKDF2 -> verify, shard_map'd over the mesh.

``build_crack_step`` returns one callable that runs the complete pipeline
for a candidate batch:

- the [B, 16] packed-password batch is split over the "dp" mesh axis;
- each device runs PBKDF2(4096) + every net's MIC/PMKID check on its local
  candidate shard — no communication at all in the hot loop;
- the only collective is a ``psum`` of the scalar hit count over ICI, used
  by the host as a cheap "anything found?" gate before it pulls the
  (dp-sharded) per-net match matrix back for the rare positives.

Compilation strategy (the part that matters operationally): a reference
work unit is one ESSID group (all nets sharing the target's SSID,
web/content/get_work.php:96-109), so a design that bakes the group's
constants into the trace pays a full XLA compile (~tens of seconds on
TPU) for every new work unit.  Here nothing net-specific is baked:

- the PBKDF2 step takes the ESSID salt blocks as *data* — one compile
  per batch size serves every ESSID ever cracked;
- the verify steps take the nets' constants as stacked arrays and
  ``vmap`` over the net axis, cached per shape signature
  ``(kind, keyver, V variants, E eapol blocks)`` with the net count
  padded up to a power-of-two bucket — a handful of compilations for a
  server's whole lifetime, all shared across groups, engines and work
  units.

This is the TPU mapping of the reference's work distribution (volunteer
data parallelism + ESSID-amortized PBKDF2) described in SURVEY.md §5.7.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import m22000 as m
from .mesh import DP_AXIS

# jax >= 0.6 exposes shard_map at the top level with the replication check
# spelled ``check_vma``; on the 0.4/0.5 line it lives in jax.experimental
# and the same knob is ``check_rep``.  Resolve once at import so every
# step builder below is version-agnostic.
if hasattr(jax, "shard_map"):
    _shard_map, _CHECK_KW = jax.shard_map, "check_vma"
else:  # pragma: no cover - exercised only on older jax installs
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

#: (mesh, kind, *static) -> jitted sharded step, shared process-wide.
_STEP_CACHE = {}


def _shard(mesh, fn, in_specs, out_specs):
    # check_vma/check_rep=False: the rolled compressions seed their fori_loop
    # carries from unsharded per-net constants, which fails JAX's
    # varying-manual-axes check even though every carry is elementwise over
    # the dp-sharded batch (each device runs the identical replicated
    # constants against its own candidate shard, so replication is trivially
    # consistent).
    return jax.jit(
        _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **{_CHECK_KW: False}
        )
    )


def pmk_step(mesh):
    """jitted ``(pw_words[B,16], salt1[16], salt2[16]) -> pmk uint32[8, B]``.

    Salts are data, so one compile per batch size serves every ESSID.
    """
    key = (mesh, "pmk")
    if key not in _STEP_CACHE:
        use_pallas = all(d.platform == "tpu" for d in mesh.devices.flat)

        def local(pw_words, s1, s2):
            return m._pmk_impl(pw_words, s1, s2, use_pallas=use_pallas)

        _STEP_CACHE[key] = _shard(
            mesh, local, (P(DP_AXIS, None), P(), P()), P(None, DP_AXIS)
        )
    return _STEP_CACHE[key]


def fused_pmk_step(mesh):
    """jitted ``(pw_words[B,16], unit_id[B], table1[U,16], table2[U,16])
    -> pmk uint32[8, B]`` — the mixed-ESSID fused PBKDF2 step.

    Each lane gathers its OWN salt blocks from the replicated per-unit
    tables (``table[unit_id]``, a device-side [b, 16] gather on the
    local shard) and the per-lane-salt PBKDF2 kernel runs unchanged —
    the H2D cost of mixing ESSIDs in one batch is 4 bytes/lane of
    ``unit_id``, not 128 bytes/lane of salt blocks.  Everything is
    data: one compile serves every unit combination ever fused, keyed
    only on the (bounded) lane-width/table-shape signature — callers
    pad ``B`` to the static fused-width table (``sched.fuse``, lint
    rule DW109) and ``U`` to the fixed ``fuse_max_units`` bucket
    (repeat row 0), so the jit cache stays a handful of entries.
    """
    key = (mesh, "pmk_fused")
    if key not in _STEP_CACHE:
        use_pallas = all(d.platform == "tpu" for d in mesh.devices.flat)

        def local(pw_words, unit_id, t1, t2):
            return m._pmk_impl(pw_words, t1[unit_id], t2[unit_id],
                               use_pallas=use_pallas)

        _STEP_CACHE[key] = _shard(
            mesh, local,
            (P(DP_AXIS, None), P(DP_AXIS), P(), P()),
            P(None, DP_AXIS),
        )
    return _STEP_CACHE[key]


def _gate(found, mask):
    """found bool[N, V, b], mask bool[N] -> replicated exact hit count.

    The mask (data, so it never retriggers a trace) zeroes the bucket-pad
    rows out of both the count and the returned matrix, keeping ``hits``
    an exact match count and pad rows all-False for consumers.
    """
    found = found & mask[:, None, None]
    return jax.lax.psum(jnp.sum(found, dtype=jnp.int32), DP_AXIS), found


# One descriptor per verify code path — the single place that ties
# together (a) the static trace parameters extracted from a net, (b) the
# PreppedNet fields shipped to the device, and (c) the per-net match
# function.  _partition, build_crack_step and verify_step all read this
# table, so a new keyver is one new row, not three hand-synced switches.
# Each match fn: (pmk[8,b], static tuple, *per-net consts) -> bool[V, b].
_KINDS = {
    "pmkid": (
        lambda net: (),
        ("pmkid_block", "target"),
        lambda pmk, st, blk, tgt: m._pmkid_impl(pmk, blk, tgt)[None],
    ),
    "eapol": (
        lambda net: (net.keyver,),
        ("prf_blocks", "eapol_blocks", "target"),
        lambda pmk, st, prf, eap, tgt: m.eapol_match(
            pmk, prf, eap, tgt, keyver=st[0]
        ),
    ),
    "cmac": (
        lambda net: (bool(net.cmac_last_complete),),
        ("prf_blocks", "cmac_full", "cmac_last", "cmac_target"),
        lambda pmk, st, prf, full, last, tgt: m.eapol_cmac_match(
            pmk, prf, full, last, tgt, last_complete=st[0]
        ),
    ),
}


def _kind_of(net) -> str:
    if net.keyver == 100:
        return "pmkid"
    return "cmac" if net.keyver == 3 else "eapol"


def verify_step(mesh, kind, static):
    """jitted ``(pmk[8,B], mask[N], *stacked consts) -> (hits, found[N,V,B])``.

    ``kind``/``static`` select the code path; array shapes (net-count
    bucket, variant count, EAPOL blocks, batch) key jit's own cache.
    """
    key = (mesh, kind, static)
    if key not in _STEP_CACHE:
        _, fields, match = _KINDS[kind]

        def local(pmk, mask, *consts):
            fnd = jax.vmap(lambda *cs: match(pmk, static, *cs))(*consts)
            return _gate(fnd, mask)

        _STEP_CACHE[key] = _shard(
            mesh,
            local,
            (P(None, DP_AXIS), P()) + (P(),) * len(fields),
            (P(), P(None, None, DP_AXIS)),
        )
    return _STEP_CACHE[key]


def _bucket(n: int) -> int:
    """Pad net counts to powers of two so jit's shape cache hits across
    groups of nearby sizes."""
    b = 1
    while b < n:
        b *= 2
    return b


def _pad_nets(arrs):
    """Stack per-net arrays and pad the net axis to its bucket by
    repeating the last row.  Pad rows are dead weight whose hits the
    verify step's mask (see ``_gate``) excludes from both the count and
    the matrix; callers additionally slice found[:n]."""
    stacked = np.stack(arrs)
    pad = _bucket(len(arrs)) - len(arrs)
    if pad:
        stacked = np.concatenate([stacked, np.repeat(stacked[-1:], pad, axis=0)])
    return stacked


def _partition(nets):
    """Group net indices by verify-step signature (kind, static params,
    device-const shapes — everything that keys a distinct compilation)."""
    parts = {}
    for i, net in enumerate(nets):
        kind = _kind_of(net)
        statics, fields, _ = _KINDS[kind]
        sig = (kind, statics(net),
               tuple(getattr(net, f).shape for f in fields))
        parts.setdefault(sig, []).append(i)
    return parts


def _assemble_step(mesh, struct, v_max, inv):
    """jitted ``(*found parts) -> found[N, v_max, B]``: slice off bucket
    padding, zero-pad variant axes, concatenate, restore input order.
    Cached per part structure so the whole assembly stays one fused XLA
    program instead of a chain of eager device ops per batch."""
    key = (mesh, "asm", struct, v_max, None if inv is None else tuple(inv))
    if key not in _STEP_CACHE:

        def assemble(*fnds):
            rows = []
            for fnd, (n, v) in zip(fnds, struct):
                fnd = fnd[:n]
                if v < v_max:
                    fnd = jnp.pad(fnd, ((0, 0), (0, v_max - v), (0, 0)))
                rows.append(fnd)
            found = rows[0] if len(rows) == 1 else jnp.concatenate(rows)
            return found if inv is None else found[np.asarray(inv)]

        _STEP_CACHE[key] = jax.jit(assemble)
    return _STEP_CACHE[key]


def mix_step(mesh):
    """jitted ``(pmk_miss[8, Mb], cached[8, B], idx[B]) -> pmk uint32[8, B]``.

    The PMK-store mixed-block assembly: ``pmk_miss`` is the PBKDF2 output
    of the compacted miss sub-batch, ``cached`` the host-built matrix
    with cache-hit PMKs at their batch columns, and ``idx`` the gather
    map over ``concat([pmk_miss, cached], axis=1)`` (misses read their
    computed slot, hits and padding read ``cached`` at their own column
    — ``pmkstore.stage.split_block`` builds it).  ``idx`` is data, never
    a trace constant; one jit object per mesh, so XLA recompiles only
    per ``(Mb, B)`` shape pair — and the miss widths are bucketed
    (``pmkstore.stage.miss_widths``, <= 3 values) precisely so that
    count stays bounded however the hit ratio wanders.
    """
    key = (mesh, "mix")
    if key not in _STEP_CACHE:
        _STEP_CACHE[key] = jax.jit(
            lambda pm, cached, idx: jnp.concatenate(
                [pm, cached], axis=1)[:, idx],
            out_shardings=NamedSharding(mesh, P(None, DP_AXIS)),
        )
    return _STEP_CACHE[key]


#: Rules per fused dispatch (build_rules_step).  Fixed so the step's jit
#: signature is independent of the ruleset size: a 134-line set runs in
#: ceil(134/8) dispatches, the last padded with noop rules (<= 1 chunk
#: of waste per base batch) — vs a multi-second XLA compile per distinct
#: ruleset size.
RULES_CHUNK = 8


def build_rules_step(mesh, nets, salt1, salt2):
    """The fused rules crack step: expand + PBKDF2 + verify, one dispatch.

    Returns ``step(base[B,16], lens[B], steps[RULES_CHUNK,S,3]) ->
    (hits, foundbits[R, B/32])``: each rule of the chunk mangles the
    base batch ON DEVICE (rules/device.expand_traced) and feeds PBKDF2
    + every net's verify, with ONE psum'd hit scalar gating the whole
    chunk.  Fusion is what makes a rules attack sustain the dict rate
    through the axon tunnel: separate expansion/crack dispatches cost
    ~0.1 s fixed each, and hashcat's GPU rule engine exists for exactly
    this reason — mangling must live in the kernel, not on the feed
    path.

    The find output is a BIT-PACKED any-net-matched mask (uint32, bit b
    of word b>>5 = column b) rather than the [N, V, B] matrix + PMKs:
    through the tunnel a chunk's dense matrices are tens of MB (~7 s)
    while the bitmask is B/8 bytes (~32 KB).  The engine re-derives
    (net, NC, endian, PMK) for the rare hit columns with the host
    oracle — the executable spec — so no information is lost.

    Like build_crack_step, nothing group-specific is compiled: salts
    and rule programs are data; the jit cache keys on (batch, step
    bucket, net-part signatures) only.
    """
    from ..rules.device import _get_branches, expand_traced

    _get_branches()  # op table must exist before any trace

    repl = NamedSharding(mesh, P())
    s1 = jax.device_put(np.asarray(salt1), repl)
    s2 = jax.device_put(np.asarray(salt2), repl)
    use_pallas = all(d.platform == "tpu" for d in mesh.devices.flat)

    parts = []
    for sig, idxs in _partition(nets).items():
        kind, static = sig[0], sig[1]
        _, fields, match = _KINDS[kind]
        group = [nets[i] for i in idxs]
        mask = np.zeros(_bucket(len(group)), dtype=bool)
        mask[: len(group)] = True
        consts = (mask,) + tuple(
            _pad_nets([getattr(g, f) for g in group]) for f in fields
        )
        consts = tuple(jax.device_put(c, repl) for c in consts)
        parts.append((kind, static, match, consts))

    key = (mesh, "rules_step", use_pallas,
           tuple((p[0], p[1]) for p in parts),
           tuple(tuple(c.shape for c in p[3]) for p in parts))
    if key not in _STEP_CACHE:
        # The cached closure must NOT capture ``parts``: its const
        # arrays are the first-built group's replicated device buffers,
        # and the cache entry outlives that group (verify_step has the
        # same contract).  Capture only code + arity metadata; consts
        # arrive per call via *flat_consts.
        meta = tuple((p[0], p[1], p[2], 1 + len(_KINDS[p[0]][1]))
                     for p in parts)

        def local(base, lens, steps, s1, s2, *flat_consts):
            # reassemble the per-part const tuples from the flat arg list
            it = iter(flat_consts)
            pcs = [tuple(next(it) for _ in range(nc)) for *_m, nc in meta]

            def one_rule(_carry, rsteps):
                pw = expand_traced(base, lens, rsteps)
                pmk = m._pmk_impl(pw, s1, s2, use_pallas=use_pallas)
                hits_l = jnp.int32(0)
                any_l = None
                for (kind, static, match, _nc), consts in zip(meta, pcs):
                    mask = consts[0]
                    fnd = jax.vmap(lambda *cs: match(pmk, static, *cs))(
                        *consts[1:]
                    )
                    fnd = fnd & mask[:, None, None]
                    hits_l = hits_l + jnp.sum(fnd, dtype=jnp.int32)
                    a = fnd.any(axis=(0, 1))  # [b]
                    any_l = a if any_l is None else (any_l | a)
                pad = (-any_l.shape[0]) % 32  # static: local batch shard
                if pad:
                    any_l = jnp.pad(any_l, (0, pad))
                bits = (
                    any_l.reshape(-1, 32).astype(jnp.uint32)
                    << jnp.arange(32, dtype=jnp.uint32)[None, :]
                ).sum(axis=1, dtype=jnp.uint32)
                return None, (hits_l, bits)

            _, (h, bits) = jax.lax.scan(one_rule, None, steps)
            return jax.lax.psum(h.sum(), DP_AXIS), bits

        n_specs = sum(1 + len(_KINDS[p[0]][1]) for p in parts)
        _STEP_CACHE[key] = _shard(
            mesh, local,
            (P(DP_AXIS, None), P(DP_AXIS), P(), P(), P()) + (P(),) * n_specs,
            (P(), P(None, DP_AXIS)),
        )
    fn = _STEP_CACHE[key]
    flat_consts = tuple(c for p in parts for c in p[3])

    def step(base, lens, steps):
        return fn(base, lens, steps, s1, s2, *flat_consts)

    return step


def build_crack_step(mesh, nets, salt1, salt2):
    """The full crack step for one ESSID group over ``mesh``.

    ``nets``: list of PreppedNet sharing one ESSID.  Returns
    ``step(pw_words[B,16]) -> (hits, found, pmk)`` where ``found`` is
    bool[N, V_max, B] in the order of ``nets`` (variant axes zero-padded
    so the per-net matrices stack) and ``pmk`` is uint32[8, B]; B must be
    divisible by the mesh size.  The host should gate on the replicated
    scalar ``hits`` and only fetch ``found``/``pmk`` for the rare
    positives (the psum hits-gate, SURVEY.md §5.7).

    Building a step never compiles anything group-specific: all jitted
    pieces come from the process-wide shape-keyed cache above.
    """
    repl = NamedSharding(mesh, P())
    s1 = jax.device_put(np.asarray(salt1), repl)
    s2 = jax.device_put(np.asarray(salt2), repl)
    v_max = max(1 if n.keyver == 100 else len(n.variants) for n in nets)
    pmk_fn = pmk_step(mesh)

    parts = []
    order = []   # original net index per concatenated found row
    struct = []  # (real net count, variant count) per part
    for sig, idxs in _partition(nets).items():
        kind, static = sig[0], sig[1]
        _, fields, _ = _KINDS[kind]
        group = [nets[i] for i in idxs]
        mask = np.zeros(_bucket(len(group)), dtype=bool)
        mask[: len(group)] = True
        consts = (mask,) + tuple(
            _pad_nets([getattr(g, f) for g in group]) for f in fields
        )
        consts = tuple(jax.device_put(c, repl) for c in consts)
        parts.append((verify_step(mesh, kind, static), consts))
        v = 1 if kind == "pmkid" else len(group[0].variants)
        struct.append((len(group), v))
        order.extend(idxs)
    inv = np.argsort(np.asarray(order)) if order != sorted(order) else None
    # Fast path: one part, no bucket padding, full variant width, input
    # order — the verify step's output IS the final found matrix.
    trivial = (
        len(parts) == 1
        and struct[0] == (len(nets), v_max)
        and _bucket(len(nets)) == len(nets)
        and inv is None
    )
    asm = None if trivial else _assemble_step(mesh, tuple(struct), v_max, inv)

    def compute_pmk(pw_words):
        return pmk_fn(pw_words, s1, s2)

    def verify(pmk):
        hits = None
        fnds = []
        for fn, consts in parts:
            h, fnd = fn(pmk, *consts)
            hits = h if hits is None else hits + h
            fnds.append(fnd)
        found = fnds[0] if asm is None else asm(*fnds)
        return hits, found, pmk

    def step(pw_words):
        return verify(compute_pmk(pw_words))

    # The two halves are the PMK-store seams (M22000Engine._dispatch_mixed):
    # PBKDF2 over a miss sub-batch of any static width, and verification
    # of a PMK matrix that arrived by any route (computed, cached via
    # mix_step, or fully cached) — same jit caches either way.
    step.compute_pmk = compute_pmk
    step.verify = verify
    return step
