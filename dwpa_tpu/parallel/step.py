"""The sharded full crack step: PBKDF2 -> verify, shard_map'd over the mesh.

``build_crack_step`` closes over a prepped net list and returns one jitted
function that runs the complete pipeline for a candidate batch:

- the [B, 16] packed-password batch is split over the "dp" mesh axis;
- each device runs PBKDF2(4096) + every net's MIC/PMKID check on its local
  candidate shard — no communication at all in the hot loop;
- the only collective is a ``psum`` of the scalar hit count over ICI, used
  by the host as a cheap "anything found?" gate before it pulls the
  (dp-sharded) per-net match matrix back for the rare positives.

This is the TPU mapping of the reference's work distribution (volunteer
data parallelism + ESSID-amortized PBKDF2, web/content/get_work.php:96-109)
described in SURVEY.md §5.7.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import m22000 as m
from .mesh import DP_AXIS


def build_crack_step(mesh, nets, salt1, salt2):
    """Jit the full crack step for one ESSID group over ``mesh``.

    ``nets``: list of PreppedNet sharing one ESSID (constants are folded
    into the trace).  Returns ``step(pw_words[B,16]) -> (hits[], found,
    pmk)`` where ``found`` is bool[N, V_max, B] (variant axes zero-padded
    so the per-net matrices stack) and ``pmk`` is uint32[8, B]; B must be
    divisible by the mesh size.  The host should gate on the replicated
    scalar ``hits`` and only fetch ``found``/``pmk`` for the rare
    positives (the psum hits-gate, SURVEY.md §5.7).
    """
    s1 = jnp.asarray(salt1)
    s2 = jnp.asarray(salt2)
    v_max = max(1 if n.keyver == 100 else len(n.variants) for n in nets)
    use_pallas = all(d.platform == "tpu" for d in mesh.devices.flat)

    def local_step(pw_words):
        pmk = m._pmk_impl(pw_words, s1, s2, use_pallas=use_pallas)
        per_net = []
        for net in nets:
            mv = m.net_match(pmk, net)  # [V, b]
            pad = v_max - mv.shape[0]
            if pad:
                mv = jnp.concatenate(
                    [mv, jnp.zeros((pad,) + mv.shape[1:], dtype=mv.dtype)]
                )
            per_net.append(mv)
        found = jnp.stack(per_net)  # [N, V_max, b]
        hits = jax.lax.psum(jnp.sum(found, dtype=jnp.int32), DP_AXIS)
        return hits, found, pmk

    # check_vma=False: the rolled compressions seed their fori_loop carries
    # from unsharded per-net constants, which fails JAX's varying-manual-axes
    # check even though every carry is elementwise over the dp-sharded batch
    # (each device runs the identical closed-over constants against its own
    # candidate shard, so replication is trivially consistent).
    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DP_AXIS, None),),
        out_specs=(P(), P(None, None, DP_AXIS), P(None, DP_AXIS)),
        check_vma=False,
    )
    return jax.jit(
        sharded,
        in_shardings=(NamedSharding(mesh, P(DP_AXIS, None)),),
    )
