"""Device-mesh data parallelism for the cracking pipeline."""

from .mesh import default_mesh, multihost_mesh, shard_candidates  # noqa: F401
from .step import build_crack_step  # noqa: F401
from .streams import (  # noqa: F401
    DeviceStream, StreamError, StreamExecutor, default_feed_workers,
    streams_default)
