"""Native (C++) fast paths, loaded via ctypes.

``capture_fast`` is the bulk pcap/pcapng -> m22000 extractor
(capture_fast.cpp), the native seat the reference fills with
hcxpcapngtool (web/common.php:481).  The shared library is built on
demand with the toolchain's g++ and cached next to the source; loading
degrades gracefully (``load() -> None``) so every caller keeps the pure
Python parser as fallback — the native path is an optimization, never a
requirement.
"""

import ctypes
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "capture_fast.cpp")
_SO = os.path.join(_DIR, "capture_fast.so")
_lib = None
_tried = False


def build(force: bool = False) -> str:
    """Compile capture_fast.so if missing/stale; returns the .so path."""
    if (not force and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
        check=True, capture_output=True,
    )
    return _SO


def load(auto_build: bool = True):
    """ctypes handle to the native library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried and not auto_build:
        return None
    _tried = True
    try:
        if auto_build:
            build()
        lib = ctypes.CDLL(_SO)
    except (OSError, subprocess.CalledProcessError):
        return None
    lib.dwpa_extract.restype = ctypes.c_int
    lib.dwpa_extract.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.dwpa_free.argtypes = [ctypes.c_char_p]
    _lib = lib
    return lib


def extract_hashlines_fast(blob: bytes, nc_hint: bool = True):
    """Native twin of server.capture.extract_hashlines.

    Returns ([hashline str, ...], [probe ssid bytes, ...]); raises
    RuntimeError when the library is unavailable (callers select the
    fast path explicitly and fall back themselves).
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native capture parser unavailable (g++ build failed?)")
    out = ctypes.c_char_p()
    out_len = ctypes.c_size_t()
    rc = lib.dwpa_extract(blob, len(blob), int(nc_hint),
                          ctypes.byref(out), ctypes.byref(out_len))
    if rc != 0:
        raise RuntimeError(f"dwpa_extract failed: rc={rc}")
    try:
        text = ctypes.string_at(out, out_len.value)
    finally:
        lib.dwpa_free(out)
    lines, probes = [], []
    for rec in text.split(b"\n"):
        if rec.startswith(b"H "):
            lines.append(rec[2:].decode("ascii"))
        elif rec.startswith(b"P "):
            probes.append(bytes.fromhex(rec[2:].decode("ascii")))
    return lines, probes
