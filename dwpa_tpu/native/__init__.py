"""Native (C++) fast paths, loaded via ctypes.

``capture_fast`` is the bulk pcap/pcapng -> m22000 extractor
(capture_fast.cpp), the native seat the reference fills with
hcxpcapngtool (web/common.php:481).  The shared library is built on
demand with the toolchain's g++ and cached next to the source; loading
degrades gracefully (``load() -> None``) so every caller keeps the pure
Python parser as fallback — the native path is an optimization, never a
requirement.
"""

import ctypes
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "capture_fast.cpp")
_SO = os.path.join(_DIR, "capture_fast.so")


def build(force: bool = False) -> str:
    """Compile capture_fast.so if missing/stale; returns the .so path."""
    if (not force and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
        check=True, capture_output=True,
    )
    return _SO


def _configure_capture(lib):
    lib.dwpa_extract.restype = ctypes.c_int
    lib.dwpa_extract.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_double,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.dwpa_free.argtypes = [ctypes.c_char_p]


def load(auto_build: bool = True):
    """ctypes handle to the native capture library, or None."""
    return _load_lib(_SRC, _SO, _configure_capture, auto_build)


def extract_hashlines_fast(blob: bytes, nc_hint: bool = True,
                           eapol_timeout: float = 30.0):
    """Native twin of server.capture.extract_hashlines.

    Returns ([hashline str, ...], [probe ssid bytes, ...]); raises
    RuntimeError when the library is unavailable (callers select the
    fast path explicitly and fall back themselves).  ``eapol_timeout``
    mirrors hcxpcapngtool's --eapoltimeout pairing gate (seconds).
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native capture parser unavailable (g++ build failed?)")
    out = ctypes.c_char_p()
    out_len = ctypes.c_size_t()
    rc = lib.dwpa_extract(blob, len(blob), int(nc_hint),
                          ctypes.c_double(eapol_timeout),
                          ctypes.byref(out), ctypes.byref(out_len))
    if rc != 0:
        raise RuntimeError(f"dwpa_extract failed: rc={rc}")
    try:
        text = ctypes.string_at(out, out_len.value)
    finally:
        lib.dwpa_free(out)
    lines, probes = [], []
    for rec in text.split(b"\n"):
        if rec.startswith(b"H "):
            lines.append(rec[2:].decode("ascii"))
        elif rec.startswith(b"P "):
            probes.append(bytes.fromhex(rec[2:].decode("ascii")))
    return lines, probes


# ---------------------------------------------------------------------------
# pack_fast: the candidate-feed fast path (unhex + filter + pack in C)
# ---------------------------------------------------------------------------

_PACK_SRC = os.path.join(_DIR, "pack_fast.cpp")
_PACK_SO = os.path.join(_DIR, "pack_fast.so")
#: src path -> ctypes lib | None (None = build/load failed; cached so the
#: per-batch hot path never re-attempts a doomed g++ run)
_LIBS = {}


def _load_lib(src: str, so: str, configure, auto_build: bool = True):
    """Shared build-if-stale + CDLL + cache logic for every native lib.

    ``configure(lib)`` sets restype/argtypes.  Failures are cached as
    None — callers on hot paths fall back to Python exactly once.
    """
    if src in _LIBS:
        return _LIBS[src]
    lib = None
    try:
        if auto_build and not (
            os.path.exists(so)
            and os.path.getmtime(so) >= os.path.getmtime(src)
        ):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", so, src],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(so)
        configure(lib)
    except (OSError, subprocess.CalledProcessError):
        lib = None
    _LIBS[src] = lib
    return lib


def _configure_pack(lib):
    lib.dwpa_pack.restype = ctypes.c_long
    lib.dwpa_pack.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_long, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint8),
    ]


def load_pack(auto_build: bool = True):
    """ctypes handle to pack_fast.so, or None if unavailable."""
    return _load_lib(_PACK_SRC, _PACK_SO, _configure_pack, auto_build)


def pack_candidates_fast(words, min_len: int, max_len: int,
                         capacity: int = None):
    """Fused unhex + length-filter + key-block pack over a word list.

    ``words``: list of bytes.  Returns ``(pw_words uint32[cap, 16],
    lens uint8[n], n)`` with accepted rows 0..n-1 packed and rows n..cap
    zero (cap = max(capacity, len(words)) — callers pass their batch
    target so the padding rows come for free), or None when the native
    library is unavailable or the input isn't a plain bytes list.
    """
    import numpy as np

    lib = load_pack()
    if lib is None or not all(type(w) is bytes for w in words):
        return None
    count = len(words)
    blob = b"".join(words)
    lens_in = np.fromiter((len(w) for w in words), np.int64, count=count)
    offs = np.zeros(count, dtype=np.int64)
    if count > 1:
        np.cumsum(lens_in[:-1], out=offs[1:])
    cap = max(capacity or 0, count)
    out = np.zeros((cap, 16), dtype=np.uint32)
    out_lens = np.empty(count, dtype=np.uint8)
    n = lib.dwpa_pack(
        blob, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lens_in.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        count, min_len, max_len,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if n < 0:
        return None
    return out, out_lens[:n], int(n)
