// Candidate packing fast path: a joined candidate blob + per-word
// offsets/lengths -> packed big-endian uint32[n][16] HMAC key blocks.
//
// The native seat of the host feed stage (SURVEY.md §7.3.3 "keeping the
// device fed"): the engine's prepare step — $HEX[...] decode
// (web/common.php:3-25 semantics), PSK length filter (8..63,
// INSTALL.md:83), zero-padded 64-byte key-block packing — fused into
// one pass, so a multi-chip mesh can be fed from a single host core.
// Words are addressed by (offset, length) rather than separators
// because decoded candidates may contain any byte value.
// Differentially tested against the Python pipeline (oracle.hc_unhex +
// bytesops.pack_passwords_be) in tests/test_native_pack.py.
//
// Contract (ctypes, see native/__init__.py):
//   n = dwpa_pack(blob, offs, wlens, count, min_len, max_len,
//                 out_words, out_lens)
// out_words: caller-zeroed capacity [count][16] uint32; out_lens:
// [count] uint8.  Returns the accepted row count (rows are written
// contiguously from 0), or -1 on bad arguments.  A $HEX[...] wrapper
// with valid even-length hex decodes; an invalid one is taken
// literally (hashcat behavior).

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

inline int hexval(uint8_t c) {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
}

// decode $HEX[...] into buf (capacity 64); returns decoded length, or
// -1 if the wrapper is invalid (caller treats the word literally)
inline int try_unhex(const uint8_t* w, size_t len, uint8_t* buf) {
    if (len < 7 || memcmp(w, "$HEX[", 5) != 0 || w[len - 1] != ']')
        return -1;
    size_t ndig = len - 6;
    if (ndig % 2 != 0 || ndig / 2 > 64) return -1;
    for (size_t i = 0; i < ndig; i += 2) {
        int hi = hexval(w[5 + i]), lo = hexval(w[5 + i + 1]);
        if (hi < 0 || lo < 0) return -1;
        buf[i / 2] = (uint8_t)((hi << 4) | lo);
    }
    return (int)(ndig / 2);
}

}  // namespace

extern "C" long dwpa_pack(const uint8_t* blob, const long long* offs,
                          const long long* wlens, long count, int min_len,
                          int max_len, uint32_t* out_words,
                          uint8_t* out_lens) {
    if (!blob || !offs || !wlens || !out_words || !out_lens ||
        min_len < 0 || max_len > 63 || min_len > max_len || count < 0)
        return -1;
    long n = 0;
    uint8_t decoded[64];
    for (long i = 0; i < count; i++) {
        const uint8_t* w = blob + offs[i];
        size_t wlen = (size_t)wlens[i];
        const uint8_t* src = w;
        size_t slen = wlen;
        if (wlen <= 134) {  // $HEX[ + 2*64 + ] — anything longer can't decode
            int dlen = try_unhex(w, wlen, decoded);
            if (dlen >= 0) {
                src = decoded;
                slen = (size_t)dlen;
            }
        }
        if (slen < (size_t)min_len || slen > (size_t)max_len) continue;
        uint32_t* row = out_words + n * 16;
        for (size_t b = 0; b < slen; b++)
            row[b / 4] |= (uint32_t)src[b] << (8 * (3 - (b % 4)));
        out_lens[n] = (uint8_t)slen;
        n++;
    }
    return n;
}
