// Bulk pcap/pcapng -> m22000 extraction, C++ fast path.
//
// Native counterpart of dwpa_tpu/server/capture.py (itself the
// hcxpcapngtool equivalent -- the one external C tool the reference
// server cannot run without, web/common.php:481).  The Python parser
// stays the readable specification; this library exists for bulk
// archive re-parses (fill_pr / enrich over years of submissions,
// misc/fill_pr.php:33-71) where Python-loop throughput dominates.
//
// Semantics are kept bit-identical to the Python parser -- same
// container handling, 802.11 walk, EAPOL classification, pairing
// preference order, ordered-map tie-breaks -- enforced by differential
// tests (tests/test_native_capture.py).
//
// C ABI:
//   int  dwpa_extract(const uint8_t* blob, size_t len, int nc_hint,
//                     double eapol_timeout_s, char** out, size_t* out_len);
//       out: malloc'd text, one record per line:
//            "H <m22000 hashline>"  or  "P <hex probe ssid>"
//       returns 0 on success (caller frees with dwpa_free), -1 on error.
//   void dwpa_free(char* p);
//
// Build: g++ -O2 -shared -fPIC -o capture_fast.so capture_fast.cpp

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace {

using Bytes = std::string;  // raw byte strings

uint16_t rd16(const uint8_t* p, bool be) {
    return be ? (p[0] << 8) | p[1] : (p[1] << 8) | p[0];
}
uint32_t rd32(const uint8_t* p, bool be) {
    return be ? ((uint32_t)p[0] << 24) | (p[1] << 16) | (p[2] << 8) | p[3]
              : ((uint32_t)p[3] << 24) | (p[2] << 16) | (p[1] << 8) | p[0];
}
uint64_t rd64be(const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
    return v;
}

std::string hex(const uint8_t* p, size_t n) {
    static const char* d = "0123456789abcdef";
    std::string out;
    out.reserve(2 * n);
    for (size_t i = 0; i < n; i++) {
        out.push_back(d[p[i] >> 4]);
        out.push_back(d[p[i] & 15]);
    }
    return out;
}
std::string hex(const Bytes& b) { return hex((const uint8_t*)b.data(), b.size()); }

struct EapolMsg {
    int num;
    Bytes ap, sta;
    uint64_t replay;
    Bytes nonce;
    Bytes frame;  // full EAPOL, MIC zeroed, truncated to declared length
    Bytes mic;
    std::vector<Bytes> pmkids;
    double ts = 0.0;      // capture timestamp, epoch seconds
    bool has_ts = false;  // pcapng SPBs carry no timestamp
};

struct Frame {
    const uint8_t* p;
    size_t n;
    double ts;
    bool has_ts;
};

// ---- container readers --------------------------------------------------

void pcap_frames(const uint8_t* d, size_t len, std::vector<Frame>& frames,
                 std::vector<uint32_t>& linktypes) {
    if (len < 24) return;
    bool be;
    if (!memcmp(d, "\xd4\xc3\xb2\xa1", 4) || !memcmp(d, "\x4d\x3c\xb2\xa1", 4))
        be = false;
    else if (!memcmp(d, "\xa1\xb2\xc3\xd4", 4) || !memcmp(d, "\xa1\xb2\x3c\x4d", 4))
        be = true;
    else
        return;
    // Nanosecond-resolution magics (a1b23c4d and its byte swap).
    bool nsec = !memcmp(d, "\xa1\xb2\x3c\x4d", 4) || !memcmp(d, "\x4d\x3c\xb2\xa1", 4);
    double frac = nsec ? 1e-9 : 1e-6;
    uint32_t linktype = rd32(d + 20, be) & 0xFFFF;
    size_t off = 24;
    while (off + 16 <= len) {
        uint32_t sec = rd32(d + off, be);
        uint32_t sub = rd32(d + off + 4, be);
        uint32_t caplen = rd32(d + off + 8, be);
        off += 16;
        if (off + caplen > len) break;
        frames.push_back({d + off, caplen, sec + sub * frac, true});
        linktypes.push_back(linktype);
        off += caplen;
    }
}

// seconds per timestamp unit from an IDB's if_tsresol option (code 9)
double idb_tsresol(const uint8_t* body, size_t bodylen, bool be) {
    size_t off = 8;  // linktype(2) + reserved(2) + snaplen(4)
    while (off + 4 <= bodylen) {
        uint16_t code = rd16(body + off, be), ln = rd16(body + off + 2, be);
        if (code == 0) break;  // opt_endofopt
        if (code == 9 && ln >= 1 && off + 4 < bodylen) {
            uint8_t v = body[off + 4];
            double r = 1.0;
            if (v & 0x80) {
                for (int i = 0; i < (v & 0x7F); i++) r /= 2.0;
            } else {
                for (int i = 0; i < (v & 0x7F); i++) r /= 10.0;
            }
            return r;
        }
        off += 4 + ln + ((4 - ln % 4) % 4);
    }
    return 1e-6;
}

void pcapng_frames(const uint8_t* d, size_t len, std::vector<Frame>& frames,
                   std::vector<uint32_t>& linktypes) {
    if (len < 12 || memcmp(d, "\x0a\x0d\x0d\x0a", 4)) return;
    bool be = !(len >= 12 && !memcmp(d + 8, "\x4d\x3c\x2b\x1a", 4));
    size_t off = 0;
    std::vector<std::pair<uint32_t, double>> ifaces;  // (linktype, tsresol)
    while (off + 12 <= len) {
        uint32_t btype = rd32(d + off, be);
        uint32_t blen = rd32(d + off + 4, be);
        if (blen < 12 || off + blen > len) break;
        const uint8_t* body = d + off + 8;
        size_t bodylen = blen - 12;
        if (btype == 0x00000001 && bodylen >= 2) {  // IDB
            ifaces.emplace_back(rd16(body, be), idb_tsresol(body, bodylen, be));
        } else if (btype == 0x00000006 && bodylen >= 20) {  // EPB
            uint32_t iface = rd32(body, be);
            uint32_t tsh = rd32(body + 4, be), tsl = rd32(body + 8, be);
            uint32_t caplen = rd32(body + 12, be);
            if (caplen > bodylen - 20) caplen = bodylen - 20;
            double res = iface < ifaces.size() ? ifaces[iface].second : 1e-6;
            double ts = (double)(((uint64_t)tsh << 32) | tsl) * res;
            frames.push_back({body + 20, caplen, ts, true});
            linktypes.push_back(iface < ifaces.size() ? ifaces[iface].first : 105);
        } else if (btype == 0x00000003 && bodylen >= 4) {  // SPB: no timestamp
            uint32_t caplen = rd32(body, be);
            if (caplen > bodylen - 4) caplen = bodylen - 4;
            frames.push_back({body + 4, caplen, 0.0, false});
            linktypes.push_back(ifaces.empty() ? 105 : ifaces[0].first);
        }
        off += blen;
    }
}

// strip link-layer wrappers; returns empty frame to drop
Frame unwrap(Frame f, uint32_t lt) {
    if (lt == 127 || lt == 192) {  // radiotap / PPI: LE length at offset 2
        if (f.n < 4) return {nullptr, 0, 0.0, false};
        uint16_t hl = rd16(f.p + 2, false);
        if (hl > f.n) return {nullptr, 0, 0.0, false};
        return {f.p + hl, f.n - hl, f.ts, f.has_ts};
    }
    if (lt != 105) return {nullptr, 0, 0.0, false};
    return f;
}

// ---- 802.11 -------------------------------------------------------------

// walk tagged parameters from `off`; SSID tag with 0 < len <= 32, nonzero
bool tagged_ssid(const uint8_t* p, size_t n, size_t off, Bytes& out) {
    while (off + 2 <= n) {
        uint8_t tag = p[off], ln = p[off + 1];
        if (off + 2 + ln > n) return false;
        if (tag == 0) {
            if (ln == 0 || ln > 32) return false;
            bool nz = false;
            for (int i = 0; i < ln; i++) nz |= p[off + 2 + i] != 0;
            if (!nz) return false;
            out.assign((const char*)p + off + 2, ln);
            return true;
        }
        off += 2 + ln;
    }
    return false;
}

bool parse_eapol_key(const Bytes& ap, const Bytes& sta, const uint8_t* e,
                     size_t n, EapolMsg& m) {
    if (n < 99 || e[1] != 3) return false;
    if (e[4] != 2 && e[4] != 254) return false;  // RSN / WPA descriptor
    uint16_t ki = rd16(e + 5, true);
    if (!(ki & 0x0008)) return false;  // pairwise
    m.replay = rd64be(e + 9);
    m.nonce.assign((const char*)e + 17, 32);
    m.mic.assign((const char*)e + 81, 16);
    uint16_t kd_len = rd16(e + 97, true);
    size_t kd_end = 99 + kd_len;
    if (kd_end > n) kd_end = n;

    bool ack = ki & 0x0080, has_mic = ki & 0x0100, secure = ki & 0x0200;
    if (ack && !has_mic) m.num = 1;
    else if (ack && has_mic) m.num = 3;
    else if (has_mic && !secure) m.num = 2;
    else m.num = 4;

    if (m.num == 1 || m.num == 3) {
        size_t off = 99;
        while (off + 2 <= kd_end) {
            uint8_t t = e[off], ln = e[off + 1];
            size_t cend = off + 2 + ln;
            if (cend > kd_end) cend = kd_end;
            if (t == 0xDD && ln >= 20 && cend - (off + 2) >= 20 &&
                !memcmp(e + off + 2, "\x00\x0f\xac\x04", 4)) {
                const uint8_t* pk = e + off + 6;
                bool nz = false, allff = true;
                for (int i = 0; i < 16; i++) {
                    nz |= pk[i] != 0;
                    allff &= pk[i] == 0xFF;
                }
                if (nz && !allff) m.pmkids.emplace_back((const char*)pk, 16);
            }
            off += 2 + ln;
        }
    }

    Bytes zeroed((const char*)e, n);
    memset(&zeroed[81], 0, 16);
    size_t declared = (size_t)rd16(e + 2, true) + 4;
    size_t keep = declared < n ? declared : n;
    if (keep < 95) keep = 95;
    zeroed.resize(keep < n ? keep : n);
    m.frame = std::move(zeroed);
    m.ap = ap;
    m.sta = sta;
    return true;
}

// ---- assembly -----------------------------------------------------------

struct Pairing {
    int sta_num, ap_num, delta, mp;
};
const Pairing PAIRINGS[] = {
    {2, 1, 0, 0x00}, {2, 3, 1, 0x02}, {4, 1, -1, 0x01}, {4, 3, 0, 0x03},
};

// insertion-ordered map: linear scan (captures hold few stations)
template <typename V>
struct OrderedMap {
    std::vector<std::pair<Bytes, V>> items;
    V* find(const Bytes& k) {
        for (auto& it : items)
            if (it.first == k) return &it.second;
        return nullptr;
    }
    V& get(const Bytes& k) {
        if (V* v = find(k)) return *v;
        items.emplace_back(k, V{});
        return items.back().second;
    }
};

std::string serialize(int type, const Bytes& mic, const Bytes& ap,
                      const Bytes& sta, const Bytes& essid,
                      const Bytes& anonce, const Bytes& eapol, int mp) {
    char t[4], mpbuf[4];
    snprintf(t, sizeof t, "%02d", type);
    snprintf(mpbuf, sizeof mpbuf, "%02x", mp);
    return std::string("WPA*") + t + "*" + hex(mic) + "*" + hex(ap) + "*" +
           hex(sta) + "*" + hex(essid) + "*" + hex(anonce) + "*" + hex(eapol) +
           "*" + mpbuf;
}

}  // namespace

extern "C" {

int dwpa_extract(const uint8_t* blob, size_t len, int nc_hint,
                 double eapol_timeout_s, char** out, size_t* out_len) {
    if (!blob || !out || !out_len) return -1;
    std::vector<Frame> raw;
    std::vector<uint32_t> lts;
    if (len >= 4 && !memcmp(blob, "\x0a\x0d\x0d\x0a", 4))
        pcapng_frames(blob, len, raw, lts);
    else
        pcap_frames(blob, len, raw, lts);

    // ap -> [(ssid, count)] in first-seen order (Counter.most_common tie
    // semantics: max count, earliest insertion wins)
    OrderedMap<std::vector<std::pair<Bytes, int>>> essids;
    std::vector<Bytes> probes;
    OrderedMap<std::vector<EapolMsg>> ap_msgs, sta_msgs;  // key: ap||sta
    OrderedMap<std::vector<Bytes>> ap_nonces;             // key: ap
    std::vector<std::pair<Bytes, Bytes>> pmkid_keys;      // dedup keys seen
    struct PmkidRow { Bytes ap, sta, pmkid; };
    std::vector<PmkidRow> pmkid_rows;

    for (size_t fi = 0; fi < raw.size(); fi++) {
        Frame f = unwrap(raw[fi], lts[fi]);
        if (!f.p || f.n < 24) continue;
        const uint8_t* p = f.p;
        uint16_t fc = rd16(p, false);
        int ftype = (fc >> 2) & 3, subtype = (fc >> 4) & 0xF;
        bool to_ds = fc & 0x100, from_ds = fc & 0x200;
        Bytes a1((const char*)p + 4, 6), a2((const char*)p + 10, 6),
            a3((const char*)p + 16, 6);

        if (ftype == 0) {  // management
            Bytes ssid;
            if (subtype == 8 || subtype == 5) {
                if (tagged_ssid(p, f.n, 24 + 12, ssid)) {
                    auto& vec = essids.get(a3);
                    bool hit = false;
                    for (auto& sc : vec)
                        if (sc.first == ssid) { sc.second++; hit = true; break; }
                    if (!hit) vec.emplace_back(ssid, 1);
                }
            } else if (subtype == 4) {
                if (tagged_ssid(p, f.n, 24, ssid)) {
                    bool seen = false;
                    for (auto& pr : probes) seen |= pr == ssid;
                    if (!seen) probes.push_back(ssid);
                }
            } else if (subtype == 0 || subtype == 2) {
                size_t skip = subtype == 0 ? 4 : 10;
                if (tagged_ssid(p, f.n, 24 + skip, ssid)) {
                    auto& vec = essids.get(a3);
                    bool hit = false;
                    for (auto& sc : vec)
                        if (sc.first == ssid) { sc.second++; hit = true; break; }
                    if (!hit) vec.emplace_back(ssid, 1);
                }
            }
            continue;
        }
        if (ftype != 2) continue;  // data only

        size_t hdr = 24;
        if (to_ds && from_ds) hdr += 6;
        if (subtype & 8) hdr += 2;      // QoS
        if (fc & 0x8000) hdr += 4;      // HT control
        if (hdr + 8 > f.n) continue;
        if (memcmp(p + hdr, "\xaa\xaa\x03", 3) ||
            rd16(p + hdr + 6, true) != 0x888E)
            continue;
        const uint8_t* eapol = p + hdr + 8;
        size_t elen = f.n - hdr - 8;
        Bytes ap, sta;
        if (to_ds) { ap = a1; sta = a2; }
        else if (from_ds) { ap = a2; sta = a1; }
        else { ap = a3; sta = a2; }

        EapolMsg m;
        if (!parse_eapol_key(ap, sta, eapol, elen, m)) continue;
        m.ts = f.ts;
        m.has_ts = f.has_ts;
        Bytes key = ap + sta;
        (m.num == 1 || m.num == 3 ? ap_msgs : sta_msgs).get(key).push_back(m);
        if (m.num == 1 || m.num == 3) ap_nonces.get(ap).push_back(m.nonce);
        for (auto& pk : m.pmkids) {
            bool seen = false;
            for (auto& row : pmkid_rows)
                seen |= row.ap == ap && row.sta == sta && row.pmkid == pk;
            if (!seen) pmkid_rows.push_back({ap, sta, pk});
        }
    }

    // Observed nonce-increment endianness -> MP_LE (0x20) / MP_BE (0x40)
    // hint bits, mirroring the Python parser's endian_bits().  Memoized
    // per AP: ap_nonces is immutable by the time the pairing loop runs,
    // and one AP can emit many handshake lines.
    OrderedMap<int> endian_cache;
    auto endian_bits = [&](const Bytes& ap) -> int {
        if (int* hit = endian_cache.find(ap)) return *hit;
        bool le = false, be = false;
        int& slot = endian_cache.get(ap);
        auto* nonces = ap_nonces.find(ap);
        if (!nonces) return slot = 0;
        for (size_t i = 0; i + 1 < nonces->size(); i++) {
            const Bytes& a = (*nonces)[i];
            const Bytes& b = (*nonces)[i + 1];
            if (a == b || a.compare(0, 28, b, 0, 28) != 0) continue;
            const uint8_t* ap4 = (const uint8_t*)a.data() + 28;
            const uint8_t* bp4 = (const uint8_t*)b.data() + 28;
            bool hit = false;
            for (int isle = 1; isle >= 0 && !hit; isle--) {
                uint32_t av = isle ? rd32(ap4, false) : rd32(ap4, true);
                uint32_t bv = isle ? rd32(bp4, false) : rd32(bp4, true);
                int64_t d = (int64_t)(uint32_t)(bv - av);
                if (d >= 0x80000000LL) d -= 0x100000000LL;
                if (d != 0 && (d < 0 ? -d : d) <= 128) {
                    (isle ? le : be) = true;
                    hit = true;
                }
            }
        }
        return slot = (le != be ? (le ? 0x20 : 0x40) : 0);
    };

    auto best_essid = [&](const Bytes& ap, Bytes& out_ssid) {
        auto* vec = essids.find(ap);
        if (!vec || vec->empty()) return false;
        int best = -1;
        for (auto& sc : *vec)
            if (sc.second > best) { best = sc.second; out_ssid = sc.first; }
        return true;
    };

    std::string text;
    for (auto& row : pmkid_rows) {
        Bytes essid;
        if (!best_essid(row.ap, essid)) continue;
        text += "H " +
                serialize(1, row.pmkid, row.ap, row.sta, essid, "", "", 1) +
                "\n";
    }

    for (auto& kv : sta_msgs.items) {
        const Bytes& key = kv.first;
        Bytes ap = key.substr(0, 6);
        Bytes essid;
        if (!best_essid(ap, essid)) continue;
        auto* aps = ap_msgs.find(key);
        bool done = false;
        for (const auto& pr : PAIRINGS) {
            if (done) break;
            for (auto& sm : kv.second) {
                if (done) break;
                if (sm.num != pr.sta_num) continue;
                bool nz = false;
                for (char c : sm.nonce) nz |= c != 0;
                if (!nz) continue;
                if (!aps) continue;
                for (auto& am : *aps) {
                    if (am.num != pr.ap_num) continue;
                    if ((int64_t)(am.replay - sm.replay) != pr.delta) continue;
                    // --eapoltimeout gate (web/common.php:481): messages
                    // captured too far apart are different exchanges.
                    if (am.has_ts && sm.has_ts) {
                        double dt = am.ts - sm.ts;
                        if (dt < 0) dt = -dt;
                        if (dt > eapol_timeout_s) continue;
                    }
                    int mp = pr.mp | (nc_hint ? 0x80 : 0) | endian_bits(ap);
                    text += "H " +
                            serialize(2, sm.mic, ap, sm.sta, essid, am.nonce,
                                      sm.frame, mp) +
                            "\n";
                    done = true;
                    break;
                }
            }
        }
    }

    for (auto& pr : probes) text += "P " + hex(pr) + "\n";

    char* buf = (char*)malloc(text.size() + 1);
    if (!buf) return -1;
    memcpy(buf, text.data(), text.size());
    buf[text.size()] = 0;
    *out = buf;
    *out_len = text.size();
    return 0;
}

void dwpa_free(char* p) { free(p); }

}  // extern "C"
