"""Hashcat mask attack generator.

Pure-host enumeration of ``?d?l?u?s?a?b?h?H`` masks with literals — e.g.
``?d?d?d?d?d?d?d?d`` is the 8-digit brute sweep tracked as BASELINE.json
config #5.  The generator yields in hashcat's positional order (last
position fastest) so keyspace slices (skip/limit) line up with hashcat's
``-s``/``-l`` semantics for resume.
"""

import string

CHARSETS = {
    "l": string.ascii_lowercase.encode(),
    "u": string.ascii_uppercase.encode(),
    "d": string.digits.encode(),
    "s": b" !\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~",
    "h": b"0123456789abcdef",
    "H": b"0123456789ABCDEF",
}
CHARSETS["a"] = CHARSETS["l"] + CHARSETS["u"] + CHARSETS["d"] + CHARSETS["s"]
CHARSETS["b"] = bytes(range(256))


def parse_mask(mask: str, custom: dict = None):
    """Mask string -> list of per-position byte alphabets."""
    custom = custom or {}
    out = []
    i = 0
    while i < len(mask):
        c = mask[i]
        if c == "?":
            if i + 1 >= len(mask):
                raise ValueError("dangling '?' in mask")
            key = mask[i + 1]
            if key == "?":
                out.append(b"?")
            elif key in "1234":
                out.append(custom[key])
            elif key in CHARSETS:
                out.append(CHARSETS[key])
            else:
                raise ValueError(f"unknown mask charset ?{key}")
            i += 2
        else:
            out.append(c.encode("latin1"))
            i += 1
    return out


def mask_keyspace(mask: str, custom: dict = None) -> int:
    n = 1
    for alpha in parse_mask(mask, custom):
        n *= len(alpha)
    return n


def mask_words(mask: str, custom: dict = None, skip: int = 0, limit: int = None):
    """Yield mask words; ``skip``/``limit`` slice the keyspace for resume.

    Odometer enumeration: the digit vector is seeded once from ``skip``
    (the only arbitrary-precision divmod walk), then each word is the
    previous one with a last-position-fastest increment — O(1) amortized
    carries per word instead of a full per-index divmod chain, which
    keeps the host parity-oracle legs in tests (and the no-device
    fallback) off the slow path.
    """
    alphas = parse_mask(mask, custom)
    total = mask_keyspace(mask, custom)
    end = total if limit is None else min(total, skip + limit)
    if skip >= end:
        return
    sizes = [len(a) for a in alphas]
    digits = mask_digits_at(mask, skip, custom)
    word = bytearray(alphas[p][digits[p]] for p in range(len(alphas)))
    last = len(alphas) - 1
    for _ in range(end - skip - 1):
        yield bytes(word)
        p = last
        while True:  # increment with carry, last position fastest
            d = digits[p] + 1
            if d < sizes[p]:
                digits[p] = d
                word[p] = alphas[p][d]
                break
            digits[p] = 0
            word[p] = alphas[p][0]
            p -= 1
    yield bytes(word)


class MaskPrep:
    """Block prep marking a keyspace slice for ON-DEVICE generation.

    The mask analog of ``feed.framing.RulesPrep``: a ``Block`` carrying
    one of these owns the keyspace range ``[start, start + count)`` and
    materializes NO host-side bytes — ``M22000Engine._prepare_block``
    recognizes the ``mask_gen`` marker and runs ``device_mask_words``
    under its own mesh sharding (lockstep full-mesh engines and
    per-device stream engines each generate exactly their own shard).
    This puts mask work behind the same framed-block interface as dict
    and rules feeds: ``crack_blocks``/``crack_streams`` schedule it
    with no new dispatch regime.
    """

    __slots__ = ("mask", "custom", "start")

    mask_gen = True

    def __init__(self, mask: str, custom: dict, start: int):
        self.mask = mask
        self.custom = custom
        self.start = start


def mask_blocks(mask: str, batch_size: int, skip: int = 0,
                limit: int = None, custom: dict = None):
    """Frame a mask keyspace slice into feed ``Block``s of ``MaskPrep``
    — same ``(offset, count)`` geometry as ``mask_words`` consumed
    through ``frame_blocks``, zero candidate bytes.  ``offset`` is the
    ABSOLUTE keyspace index (hashcat ``-s`` coordinates), so resume
    checkpoints interop with ``crack_mask(skip=...)``."""
    from ..feed.framing import Block

    total = mask_keyspace(mask, custom)
    end = total if limit is None else min(total, skip + limit)
    pos = skip
    while pos < end:
        n = min(batch_size, end - pos)
        yield Block(offset=pos, count=n, words=[],
                    prep=MaskPrep(mask, custom, pos))
        pos += n


def mask_digits_at(mask: str, idx: int, custom: dict = None):
    """Mixed-radix digit vector (last position fastest) for keyspace
    index ``idx`` — the host-side seed for the on-device generator
    (arbitrary-precision here, so keyspaces beyond 2^32 slice fine)."""
    alphas = parse_mask(mask, custom)
    digits = [0] * len(alphas)
    rem = idx
    for p in range(len(alphas) - 1, -1, -1):
        rem, digits[p] = divmod(rem, len(alphas[p]))
    return digits


def _device_mask_impl(alphas, batch, start_digits):
    import jax.numpy as jnp

    carry = jnp.arange(batch, dtype=jnp.uint32)
    byte_cols = [None] * len(alphas)
    for p in range(len(alphas) - 1, -1, -1):
        radix = jnp.uint32(len(alphas[p]))
        total = carry + start_digits[p]
        digit = total % radix
        carry = total // radix
        lut = jnp.asarray(list(alphas[p]), dtype=jnp.uint32)
        byte_cols[p] = lut[digit]  # [batch]
    words = []
    for w in range(16):
        acc = jnp.zeros((batch,), dtype=jnp.uint32)
        for k in range(4):
            p = w * 4 + k
            if p < len(byte_cols):
                acc = acc | (byte_cols[p] << jnp.uint32(8 * (3 - k)))
        words.append(acc)
    return jnp.stack(words, axis=1)  # [batch, 16]


_device_mask_jits = {}  # output sharding (or None) -> jitted generator


def device_mask_words(mask: str, start: int, batch: int, custom: dict = None,
                      sharding=None):
    """uint32[batch, 16] packed HMAC key blocks for ``batch`` consecutive
    mask words starting at keyspace index ``start`` — generated entirely
    on device (SURVEY §7 M5: the pure iota→digits generator; no host
    packing, no H2D of candidates).

    The host contributes only the O(positions) starting digit vector
    (as *data*, so one compilation per (mask shape, batch) serves every
    keyspace slice); the device runs a carry chain over positions
    (least-significant last, matching ``mask_words`` order), maps digits
    through the per-position alphabets, and packs big-endian words.
    The absolute keyspace index is unbounded — it never crosses to the
    device, only its per-position digit remainders do.

    ``sharding``: an optional NamedSharding for the output — XLA's SPMD
    partitioner then generates each candidate shard directly on its
    owning device (each device materializes only its slice of the iota),
    so a mesh consumes the batch with no generation bottleneck and no
    redistribution, on one host or many.
    """
    import jax
    import jax.numpy as jnp

    fn = _device_mask_jits.get(sharding)
    if fn is None:
        kw = {} if sharding is None else {"out_shardings": sharding}
        fn = jax.jit(_device_mask_impl, static_argnames=("alphas", "batch"),
                     **kw)
        _device_mask_jits[sharding] = fn
    alphas = tuple(parse_mask(mask, custom))
    if len(alphas) > 63:
        raise ValueError(f"mask has {len(alphas)} positions; a WPA PSK "
                         "caps at 63")
    if not 0 < batch < 2**31:
        raise ValueError(f"batch {batch} outside (0, 2^31) — the "
                         "within-batch carry chain is uint32")
    digits = jnp.asarray(mask_digits_at(mask, start, custom), dtype=jnp.uint32)
    return fn(alphas, batch, digits)
