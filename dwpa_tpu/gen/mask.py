"""Hashcat mask attack generator.

Pure-host enumeration of ``?d?l?u?s?a?b?h?H`` masks with literals — e.g.
``?d?d?d?d?d?d?d?d`` is the 8-digit brute sweep tracked as BASELINE.json
config #5.  The generator yields in hashcat's positional order (last
position fastest) so keyspace slices (skip/limit) line up with hashcat's
``-s``/``-l`` semantics for resume.
"""

import string

CHARSETS = {
    "l": string.ascii_lowercase.encode(),
    "u": string.ascii_uppercase.encode(),
    "d": string.digits.encode(),
    "s": b" !\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~",
    "h": b"0123456789abcdef",
    "H": b"0123456789ABCDEF",
}
CHARSETS["a"] = CHARSETS["l"] + CHARSETS["u"] + CHARSETS["d"] + CHARSETS["s"]
CHARSETS["b"] = bytes(range(256))


def parse_mask(mask: str, custom: dict = None):
    """Mask string -> list of per-position byte alphabets."""
    custom = custom or {}
    out = []
    i = 0
    while i < len(mask):
        c = mask[i]
        if c == "?":
            if i + 1 >= len(mask):
                raise ValueError("dangling '?' in mask")
            key = mask[i + 1]
            if key == "?":
                out.append(b"?")
            elif key in "1234":
                out.append(custom[key])
            elif key in CHARSETS:
                out.append(CHARSETS[key])
            else:
                raise ValueError(f"unknown mask charset ?{key}")
            i += 2
        else:
            out.append(c.encode("latin1"))
            i += 1
    return out


def mask_keyspace(mask: str, custom: dict = None) -> int:
    n = 1
    for alpha in parse_mask(mask, custom):
        n *= len(alpha)
    return n


def mask_words(mask: str, custom: dict = None, skip: int = 0, limit: int = None):
    """Yield mask words; ``skip``/``limit`` slice the keyspace for resume."""
    alphas = parse_mask(mask, custom)
    total = mask_keyspace(mask, custom)
    end = total if limit is None else min(total, skip + limit)
    sizes = [len(a) for a in alphas]
    for idx in range(skip, end):
        word = bytearray(len(alphas))
        rem = idx
        for p in range(len(alphas) - 1, -1, -1):
            rem, d = divmod(rem, sizes[p])
            word[p] = alphas[p][d]
        yield bytes(word)
