"""Vendor default-key generators (routerkeygen-cli equivalent).

The reference server shells out to ``routerkeygen-cli -q -k -m <mac>
-s <ssid>`` during keygen precompute (web/rkg.php:109) to derive the
factory-default WPA keys many routers ship with.  That Qt/C++ binary is
external to the repo; this module provides the same capability as native
generators, each implementing a publicly documented default-key scheme:

- ``thomson``   — Thomson/SpeedTouch serial-space SHA-1 search (Kevin
  Devine's "stkeys" attack, 2008): the default key and the SSID suffix
  are both digests of the manufacturing serial, so the ~22M serial space
  is searched for serials whose digest tail matches the SSID.  The
  search runs as a batched single-block SHA-1 sweep on the accelerator
  (reusing ops/sha1), with a hashlib fallback for tiny spaces.
- ``belkin``    — Belkin's per-nibble substitution of the WAN MAC
  (Jakob Lell's 2012 writeup): 8 key chars drawn from a 16-char charset
  indexed by a fixed permutation of the MAC's last 8 nibbles.
- ``easybox``   — Arcadyan/Vodafone EasyBox MAC-derived 9-hex-digit key
  (structure per Stefan Viehböck's 2012 advisory: mix the decimal and
  hex digits of the MAC's last two bytes through two mod-16 sums).
- ``mac_tail``  — the "key is printed from the radio MAC" family common
  on budget APs (Tenda et al.): hex tails/decimalizations of BSSID±1.
- ``imei_hotspot`` — mobile-hotspot default keys derived from the device
  IMEI (imeigen-equivalent, gen/imei.py) for tethering SSID prefixes,
  sweeping a small set of common TACs per prefix.
- ``zyxel``     — ZyXEL CPE: first 20 hex chars of MD5 over the
  uppercase MAC string (routerkeygen ZyxelKeygen disposition).
- ``sky``       — Sky SKYxxxxx units: 8 A-Z letters mapped from an MD5
  of the MAC (routerkeygen SkyKeygen disposition).
- ``comtrend``  — Spanish WLAN_XXXX/JAZZTEL_XXXX: MD5 over the
  ``bcgbghgg`` magic + MAC prefix + SSID suffix + MAC (published 2010).
- ``eircom``    — Netopia "eircomXXXX XXXX": SHA-1 over the 8-digit
  serial + the published lyric constant, 26-hex WEP-shaped keys.
- ``alice_agpf``— Pirelli Alice-XXXXXXXX: SHA-256 over a 32-byte magic
  + manufacturing serial + MAC -> 24 base-36 chars (white-hats-crew
  2009); the SSID->serial mapping tables are deployment data (the
  routerkeygen alice.xml equivalent) supplied via ``alice_configs``.
- ``mac_full``  — "the key is the MAC" vendors (Cabovisao CVTV,
  Megared, InterCable): full/10-char MAC hex in both cases.

Every generator yields ``(algo_name, candidate_bytes)`` pairs, the shape
the keygen-precompute seam expects (server/jobs.py keygen_precompute);
``vendor_candidates`` dispatches on SSID/BSSID and is the default plug-in.

Fidelity note: these schemes were published as reverse-engineering
results; constants follow the public writeups cited above, reproduced
from their descriptions (this build environment has no network access to
re-verify against the original tools, so the KAT vectors in
tests/test_vendors.py pin THIS implementation against regression rather
than third-party output).  Outputs are cheap *candidates* — the
precompute path verifies every one against the real handshake before
accepting it (web/rkg.php:126 equivalent), so an imperfect generator
costs a few wasted PBKDF2s, never a false accept.
"""

import hashlib
import re

from .imei import imei_candidates

# ---------------------------------------------------------------------------
# Thomson / SpeedTouch (stkeys)

#: SSID prefixes of Thomson-made CPE that used the serial-derived scheme.
THOMSON_SSID_RE = re.compile(
    rb"^(SpeedTouch|Thomson|BigPond|O2Wireless|Orange-|INFINITUM|BBox|"
    rb"DMAX|privat|CYTA|Blink)([0-9A-Fa-f]{6})$"
)
_CODE_CHARS = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"


def _thomson_serial(yy: int, ww: int, code: str) -> bytes:
    """Processed serial hashed by the scheme: CPYYWW + hex(code chars)."""
    return ("CP%02d%02d%s" % (yy, ww, code.encode().hex().upper())).encode()


def thomson_key(serial: bytes):
    """-> (ssid_suffix_hex, key) for one processed serial."""
    d = hashlib.sha1(serial).digest()
    return d[-3:].hex().upper(), d[:5].hex().upper().encode()


def thomson_candidates(ssid_suffix: str, years=range(4, 13), weeks=range(1, 54),
                       device: bool = None):
    """Search the serial space for keys matching an SSID suffix.

    ``ssid_suffix``: the 6 hex chars after the vendor prefix.  Yields the
    default-key candidates (10 uppercase hex chars each).  ``device``:
    force the accelerator sweep on/off (default: on iff a TPU is
    present — the full 9-year space is ~22M SHA-1s, trivial on-device
    and ~30 s in hashlib).
    """
    target = ssid_suffix.upper()
    if device is None:
        try:
            import jax
            device = jax.devices()[0].platform == "tpu"
        except Exception:  # pragma: no cover - jax is a hard dep in-tree
            device = False
    if device:
        yield from _thomson_search_device(target, list(years), list(weeks))
        return
    for yy in years:
        for ww in weeks:
            for a in _CODE_CHARS:
                for b in _CODE_CHARS:
                    for c in _CODE_CHARS:
                        sfx, key = thomson_key(_thomson_serial(yy, ww, a + b + c))
                        if sfx == target:
                            yield key


def _thomson_search_device(target: str, years, weeks, chunk: int = 1 << 20,
                           compress=None):
    """Accelerator sweep: build serial blocks from iota, one SHA-1 each.

    The 12-byte serial fits one padded block, so each candidate costs a
    single compression — the same ops/sha1 primitive the PBKDF2 kernel
    uses, here in its pure-XLA unrolled form (the sweep is a one-shot
    cron job; no Pallas needed to saturate it).
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.sha1 import sha1_compress, sha1_compress_rolled, sha1_init

    if compress is None:
        # The unrolled form is fastest on TPU; XLA:CPU takes minutes to
        # compile 80 straight-line rounds, so fall back to the rolled one.
        on_tpu = jax.devices()[0].platform == "tpu"
        compress = sha1_compress if on_tpu else sha1_compress_rolled

    yw = [(yy, ww) for yy in years for ww in weeks]
    ncodes = 36 ** 3
    tgt = int(target, 16)

    @functools.partial(jax.jit, static_argnames=("n",))
    def sweep(base, yw_arr, n):
        i = base + jnp.arange(n, dtype=jnp.uint32)
        code = i % ncodes
        ywi = (i // ncodes).astype(jnp.int32)
        yy = yw_arr[ywi, 0]
        ww = yw_arr[ywi, 1]

        def ascii36(v):  # 0..35 -> ASCII of the code char
            return jnp.where(v < 10, v + 48, v + 55).astype(jnp.uint32)

        def hexd(v):  # 0..15 -> ASCII of an uppercase hex digit
            return jnp.where(v < 10, v + 48, v + 55).astype(jnp.uint32)

        c = [ascii36(code // 36 ** (2 - k) % 36) for k in range(3)]
        # serial chars: 'C' 'P' y1 y2 w1 w2 then hex-expansion of c0 c1 c2
        ch = [
            jnp.full_like(i, 67), jnp.full_like(i, 80),
            yy // 10 + 48, yy % 10 + 48, ww // 10 + 48, ww % 10 + 48,
            hexd(c[0] >> 4), hexd(c[0] & 15),
            hexd(c[1] >> 4), hexd(c[1] & 15),
            hexd(c[2] >> 4), hexd(c[2] & 15),
        ]
        w0 = (ch[0] << 24) | (ch[1] << 16) | (ch[2] << 8) | ch[3]
        w1 = (ch[4] << 24) | (ch[5] << 16) | (ch[6] << 8) | ch[7]
        w2 = (ch[8] << 24) | (ch[9] << 16) | (ch[10] << 8) | ch[11]
        block = [w0, w1, w2, 0x80000000] + [0] * 11 + [12 * 8]
        st = compress(sha1_init(i.shape), block)
        hit = (st[4] & jnp.uint32(0xFFFFFF)) == jnp.uint32(tgt)
        return hit, st[0], st[1]

    yw_arr = jnp.asarray(np.array(yw, dtype=np.uint32))
    total = len(yw) * ncodes
    for base in range(0, total, chunk):
        n = min(chunk, total - base)
        hit, s0, s1 = sweep(jnp.uint32(base), yw_arr, n)
        idx = np.flatnonzero(np.asarray(hit))
        if idx.size:
            h0 = np.asarray(s0)[idx]
            h1 = np.asarray(s1)[idx]
            for a, b in zip(h0, h1):
                yield ("%08X%02X" % (int(a), int(b) >> 24)).encode()


# ---------------------------------------------------------------------------
# Belkin (per-nibble MAC substitution, Jakob Lell 2012)

def _mac_neighbours(bssid: bytes, offsets=(0, 1, -1)):
    """Uppercase 12-hex MAC strings for BSSID and its radio/WAN
    neighbours — the shared sweep of every MAC-derived family (vendors
    print the key from a MAC one or two off the beacon BSSID)."""
    base = int.from_bytes(bssid, "big")
    for off in offsets:
        yield format((base + off) & 0xFFFFFFFFFFFF, "012X")


BELKIN_SSID_RE = re.compile(rb"^(?:Belkin[._]|belkin\.)([0-9A-Fa-f]{3,6})$")
_BELKIN_CHARSET = "024613578ACE9BDF"
_BELKIN_ORDER = (6, 2, 3, 8, 5, 1, 7, 4)  # 1-indexed into the last 8 nibbles


def belkin_keys(bssid: bytes):
    """Default keys for the WAN-MAC offsets Belkin units are seen with."""
    for mac in _mac_neighbours(bssid, offsets=(0, 1, 2, -1)):
        tail = mac[4:]
        yield "".join(
            _BELKIN_CHARSET[int(tail[p - 1], 16)] for p in _BELKIN_ORDER
        ).encode()


# ---------------------------------------------------------------------------
# Arcadyan / Vodafone EasyBox (Viehböck 2012)

EASYBOX_SSID_RE = re.compile(rb"^(?:EasyBox-|Arcor-|Vodafone)[0-9A-Fa-f]{6}$")


def easybox_keys(bssid: bytes):
    """9-hex-digit default key mixed from the MAC's last two bytes."""
    for mac in _mac_neighbours(bssid, offsets=(0, 1)):
        tail = mac[8:]
        sn = "%05d" % int(tail, 16)
        d = [int(ch) for ch in sn]
        h = [int(ch, 16) for ch in tail]
        k1 = (d[0] + d[1] + h[2] + h[3]) % 16
        k2 = (d[2] + d[3] + h[0] + h[1]) % 16
        digits = (
            k1 ^ d[4], k2 ^ h[1], h[2] ^ d[4],
            k1 ^ d[3], k2 ^ h[2], h[3] ^ d[1],
            k1 ^ d[2], k2 ^ h[3], k1 ^ k2,
        )
        yield "".join("%X" % (v & 0xF) for v in digits).encode()


# ---------------------------------------------------------------------------
# MAC-printed-on-the-label family (Tenda and friends)

MAC_TAIL_SSID_RE = re.compile(rb"^(?:Tenda_|TP-LINK_|FAST_|MERCURY_)", re.I)


def mac_tail_keys(bssid: bytes):
    """Decimalized-MAC default keys (BSSID±1, 8- and 10-digit widths).

    The hex-tail variants of this family are already produced by the
    Single generator that precompute runs first (server/jobs.py
    single_mode_candidates), so only the decimalizations are emitted here
    — duplicates would cost a second PBKDF2 verify each.
    """
    base = int.from_bytes(bssid, "big")
    for off in (0, 1, -1):
        v = (base + off) & 0xFFFFFFFFFFFF
        yield str(v % 10 ** 8).zfill(8).encode()
        yield str(v % 10 ** 10).zfill(10).encode()


# ---------------------------------------------------------------------------
# Zyxel (MD5 of the uppercase MAC string; routerkeygen's ZyxelKeygen
# disposition for ZyXEL-branded CPE)

ZYXEL_SSID_RE = re.compile(rb"^ZyXEL[0-9A-Fa-f]{6}$", re.I)


def zyxel_keys(bssid: bytes):
    """First 20 uppercase hex chars of MD5 over the uppercase MAC hex
    string, for BSSID and its radio/WAN neighbours."""
    for mac in _mac_neighbours(bssid):
        yield hashlib.md5(mac.encode()).hexdigest().upper()[:20].encode()


# ---------------------------------------------------------------------------
# Sky (Sagemcom-era SKYxxxxx: 8 A-Z letters from an MD5 of the MAC;
# routerkeygen's SkyKeygen disposition)

SKY_SSID_RE = re.compile(rb"^SKY[0-9]{5}$")


def sky_keys(bssid: bytes):
    for mac in _mac_neighbours(bssid):
        d = hashlib.md5(mac.encode()).digest()
        yield bytes(65 + b % 26 for b in d[:8])


# ---------------------------------------------------------------------------
# Comtrend (the Spanish WLAN_XXXX / JAZZTEL_XXXX scheme, published 2010:
# MD5 over the "bcgbghgg" magic + MAC prefix + SSID suffix + full MAC)

COMTREND_SSID_RE = re.compile(rb"^(?:WLAN|JAZZTEL)_([0-9A-Fa-f]{4})$")
_COMTREND_MAGIC = "bcgbghgg"


def comtrend_keys(bssid: bytes, ssid_suffix: str):
    suffix = ssid_suffix.upper()
    for mac in _mac_neighbours(bssid):
        seed = _COMTREND_MAGIC + mac[:8] + suffix + mac
        yield hashlib.md5(seed.encode()).hexdigest()[:20].encode()


# ---------------------------------------------------------------------------
# Eircom (Netopia-era "eircomXXXX XXXX": SHA-1 over the serial digits
# concatenated with the published lyric constant; WEP-shaped 26-hex
# keys, emitted because the precompute path verifies every candidate)

EIRCOM_SSID_RE = re.compile(rb"^eircom[0-9]{4} ?[0-9]{4}$")
_EIRCOM_SALT = "Although your world wonders me, "


def eircom_keys(bssid: bytes):
    mac24 = int.from_bytes(bssid[3:], "big")
    for off in (0, 1, -1):
        serial = "%08d" % ((mac24 + off) & 0xFFFFFF)
        digest = hashlib.sha1((serial + _EIRCOM_SALT).encode()).hexdigest()
        yield digest[:26].encode()


# ---------------------------------------------------------------------------
# Alice AGPF (Pirelli "Alice-XXXXXXXX", the 2009 white-hats-crew
# derivation: SHA-256 over a fixed 32-byte magic + manufacturing serial
# + MAC, mapped to 24 lowercase base-36 chars)

ALICE_SSID_RE = re.compile(rb"^Alice-([0-9]{8})$")
_ALICE_MAGIC = bytes((
    0x64, 0xC6, 0xDD, 0xE3, 0xE5, 0x79, 0xB6, 0xD9, 0x86, 0x96, 0x8D, 0x34,
    0x45, 0xD2, 0x3B, 0x15, 0xCA, 0xAF, 0x12, 0x84, 0x02, 0xAC, 0x56, 0x00,
    0x05, 0xCE, 0x20, 0x75, 0x91, 0x3F, 0xDC, 0xE8,
))
_ALICE_CHARSET = "0123456789abcdefghijklmnopqrstuvwxyz"

#: SSID-series -> serial-derivation entries (the deployment data
#: routerkeygen ships as alice.xml): {"96": [{"sn": "69102", "q": ..,
#: "k": ..}], ...}.  The mapping tables are ISP data, not algorithm;
#: deployments supply their own via vendor_candidates(alice_configs=...).
ALICE_CONFIGS = {}


def alice_agpf_key(serial: str, mac: bytes, magic: bytes = None,
                   charset: str = None, take: int = 24) -> bytes:
    """The core AGPF derivation for one (serial, MAC) pair.

    ``serial``: the full manufacturing serial, e.g. ``69102X0013305``.
    ``magic``/``charset``/``take`` default to the published Alice-Italy
    constants; the AGPF siblings that reuse this structure with other
    vendor seeds supply theirs via a deployment pack
    (gen/vendor_data.py ``serial_hash`` entries).
    """
    magic = _ALICE_MAGIC if magic is None else magic
    charset = _ALICE_CHARSET if charset is None else charset
    d = hashlib.sha256(magic + serial.encode() + mac).digest()
    return "".join(charset[b % len(charset)] for b in d[:take]).encode()


def alice_agpf_keys(ssid_digits: str, bssid: bytes, configs=None,
                    magic: bytes = None, charset: str = None,
                    take: int = 24):
    """Candidates for an Alice-XXXXXXXX SSID given serial-mapping config.

    Each config entry maps the SSID number S to a serial via
    ``sn + 'X' + %07d((S - q) / k)`` — the published AGPF structure.
    Entries whose (S - q) is not divisible by k do not apply.
    """
    configs = ALICE_CONFIGS if configs is None else configs
    s = int(ssid_digits)
    for entry in configs.get(ssid_digits[:2], []):
        q, k = entry["q"], entry["k"]
        # s < q would format a negative quotient into the serial — no
        # such device exists; skip rather than emit garbage candidates.
        if k <= 0 or s < q or (s - q) % k:
            continue
        serial = "%sX%07d" % (entry["sn"], (s - q) // k)
        base = int.from_bytes(bssid, "big")
        for off in (0, 1, -1):
            mac = ((base + off) & 0xFFFFFFFFFFFF).to_bytes(6, "big")
            yield alice_agpf_key(serial, mac, magic=magic,
                                 charset=charset, take=take)


# ---------------------------------------------------------------------------
# Full-MAC-as-key family (Cabovisao/Megared-style: the printed default
# key IS the device MAC, or its 10-char tail)

MAC_FULL_SSID_RE = re.compile(rb"^(?:CVTV|Megared|INTERCABLE)", re.I)


def mac_full_keys(bssid: bytes):
    seen = set()
    for umac in _mac_neighbours(bssid):
        mac = umac.lower()
        for cand in (mac.encode(), umac.encode(),
                     mac[2:].encode(), umac[2:].encode()):
            # all-decimal MACs make the case variants identical; each
            # duplicate would cost a wasted PBKDF2 verify downstream
            if cand not in seen:
                seen.add(cand)
                yield cand


# ---------------------------------------------------------------------------
# Mobile-hotspot IMEI keys (imeigen-equivalent)

HOTSPOT_SSID_RE = re.compile(
    rb"^(AndroidAP|MIFI|MiFi|4G-Gateway|4G Wi-?Fi|Alcatel|Franklin|"
    rb"Jetpack|Verizon-|ZTE|Coolpad|Moxee)", re.I,
)
#: A few common TACs per hotspot family keeps the sweep bounded; real
#: deployments extend this via the extra_generators seam.
HOTSPOT_TACS = ("35684610", "35404311", "86723604")


#: routers that print their WPS PIN as the default WPA key (TP-LINK WR
#: era, some D-Link/Netgear) — the SSID families where an 8-digit PIN
#: candidate is worth the PBKDF2
WPS_PIN_SSID_RE = re.compile(
    rb"^(?:TP-LINK_|D-?Link[-_]|NETGEAR[0-9]{2}$)", re.I
)

#: factory-default PINs shipped verbatim on many devices
WPS_STATIC_PINS = (b"12345670", b"00000000", b"12345678", b"88888888")


def wps_checksum_digit(pin7: int) -> int:
    """The WPS checksum digit (WSC spec §7.4.1): weights 3,1,3,1,...
    over the 7 data digits, most-significant first."""
    accum = 0
    t = pin7
    while t:
        accum += 3 * (t % 10)
        t //= 10
        accum += t % 10
        t //= 10
    return (10 - accum % 10) % 10


def wps_pin_keys(bssid: bytes):
    """Default-PIN candidates for the "WPS PIN is the WPA key" family.

    The widely shipped derivation (Viehböck's WPS attack writeups, and
    routerkeygen's ComputePIN dispositions): the 7 data digits are the
    NIC-specific last 24 bits of the MAC modulo 10^7, completed with the
    WSC checksum digit; BSSID±1 covers the radio/WAN MAC offset, and a
    handful of factory-static PINs ride along.
    """
    base = int.from_bytes(bssid[3:], "big")
    for delta in (0, 1, -1):
        pin7 = ((base + delta) & 0xFFFFFF) % 10_000_000
        yield b"%07d%d" % (pin7, wps_checksum_digit(pin7))
    yield from WPS_STATIC_PINS


def imei_hotspot_keys(limit_per_tac: int = 64):
    """A bounded slice of IMEI-derived keys for the precompute path.

    The full 10^6-serial sweep per TAC belongs to the client's targeted
    pass-1 (fed to the TPU engine); precompute only tries the low-serial
    slice where factory units cluster.
    """
    for tac in HOTSPOT_TACS:
        for i, cand in enumerate(imei_candidates(tac)):
            if i >= limit_per_tac:
                break
            yield cand


# ---------------------------------------------------------------------------
# Dispatch

def vendor_candidates(bssid: bytes, ssid: bytes, thomson_kw=None,
                      alice_configs=None, imei_limit: int = None):
    """The default ``extra_generators`` plug-in for keygen precompute.

    Yields ``(algo, candidate)`` pairs for every vendor family whose
    SSID/BSSID fingerprint matches (routerkeygen-cli dispatch equivalent,
    web/rkg.php:109).  ``imei_limit`` widens (or narrows) the per-TAC
    IMEI serial slice — the batched server pre-crack path absorbs a much
    deeper sweep than the per-candidate host loop the default budget was
    sized for.
    """
    m = THOMSON_SSID_RE.match(ssid)
    if m:
        # The serial sweep is ~22M SHA-1s: sub-second on an accelerator,
        # ~30 s/net in hashlib — so without an explicit thomson_kw budget
        # it only runs when an accelerator is present, keeping the cron
        # job bounded on CPU-only server hosts.
        kw = thomson_kw
        if kw is None:
            try:
                import jax
                on_acc = jax.devices()[0].platform == "tpu"
            except Exception:  # pragma: no cover
                on_acc = False
            kw = {} if on_acc else None
        if kw is not None:
            for key in thomson_candidates(m.group(2).decode(), **kw):
                yield ("Thomson", key)
    if BELKIN_SSID_RE.match(ssid):
        for key in belkin_keys(bssid):
            yield ("Belkin", key)
    if EASYBOX_SSID_RE.match(ssid):
        for key in easybox_keys(bssid):
            yield ("EasyBox", key)
    if MAC_TAIL_SSID_RE.match(ssid):
        for key in mac_tail_keys(bssid):
            yield ("MacTail", key)
    if WPS_PIN_SSID_RE.match(ssid):
        for key in wps_pin_keys(bssid):
            yield ("WPSPin", key)
    if HOTSPOT_SSID_RE.match(ssid):
        for key in (imei_hotspot_keys() if imei_limit is None
                    else imei_hotspot_keys(limit_per_tac=imei_limit)):
            yield ("IMEI", key)
    if ZYXEL_SSID_RE.match(ssid):
        for key in zyxel_keys(bssid):
            yield ("Zyxel", key)
    if SKY_SSID_RE.match(ssid):
        for key in sky_keys(bssid):
            yield ("Sky", key)
    m = COMTREND_SSID_RE.match(ssid)
    if m:
        for key in comtrend_keys(bssid, m.group(1).decode()):
            yield ("Comtrend", key)
    if EIRCOM_SSID_RE.match(ssid):
        for key in eircom_keys(bssid):
            yield ("Eircom", key)
    m = ALICE_SSID_RE.match(ssid)
    if m:
        for key in alice_agpf_keys(m.group(1).decode(), bssid,
                                   configs=alice_configs):
            yield ("AliceAGPF", key)
    if MAC_FULL_SSID_RE.match(ssid):
        for key in mac_full_keys(bssid):
            yield ("MacFull", key)
