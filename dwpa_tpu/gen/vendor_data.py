"""Deployment-data vendor keygen families (routerkeygen data packs).

routerkeygen-cli bundles ISP-specific constant tables (alice.xml, magic
seeds, serial maps) next to its algorithms; the reference server just
invokes the binary (web/rkg.php:109).  Most routerkeygen families not
implemented natively in ``gen/vendors.py`` differ from an implemented
one only in DATA — a magic string, a charset, a MAC-substring recipe, a
serial table — which this offline build cannot reproduce faithfully
(fabricated constants would emit garbage candidates and waste verify
PBKDF2s).  This module implements the four algorithm ARCHETYPES those
families reduce to, driven entirely by a deployment-supplied JSON pack,
so an operator holding the real tables gets the remaining routerkeygen
surface with zero code changes.  PARITY.md carries the per-family
classification (implemented / needs-data / obsolete).

Pack format — JSON ``{"families": [entry, ...]}``; every entry has:

- ``name``     — algo label recorded in the ``rkg`` table;
- ``ssid_re``  — regex matched (``re.match``) against the SSID bytes;
  capture groups are referenced by hash_map inputs as ``@ssid_group1``…;
- ``kind`` + kind-specific fields:

``fixed``      — ``{"keys": ["...", ...]}``: constant factory keys
  (the Andared-style single-key networks); every key must be a
  non-empty string (validated at load).
``mac_map``    — ``{"slices": [[s, e], ...], "case": "lower"|"upper",
  "prefix": "", "suffix": "", "offsets": [0, 1, -1]}``: the key is a
  concatenation of substrings of the 12-char MAC hex (Megared/Conn/
  InterCable archetype, BSSID neighbourhood swept via ``offsets``).
``hash_map``   — ``{"hash": "md5"|"sha1"|"sha256",
  "input": [token, ...], "skip": 0, "take": N,
  "charset": "hex"|"HEX"|"<alphabet>", "group_bits": 0,
  "offsets": [0]}``: digest over the concatenated input tokens,
  rendered as hex, by indexing an alphabet with each digest byte, or —
  with ``group_bits`` — by consuming the digest as a bitstream in
  N-bit groups (the 5-bit base-32 rendering several ISP schemes use)
  and indexing the alphabet with each group (the Zyxel/Sky/Fastweb/
  Arnet/Meo archetype).  Tokens: a literal string, ``@mac``/``@MAC``
  (hex str), ``@mac_bytes`` (raw 6 bytes), ``@ssid``, ``@ssid_groupN``,
  or ``hex:<bytes in hex>`` for binary magics.
``serial_hash``— ``{"series": {"NN": [{"sn": .., "q": .., "k": ..},
  ...]}, "magic_hex": .., "charset": .., "take": ..}``: the Alice-AGPF
  serial-table scheme (gen/vendors.alice_agpf_keys) with per-pack
  magic/charset overrides — covers the AGPF siblings that reuse the
  structure with different constants.  Its ``ssid_re`` must carry
  EXACTLY one mandatory capture group (the serial digits fed to the
  scheme); optional or alternated groups are rejected at load.

Every candidate is still verified against the real handshake by keygen
precompute (server/jobs.py) before acceptance, so a bad pack costs
wasted PBKDF2s, never a false accept.
"""

import hashlib
import json
import re

try:  # the sre parse tree moved in 3.11+; same structure either way
    from re import _constants as sre_constants, _parser as sre_parse
except ImportError:  # pragma: no cover - 3.10 spelling
    import sre_constants
    import sre_parse

_HASHES = {"md5": hashlib.md5, "sha1": hashlib.sha1, "sha256": hashlib.sha256}


def _mandatory_group_nums(parsed) -> set:
    """Group numbers that participate in EVERY match of the parsed
    pattern: not under a ``{0,n}``/``?``/``*`` repeat and present in all
    branches of every alternation.  A group outside this set can be
    ``None`` on a successful match — the ``AttributeError`` landmine
    ``serial_hash`` validation exists to disarm."""
    out = set()
    for op, av in parsed:
        if op is sre_constants.SUBPATTERN:
            group, _af, _df, sub = av
            if group:
                out.add(group)
            out |= _mandatory_group_nums(sub)
        elif op in (sre_constants.MAX_REPEAT, sre_constants.MIN_REPEAT):
            lo, _hi, sub = av
            if lo >= 1:
                out |= _mandatory_group_nums(sub)
        elif op is sre_constants.BRANCH:
            sets = [_mandatory_group_nums(b) for b in av[1]]
            common = sets[0]
            for s in sets[1:]:
                common = common & s
            out |= common
    return out


def _mac_neighbourhood(bssid: bytes, offsets):
    base = int.from_bytes(bssid, "big")
    for off in offsets:
        yield ((base + off) & 0xFFFFFFFFFFFF).to_bytes(6, "big")


def _resolve_token(tok: str, mac: bytes, ssid: bytes, m) -> bytes:
    if tok == "@mac":
        return mac.hex().encode()
    if tok == "@MAC":
        return mac.hex().upper().encode()
    if tok == "@mac_bytes":
        return mac
    if tok == "@ssid":
        return ssid
    if tok.startswith("@ssid_group"):
        return m.group(int(tok[len("@ssid_group"):]))
    if tok.startswith("hex:"):
        return bytes.fromhex(tok[4:])
    return tok.encode()


class _Family:
    """One compiled pack entry: a ``(bssid, ssid) -> (algo, cand)``
    generator (the ``extra_generators`` shape keygen precompute takes)."""

    #: fields a kind cannot run without — checked at load so a typo'd
    #: pack fails immediately, not silently mid-cron
    _REQUIRED = {"fixed": ("keys",), "mac_map": ("slices",),
                 "hash_map": ("input", "take"), "serial_hash": ("series",)}

    def __init__(self, entry: dict):
        self.name = entry["name"]
        self.ssid_re = re.compile(entry["ssid_re"].encode())
        self.kind = entry["kind"]
        self.entry = entry
        if self.kind not in self._REQUIRED:
            raise ValueError(f"unknown vendor-pack kind {self.kind!r}")
        for field in self._REQUIRED[self.kind]:
            if field not in entry:
                raise KeyError(field)
        # Data validation at LOAD: the smoke run below only executes an
        # entry whose regex happens to match the dummy SSID, so every
        # value that could raise mid-cron is checked here instead.
        if self.kind == "hash_map":
            if entry.get("hash", "md5") not in _HASHES:
                raise ValueError(f"unknown hash {entry.get('hash')!r}")
            groups = re.compile(entry["ssid_re"]).groups
            for tok in entry["input"]:
                if tok.startswith("hex:"):
                    bytes.fromhex(tok[4:])
                elif tok.startswith("@ssid_group"):
                    if int(tok[len("@ssid_group"):]) > groups:
                        raise ValueError(f"{tok}: ssid_re has {groups} groups")
                elif tok.startswith("@") and tok not in (
                        "@mac", "@MAC", "@mac_bytes", "@ssid"):
                    raise ValueError(f"unknown input token {tok!r}")
            if not (0 <= int(entry.get("group_bits", 0)) <= 16):
                raise ValueError("group_bits out of range")
        elif self.kind == "mac_map":
            for s, t in entry["slices"]:
                if not 0 <= int(s) <= int(t) <= 12:
                    raise ValueError(f"mac slice [{s}, {t}] out of range")
        elif self.kind == "fixed":
            # mirror hash_map's eager posture: a non-string (JSON number,
            # null, nested list) or empty key would TypeError on .encode()
            # or emit an empty candidate on the first matching net mid-cron
            if not isinstance(entry["keys"], (list, tuple)) or not entry["keys"]:
                raise ValueError("fixed 'keys' must be a non-empty list")
            for k in entry["keys"]:
                if not isinstance(k, str) or not k:
                    raise ValueError(
                        f"fixed key {k!r} must be a non-empty string")
        elif self.kind == "serial_hash":
            # __call__ feeds m.group(1) to the serial scheme, so the
            # regex must GUARANTEE that group exists on every match — an
            # optional/alternated group would return None and raise
            # AttributeError on .decode() mid-cron instead of at load
            if (self.ssid_re.groups != 1
                    or 1 not in _mandatory_group_nums(
                        sre_parse.parse(entry["ssid_re"]))):
                raise ValueError(
                    "serial_hash ssid_re must have exactly one mandatory "
                    f"capture group (the serial digits): "
                    f"{entry['ssid_re']!r}")
            if "magic_hex" in entry:
                bytes.fromhex(entry["magic_hex"])
            for series in entry["series"].values():
                for cfg in series:
                    cfg["sn"], int(cfg["q"]), int(cfg["k"])

    def __call__(self, bssid: bytes, ssid: bytes):
        m = self.ssid_re.match(ssid)
        if not m:
            return
        e = self.entry
        if self.kind == "fixed":
            for k in e["keys"]:
                yield (self.name, k.encode())
        elif self.kind == "mac_map":
            for mac in _mac_neighbourhood(bssid, e.get("offsets", (0,))):
                h = mac.hex()
                if e.get("case", "lower") == "upper":
                    h = h.upper()
                body = "".join(h[s:t] for s, t in e["slices"])
                yield (self.name,
                       (e.get("prefix", "") + body + e.get("suffix", ""))
                       .encode())
        elif self.kind == "hash_map":
            fn = _HASHES[e.get("hash", "md5")]
            for mac in _mac_neighbourhood(bssid, e.get("offsets", (0,))):
                data = b"".join(
                    _resolve_token(t, mac, ssid, m) for t in e["input"]
                )
                digest = fn(data).digest()[e.get("skip", 0):]
                cs = e.get("charset", "hex")
                gb = int(e.get("group_bits", 0))
                if cs == "hex":
                    key = digest.hex()[: e["take"]]
                elif cs == "HEX":
                    key = digest.hex().upper()[: e["take"]]
                elif gb:
                    # bitstream rendering: successive gb-bit groups
                    # (MSB-first) index the alphabet
                    stream = int.from_bytes(digest, "big")
                    nbits = len(digest) * 8
                    key = "".join(
                        cs[((stream >> (nbits - gb * (i + 1)))
                            & ((1 << gb) - 1)) % len(cs)]
                        for i in range(min(e["take"], nbits // gb))
                    )
                else:
                    key = "".join(cs[b % len(cs)]
                                  for b in digest[: e["take"]])
                yield (self.name, key.encode())
        elif self.kind == "serial_hash":
            from .vendors import alice_agpf_keys

            # the single mandatory capture group (validated at load)
            # carries the serial digits
            digits = m.group(1).decode()
            magic = bytes.fromhex(e["magic_hex"]) if "magic_hex" in e else None
            for key in alice_agpf_keys(
                digits, bssid, configs=e["series"], magic=magic,
                charset=e.get("charset"), take=e.get("take", 24),
            ):
                yield (self.name, key)


def load_vendor_pack(source):
    """``source``: a path to a JSON pack, or an already-parsed dict.
    Returns the list of generator callables, validated eagerly (a typo'd
    pack must fail at load, not silently yield nothing mid-cron)."""
    if isinstance(source, (str, bytes)):
        with open(source) as f:
            source = json.load(f)
    fams = [_Family(e) for e in source.get("families", [])]
    for f in fams:  # eager smoke-validation against a dummy net
        list(f(b"\x00\x11\x22\x33\x44\x55", b"__pack_validation__"))
    return fams
