"""Streaming wordlist reading (plain or gzip) with md5 integrity checks.

Mirrors the reference client's dictionary handling: dicts arrive as
``.txt.gz`` files whose md5 must match the server's ``dicts.dhash``
(help_crack/help_crack.py:533-534); words are one candidate per line.
Reading is chunked so multi-GB dictionaries never fully materialize —
the host stays ahead of the device by yielding fixed-size batches.
"""

import gzip
import hashlib
import io


def md5_file(path: str, chunk: int = 1 << 20) -> str:
    """Hex md5 of a file (the reference's dict integrity check)."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return h.hexdigest()
            h.update(b)


class DictStream:
    """Iterate candidate byte-strings from a wordlist file or fileobj.

    Transparently gunzips (by magic, like the reference's valid_cap gz
    handling, web/common.php:454-456).  Strips line endings only; interior
    whitespace is significant.  ``skip``/``limit`` support keyspace
    slicing for resume.
    """

    def __init__(self, source, skip: int = 0, limit: int = None):
        self.source = source
        self.skip = skip
        self.limit = limit

    def _open(self):
        """-> (fileobj, owned_raw) — only close files this stream opened
        itself, so a caller-supplied fileobj stays usable for re-iteration
        (seekable sources are rewound instead)."""
        if isinstance(self.source, (str, bytes)):
            f = open(self.source, "rb")
            owns = True
        else:
            f = self.source
            owns = False
            if getattr(f, "seekable", lambda: False)():
                f.seek(0)
        # Sniff gzip on any peekable or seekable object, not just
        # BufferedReader.
        head = b""
        if hasattr(f, "peek"):
            head = f.peek(2)[:2]
        elif getattr(f, "seekable", lambda: False)():
            head = f.read(2)
            f.seek(0)
        if head == b"\x1f\x8b" or (
            isinstance(self.source, (str, bytes))
            and str(self.source).endswith(".gz")
        ):
            return gzip.open(f), (f if owns else None)
        return f, (f if owns else None)

    #: decompressed bytes per read — one gunzip call amortized over
    #: thousands of lines instead of the line iterator's per-line trips
    #: through the gzip object (bench: host_feed.dictstream_words_per_s)
    CHUNK = 1 << 18

    def __iter__(self):
        n = 0
        f, owned_raw = self._open()
        try:
            # Chunked read + manual b"\n" split with a carry for the
            # partial tail.  Semantics are bit-identical to iterating
            # the binary fileobj line-by-line: lines split on b"\n"
            # ONLY (a lone \r stays inside its line), ``skip`` counts
            # line indices INCLUDING blank lines, ``limit`` counts
            # yielded words, trailing \r/\n runs are stripped, and a
            # final line without a newline still counts.
            skip, limit = self.skip, self.limit
            i = 0
            carry = b""
            while True:
                chunk = f.read(self.CHUNK)
                if not chunk:
                    break
                if carry:
                    chunk = carry + chunk
                lines = chunk.split(b"\n")
                carry = lines.pop()
                for line in lines:
                    if i < skip:
                        i += 1
                        continue
                    i += 1
                    if limit is not None and n >= limit:
                        return
                    word = line.rstrip(b"\r\n")
                    if word:
                        n += 1
                        yield word
            if carry and i >= skip and (limit is None or n < limit):
                word = carry.rstrip(b"\r\n")
                if word:
                    yield word
        finally:
            if f is not self.source and f is not owned_raw:
                f.close()  # the gzip wrapper (never closes the underlying)
            if owned_raw is not None:
                owned_raw.close()

    def batches(self, size: int):
        """Yield lists of up to ``size`` words."""
        batch = []
        for w in self:
            batch.append(w)
            if len(batch) == size:
                yield batch
                batch = []
        if batch:
            yield batch
