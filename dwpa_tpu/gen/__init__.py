"""Candidate generators: dict streams, masks, targeted PSK patterns."""

from .dicts import DictStream, md5_file  # noqa: F401
from .mask import mask_keyspace, mask_words  # noqa: F401
from .imei import imei_candidates, luhn_check_digit  # noqa: F401
from .psktool import psk_candidates  # noqa: F401
from .vendors import vendor_candidates  # noqa: F401
