"""Targeted PSK candidates derived from the hash material itself
(hcxpsktool-equivalent).

The reference client falls back to ``hcxpsktool -c help_crack.hash -o
candidates.txt`` (help_crack/help_crack.py:643-646) to derive candidates
from the ESSID/MAC patterns of the target nets.  This generator covers the
same candidate families from first principles:

- the ESSID itself, case-mangled, and with common suffixes;
- digits embedded in the ESSID (zero-padded to the 8-char minimum);
- BSSID/STA-MAC derived strings: hex tails, decimalized, +/-1 neighbors
  (routers frequently default to a key printed from their own MAC);
- WPS-style 8-digit pins seeded from the MAC tail;
- 10-digit phone-number style candidates when the ESSID embeds one.

Everything is deduped and respects the 8..63-byte PSK constraint.
"""

import re


def _mac_variants(mac: bytes):
    h = mac.hex()
    for s in (h, h.upper(), h[4:], h[4:].upper(), h[6:], h[6:].upper()):
        yield s
    asint = int(h, 16)
    for delta in (-1, 1):
        yield format((asint + delta) & 0xFFFFFFFFFFFF, "012x")
    # decimalized tail (zero-padded into pin-like widths)
    tail = int(h[6:], 16)
    for width in (8, 10):
        yield str(tail % 10**width).zfill(width)


def psk_candidates(essid: bytes, mac_ap: bytes = None, mac_sta: bytes = None):
    """Yield deduped candidate PSKs (8..63 bytes) for one net."""
    seen = set()

    def emit(cand):
        if isinstance(cand, str):
            cand = cand.encode("latin1", "ignore")
        if 8 <= len(cand) <= 63 and cand not in seen:
            seen.add(cand)
            return cand
        return None

    out = []

    def push(c):
        e = emit(c)
        if e is not None:
            out.append(e)

    text = essid.decode("latin1")
    for base in (text, text.lower(), text.upper(), text.capitalize()):
        push(base)
        for suffix in ("1", "123", "1234", "12345", "123456", "2024", "2023", "!"):
            push(base + suffix)
    # digit runs inside the ESSID, raw and zero-padded
    for run in re.findall(r"\d{4,}", text):
        push(run)
        push(run.zfill(8))
        push((run * 3)[:8])
    # 10-digit phone-like content (strip separators first)
    stripped = re.sub(r"[^0-9]", "", text)
    if len(stripped) >= 10:
        push(stripped[-10:])
        push(stripped[:10])
    for mac in (mac_ap, mac_sta):
        if not mac:
            continue
        for v in _mac_variants(mac):
            push(v)
            push(text + v[-4:])
    yield from out
