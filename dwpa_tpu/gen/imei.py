"""IMEI-derived default-PSK candidates (imeigen-equivalent).

The reference client shells out to a local ``imeigen <ssid-prefix>`` binary
for ~70 mobile-hotspot SSID prefixes (help_crack/help_crack.py:667-687) —
many LTE hotspots ship with a default WPA key derived from the device IMEI
(typically its last 8 digits).  IMEIs are 15 digits: an 8-digit TAC (type
allocation code, per device model), a 6-digit serial, and a Luhn check
digit — so given a TAC the candidate space is only 10^6 serials, each
completed with the forced check digit.

This reimplements that as a host generator: TAC (or longer IMEI prefix)
-> enumerate the free digits -> append the Luhn digit -> emit the PSK
substring (default: last 8 digits, the common vendor scheme).
"""


def luhn_check_digit(digits: str) -> int:
    """Check digit making ``digits + d`` pass the Luhn mod-10 test."""
    total = 0
    # positions counted from the right of the final number; the check digit
    # itself is position 0, so digits here start at position 1 (doubled).
    for i, ch in enumerate(reversed(digits)):
        d = int(ch)
        if i % 2 == 0:
            d *= 2
            if d > 9:
                d -= 9
        total += d
    return (10 - total % 10) % 10


def imei_candidates(tac: str, psk_digits: int = 8, serial_range=None):
    """Yield PSK candidates for every valid IMEI with the given prefix.

    ``tac``: 8..14 leading digits of the IMEI.  ``serial_range``: optional
    (start, stop) over the free-digit space to shard the sweep.
    """
    tac = "".join(c for c in tac if c.isdigit())
    if not 8 <= len(tac) <= 14:
        raise ValueError("IMEI prefix must be 8..14 digits")
    free = 14 - len(tac)
    start, stop = serial_range or (0, 10 ** free)
    for serial in range(start, stop):
        body = tac + str(serial).zfill(free)
        imei = body + str(luhn_check_digit(body))
        yield imei[-psk_digits:].encode()
