"""Seeded fault schedules.

A :class:`FaultPlan` is consulted once per raw transport call and answers
"which fault, if any, hits this call?".  Decisions come from a private
``random.Random(seed)`` — given the same seed and the same call sequence,
the schedule is bit-identical, and :meth:`FaultPlan.schedule` returns the
full decision log so two runs can be compared outright.

Two scheduling modes compose:

- probabilistic: each call faults with probability ``rate``, the kind
  drawn uniformly from ``kinds``;
- forced: ``force(endpoint, kind)`` queues a fault for the next call to
  that endpoint — how soak tests guarantee "at least one timeout, one
  5xx, one truncated body, one put_work reject" without fishing for a
  lucky seed.
"""

import random

# Transport fault kinds understood by ChaosTransport:
#   drop      connection reset mid-exchange
#   timeout   socket timeout
#   truncate  response body cut in half
#   garbage   response body replaced with non-JSON bytes
#   http_4xx  HTTP 404 (classified permanent)
#   http_429  HTTP 429 + Retry-After (server admission control; transient)
#   http_5xx  HTTP 503 (classified transient)
#   slow      response delayed by ``slow_s``
#   reject    response body replaced with a non-OK refusal
FAULT_KINDS = ("drop", "timeout", "truncate", "garbage",
               "http_4xx", "http_429", "http_5xx", "slow", "reject")

# Kinds safe for blanket probabilistic injection: every one is either
# retried as transient or re-fetched by validation — a schedule of these
# never makes a correct client lose work.  http_429 is transient too but
# deliberately NOT listed: adding a kind here would shift every existing
# seeded schedule's uniform draws — 429s are injected via force() or an
# explicit kinds= override instead.
TRANSIENT_KINDS = ("drop", "timeout", "truncate", "garbage", "http_5xx",
                   "slow")


class FaultPlan:
    def __init__(self, seed: int, rate: float = 0.0, kinds=TRANSIENT_KINDS):
        self.seed = seed
        self.rate = rate
        self.kinds = tuple(kinds)
        self._rng = random.Random(seed)
        self._forced = {}  # endpoint -> [kind, ...] FIFO
        self._log = []     # (call_index, endpoint, kind-or-None)

    def force(self, endpoint: str, kind: str) -> "FaultPlan":
        """Queue ``kind`` for the next call to ``endpoint`` (FIFO when
        called repeatedly).  Chains for terse soak setup."""
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind: {kind!r}")
        self._forced.setdefault(endpoint, []).append(kind)
        return self

    def next_fault(self, endpoint: str):
        """The fault for this call (or None) — one decision per call."""
        queue = self._forced.get(endpoint)
        if queue:
            kind = queue.pop(0)
        elif self.rate and self._rng.random() < self.rate:
            kind = self.kinds[self._rng.randrange(len(self.kinds))]
        else:
            kind = None
        self._log.append((len(self._log), endpoint, kind))
        return kind

    def schedule(self) -> list:
        """The decision log so far: ``[(index, endpoint, kind), ...]``."""
        return list(self._log)

    def kinds_injected(self) -> set:
        return {kind for _, _, kind in self._log if kind is not None}
