"""Fault-injecting and loopback raw transports.

Both classes implement the :meth:`ServerAPI._transport` callable shape —
``(url, body=None, headers=None) -> bytes``, raising the same exception
taxonomy as the real urllib hop — so they slot under the genuine
retry/classification/circuit-breaker stack rather than around it.
"""

import io
import urllib.error
import urllib.parse


class VirtualClock:
    """Deterministic time source: ``sleep`` advances ``now`` instantly.

    Wire ``now`` into ``CircuitBreaker``/``RetryPolicy`` clocks and
    ``sleep`` into ``ServerAPI.sleep`` and a chaos run consumes zero
    wall-clock on backoff while still exercising every cooldown path.
    """

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float):
        self._now += max(0.0, float(seconds))


class ChaosTransport:
    """Wrap a raw transport; inject whatever the plan schedules.

    Pre-exchange kinds (drop/timeout/http_*) raise without touching the
    inner transport — the request never "happened", matching a fault on
    the wire.  Post-exchange kinds (truncate/garbage/reject/slow) let
    the exchange complete and corrupt only the response, matching a
    fault between server and client — the server HAS processed the
    request, which is exactly the double-submission hazard the outbox
    exists for.
    """

    def __init__(self, inner, plan, sleep=None, slow_s: float = 0.05):
        self.inner = inner
        self.plan = plan
        self.sleep = sleep if sleep is not None else (lambda s: None)
        self.slow_s = slow_s

    def __call__(self, url: str, body: bytes = None, headers: dict = None) -> bytes:
        from ..client.protocol import _endpoint_label

        kind = self.plan.next_fault(_endpoint_label(url))
        if kind == "drop":
            raise ConnectionResetError("chaos: connection dropped")
        if kind == "timeout":
            raise TimeoutError("chaos: request timed out")
        if kind == "http_4xx":
            raise urllib.error.HTTPError(
                url, 404, "chaos: injected 404", None, io.BytesIO(b""))
        if kind == "http_429":
            raise urllib.error.HTTPError(
                url, 429, "chaos: injected 429",
                {"Retry-After": "2"}, io.BytesIO(b"overloaded"))
        if kind == "http_5xx":
            raise urllib.error.HTTPError(
                url, 503, "chaos: injected 503", None, io.BytesIO(b""))
        out = self.inner(url, body, headers)
        if kind == "truncate":
            return out[:len(out) // 2]
        if kind == "garbage":
            return b"\x00chaos{not-json"
        if kind == "reject":
            return b"chaos: rejected"
        if kind == "slow":
            self.sleep(self.slow_s)
        return out


class WsgiTransport:
    """Raw transport bridged to an in-process WSGI app (loopback server).

    Unlike the test-suite ``LoopbackAPI`` (which swaps out ``fetch``
    wholesale and with it the whole retry stack), this sits at the
    ``_transport`` seam: non-2xx statuses raise ``urllib.error.HTTPError``
    exactly like the real urllib hop, so classification, backoff and the
    circuit breaker run for real against an in-memory server.
    """

    def __init__(self, app):
        self.app = app
        self.requests = []  # (method, path, query) per exchange

    def __call__(self, url: str, body: bytes = None, headers: dict = None) -> bytes:
        parts = urllib.parse.urlsplit(url)
        method = "POST" if body is not None else "GET"
        environ = {
            "REQUEST_METHOD": method,
            "PATH_INFO": parts.path or "/",
            "QUERY_STRING": parts.query,
            "CONTENT_TYPE": (headers or {}).get("Content-Type", ""),
            "CONTENT_LENGTH": str(len(body or b"")),
            "REMOTE_ADDR": "127.0.0.1",
            "wsgi.input": io.BytesIO(body or b""),
        }
        self.requests.append((method, environ["PATH_INFO"], parts.query))
        captured = {}

        def start_response(status, headers_out):
            captured["status"] = status
            captured["headers"] = dict(headers_out)

        chunks = self.app(environ, start_response)
        data = b"".join(chunks)
        code = int(captured["status"].split()[0])
        if not 200 <= code < 300:
            # headers ride along so Retry-After reaches the retry stack
            raise urllib.error.HTTPError(
                url, code, captured["status"], captured["headers"],
                io.BytesIO(data))
        return data
