"""Deterministic chaos harness: seeded fault schedules for every layer.

PRs 7–9 each grew their own ad-hoc fault plumbing (feed, pmkstore,
dictcache, streams); this package unifies it.  One seeded
:class:`FaultPlan` decides, call by call, which fault (if any) a
:class:`ChaosTransport` injects under the real retry stack, and
``fsfault`` provides torn-write/short-read injection for the journal
formats.  Everything is driven by explicit seeds and virtual clocks —
the same seed replays the identical fault schedule, so a soak failure
is a one-line repro, not a flake.
"""

from .plan import FAULT_KINDS, FaultPlan
from .transport import ChaosTransport, VirtualClock, WsgiTransport
from .fsfault import FsFaultInjector, flip_byte, tear_tail
from .dbfault import (DB_FAULT_KINDS, DbFaultPlan, SimulatedCrash,
                      install as install_db_faults, sweep_invariants)

__all__ = [
    "FAULT_KINDS",
    "FaultPlan",
    "ChaosTransport",
    "VirtualClock",
    "WsgiTransport",
    "FsFaultInjector",
    "flip_byte",
    "tear_tail",
    "DB_FAULT_KINDS",
    "DbFaultPlan",
    "SimulatedCrash",
    "install_db_faults",
    "sweep_invariants",
]
