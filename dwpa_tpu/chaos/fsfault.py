"""Torn-write / short-read filesystem fault injection.

The journal formats (found outbox, PMK store, dict cache, resume file)
all promise "a torn tail is skipped, not fatal".  These helpers produce
the torn states those promises are tested against — deterministic
primitives plus a seeded injector for soak-style sweeps.
"""

import os
import random


def tear_tail(path: str, nbytes: int) -> int:
    """Simulate a power loss mid-append: drop the last ``nbytes`` of the
    file (clamped to its size).  Returns the bytes actually removed."""
    size = os.path.getsize(path)
    cut = min(max(0, int(nbytes)), size)
    with open(path, "r+b") as f:
        f.truncate(size - cut)
    return cut


def flip_byte(path: str, offset: int) -> int:
    """Corrupt one byte in place (negative offsets index from the end)
    — the classic bit-rot a CRC frame must catch.  Returns the absolute
    offset flipped."""
    size = os.path.getsize(path)
    if offset < 0:
        offset += size
    if not 0 <= offset < size:
        raise ValueError(f"offset {offset} outside file of {size} bytes")
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ 0xFF]))
    return offset


def short_read(path: str, nbytes: int) -> bytes:
    """Read as a crashing reader would: only the first ``nbytes``."""
    with open(path, "rb") as f:
        return f.read(max(0, int(nbytes)))


class FsFaultInjector:
    """Seeded sweep driver over the primitives above: each call draws
    its parameters from ``random.Random(seed)``, so a failing sweep
    index is reproducible from the seed alone."""

    def __init__(self, seed: int):
        self.seed = seed
        self._rng = random.Random(seed)
        self.log = []  # (op, path, arg)

    def tear(self, path: str, max_bytes: int = 64) -> int:
        cut = tear_tail(path, self._rng.randint(1, max(1, max_bytes)))
        self.log.append(("tear", path, cut))
        return cut

    def flip(self, path: str) -> int:
        size = os.path.getsize(path)
        if size == 0:
            raise ValueError(f"cannot flip a byte of empty {path}")
        off = flip_byte(path, self._rng.randrange(size))
        self.log.append(("flip", path, off))
        return off
