"""Seeded fault injection at the Database statement seam.

The transport chaos kit (plan.py/transport.py) shakes the client side of
the wire; this module shakes the server's floor.  ``install`` wraps
``Database._exec`` — the single funnel every statement passes through,
inside and outside transactions — so a fault lands at an exact statement
boundary and the ``Database.tx`` machinery has to cope:

- ``op_error``   sqlite3.OperationalError("database is locked"): the
                 classic contention error; the API layer maps it to
                 HTTP 503 + Retry-After.
- ``disk_io``    sqlite3.OperationalError("disk I/O error"): a scarier
                 flavor with the same contract — the open transaction
                 rolls back, no partial multi-statement effect survives.
- ``crash``      simulated process death mid-transaction: the connection
                 is rolled back (what the OS does for us when a process
                 holding an uncommitted sqlite transaction dies) and
                 :class:`SimulatedCrash` propagates.  The Database object
                 stays usable afterwards — "the operator restarted the
                 core" — so soak tests can crash at every statement
                 boundary of every endpoint in one process.

Like :class:`dwpa_tpu.chaos.plan.FaultPlan`, decisions are drawn from a
private ``random.Random(seed)`` keyed by the statement's leading SQL verb
(``insert``/``update``/``select``/...), forced faults queue FIFO per
verb, and ``schedule()`` returns the full decision log so two runs with
the same seed can be compared outright.

``sweep_invariants`` is the post-run judge: given a (re)opened Database
it checks the lease/coverage ledgers for the damage a torn multi-
statement path would leave — orphan in-flight rows, coverage under dead
leases, double-live leases, residue under cracked nets.
"""

import random
import sqlite3

# Statement-seam fault kinds understood by install():
DB_FAULT_KINDS = ("op_error", "disk_io", "crash")


class SimulatedCrash(RuntimeError):
    """The core 'process' died at a statement boundary.

    Deliberately NOT an sqlite3.Error: nothing in the stack may catch
    and absorb it — it must unwind like a kill -9 would.
    """


class DbFaultPlan:
    """Seeded schedule of statement-seam faults (FaultPlan's shape).

    Consulted once per executed statement; the key is the statement's
    lowercased first word, so ``force("insert", "crash")`` crashes the
    core at the next INSERT regardless of which endpoint issues it.
    ``begin``/``commit`` are valid keys too — faulting the commit itself
    is the nastiest torn-write case.
    """

    def __init__(self, seed: int, rate: float = 0.0, kinds=DB_FAULT_KINDS):
        self.seed = seed
        self.rate = rate
        self.kinds = tuple(kinds)
        self._rng = random.Random(seed)
        self._forced = {}  # verb -> [kind, ...] FIFO
        self._at = {}      # stmt_index -> kind
        self._log = []     # (stmt_index, verb, kind-or-None)

    def force(self, verb: str, kind: str) -> "DbFaultPlan":
        if kind not in DB_FAULT_KINDS:
            raise ValueError(f"unknown db fault kind: {kind!r}")
        self._forced.setdefault(verb.lower(), []).append(kind)
        return self

    def force_at(self, index: int, kind: str) -> "DbFaultPlan":
        """Queue ``kind`` for the ``index``-th executed statement
        (0-based) — how the consistency sweep crashes the core at EVERY
        statement boundary of an endpoint, one boundary per run."""
        if kind not in DB_FAULT_KINDS:
            raise ValueError(f"unknown db fault kind: {kind!r}")
        self._at[int(index)] = kind
        return self

    def next_fault(self, verb: str):
        queue = self._forced.get(verb)
        if len(self._log) in self._at:
            kind = self._at.pop(len(self._log))
        elif queue:
            kind = queue.pop(0)
        elif self.rate and self._rng.random() < self.rate:
            kind = self.kinds[self._rng.randrange(len(self.kinds))]
        else:
            kind = None
        self._log.append((len(self._log), verb, kind))
        return kind

    def schedule(self) -> list:
        return list(self._log)

    def kinds_injected(self) -> set:
        return {kind for _, _, kind in self._log if kind is not None}


def install(db, plan):
    """Wrap ``db._exec`` with ``plan``; returns an uninstall closure.

    The fault fires BEFORE the statement executes — the canonical torn
    write: everything earlier in the transaction happened, this
    statement and everything after did not.  On ``crash`` the open
    transaction is rolled back first (a dead process's uncommitted
    transaction never reaches the file) so the same Database object can
    keep serving as "the restarted core".
    """
    inner = db._exec

    def faulted_exec(sql, params=()):
        verb = sql.split(None, 1)[0].lower() if sql else ""
        kind = plan.next_fault(verb)
        if kind == "op_error":
            raise sqlite3.OperationalError("database is locked")
        if kind == "disk_io":
            raise sqlite3.OperationalError("disk I/O error")
        if kind == "crash":
            try:
                db.conn.rollback()
            except sqlite3.Error:
                pass
            db._tx_depth = 0
            raise SimulatedCrash(f"chaos: core died before {verb!r}")
        return inner(sql, params)

    db._exec = faulted_exec

    def uninstall():
        db._exec = inner

    return uninstall


def sweep_invariants(db) -> list:
    """Post-run consistency sweep; returns a list of violation strings
    (empty == healthy).  Every check is a property a torn multi-
    statement path would break and an atomic one cannot:

    - in-flight coverage (n2d.hkey set) must reference a LIVE lease of
      the same epoch — a released/reaped lease with coverage still
      checked out is a double-credit hazard;
    - a live lease must have coverage rows — a lease with nothing
      checked out can never be released by honest work;
    - one live lease per hkey (schema UNIQUE makes this structural, but
      the sweep re-checks in case the schema drifted);
    - cracked nets (n_state=1) must have zero n2d rows — the accept
      cascade deletes them so dict stats never count a solved net.
    """
    bad = []
    for r in db.q(
        """SELECT n.net_id, n.hkey, n.epoch FROM n2d n
           WHERE n.hkey IS NOT NULL AND NOT EXISTS
             (SELECT 1 FROM leases l
              WHERE l.hkey = n.hkey AND l.epoch = n.epoch AND l.state = 0)"""
    ):
        bad.append("orphan in-flight coverage: net %s under hkey %s epoch %s "
                   "has no live lease" % (r["net_id"], r["hkey"], r["epoch"]))
    for r in db.q(
        """SELECT l.hkey FROM leases l
           WHERE l.state = 0 AND NOT EXISTS
             (SELECT 1 FROM n2d n WHERE n.hkey = l.hkey)"""
    ):
        bad.append("hollow live lease: hkey %s holds no coverage" % r["hkey"])
    for r in db.q(
        """SELECT hkey, COUNT(*) c FROM leases
           WHERE state = 0 GROUP BY hkey HAVING c > 1"""
    ):
        bad.append("double-live lease: hkey %s live %d times"
                   % (r["hkey"], r["c"]))
    for r in db.q(
        """SELECT DISTINCT n2d.net_id FROM n2d
           JOIN nets ON nets.net_id = n2d.net_id
           WHERE nets.n_state = 1"""
    ):
        bad.append("coverage residue under cracked net %s" % r["net_id"])
    return bad
