"""Pass-regex -> hashcat-mask compiler (the ``ks`` vertical's front end).

Compiles a deliberately bounded regex dialect to one or more hashcat
masks (``gen/mask.py`` syntax) with custom charsets and exact keyspace
counts, so router-default keyspaces written as regexes become
device-generated mask shards with zero dict bytes on the wire.

Supported dialect — anything else raises :class:`KeyspaceError` (loud
rejection, never a silently truncated keyspace):

- literal characters; ``\\`` escapes a metacharacter (``\\.``, ``\\{``,
  ``\\?``, ``\\\\``, ...);
- character classes ``[a-z0-9_]`` with ranges and singles; negation
  (``[^...]``) is rejected;
- the class escape ``\\d`` (= ``[0-9]``, emitted as hashcat ``?d``;
  other letter escapes are rejected);
- bounded repetition ``{n}`` / ``{m,n}`` and ``?`` (= ``{0,1}``) on the
  preceding atom; each length choice expands to its own mask;
- top-level alternation ``a|b`` — each branch compiles independently
  and the masks concatenate;
- ``^`` / ``$`` anchors at the pattern edges (accepted and dropped:
  matching is whole-password either way).

Rejected outright: unbounded ``*``/``+``, ``.``, groups, backrefs,
lookaround, negated classes, unknown escapes, non-latin1 characters,
masks longer than 63 positions (the ``device_mask_words`` limit), more
than 4 custom charsets per mask, and expansions past ``max_masks``.
A mask keyspace must be finite and exactly enumerable; anything the
dialect cannot express is an explicit error for the ks-table admin.
"""

import itertools

from ..gen.mask import CHARSETS, mask_keyspace

#: expansion bound: one pattern may compile to at most this many masks
MAX_MASKS = 64
#: hashcat mask position bound (device_mask_words packs indices in 63 lanes)
MAX_POSITIONS = 63

_CLASS_ESCAPES = {"d": "0123456789"}

#: builtin hashcat charsets by content (set-compare: class order does not
#: change the language, only the enumeration order)
_BUILTIN = {frozenset(v): "?" + k for k, v in CHARSETS.items()}


class KeyspaceError(ValueError):
    """A pass-regex outside the compilable dialect.  Carries the pattern
    and a human reason so ks-table admin tooling can surface both."""

    def __init__(self, pattern, reason):
        super().__init__(f"pass-regex {pattern!r} not compilable: {reason}")
        self.pattern = pattern
        self.reason = reason


class CompiledMask:
    """One hashcat mask: string + custom charsets + exact keyspace.

    ``custom`` maps slot keys ``"1"``-``"4"`` to latin1 *str* alphabets
    (JSON-safe for the work-unit wire format); :meth:`custom_bytes`
    yields the bytes dict ``gen.mask.parse_mask`` expects.
    """

    __slots__ = ("mask", "custom", "keyspace")

    def __init__(self, mask, custom, keyspace):
        self.mask = mask
        self.custom = custom
        self.keyspace = keyspace

    def custom_bytes(self):
        return {k: v.encode("latin1") for k, v in self.custom.items()}

    def __repr__(self):
        return f"CompiledMask({self.mask!r}, {self.custom!r}, {self.keyspace})"


class CompiledKeyspace:
    """A compiled pass-regex: the mask set plus the summed keyspace."""

    __slots__ = ("pattern", "masks", "keyspace")

    def __init__(self, pattern, masks, keyspace):
        self.pattern = pattern
        self.masks = masks
        self.keyspace = keyspace

    def __repr__(self):
        return (f"CompiledKeyspace({self.pattern!r}, "
                f"{len(self.masks)} masks, {self.keyspace})")


def _parse_class(pattern, branch, i):
    """Parse ``[...]`` starting just past ``[``; returns (alphabet, j)
    with ``j`` past the closing ``]``.  Duplicate members are dropped so
    the keyspace count stays exact."""
    n = len(branch)
    if i < n and branch[i] == "^":
        raise KeyspaceError(pattern, "negated character class [^...]")
    chars, seen = [], set()

    def add(c):
        if c not in seen:
            seen.add(c)
            chars.append(c)

    while i < n and branch[i] != "]":
        ch = branch[i]
        if ch == "\\":
            if i + 1 >= n:
                raise KeyspaceError(pattern, "dangling escape in class")
            esc = branch[i + 1]
            if esc in _CLASS_ESCAPES:
                for c in _CLASS_ESCAPES[esc]:
                    add(c)
                i += 2
                continue
            if esc.isalnum():
                raise KeyspaceError(pattern, f"unsupported escape \\{esc}")
            ch = esc
            i += 2
        else:
            i += 1
        # range a-z: '-' with a live left side and a right side before ']'
        if i + 1 < n and branch[i] == "-" and branch[i + 1] != "]":
            lo, hi = ch, branch[i + 1]
            if hi == "\\":
                raise KeyspaceError(pattern, "escape as range endpoint")
            if ord(lo) > ord(hi):
                raise KeyspaceError(pattern, f"reversed range {lo}-{hi}")
            for o in range(ord(lo), ord(hi) + 1):
                add(chr(o))
            i += 2
        else:
            add(ch)
    if i >= n:
        raise KeyspaceError(pattern, "unterminated character class")
    if not chars:
        raise KeyspaceError(pattern, "empty character class")
    return "".join(chars), i + 1


def _parse_quant(pattern, branch, i):
    """Parse ``{n}`` / ``{m,n}`` starting just past ``{``; returns
    (lo, hi, j).  A ``{`` that is not a bounded quantifier is rejected
    (literal braces must be escaped) — never silently literal."""
    j = branch.find("}", i)
    if j < 0:
        raise KeyspaceError(pattern, "unterminated {...} quantifier")
    body = branch[i:j]
    lo, sep, hi = body.partition(",")
    if not lo.isdigit() or (sep and not hi.isdigit()):
        raise KeyspaceError(pattern, f"malformed quantifier {{{body}}}")
    lo = int(lo)
    hi = int(hi) if sep else lo
    if hi < lo:
        raise KeyspaceError(pattern, f"reversed quantifier {{{body}}}")
    return lo, hi, j + 1


def _parse_branch(pattern, branch):
    """One alternation branch -> list of [alphabet, lo, hi] atoms."""
    atoms = []          # [alphabet, lo, hi]
    quantified = set()  # atom indices that already carry a quantifier
    i, n = 0, len(branch)
    while i < n:
        ch = branch[i]
        if ch == "^" and i == 0:
            i += 1
            continue
        if ch == "$" and i == n - 1:
            i += 1
            continue
        if ch in "*+":
            raise KeyspaceError(pattern,
                                f"unbounded repetition '{ch}' (keyspace "
                                "must be finite; use {m,n})")
        if ch in "()":
            raise KeyspaceError(pattern, "groups are not supported")
        if ch == ".":
            raise KeyspaceError(pattern,
                                "'.' is not supported (spell the class out)")
        if ch in "^$":
            raise KeyspaceError(pattern, f"mid-pattern anchor '{ch}'")
        if ch == "?":
            if not atoms or (len(atoms) - 1) in quantified:
                raise KeyspaceError(pattern, "'?' without a free atom")
            atoms[-1][1] = 0
            quantified.add(len(atoms) - 1)
            i += 1
            continue
        if ch == "{":
            if not atoms or (len(atoms) - 1) in quantified:
                raise KeyspaceError(pattern, "quantifier without a free atom")
            lo, hi, i = _parse_quant(pattern, branch, i + 1)
            atoms[-1][1] = lo
            atoms[-1][2] = hi
            quantified.add(len(atoms) - 1)
            continue
        if ch == "[":
            alpha, i = _parse_class(pattern, branch, i + 1)
        elif ch == "\\":
            if i + 1 >= n:
                raise KeyspaceError(pattern, "dangling escape")
            esc = branch[i + 1]
            if esc in _CLASS_ESCAPES:
                alpha = _CLASS_ESCAPES[esc]
            elif esc.isalnum():
                raise KeyspaceError(pattern, f"unsupported escape \\{esc}")
            else:
                alpha = esc
            i += 2
        else:
            alpha = ch
            i += 1
        for c in alpha:
            if ord(c) > 0xFF:
                raise KeyspaceError(pattern,
                                    f"non-latin1 character {c!r} (PSKs are "
                                    "byte strings)")
        atoms.append([alpha, 1, 1])
    return atoms


def _emit_mask(pattern, positions):
    """Per-position alphabets -> (mask string, custom charset dict)."""
    parts, custom, slots = [], {}, {}
    for alpha in positions:
        if len(alpha) == 1:
            parts.append("??" if alpha == "?" else alpha)
            continue
        tok = _BUILTIN.get(frozenset(alpha.encode("latin1")))
        if tok:
            parts.append(tok)
            continue
        key = frozenset(alpha)
        slot = slots.get(key)
        if slot is None:
            if len(slots) == 4:
                raise KeyspaceError(pattern,
                                    "more than 4 custom charsets in one mask")
            slot = str(len(slots) + 1)
            slots[key] = slot
            custom[slot] = alpha
        parts.append("?" + slot)
    return "".join(parts), custom


def _split_top(pattern):
    """Split on top-level ``|`` only: a ``|`` behind a backslash or
    inside ``[...]`` stays in its branch."""
    parts, cur, depth, i, n = [], [], 0, 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c == "\\" and i + 1 < n:
            cur += [c, pattern[i + 1]]
            i += 2
            continue
        if c == "[":
            depth = 1
        elif c == "]":
            depth = 0
        elif c == "|" and depth == 0:
            parts.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    parts.append("".join(cur))
    return parts


def compile_pass_regex(pattern, max_masks=MAX_MASKS):
    """Compile ``pattern`` to a :class:`CompiledKeyspace` or raise
    :class:`KeyspaceError`.

    Every mask's keyspace is the exact ``mask_keyspace`` count; the
    CompiledKeyspace total is their sum (for well-formed ks rows the
    expansions are disjoint, so the sum is the language size).
    """
    if not isinstance(pattern, str) or not pattern:
        raise KeyspaceError(pattern, "empty pattern")
    masks, seen = [], set()
    for branch in _split_top(pattern):
        if not branch.strip("^$"):
            raise KeyspaceError(pattern, "empty alternation branch")
        atoms = _parse_branch(pattern, branch)
        combos = 1
        for _, lo, hi in atoms:
            combos *= hi - lo + 1
        if len(masks) + combos > max_masks * 4:
            # cheap pre-check so a {0,60}{0,60} pattern cannot make us
            # enumerate millions of combos before the real bound trips
            raise KeyspaceError(pattern,
                                f"expands to more than {max_masks} masks")
        for lengths in itertools.product(*(range(lo, hi + 1)
                                           for _, lo, hi in atoms)):
            positions = []
            for (alpha, _, _), cnt in zip(atoms, lengths):
                positions.extend([alpha] * cnt)
            if not positions:
                raise KeyspaceError(pattern, "matches the empty string")
            if len(positions) > MAX_POSITIONS:
                raise KeyspaceError(pattern,
                                    f"mask longer than {MAX_POSITIONS} "
                                    "positions")
            mask, custom = _emit_mask(pattern, positions)
            key = (mask, tuple(sorted(custom.items())))
            if key in seen:
                continue
            seen.add(key)
            ksize = mask_keyspace(mask, {k: v.encode("latin1")
                                         for k, v in custom.items()})
            masks.append(CompiledMask(mask, custom, ksize))
            if len(masks) > max_masks:
                raise KeyspaceError(pattern,
                                    f"expands to more than {max_masks} masks")
    masks.sort(key=lambda m: (m.keyspace, m.mask))
    return CompiledKeyspace(pattern, tuple(masks),
                            sum(m.keyspace for m in masks))
