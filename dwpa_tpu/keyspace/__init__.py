"""Smart keyspace: the ``ks`` vertical (ROADMAP item 4).

The reference shipped an ``ks`` table mapping ssid-regex -> pass-regex
and never wired it (SURVEY §2.6, TODO:3).  This package makes it real:

- :mod:`.compiler` turns a bounded pass-regex dialect into one or more
  hashcat masks with custom charsets and exact keyspace counts;
- :mod:`.schedule` holds the server-side helpers: the compiled-mask
  cache keyed by pass_regex, ssid-regex matching, shard-coverage math
  over the ``n2m`` table, and the keyspace progress totals exposed by
  maintenance stats and ``observe_metrics``.

Mask shards are the one work-unit species that ships zero candidate
bytes on the wire: the client regenerates the range on device from
``(mask, custom, skip, limit)`` alone (gen/mask.py, PR 11).
"""

from .compiler import (CompiledKeyspace, CompiledMask, KeyspaceError,
                       compile_pass_regex)
from .schedule import (MaskCache, ks_matches, mask_keyspace_totals,
                       next_uncovered)

__all__ = [
    "CompiledKeyspace", "CompiledMask", "KeyspaceError",
    "compile_pass_regex", "MaskCache", "ks_matches",
    "mask_keyspace_totals", "next_uncovered",
]
