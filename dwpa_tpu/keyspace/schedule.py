"""Server-side smart-keyspace helpers.

The scheduler pieces that live above the compiler: the compiled-mask
cache keyed by pass_regex, ssid-regex matching against net ESSIDs,
first-gap coverage math over ``n2m`` shard intervals, and the keyspace
progress totals shared by maintenance stats and ``observe_metrics``.
Pure functions over the Database plus one small cache object — the
ServerCore owns the locking and transactions.
"""

import re
import threading

from ..obs import get_logger
from .compiler import KeyspaceError, compile_pass_regex

_log = get_logger(__name__)


class MaskCache:
    """Compiled-mask cache keyed by pass_regex.

    Compilation is pure and deterministic, so entries never invalidate.
    Uncompilable patterns cache as misses (logged once) so a bad ks row
    costs one compile attempt, not one per get_work — ``ks_add``
    validates loudly at admin time, this cache only has to stay robust
    against rows inserted behind its back.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._ok = {}
        self._bad = set()
        self.compiles = 0  # cold compile count (warm lookups leave it flat)

    def get(self, pass_regex):
        """CompiledKeyspace for ``pass_regex``, or None if uncompilable."""
        with self._lock:
            hit = self._ok.get(pass_regex)
            if hit is not None:
                return hit
            if pass_regex in self._bad:
                return None
        try:
            ck = compile_pass_regex(pass_regex)
        except KeyspaceError as e:
            _log.warning("skipping uncompilable ks row: %s", e)
            with self._lock:
                self._bad.add(pass_regex)
            return None
        with self._lock:
            self.compiles += 1
            self._ok[pass_regex] = ck
        return ck

    def keyspace(self, pass_regex):
        ck = self.get(pass_regex)
        return ck.keyspace if ck is not None else 0


def ks_matches(ks_rows, ssid):
    """The ks rows whose ssid_regex matches ``ssid`` (latin1-decoded,
    ``re.search`` semantics — admins anchor with ``^...$`` when they
    mean whole-ESSID), in the given order.  Rows with a broken
    ssid_regex are skipped (``ks_add`` rejects them up front; this
    guards rows edited behind the API)."""
    text = (ssid.decode("latin1")
            if isinstance(ssid, (bytes, bytearray)) else str(ssid))
    out = []
    for r in ks_rows:
        try:
            if re.search(r["ssid_regex"], text):
                out.append(r)
        except re.error:
            continue
    return out


def next_uncovered(rows, keyspace, span, extra=()):
    """First uncovered ``(skip, limit)`` range of at most ``span``
    candidates, or None when ``[0, keyspace)`` is fully covered.

    ``rows`` are n2m coverage rows (mappings with ``skip``/``span``);
    ``extra`` carries ``(skip, span)`` pairs allocated earlier in the
    same planning pass but not yet inserted.  Reaped ranges are DELETEd
    rather than flagged, so abandoned work reappears here as a gap and
    gets re-issued.
    """
    ivals = sorted([(r["skip"], r["span"]) for r in rows] + list(extra))
    pos = 0
    for s, n in ivals:
        if s > pos:
            return pos, min(span, s - pos)
        pos = max(pos, s + n)
    if pos < keyspace:
        return pos, min(span, keyspace - pos)
    return None


def mask_keyspace_totals(db, cache):
    """(total, done) scheduled-mask keyspace counters.

    ``total``: summed compiled keyspace of every enabled ks row matched
    against every uncracked net's ESSID — the mask analog of
    ``uncracked × Σ wcount``.  ``done``: summed span of completed
    (lease-released, ``hkey IS NULL``) n2m coverage rows; rows of
    cracked nets are deleted by ``_mark_cracked``, so done tracks work
    retired against still-open nets.
    """
    ks_rows = db.q("SELECT * FROM ks WHERE enabled = 1")
    total = 0
    if ks_rows:
        per_ssid = {}
        for net in db.q("SELECT ssid FROM nets WHERE n_state = 0"):
            ssid = net["ssid"]
            if ssid not in per_ssid:
                per_ssid[ssid] = sum(cache.keyspace(r["pass_regex"])
                                     for r in ks_matches(ks_rows, ssid))
            total += per_ssid[ssid]
    done = db.q1(
        "SELECT COALESCE(SUM(span), 0) c FROM n2m WHERE hkey IS NULL")["c"]
    return total, done
