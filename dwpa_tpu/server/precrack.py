"""Batched server-side pre-crack: fused mixed-ESSID PMK derivation.

The reference server gates every new net behind a per-candidate host
PBKDF2 pass (rkg.php) and replays cracked PSKs one ``check_key_m22000``
call at a time (common.php:916-932).  PBKDF2 is ~99% of that cost and
the client stack already knows how to batch it: the per-lane-salt
``pmk_kernel`` (models/m22000.py) derives one PMK per lane for a
*mixed-ESSID* batch, and ``sched.fuse`` owns the static-width packing
discipline.  This module points that machinery at the server's own
workload:

- :class:`PmkBatcher` — derive PMKs for ``(essid, word)`` pairs in
  fused device batches (static widths from ``fused_width``, per-lane
  salts from ``essid_salt_lanes``), backed by the persistent PMK store
  and an in-process memo; a pure-host ``pmk_from_psk`` path covers
  CPU-only deployments and device-ineligible word lengths.  Every PMK
  it returns equals ``pmk_from_psk(word, essid)`` bit-for-bit (the
  device kernel computes the identical integer recurrence), so verdicts
  finished through the oracle are independent of which path derived.
- :func:`verify_batch` — the one entry point every server-side verify
  loop routes through (lint rule DW115 keeps scalar oracle loops out of
  ``dwpa_tpu/server/``): items follow the oracle's ``(line, keys,
  pmk)`` contract, PBKDF2 for all items is batched up front, and each
  verdict is finished by ``oracle.check_key_m22000(..., pmk=...)`` —
  bit-identical to the per-candidate oracle by construction.
- :class:`PrecrackEngine` — the ingestion sweep / recurring job: per
  unprocessed net, collect the vendor packs, IMEI sweeps, Single/
  Pattern mutations, the cracked-corpus dictionary and cross-net
  replay candidates; derive the whole wave as one fused mixed-ESSID
  batch; then demux hits per net inside the existing per-net
  ``Database.tx()`` accept cascade (rkg attempt rows + crack mark +
  ``algo`` release commit together, exactly like ``keygen_precompute``).

Trust boundary: the PMK store and ``seed()`` are caches, not oracles —
a poisoned entry can only make the MIC/PMKID comparison *fail* (costing
a miss); it can never manufacture an accept.  ``put_work``'s verifier
runs store-less, so its verdicts are always bit-identical to the pure
oracle.

This module is the one sanctioned home of the scalar oracle fallback
loop (DW115) and of the store write-back seam outside the engine
(DW108(b) ``PMKSTORE_WRITEBACK_FILES``).
"""

import os
import threading

from ..models import hashline as hl
from ..obs import SpanTracer
from ..oracle import m22000 as oracle
from .db import long2mac

# WPA passphrase bounds (models.m22000.MIN/MAX_PSK_LEN without importing
# the jax-backed module at server start): only these lengths are
# device-packable and store-worthy; anything else host-derives.
_MIN_LEN, _MAX_LEN = 8, 63


def _device_available() -> bool:
    """Device batching is worth it only on a real accelerator — the XLA
    CPU PBKDF2 lane code loses to OpenSSL's ``hashlib.pbkdf2_hmac`` (the
    same gate ``gen.vendors`` applies to the Thomson sweep)."""
    try:
        import jax

        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no jax / no devices
        return False


class PmkBatcher:
    """Batched PMK derivation with store/memo reuse.

    ``device``: ``"auto"`` (accelerator only), ``"on"`` (force the jax
    path — CPU jax included, for parity tests), or ``"off"`` (pure
    host).  ``store``: an optional ``pmkstore.PMKStore``; hits skip
    PBKDF2 entirely and fresh derivations are written back so no PMK is
    ever computed twice across server restarts.  Words are *decoded*
    candidate bytes (post ``hc_unhex``) — callers decode exactly once,
    the same place the oracle would.
    """

    def __init__(self, store=None, device: str = "auto", batch: int = 2048,
                 registry=None, max_memo: int = 1 << 16):
        if device not in ("auto", "on", "off"):
            raise ValueError(f"device={device!r} not in auto/on/off")
        self.store = store
        self.device = device
        self.batch = batch
        self.max_memo = max_memo
        self._memo = {}
        # the memo is shared between request handlers (put_work /
        # ingestion) and the cron thread — every mutation holds this
        self._lock = threading.Lock()
        self._fill = None
        if registry is not None:
            self._fill = registry.gauge(
                "dwpa_precrack_batch_fill_fraction",
                "valid-lane fraction of the last fused pre-crack derive "
                "batch (padded to the static fused widths)")

    def device_enabled(self) -> bool:
        if self.device == "off":
            return False
        if self.device == "on":
            return True
        return _device_available()

    def seed(self, essid: bytes, word: bytes, pmk: bytes):
        """Pre-load a known PMK (e.g. a cracked sibling's stored PMK) so
        the sweep replays it for free.  Cache-trust only: a wrong value
        costs a miss at the MIC comparison, never a false accept."""
        with self._lock:
            self._memo[(essid, word)] = pmk

    def pmk(self, essid: bytes, word: bytes) -> bytes:
        """The PMK for one pair; memo -> single host derive fallback."""
        key = (essid, word)
        with self._lock:
            p = self._memo.get(key)
        if p is None:
            p = oracle.pmk_from_psk(word, essid)
            with self._lock:
                self._memo[key] = p
        return p

    def prewarm(self, pairs) -> dict:
        """Derive PMKs for every ``(essid, word)`` pair in one wave.

        Dedups, consults the store, batches the misses through the
        fused device kernel (or host PBKDF2), writes fresh derivations
        back to the store, and fills the memo ``pmk()`` reads from.
        Returns derivation stats (for logs/benches).
        """
        with self._lock:
            if len(self._memo) > self.max_memo:
                # bounded memo: dropping entries only costs re-derivation
                self._memo.clear()
            todo, seen = [], set()
            for essid, word in pairs:
                key = (essid, word)
                if key in seen or key in self._memo:
                    continue
                seen.add(key)
                todo.append(key)
        stats = {"requested": len(pairs), "unique": len(todo),
                 "store_hits": 0, "derived": 0, "fill": 1.0}
        if self.store is not None and todo:
            by_essid = {}
            for essid, word in todo:
                by_essid.setdefault(essid, []).append(word)
            todo, hits = [], []
            for essid, words in by_essid.items():
                for word, p in zip(words, self.store.lookup(essid, words)):
                    if p is None:
                        todo.append((essid, word))
                    else:
                        hits.append(((essid, word), p))
            stats["store_hits"] = len(hits)
            with self._lock:
                self._memo.update(hits)
        packable = [(e, w) for e, w in todo
                    if _MIN_LEN <= len(w) <= _MAX_LEN]
        oddball = [(e, w) for e, w in todo
                   if not (_MIN_LEN <= len(w) <= _MAX_LEN)]
        if packable:
            if self.device_enabled():
                pmks, fill = self._derive_device(packable)
            else:
                pmks = [oracle.pmk_from_psk(w, e) for e, w in packable]
                fill = 1.0
            stats["fill"] = fill
            if self._fill is not None:
                self._fill.set(fill)
            with self._lock:
                self._memo.update(zip(packable, pmks))
            if self.store is not None:
                by_essid = {}
                for (essid, word), p in zip(packable, pmks):
                    by_essid.setdefault(essid, ([], []))
                    by_essid[essid][0].append(word)
                    by_essid[essid][1].append(p)
                self.store.put_many(
                    (e, ws, ps) for e, (ws, ps) in by_essid.items())
        if oddball:
            # out-of-range lengths the oracle still derives (and rejects
            # at the MIC stage) — host-only, never stored
            derived = [((e, w), oracle.pmk_from_psk(w, e))
                       for e, w in oddball]
            with self._lock:
                self._memo.update(derived)
        stats["derived"] = len(packable) + len(oddball)
        return stats

    def _derive_device(self, items):
        """Fused mixed-ESSID device derive: per-lane salts, static
        widths.  Returns (pmk bytes list, fill fraction of the last
        wave)."""
        import jax
        import numpy as np

        from ..models.m22000 import pmk_kernel
        from ..sched.fuse import pack_salted_lanes

        out, fill = [], 1.0
        for lo in range(0, len(items), self.batch):
            chunk = items[lo:lo + self.batch]
            rows, salt1, salt2, nvalid = pack_salted_lanes(
                chunk, self.batch, 1)
            pmks = np.asarray(jax.device_get(pmk_kernel(rows, salt1, salt2)),
                              dtype=np.uint32)
            cols = np.ascontiguousarray(pmks[:, :nvalid].T).astype(">u4")
            out.extend(cols[i].tobytes() for i in range(nvalid))
            fill = nvalid / rows.shape[0]
        return out, fill


def verify_batch(items, nc: int, batcher: PmkBatcher = None):
    """Batch-verify oracle items; verdicts bit-identical to the oracle.

    ``items``: iterable of ``(line, keys, pmk)`` following the
    ``oracle.check_key_m22000`` contract (``line`` may be a parsed
    ``Hashline``; ``pmk`` applies to the first key only, exactly like
    the oracle).  All PBKDF2 work across all items is derived in one
    batched wave up front; each verdict is then *finished* by the oracle
    itself with the derived PMK injected, so the returned list matches
    ``[oracle.check_key_m22000(line, keys, pmk=pmk, nc=nc) for ...]``
    element for element — on device, on host, with or without a store
    (a poisoned store entry can only turn a match into a miss, and the
    default store-less batcher removes even that).
    """
    if batcher is None:
        batcher = PmkBatcher(device="off")
    parsed, pairs = [], []
    for line, keys, pmk in items:
        h = line if isinstance(line, hl.Hashline) else hl.parse(line)
        keys = list(keys)
        dec = [oracle.hc_unhex(k) for k in keys]
        parsed.append((h, keys, dec, pmk))
        # the provided pmk covers the first key (oracle semantics);
        # every later key needs its own derivation
        start = 1 if pmk is not None else 0
        pairs.extend((h.essid, d) for d in dec[start:])
    if pairs:
        batcher.prewarm(pairs)
    out = []
    for h, keys, dec, pmk in parsed:
        r = None
        for i, (k, d) in enumerate(zip(keys, dec)):
            p = pmk if (i == 0 and pmk is not None) \
                else batcher.pmk(h.essid, d)
            r = oracle.check_key_m22000(h, [k], pmk=p, nc=nc)
            if r:
                break
        out.append(r)
    return out


class PrecrackEngine:
    """The fused ingestion sweep / recurring pre-crack job.

    Collects every unprocessed net's candidate set — Single/Pattern
    mutations, vendor packs, IMEI sweeps, the cracked-corpus dictionary,
    cross-net replay — derives the whole wave as one fused mixed-ESSID
    batch through the :class:`PmkBatcher`, then demuxes hits per net
    with the same per-net transaction shape as ``keygen_precompute``:
    rkg attempt rows, the crack mark and the ``algo`` release commit
    together, so a crash mid-sweep leaves every net either fully
    processed or untouched (never half-recorded).
    """

    def __init__(self, core, batch: int = 2048, device: str = "auto",
                 store=None, generators=None, dict_limit: int = 64,
                 imei_limit: int = None, nc: int = None):
        from .core import SERVER_NC

        self.core = core
        self.nc = SERVER_NC if nc is None else nc
        self.batcher = PmkBatcher(store=store, device=device, batch=batch,
                                  registry=core.registry)
        self.generators = generators
        self.dict_limit = dict_limit
        self.imei_limit = imei_limit
        reg = core.registry
        self._m_cands = reg.counter(
            "dwpa_precrack_candidates_total",
            "pre-crack candidates collected, by source family")
        self._m_founds = reg.counter(
            "dwpa_precrack_free_founds_total",
            "nets cracked server-side by the batched pre-crack sweep")
        self._tracer = SpanTracer(reg)

    # -- candidate collection ---------------------------------------------

    def _generators(self):
        if self.generators is not None:
            return self.generators
        from ..gen.vendors import vendor_candidates

        if self.imei_limit is None:
            return [vendor_candidates]
        return [lambda bssid, ssid: vendor_candidates(
            bssid, ssid, imei_limit=self.imei_limit)]

    def _dict_corpus(self):
        """The cracked/rkg corpus, frequency-ordered (the same ordering
        ``regen_cracked_dict`` serves volunteers)."""
        if self.dict_limit <= 0:
            return []
        rows = self.core.db.q(
            """SELECT pass, COUNT(*) c FROM nets
               WHERE n_state = 1 AND pass IS NOT NULL AND LENGTH(pass) >= 8
               GROUP BY pass ORDER BY c DESC, pass LIMIT ?""",
            (self.dict_limit,))
        return [r["pass"] for r in rows]

    def _collect(self, net, h, bssid, corpus):
        """One net's ordered candidate list as (source, algo, word).

        Order preserves ``keygen_precompute``'s attribution (Single,
        Pattern, vendor families) and appends the server-only sources
        after: replay (cracked siblings — their stored PMKs are seeded
        into the batcher, so same-ESSID replay never re-derives), then
        the cracked-corpus dictionary.
        """
        from . import jobs

        cands = [("single", "Single", c)
                 for c in jobs.single_mode_candidates(bssid, h.essid)]
        from ..gen.psktool import psk_candidates

        cands += [("single", "Pattern", c)
                  for c in psk_candidates(h.essid, bssid)]
        for gen in self._generators():
            for algo, c in gen(bssid, h.essid):
                cands.append(
                    ("imei" if algo == "IMEI" else "vendor", algo, c))
        for sib in self.core._handshakes_like(h, n_state=1):
            w = sib["pass"]
            if not w:
                continue
            cands.append(("replay", "Replay", w))
            if sib["ssid"] == h.essid and sib["pmk"] is not None:
                self.batcher.seed(h.essid, oracle.hc_unhex(w), sib["pmk"])
        cands += [("dict", "Dict", w) for w in corpus]
        return cands

    # -- the sweep ---------------------------------------------------------

    def run(self, limit: int = 100) -> dict:
        """The recurring job: process up to ``limit`` algo-IS-NULL nets."""
        nets = self.core.db.q(
            "SELECT * FROM nets WHERE algo IS NULL AND n_state = 0 "
            "ORDER BY net_id LIMIT ?", (limit,))
        return self._run_nets(nets)

    def on_ingest(self, net_ids) -> dict:
        """The ingestion hook: sweep freshly added nets immediately."""
        ids = list(net_ids)
        if not ids:
            return {"processed": 0, "cracked": 0, "candidates": 0}
        marks = ",".join("?" * len(ids))
        nets = self.core.db.q(
            f"SELECT * FROM nets WHERE net_id IN ({marks}) "
            "AND algo IS NULL AND n_state = 0 ORDER BY net_id", ids)
        return self._run_nets(nets)

    def _run_nets(self, nets) -> dict:
        with self._tracer.span("job:precrack"):
            return self._sweep(nets)

    def _sweep(self, nets) -> dict:
        db = self.core.db
        corpus = self._dict_corpus()
        plan, counts = [], {}
        for net in nets:
            h = hl.parse(net["struct"])
            cands = self._collect(net, h, long2mac(net["bssid"]), corpus)
            plan.append((net, h, cands))
            for source, _, _ in cands:
                counts[source] = counts.get(source, 0) + 1
        for source, n in sorted(counts.items()):
            self._m_cands.labels(source=source).inc(n)

        # Phase 1 — ONE fused derive across every net's candidates (no
        # locks held): siblings sharing an ESSID dedup to a single lane.
        pairs = [(h.essid, oracle.hc_unhex(w))
                 for _, h, cands in plan for _, _, w in cands]
        if pairs:
            self.batcher.prewarm(pairs)

        # Phase 2 — demux per net: verdicts finished by the oracle with
        # the derived PMK injected (bit-identical to the scalar loop),
        # then one transaction per net, same shape as keygen_precompute.
        found = total = 0
        for net, h, cands in plan:
            total += len(cands)
            tried, hit = [], None
            for _, algo, cand in cands:
                tried.append((algo, cand))
                p = self.batcher.pmk(h.essid, oracle.hc_unhex(cand))
                r = oracle.check_key_m22000(h, [cand], pmk=p, nc=self.nc)
                if r:
                    hit = (algo, cand, r)
                    break
            hit_algo = hit[0] if hit else ""
            with self.core._getwork_lock:
                with db.tx():
                    row = db.q1(
                        "SELECT algo, n_state FROM nets WHERE net_id = ?",
                        (net["net_id"],))
                    if (row is None or row["algo"] is not None
                            or row["n_state"] != 0):
                        continue  # raced: accepted/processed meanwhile
                    for algo, cand in tried:
                        db.x(
                            "INSERT INTO rkg(net_id, algo, pass) "
                            "VALUES (?, ?, ?)",
                            (net["net_id"], algo, cand))
                    if hit:
                        _, cand, r = hit
                        self.core._mark_cracked(
                            net["net_id"], r[0], r[3], r[1] or 0, r[2] or "")
                        db.x(
                            "UPDATE rkg SET n_state = 1 "
                            "WHERE net_id = ? AND pass = ?",
                            (net["net_id"], cand))
                        found += 1
                    # setting algo (even '') releases the net
                    db.x("UPDATE nets SET algo = ? WHERE net_id = ?",
                         (hit_algo, net["net_id"]))
        if found:
            self._m_founds.inc(found)
            if self.core.dictdir:
                from .jobs import regen_rkg_dict

                regen_rkg_dict(
                    self.core, os.path.join(self.core.dictdir, "rkg.txt.gz"))
        return {"processed": len(plan), "cracked": found,
                "candidates": total}
