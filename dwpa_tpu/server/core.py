"""Server business logic: ingestion, scheduling, result acceptance.

The functional equivalent of the reference's core library (web/common.php)
and work API (web/content/get_work.php, put_work.php), re-homed on sqlite:

- ``add_hashlines`` / ``submit_capture``: the ingestion pipeline
  (submission(), common.php:470-718): dedup by net identity, zero-PMK
  probe, cross-crack against already-cracked siblings, batch insert,
  PROBEREQUEST bookkeeping, user association;
- ``get_work``: the scheduler (get_work.php): pick the least-tried oldest
  released net, its untried smallest dicts, group every uncracked same-SSID
  net into the unit, lease coverage rows in n2d under a fresh hkey;
- ``put_work``: result acceptance (common.php:849-959): every claimed PSK
  is independently re-verified (oracle.check_key_m22000, full-width NC),
  then the cracked PMK is replayed against siblings sharing ssid/bssid/
  mac_sta without re-running PBKDF2; an ESSID-mismatched sibling (a
  "broken essid" net that verifies with the wrong ESSID's PMK) is
  cascade-deleted;
- maintenance & keygen jobs live in jobs.py.

Every verify loop routes through ``precrack.verify_batch`` (lint rule
DW115): PBKDF2 for a whole claim/sibling wave derives in one batched
dispatch, and each verdict is finished by the pure-Python oracle with the
derived PMK injected — bit-identical to the per-candidate oracle, on host
or device.
"""

import base64
import hashlib
import os
import re
import secrets
import sqlite3
import threading
import time

from ..keyspace import MaskCache, compile_pass_regex
from ..keyspace.schedule import ks_matches, mask_keyspace_totals, next_uncovered
from ..models import hashline as hl
from ..oracle import m22000 as oracle
from ..utils.fsio import fsync_replace
from .db import Database, mac2long, now
from .precrack import PmkBatcher, verify_batch

MAX_CANDS_PER_PUT = 200     # put_work cap (reference: common.php:937)
MAX_DICTCOUNT = 15          # dictcount clamp (get_work.php:41-46)
LEASE_REAP_S = 3 * 3600     # stale work-unit reclaim (maint.php:36)
SERVER_NC = 128             # server-side NC search width (common.php:157)
MAX_INFLIGHT = 4096         # default bound on live work-unit leases
MASK_SHARD_SPAN = 2_000_000  # candidates per mask shard (~8 s/chip @264k/s)
OVERLOAD_RETRY_AFTER_S = 2  # Retry-After hint handed to shed clients
LEASE_RETENTION_S = 7 * 86400  # released/reaped lease rows kept this long


class Overloaded(Exception):
    """get_work admission control refused: the live-lease count is at the
    in-flight cap.  The WSGI layer answers 429 + ``Retry-After`` (which
    the PR-10 client RetryPolicy honors as a backoff floor)."""

    def __init__(self, retry_after: float = OVERLOAD_RETRY_AFTER_S):
        super().__init__(f"work-unit leases at capacity; "
                         f"retry after {retry_after:.0f}s")
        self.retry_after = retry_after


class WorkQueue:
    """Precomputed issuable-target queue with sharded-lock pop.

    The materializer (inline on miss, or the background refill thread /
    jobs tick) runs the scheduling scan ONCE for a batch of targets;
    ``get_work`` then pops candidate net_ids in O(1) instead of
    re-running the ORDER BY hits,ts scan per request.  Entries are
    hints, not reservations — every pop is revalidated (net still
    uncracked/released, untried dicts remain) inside the issuing
    transaction, so staleness costs a retry, never correctness.

    Push/pop distribute round-robin over ``shards`` deques, each behind
    its own lock, so concurrent poppers do not serialize on one mutex;
    ordering is approximately FIFO (exact enough for the scheduler,
    whose order is a heuristic to begin with).
    """

    def __init__(self, shards: int = 8):
        self._shards = [[] for _ in range(max(1, int(shards)))]
        self._locks = [threading.Lock() for _ in self._shards]
        self._push = 0  # monotonic counters; races only skew round-robin
        self._pop = 0

    def __len__(self):
        return sum(len(s) for s in self._shards)

    def push_many(self, items):
        for it in items:
            i = self._push % len(self._shards)
            self._push += 1
            with self._locks[i]:
                self._shards[i].append(it)

    def pop(self):
        n = len(self._shards)
        start = self._pop
        self._pop += 1
        for off in range(n):
            i = (start + off) % n
            with self._locks[i]:
                if self._shards[i]:
                    return self._shards[i].pop(0)
        return None

    def discard(self, items):
        """Drop queued hints (e.g. every member of a just-leased SSID
        group): a sibling hint left behind would out-rank a never-tried
        net on the next pop, diverging from the scan's min-hits order."""
        drop = set(items)
        for i in range(len(self._shards)):
            with self._locks[i]:
                self._shards[i] = [x for x in self._shards[i]
                                   if x not in drop]

    def clear(self):
        for i in range(len(self._shards)):
            with self._locks[i]:
                self._shards[i].clear()


def gen_key() -> str:
    """16 random bytes hex — hkey/userkey format (common.php:976-978)."""
    return secrets.token_hex(16)


VALID_KEY_RE = re.compile(r"^[a-f0-9]{32}$")

#: scheduler locks keyed by absolute DB path: every ServerCore over the
#: same file database (e.g. the serving core and the --with-jobs cron
#: core) must share ONE mutex, or their n2d mutations could interleave
#: across connections.  :memory: handles are distinct databases, so each
#: gets its own lock.
_SCHED_LOCKS = {}


class _SchedLock:
    """Scheduler mutex for file-backed DBs: thread RLock + fcntl flock.

    The reference's lockfile is cross-*process* (create_lock,
    common.php:320-332) and the documented deployment here runs ``serve``
    and ``jobs`` as separate processes, so a thread lock alone leaves the
    n2d lease/delete interleaving unsynchronized between them.  The flock
    on ``<db>.getwork.lock`` extends the critical section across
    processes; the RLock keeps it reentrant and thread-safe within one.
    The OS drops a flock automatically if the holder dies — no 60 s
    staleness heuristic needed (the reference's TODO at common.php:319
    asked for exactly this).
    """

    def __init__(self, db_path: str):
        self._tl = threading.RLock()
        self._path = db_path + ".getwork.lock"
        self._fd = None
        self._depth = 0  # mutated only while holding _tl

    def __enter__(self):
        self._tl.acquire()
        self._depth += 1
        if self._depth == 1:
            try:
                import fcntl
            except ImportError:
                return self  # no fcntl (Windows): thread-only, like r2
            try:
                self._fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
                fcntl.flock(self._fd, fcntl.LOCK_EX)
            except BaseException:
                # A failed open/flock (read-only dir, ENOSPC) must error
                # this one request, not leave the RLock held forever.
                self._depth -= 1
                if self._fd is not None:
                    os.close(self._fd)
                    self._fd = None
                self._tl.release()
                raise
        return self

    def __exit__(self, *exc):
        self._depth -= 1
        if self._depth == 0 and self._fd is not None:
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None
        self._tl.release()
        return False


def valid_key(key: str) -> bool:
    """32 lowercase-hex chars (web/index.php:105-107)."""
    return isinstance(key, str) and bool(VALID_KEY_RE.match(key.lower()))


def valid_email(mail: str) -> bool:
    """Format check (the reference adds a DNS MX probe, common.php:981-992;
    that needs egress, so it stays out of the core path)."""
    return isinstance(mail, str) and bool(
        re.match(r"^[^@\s]+@[^@\s.]+(\.[^@\s.]+)+$", mail)
    )


class ServerCore:
    def __init__(self, db: Database, dictdir: str = None, capdir: str = None,
                 mailer=None, bosskey: str = None, captcha=None,
                 base_url: str = "", hcdir: str = None,
                 capture_cap: int = None, registry=None,
                 max_inflight: int = None, use_queue: bool = True,
                 queue_batch: int = 256):
        from ..obs import default_registry

        self.db = db
        # Admission control: get_work sheds load (Overloaded -> HTTP 429)
        # once this many work-unit leases are live.  None -> MAX_INFLIGHT;
        # 0 disables the cap.
        self.max_inflight = MAX_INFLIGHT if max_inflight is None else max_inflight
        # Precomputed issuable-target queue (None = legacy per-request
        # scheduling scan; bench:server_load compares the two paths).
        self.queue = WorkQueue() if use_queue else None
        self.queue_batch = queue_batch
        # Telemetry sink shared by the WSGI front (api.make_wsgi_app
        # reuses it), the scheduler counters below, and the cron jobs
        # (jobs.py); injectable so tests get isolated registries.
        self.registry = registry or default_registry()
        self._m_issued = self.registry.counter(
            "dwpa_server_work_issued_total", "work units handed to volunteers")
        self._m_claims = self.registry.counter(
            "dwpa_server_claims_total",
            "put_work candidate claims, by verification verdict")
        self._m_overload = self.registry.counter(
            "dwpa_server_overload_rejects_total",
            "get_work requests shed by the in-flight lease cap (HTTP 429)")
        # The batched-verify seam (precrack.verify_batch) every accept /
        # ingest / replay verdict goes through.  Store-less and host-mode
        # by default: claim verdicts stay bit-identical to the scalar
        # oracle with no cache trust involved.
        self.verifier = PmkBatcher(device="off")
        # Optional PrecrackEngine (server/__main__ wires it when the
        # pre-crack job is enabled): when set, add_hashlines sweeps
        # freshly ingested nets immediately after the commit.
        self.precrack = None
        self.dictdir = dictdir
        self.capdir = capdir
        # Upload size bound for captures (raw AND gzip-decompressed);
        # None -> api.CAPTURE_BODY_CAP's 8 MiB default.  The reference's
        # analog is the PHP upload limit — deployment-tunable, so this
        # is too (serve --capture-cap).
        self.capture_cap = capture_cap
        # Smart keyspace (ROADMAP 4): compiled-mask cache keyed by
        # pass_regex (compilation is pure, so one cache serves every
        # request thread) and the per-shard candidate budget — each
        # mask shard occupies one dictcount slot in a work unit.
        self._ks_cache = MaskCache()
        self.mask_shard_span = MASK_SHARD_SPAN
        self.hcdir = hcdir            # client-distribution dir (web/hc/)
        self.mailer = mailer          # mail.Mailer or None (delivery skipped)
        self.bosskey = bosskey        # 32-hex superuser key (conf.php)
        self.captcha = captcha        # callable(response, ip) -> bool, or None
        self.base_url = base_url      # public URL for mailed links
        # Optional e-mail validator override (e.g. external.mx_email_validator
        # adds the reference's DNS MX probe); None -> plain format check.
        self.email_check = None
        # Global mutex around the scheduler's shared state, the
        # reference's SHM lockfile (create_lock('get_work.lock'),
        # get_work.php:49): get_work's target-select + lease-record must
        # be atomic vs other volunteers AND vs the n2d-mutating crack
        # paths (_mark_cracked/_delete_net), or a concurrent accept
        # could interleave with the lease inserts and orphan rows for a
        # cracked net.  RLock semantics: accept paths may nest.  Shared
        # across every core on the same file DB (_SCHED_LOCKS) and — via
        # an fcntl flock — across separate serve/jobs processes.
        if db.path == ":memory:":
            self._getwork_lock = threading.RLock()
        else:
            self._getwork_lock = _SCHED_LOCKS.setdefault(
                os.path.abspath(db.path), _SchedLock(os.path.abspath(db.path))
            )

    # ------------------------------------------------------------------
    # Ingestion
    # ------------------------------------------------------------------

    def add_submission(self, blob: bytes, ip: str = "") -> int:
        """Record a capture file (md5-dedup); returns s_id."""
        md5 = hashlib.md5(blob).digest()
        row = self.db.q1("SELECT s_id FROM submissions WHERE hash = ?", (md5,))
        if row:
            return row["s_id"]
        localfile = None
        if self.capdir:
            # Dated archive layout CAP/Y/m/d/<md5> (common.php:492-494,
            # 507-514); flat legacy dirs migrate via the reorder-captures
            # CLI (the reference's misc/reorder_by_date.sh).
            day = time.strftime("%Y/%m/%d")
            os.makedirs(os.path.join(self.capdir, day), exist_ok=True)
            localfile = os.path.join(self.capdir, day, md5.hex())
            # tmp + fsync + rename (fsio): the DB row inserted below must
            # never point at a torn capture file after a crash — the
            # final name either holds the complete blob or nothing.
            tmp = "%s.tmp.%d.%x" % (localfile, os.getpid(),
                                    threading.get_ident())
            with open(tmp, "wb") as f:
                f.write(blob)
            fsync_replace(tmp, localfile)
        # OR IGNORE + re-select: under the threaded server two identical
        # uploads can both pass the dedup SELECT; the UNIQUE(hash) row
        # must win quietly, not 500 the second client.
        self.db.x(
            "INSERT OR IGNORE INTO submissions(localfile, hash, ip) "
            "VALUES (?, ?, ?)",
            (localfile, md5, ip),
        )
        return self.db.q1(
            "SELECT s_id FROM submissions WHERE hash = ?", (md5,)
        )["s_id"]

    def add_hashlines(self, lines, s_id: int = None, ip: str = "",
                      userkey: str = None) -> dict:
        """Ingest parsed/parsable m22000 lines; returns a report dict.

        The whole batch — per-line net inserts plus the user association
        — commits as ONE transaction: a crash mid-ingestion leaves no
        half-recorded submission (nets without their n2u rows, or a
        partial batch that would double-count on replay).  When a
        pre-crack engine is wired (``self.precrack``), fresh nets get
        their fused candidate sweep immediately after the commit — the
        sweep takes its own locks/transactions, so it must never run
        inside this one.
        """
        with self.db.tx():
            report = self._add_hashlines_tx(lines, s_id, ip, userkey)
        new_ids = report.pop("new_ids")
        if self.precrack is not None and new_ids:
            self.precrack.on_ingest(new_ids)
        return report

    def _add_hashlines_tx(self, lines, s_id, ip, userkey) -> dict:
        report = {"new": 0, "dup": 0, "bad": 0, "precracked": 0}
        new_ids = []
        for line in lines:
            try:
                h = line if isinstance(line, hl.Hashline) else hl.parse(line)
            except ValueError:
                report["bad"] += 1
                continue
            if h.hash_type == hl.TYPE_EAPOL and h.keyver not in (1, 2, 3):
                report["bad"] += 1
                continue
            key_id = h.key_id()
            if self.db.q1("SELECT 1 FROM nets WHERE hash = ?", (key_id,)):
                report["dup"] += 1
                continue

            n_state, passb, pmk, algo, nc, endian = 0, None, None, None, None, None
            # zero-PMK probe: some broken APs derive everything from an
            # all-zero PMK (ingest-time check, common.php:592-600)
            z = verify_batch([(h, [b""], b"\x00" * 32)], nc=SERVER_NC,
                             batcher=self.verifier)[0]
            if z:
                n_state, passb, pmk, algo = 1, b"", z[3], "ZeroPMK"
                nc, endian = z[1] or 0, z[2] or ""
                report["precracked"] += 1
            else:
                # cross-crack: replay PMKs of cracked siblings (same ssid /
                # bssid / mac_sta) before volunteers ever see this net —
                # every sibling hash verified in ONE batched dispatch
                sibs = [s for s in self._handshakes_like(h, n_state=1)
                        if s["pmk"] is not None]
                checks = verify_batch(
                    [(h, [s["pass"] or b""], s["pmk"]) for s in sibs],
                    nc=SERVER_NC, batcher=self.verifier)
                for sib, r in zip(sibs, checks):
                    if r:
                        n_state = 1
                        passb, nc, endian, pmk = sib["pass"], r[1] or 0, r[2] or "", r[3]
                        report["precracked"] += 1
                        break

            cur = self.db.x(
                """INSERT OR IGNORE INTO nets
                   (s_id, bssid, mac_sta, ssid, pass, pmk, algo, hash, struct,
                    message_pair, keyver, nc, endian, sip, n_state)
                   VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)""",
                (s_id, mac2long(h.mac_ap), mac2long(h.mac_sta), h.essid,
                 passb, pmk, algo, key_id, h.raw, h.message_pair, h.keyver,
                 nc, endian, ip, n_state),
            )
            if cur.rowcount:
                report["new"] += 1
                new_ids.append(cur.lastrowid)
        if userkey and new_ids:
            self.associate_user(userkey, new_ids)
        # internal: popped by add_hashlines before the report leaves the
        # core (feeds the post-commit pre-crack ingestion sweep)
        report["new_ids"] = new_ids
        return report

    def add_probe_requests(self, ssids, s_id: int):
        """PROBEREQUEST ssids -> prs/p2s (source of the dynamic dict)."""
        with self.db.tx():
            for ssid in ssids:
                if not ssid or len(ssid) > 32:
                    continue
                self.db.x("INSERT OR IGNORE INTO prs(ssid) VALUES (?)", (ssid,))
                p = self.db.q1("SELECT p_id FROM prs WHERE ssid = ?", (ssid,))
                self.db.x(
                    "INSERT OR IGNORE INTO p2s(p_id, s_id) VALUES (?, ?)",
                    (p["p_id"], s_id),
                )

    def associate_user(self, userkey: str, net_ids):
        u = self.db.q1("SELECT u_id FROM users WHERE userkey = ?", (userkey,))
        if not u:
            return
        with self.db.tx():
            for nid in net_ids:
                self.db.x(
                    "INSERT OR IGNORE INTO n2u(net_id, u_id) VALUES (?, ?)",
                    (nid, u["u_id"]),
                )

    def _handshakes_like(self, h: hl.Hashline, n_state: int):
        """Nets sharing ssid OR bssid OR mac_sta (PMK-reuse candidates,
        common.php:335-351)."""
        return self.db.q(
            """SELECT * FROM nets
               WHERE (ssid = ? OR bssid = ? OR mac_sta = ?) AND n_state = ?""",
            (h.essid, mac2long(h.mac_ap), mac2long(h.mac_sta), n_state),
        )

    # ------------------------------------------------------------------
    # Dictionaries
    # ------------------------------------------------------------------

    def add_dict(self, dpath: str, dname: str, dhash: str, wcount: int,
                 rules: str = None) -> int:
        cur = self.db.x(
            "INSERT INTO dicts(dpath, dname, dhash, rules, wcount) VALUES (?,?,?,?,?)",
            (dpath, dname, dhash, rules, wcount),
        )
        return cur.lastrowid

    # ------------------------------------------------------------------
    # Smart keyspace: the ks table
    # ------------------------------------------------------------------

    def ks_add(self, ssid_regex: str, pass_regex: str, priority: int = 0,
               enabled: bool = True) -> int:
        """Register an ssid-regex -> pass-regex keyspace row.

        Validation is loud and up-front: a broken ssid_regex raises
        ``re.error`` and an uncompilable pass_regex raises
        :class:`..keyspace.KeyspaceError` — a row never lands in ks
        unless the scheduler can actually turn it into mask shards.
        """
        re.compile(ssid_regex)
        compile_pass_regex(pass_regex)
        cur = self.db.x(
            "INSERT INTO ks(ssid_regex, pass_regex, priority, enabled) "
            "VALUES (?, ?, ?, ?)",
            (ssid_regex, pass_regex, int(priority), 1 if enabled else 0),
        )
        return cur.lastrowid

    def ks_rows(self, enabled_only: bool = True):
        """ks rows in scheduling order (priority DESC, then insertion)."""
        where = "WHERE enabled = 1 " if enabled_only else ""
        return self.db.q(
            f"SELECT * FROM ks {where}ORDER BY priority DESC, ks_id")

    # ------------------------------------------------------------------
    # The scheduler: get_work
    # ------------------------------------------------------------------

    def get_work(self, dictcount: int) -> dict:
        """Build one work unit or return None ("No nets").

        Held under the global get_work mutex (the reference's SHM lock,
        get_work.php:49,138) AND inside one ``db.tx()``: target selection
        and lease recording are atomic with respect to other volunteers,
        and a kill at any statement boundary either issues the whole
        unit (lease row + every coverage row) or nothing.
        Raises :class:`Overloaded` when live leases hit ``max_inflight``.
        """
        with self._getwork_lock:
            with self.db.tx():
                if self.max_inflight:
                    live = self.db.q1(
                        "SELECT COUNT(*) c FROM leases WHERE state = 0")["c"]
                    if live >= self.max_inflight:
                        self._m_overload.inc()
                        raise Overloaded()
                work = self._get_work_locked(dictcount)
        if work is not None:
            self._m_issued.inc()
        return work

    def _get_work_locked(self, dictcount: int) -> dict:
        dictcount = max(1, min(MAX_DICTCOUNT, int(dictcount)))
        for target in self._targets():
            work = self._lease_unit(target, dictcount)
            if work is not None:
                return work
        return None

    def _targets(self):
        """Candidate scheduling targets, best first.

        Queue path: pop precomputed net_ids (each revalidated against the
        live row — pops are hints) and refill inline at most once when
        the queue runs dry, so correctness never depends on the
        background materializer being alive.  Scan path (queue is None):
        the legacy per-request ORDER BY hits,ts scan.
        """
        if self.queue is None:
            target = self.db.q1(
                """SELECT net_id, ssid FROM nets
                   WHERE n_state = 0 AND algo = ''
                   ORDER BY hits, ts LIMIT 1"""
            )
            if target:
                yield target
            return
        refilled = False
        while True:
            net_id = self.queue.pop()
            if net_id is None:
                if refilled:
                    return
                refilled = True
                self.materialize_queue()
                continue
            row = self.db.q1(
                """SELECT net_id, ssid FROM nets
                   WHERE net_id = ? AND n_state = 0 AND algo = ''""",
                (net_id,),
            )
            if row is not None:
                yield row

    def materialize_queue(self, limit: int = None) -> int:
        """Run the scheduling scan once and queue a batch of issuable
        targets (uncracked, released, with at least one untried dict) in
        scheduler order.  Called inline when the queue runs dry and by
        the background materializer (jobs tick / serve refill thread).
        Returns the number of targets queued."""
        if self.queue is None:
            return 0
        if len(self.queue) > 0:
            return 0  # refill only from empty: stale entries age out fast
        batch = limit or self.queue_batch
        rows = self.db.q(
            """SELECT net_id FROM nets
               WHERE n_state = 0 AND algo = ''
                 AND hits < (SELECT COUNT(*) FROM dicts)
               ORDER BY hits, ts LIMIT ?""",
            (batch,),
        )
        ids = [r["net_id"] for r in rows]
        if len(ids) < batch:
            # dict-exhausted nets stay issuable while a matching ks row
            # has uncovered mask keyspace (entries are hints — the
            # pop-side revalidation and _plan_mask_shards' coverage walk
            # keep staleness from double-issuing)
            ids += self._mask_eligible(batch - len(ids), exclude=ids)
        self.queue.push_many(ids)
        return len(ids)

    def _mask_eligible(self, limit: int, exclude=()) -> list:
        """net_ids whose dicts are exhausted but whose matching ks rows
        still have uncovered mask keyspace, scheduler order."""
        ks = self.db.q("SELECT * FROM ks WHERE enabled = 1 "
                       "ORDER BY priority DESC, ks_id")
        if not ks:
            return []
        out, skip = [], set(exclude)
        for r in self.db.q(
            """SELECT net_id, ssid FROM nets
               WHERE n_state = 0 AND algo = ''
                 AND hits >= (SELECT COUNT(*) FROM dicts)
               ORDER BY hits, ts"""
        ):
            if len(out) >= limit:
                break
            if r["net_id"] in skip:
                continue
            total = sum(self._ks_cache.keyspace(k["pass_regex"])
                        for k in ks_matches(ks, r["ssid"]))
            if total == 0:
                continue
            covered = self.db.q1(
                "SELECT COALESCE(SUM(span), 0) c FROM n2m WHERE net_id = ?",
                (r["net_id"],))["c"]
            if covered < total:
                out.append(r["net_id"])
        return out

    def _lease_unit(self, target, dictcount: int) -> dict:
        """Issue one epoch-leased unit for ``target``, or None when the
        target has neither untried dicts nor uncovered mask keyspace
        left (caller moves to the next target).  Runs inside the
        caller's transaction (tx() nests).

        Dict shards fill first (smallest wordlists, the reference's
        ``ORDER BY wcount``); leftover dictcount slots carry mask
        shards from matching ks rows — up to a pure-mask unit with
        ``dicts: []`` when every dictionary is already covered.
        """
        dicts = self.db.q(
            """SELECT * FROM dicts WHERE d_id NOT IN
                 (SELECT d_id FROM n2d WHERE net_id = ?)
               ORDER BY wcount, dname LIMIT ?""",
            (target["net_id"], dictcount),
        )
        mask_entries, mask_rows = self._plan_mask_shards(
            target["net_id"], target["ssid"], dictcount - len(dicts))
        if not dicts and not mask_entries:
            return None
        d_ids = [d["d_id"] for d in dicts]
        if d_ids:
            ph = ",".join("?" * len(d_ids))
            # every uncracked net sharing the SSID, not yet covered by
            # these dicts
            nets = self.db.q(
                f"""SELECT net_id, struct FROM nets
                    WHERE ssid = ? AND n_state = 0 AND algo = ''
                      AND net_id NOT IN
                        (SELECT net_id FROM n2d WHERE d_id IN ({ph}))""",
                (target["ssid"], *d_ids),
            )
        else:
            # pure-mask unit: the whole uncracked SSID group rides along
            # (INSERT OR IGNORE leaves already-covered shards untouched)
            nets = self.db.q(
                """SELECT net_id, struct FROM nets
                   WHERE ssid = ? AND n_state = 0 AND algo = ''""",
                (target["ssid"],),
            )
        if not nets:
            return None
        hkey = gen_key()
        with self.db.tx():
            epoch = self.db.q1(
                "SELECT COALESCE(MAX(epoch), 0) + 1 e FROM leases")["e"]
            self.db.x(
                "INSERT INTO leases(hkey, epoch, issued) VALUES (?, ?, ?)",
                (hkey, epoch, now()),
            )
            for n in nets:
                for d in d_ids:
                    self.db.x(
                        "INSERT OR IGNORE INTO n2d(net_id, d_id, hkey, epoch) "
                        "VALUES (?,?,?,?)",
                        (n["net_id"], d, hkey, epoch),
                    )
                for ks_id, mask_i, skip, span in mask_rows:
                    self.db.x(
                        "INSERT OR IGNORE INTO "
                        "n2m(net_id, ks_id, mask_i, skip, span, hkey, epoch) "
                        "VALUES (?,?,?,?,?,?,?)",
                        (n["net_id"], ks_id, mask_i, skip, span, hkey, epoch),
                    )
        if self.queue is not None:
            self.queue.discard(n["net_id"] for n in nets)
        # merged, deduped per-dict rules (get_work.php:84-92)
        seen, merged = set(), []
        for d in dicts:
            for ln in (d["rules"] or "").splitlines():
                if ln and ln not in seen:
                    seen.add(ln)
                    merged.append(ln)
        work = {
            "hkey": hkey,
            "epoch": epoch,
            "dicts": [{"dhash": d["dhash"], "dpath": d["dpath"]} for d in dicts],
            "hashes": [n["struct"] for n in nets],
        }
        if merged:
            work["rules"] = base64.b64encode("\n".join(merged).encode()).decode()
        if mask_entries:
            work["masks"] = mask_entries
        if self._prdict_available(hkey):
            work["prdict"] = True
        return work

    def _plan_mask_shards(self, net_id: int, ssid: bytes, budget: int):
        """Pick up to ``budget`` uncovered mask shards for ``net_id``.

        Returns ``(entries, rows)``: wire entries
        ``{mask, custom, skip, limit}`` for the work unit, and matching
        ``(ks_id, mask_i, skip, span)`` tuples for the n2m lease
        inserts.  ks rows are tried best-priority first; within a row,
        masks smallest-keyspace first (the compiler pre-sorts — the
        mask analog of ``ORDER BY wcount``).  Every skip/limit comes
        from first-gap coverage walks bounded by the compiled
        ``mask_keyspace`` (reaped ranges reappear as gaps and are
        re-issued); runs inside the caller's scheduler lock.
        """
        entries, rows = [], []
        if budget <= 0:
            return entries, rows
        ks = self.db.q("SELECT * FROM ks WHERE enabled = 1 "
                       "ORDER BY priority DESC, ks_id")
        for k in ks_matches(ks, ssid):
            ck = self._ks_cache.get(k["pass_regex"])
            if ck is None:
                continue
            for mask_i, m in enumerate(ck.masks):
                cov = self.db.q(
                    "SELECT skip, span FROM n2m "
                    "WHERE net_id = ? AND ks_id = ? AND mask_i = ?",
                    (net_id, k["ks_id"], mask_i),
                )
                taken = []
                while len(entries) < budget:
                    shard = next_uncovered(cov, m.keyspace,
                                           self.mask_shard_span, taken)
                    if shard is None:
                        break
                    skip, span = shard
                    taken.append((skip, span))
                    entries.append({"mask": m.mask, "custom": dict(m.custom),
                                    "skip": skip, "limit": span})
                    rows.append((k["ks_id"], mask_i, skip, span))
                if len(entries) >= budget:
                    return entries, rows
        return entries, rows

    def _prdict_available(self, hkey: str) -> bool:
        """PROBEREQUEST dict availability for a work unit: prs rows joined
        through p2s -> submissions -> nets -> n2d.hkey (prdict.php:17-29)."""
        row = self.db.q1(
            """SELECT 1 FROM prs p
               JOIN p2s ON p.p_id = p2s.p_id
               JOIN nets n ON n.s_id = p2s.s_id
               JOIN n2d ON n2d.net_id = n.net_id
               WHERE n2d.hkey = ? LIMIT 1""",
            (hkey,),
        )
        return row is not None

    def prdict_words(self, hkey: str) -> list:
        rows = self.db.q(
            """SELECT DISTINCT p.ssid FROM prs p
               JOIN p2s ON p.p_id = p2s.p_id
               JOIN nets n ON n.s_id = p2s.s_id
               JOIN n2d ON n2d.net_id = n.net_id
               WHERE n2d.hkey = ?""",
            (hkey,),
        )
        out = []
        for r in rows:
            ssid = r["ssid"]
            try:
                printable = ssid.decode("ascii").isprintable()
            except UnicodeDecodeError:
                printable = False
            out.append(ssid if printable else b"$HEX[%s]" % ssid.hex().encode())
        return out

    # ------------------------------------------------------------------
    # Result acceptance: put_work
    # ------------------------------------------------------------------

    def put_work(self, data: dict) -> bool:
        """Accept one submission: verify claims, then release the lease.

        The whole call — every accept cascade plus the lease release —
        runs under the scheduler mutex and ONE transaction, so a kill at
        any statement boundary leaves no half-accepted net.  The release
        is keyed by ``(hkey, epoch, state=live)``: a stale holder whose
        unit was reaped and re-issued matches nothing, and a duplicate
        submit is an idempotent no-op (the lease state only leaves
        "live" once).
        """
        cands = data.get("cand") or []
        ctype = data.get("type", "bssid")
        hkey = data.get("hkey")
        epoch = data.get("epoch")
        if not isinstance(epoch, int) or isinstance(epoch, bool):
            epoch = None  # absent/garbage epoch: resolve from the live lease
        if not isinstance(cands, list):
            return False
        with self._getwork_lock:
            with self.db.tx():
                claims = []
                for pair in cands[:MAX_CANDS_PER_PUT]:
                    k, v = pair.get("k"), pair.get("v")
                    if not isinstance(k, str) or not isinstance(v, str) or v == "":
                        continue
                    # Candidate encoding depends on the claim type (common.php:
                    # 874-898): bssid/ssid claims carry hex2bin'd PSKs, while
                    # 'hash' claims carry raw text (hc_unhex'd by the verifier) —
                    # a raw all-digit PSK must NOT be hex-decoded here.
                    if ctype in ("bssid", "ssid"):
                        try:
                            psk = bytes.fromhex(v)
                        except ValueError:
                            continue
                    else:
                        psk = oracle.hc_unhex(v)
                    claims.append((k, psk))
                # Pre-derive the claim x net PBKDF2 superset in ONE
                # batched dispatch.  The accept cascade below re-queries
                # per claim, and accepts only REMOVE nets from n_state=0,
                # so its queries return subsets of this snapshot — a
                # superset pair costs one spare derivation, never a
                # verdict change (verify_batch single-derives any gap).
                self.verifier.prewarm(
                    [(net["ssid"], oracle.hc_unhex(psk))
                     for k, psk in claims
                     for net in self._nets_for_claim(ctype, k)])
                for k, psk in claims:
                    for net in self._nets_for_claim(ctype, k):
                        self._try_accept(net, psk, submitter=data.get("ip", ""))
                if hkey:
                    self._release_lease(hkey, epoch)
        return True

    def _release_lease(self, hkey: str, epoch: int = None) -> int:
        """Release a live lease keyed by (hkey, epoch); returns released
        row count (0 = stale holder / already released / reaped).  Legacy
        clients send no epoch — it resolves from the live lease record,
        which preserves the stale-holder guard (a reaped lease has no
        live record to resolve)."""
        with self.db.tx():
            if epoch is None:
                row = self.db.q1(
                    "SELECT epoch FROM leases WHERE hkey = ? AND state = 0",
                    (hkey,),
                )
                if row is None:
                    return 0
                epoch = row["epoch"]
            cur = self.db.x(
                """UPDATE leases SET state = 1, released = ?
                   WHERE hkey = ? AND epoch = ? AND state = 0""",
                (now(), hkey, epoch),
            )
            if cur.rowcount:
                self.db.x(
                    "UPDATE n2d SET hkey = NULL WHERE hkey = ? AND epoch = ?",
                    (hkey, epoch),
                )
                # mask shards release identically: hkey NULL = range done.
                # A reaped unit's n2m rows were DELETEd, so the stale
                # holder's keyed release above matched no lease and never
                # reaches here — a re-issued range cannot double-credit.
                self.db.x(
                    "UPDATE n2m SET hkey = NULL WHERE hkey = ? AND epoch = ?",
                    (hkey, epoch),
                )
            return cur.rowcount

    def _nets_for_claim(self, ctype: str, key: str):
        if ctype == "bssid":
            try:
                b = int(key, 16)
            except ValueError:
                return []
            return self.db.q(
                "SELECT * FROM nets WHERE bssid = ? AND n_state = 0", (b,)
            )
        if ctype == "ssid":
            # The ssid claim key arrives hex-encoded (common.php:886-887).
            try:
                essid = bytes.fromhex(key)
            except ValueError:
                return []
            return self.db.q(
                "SELECT * FROM nets WHERE ssid = ? AND n_state = 0", (essid,)
            )
        if ctype == "hash":
            try:
                hh = bytes.fromhex(key)
            except ValueError:
                return []
            return self.db.q(
                "SELECT * FROM nets WHERE hash = ? AND n_state = 0", (hh,)
            )
        return []

    def _try_accept(self, net, psk: bytes, submitter: str = ""):
        """Independent re-verification + PMK-reuse sweep, both through
        the batched verify seam (verdicts bit-identical to the scalar
        oracle: verify_batch finishes every verdict with the oracle
        itself, PMK injected)."""
        h = hl.parse(net["struct"])
        r = verify_batch([(h, [psk], None)], nc=SERVER_NC,
                         batcher=self.verifier)[0]
        if not r:
            self._m_claims.labels(verdict="rejected").inc()
            return False
        self._m_claims.labels(verdict="accepted").inc()
        psk_b, nc, endian, pmk = r
        self._mark_cracked(net["net_id"], psk_b, pmk, nc or 0, endian or "")
        # replay this PMK against uncracked siblings (common.php:916-932)
        # — every sibling hash checked in ONE verify dispatch
        sibs = self._handshakes_like(h, n_state=0)
        parsed = [hl.parse(s["struct"]) for s in sibs]
        replays = verify_batch([(sh, [psk_b], pmk) for sh in parsed],
                               nc=SERVER_NC, batcher=self.verifier)
        for sib, sh, rr in zip(sibs, parsed, replays):
            if not rr:
                continue
            if sh.essid == h.essid:
                self._mark_cracked(sib["net_id"], psk_b, pmk, rr[1] or 0, rr[2] or "")
            else:
                # MIC verifies with a PMK derived from a different ESSID:
                # the stored ESSID is broken -> cascade delete
                self._delete_net(sib["net_id"])
        return True

    def _mark_cracked(self, net_id: int, psk: bytes, pmk: bytes, nc: int, endian: str):
        # under the scheduler mutex: the n2d delete must not interleave
        # with a get_work lease loop for the same net (see __init__).
        # Lock-ordering discipline everywhere: _getwork_lock FIRST, then
        # tx() — never open a transaction and then take the scheduler
        # mutex, or a concurrent get_work (lock held, waiting on the db
        # lock) deadlocks against us.
        with self._getwork_lock:
            with self.db.tx():
                self.db.x(
                    """UPDATE nets SET pass = ?, pmk = ?, nc = ?, endian = ?,
                                      n_state = 1, ts = ? WHERE net_id = ?""",
                    (psk, pmk, nc, endian, now(), net_id),
                )
                self.db.x("DELETE FROM n2d WHERE net_id = ?", (net_id,))
                self.db.x("DELETE FROM n2m WHERE net_id = ?", (net_id,))

    def _delete_net(self, net_id: int):
        with self._getwork_lock:
            with self.db.tx():
                row = self.db.q1("SELECT bssid FROM nets WHERE net_id = ?", (net_id,))
                self.db.x("DELETE FROM nets WHERE net_id = ?", (net_id,))
                if row and not self.db.q1(
                    "SELECT 1 FROM nets WHERE bssid = ? LIMIT 1", (row["bssid"],)
                ):
                    self.db.x("DELETE FROM bssids WHERE bssid = ?", (row["bssid"],))

    # ------------------------------------------------------------------
    # Telemetry
    # ------------------------------------------------------------------

    def observe_metrics(self):
        """Refresh the scrape-time gauges (work-unit lease + net-state
        stats) in ``self.registry``; called by the ``?metrics`` handler
        so every scrape reads the live database, not the hourly cron
        snapshot in the stats table."""
        reg = self.registry
        leases = self.db.q1(
            "SELECT COUNT(*) c, COUNT(DISTINCT hkey) u FROM n2d "
            "WHERE hkey IS NOT NULL")
        reg.gauge("dwpa_server_leases_active",
                  "net x dict coverage rows currently leased"
                  ).set(leases["c"])
        reg.gauge("dwpa_server_work_units_in_flight",
                  "distinct work-unit keys currently leased"
                  ).set(leases["u"] or 0)
        oldest = self.db.q1(
            "SELECT MIN(ts) t FROM n2d WHERE hkey IS NOT NULL")["t"]
        reg.gauge("dwpa_server_oldest_lease_age_seconds",
                  "age of the oldest outstanding lease (reaped at "
                  "dwpa_server_lease_reap_seconds)"
                  ).set(max(0.0, now() - oldest) if oldest else 0.0)
        reg.gauge("dwpa_server_lease_reap_seconds",
                  "stale-lease reap threshold").set(LEASE_REAP_S)
        reg.gauge("dwpa_server_leases_live",
                  "live lease records (admission-control population)"
                  ).set(self.db.q1(
                      "SELECT COUNT(*) c FROM leases WHERE state = 0")["c"])
        reg.gauge("dwpa_server_inflight_limit",
                  "max live work-unit leases before get_work sheds (0 = "
                  "uncapped)").set(self.max_inflight or 0)
        reg.gauge("dwpa_server_work_queue_depth",
                  "precomputed issuable targets awaiting pop (-1 = scan "
                  "path, queue disabled)"
                  ).set(len(self.queue) if self.queue is not None else -1)
        for state, label in ((0, "uncracked"), (1, "cracked")):
            reg.gauge("dwpa_server_nets",
                      "nets by crack state").labels(state=label).set(
                self.db.q1("SELECT COUNT(*) c FROM nets WHERE n_state = ?",
                           (state,))["c"])
        mask_total, mask_done = mask_keyspace_totals(self.db, self._ks_cache)
        reg.gauge("dwpa_keyspace_mask_total",
                  "scheduled mask keyspace over uncracked nets "
                  "(candidates, summed per matching ks row)"
                  ).set(mask_total)
        reg.gauge("dwpa_keyspace_mask_done",
                  "completed mask-shard coverage (released n2m spans, "
                  "candidates)").set(mask_done)

    # ------------------------------------------------------------------
    # Users & potfile export
    # ------------------------------------------------------------------

    def create_user(self, mail: str) -> str:
        key = gen_key()
        self.db.x(
            "INSERT INTO users(userkey, mail) VALUES (?, ?) "
            "ON CONFLICT(mail) DO UPDATE SET userkey = excluded.userkey",
            (key, mail),
        )
        return key

    def issue_user_key(self, mail: str, ip: str = "") -> tuple:
        """The key-issue flow (web/index.php:48-102).

        New mail: insert user (userkey = linkkey = fresh key), send the key
        by mail, return ("issued", key) — the caller sets the cookie.
        Known mail: rotate the linkkey at most once per 24h (users.linkkeyts
        throttle, db/wpa.sql:308-320) and mail a ``?get_key=<linkkey>``
        confirmation link; return ("reset", key) or ("throttled", None).
        Mail delivery failures are swallowed like the reference's.
        """
        key = gen_key()
        inserted, updated = True, 0
        with self.db.tx():
            # Both arms inside one tx (mail delivery stays outside it):
            # the insert-or-rotate decision and the rotate itself commit
            # together, never a rotated linkkey without its timestamp.
            try:
                self.db.x(
                    "INSERT INTO users(userkey, linkkey, linkkeyts, mail, ip) "
                    "VALUES (?, ?, ?, ?, ?)",
                    (key, key, now(), mail, ip),
                )
            except sqlite3.IntegrityError:
                inserted = False
                updated = self.db.x(
                    "UPDATE users SET linkkey = ?, linkkeyts = ? "
                    "WHERE mail = ? AND (linkkeyts IS NULL OR linkkeyts < ?)",
                    (key, now(), mail, now() - 24 * 3600),
                ).rowcount
        if not inserted:
            if updated != 1:
                return ("throttled", None)
            if self.mailer:
                self.mailer.send(
                    mail, "dwpa_tpu key change",
                    "A request for a new user key was submitted. "
                    "Please follow this link to confirm: "
                    f"{self.base_url}?get_key={key}",
                )
            return ("reset", key)
        if self.mailer:
            self.mailer.send(
                mail, "dwpa_tpu key", f"Key to access results is: {key}"
            )
        return ("issued", key)

    def confirm_linkkey(self, linkkey: str) -> bool:
        """?get_key=<linkkey>: promote linkkey -> userkey
        (web/content/get_key.php:11-31)."""
        cur = self.db.x(
            "UPDATE users SET userkey = linkkey WHERE linkkey = ?", (linkkey,)
        )
        return cur.rowcount == 1

    def user_key_exists(self, key: str) -> bool:
        return (
            self.db.q1("SELECT 1 FROM users WHERE userkey = ?", (key,)) is not None
        )

    def user_potfile(self, userkey: str) -> list:
        """All of a user's cracked nets as bssid:mac_sta:ssid:pass lines
        (api.php:9-28)."""
        rows = self.db.q(
            """SELECT n.* FROM nets n JOIN n2u ON n.net_id = n2u.net_id
               JOIN users u ON u.u_id = n2u.u_id
               WHERE u.userkey = ? AND n.n_state = 1""",
            (userkey,),
        )
        out = []
        for r in rows:
            mac_ap = f"{r['bssid']:012x}"
            mac_sta = f"{r['mac_sta']:012x}"
            ssid = r["ssid"].decode("latin1")
            out.append(f"{mac_ap}:{mac_sta}:{ssid}:{(r['pass'] or b'').decode('latin1')}")
        return out
