"""Server CLI: serve the work API/UI, run cron jobs, and ops tooling.

The reference spreads these across an Apache vhost (web/), crontab
entries (INSTALL.md:47-52), and hand-run misc/ scripts; here one entry
point covers them:

    python -m dwpa_tpu.server serve   --db wpa.db --port 8080
    python -m dwpa_tpu.server jobs    --db wpa.db [--loop]
    python -m dwpa_tpu.server recrack --db wpa.db
    python -m dwpa_tpu.server pack-dict --db wpa.db words.txt --name top1k
    python -m dwpa_tpu.server dedup-dicts a.txt.gz b.txt.gz [--db wpa.db]
    python -m dwpa_tpu.server fill-pr --db wpa.db
    python -m dwpa_tpu.server enrich  --db wpa.db
"""

import argparse
import json
import re
import sys
import time


def _load_conf(args):
    """Overlay a JSON conf file (the web/conf.php equivalent surface:
    db path, artifact dirs, bosskey, bind address, public base_url)
    under any explicitly passed flags — flags win."""
    path = getattr(args, "conf", None)
    if not path:
        return {}
    with open(path) as f:
        conf = json.load(f)
    for key in ("db", "dictdir", "capdir", "hcdir", "bosskey", "host",
                "port", "base_url", "capture_cap"):
        if key in conf and getattr(args, key, None) is None:
            setattr(args, key, conf[key])
    return conf


def _core(args):
    from .core import ServerCore
    from .db import Database

    _load_conf(args)
    if not getattr(args, "db", None):
        raise SystemExit("--db (or a conf file with a 'db' key) is required")
    core = ServerCore(
        Database(args.db),
        dictdir=getattr(args, "dictdir", None) or "dicts",
        capdir=getattr(args, "capdir", None) or "caps",
        bosskey=getattr(args, "bosskey", None),
        hcdir=getattr(args, "hcdir", None),
        base_url=getattr(args, "base_url", None) or "",
        capture_cap=getattr(args, "capture_cap", None),
        max_inflight=getattr(args, "max_inflight", None),
        use_queue=not getattr(args, "no_work_queue", False),
    )
    if getattr(args, "recaptcha_secret", None):
        from .external import RECAPTCHA_URL, RecaptchaVerifier

        core.captcha = RecaptchaVerifier(
            args.recaptcha_secret,
            url=getattr(args, "recaptcha_url", None) or RECAPTCHA_URL,
        )
    if getattr(args, "mx_check", False):
        from .external import mx_email_validator

        core.email_check = mx_email_validator()
    return core


def cmd_serve(args):
    import socketserver
    from wsgiref.simple_server import WSGIServer, make_server

    from .api import make_wsgi_app

    class ThreadingWSGIServer(socketserver.ThreadingMixIn, WSGIServer):
        """One thread per request, like the reference under Apache
        prefork: a slow capture upload must not block get_work for the
        whole fleet.  Database serializes statements; get_work holds the
        scheduler mutex (core.py).  Concurrent request handling is
        capped (Apache's MaxClients analog) so N hostile uploads cannot
        hold N x 64 MiB request bodies in memory at once — excess
        connections queue on the semaphore.
        """

        daemon_threads = True
        max_concurrent = 16
        request_timeout = 120.0  # reference client's socket timeout

        def process_request(self, request, client_address):
            # Acquire in the accept loop, BEFORE spawning the handler
            # thread: resources (threads, fds, bodies) are bounded at
            # the accept layer; excess connections wait in the kernel
            # listen backlog, exactly like Apache at MaxClients.
            self._request_slots.acquire()
            try:
                super().process_request(request, client_address)
            except Exception:
                self._request_slots.release()
                raise

        def process_request_thread(self, request, client_address):
            try:
                # An idle/stalled peer must not hold its slot forever —
                # reads time out, the handler errors, the slot frees.
                request.settimeout(self.request_timeout)
                super().process_request_thread(request, client_address)
            finally:
                self._request_slots.release()

        def server_activate(self):
            import threading

            self._request_slots = threading.BoundedSemaphore(
                self.max_concurrent
            )
            super().server_activate()

    from ..obs import setup_logging

    setup_logging()
    serve_core = _core(args)
    if not getattr(args, "no_precrack_ingest", False):
        # Ingestion-time pre-crack: add_hashlines hands freshly inserted
        # net ids to this engine AFTER the ingest tx commits, so every
        # new net gets its vendor/IMEI/replay candidate sweep before any
        # client ever leases it.
        from .precrack import PrecrackEngine

        serve_core.precrack = PrecrackEngine(
            serve_core, batch=args.precrack_batch,
            device=args.precrack_device,
            dict_limit=args.precrack_dict_limit)
    app = make_wsgi_app(serve_core)
    if getattr(args, "with_jobs", False):
        # The cron layer in-process: its own ServerCore (sqlite handles
        # are not shared across threads; WAL serializes the writers).
        import threading

        if args.db == ":memory:":
            raise SystemExit("--with-jobs needs a file-backed --db "
                             "(a second :memory: handle would be empty)")
        jobs_core = _core(args)
        geo, psk = _job_lookups(args)  # validate sources before the thread
        threading.Thread(
            target=_jobs_loop, args=(jobs_core, args, geo, psk), daemon=True
        ).start()
    host = args.host or "127.0.0.1"
    port = args.port if args.port is not None else 8080
    with make_server(host, port, app,
                     server_class=ThreadingWSGIServer) as srv:
        mat = _start_materializer(serve_core)
        print(f"dwpa_tpu server on http://{host}:{port}/", flush=True)
        try:
            srv.serve_forever()
        finally:
            if mat is not None:
                thread, stop = mat
                stop.set()
                thread.join(timeout=5.0)


def _start_materializer(core, interval: float = 1.0):
    """Background issuable-queue refill for ``serve``: keeps get_work on
    the O(1) pop path instead of the inline refill scan.  No-op when the
    queue is disabled (--no-work-queue).

    Returns ``(thread, stop)`` or None; setting ``stop`` ends the loop
    within one tick and the thread can then be joined — the thread-
    lifecycle rule every spawn in this repo follows (daemon=True is the
    backstop for serve_forever's hard exit, not the shutdown story)."""
    import threading

    if core.queue is None:
        return None

    stop = threading.Event()

    def loop():
        while not stop.is_set():
            try:
                core.materialize_queue()
            except Exception:
                pass  # transient sqlite contention: next tick retries
            stop.wait(interval)

    t = threading.Thread(target=loop, daemon=True,
                         name="dwpa-queue-materializer")
    t.start()
    return t, stop


def _geo_lookup_from_file(path):
    """Offline geolocation source (a wigle CSV/JSON export): JSON object
    ``{"aabbccddeeff": {"lat": .., "lon": .., "country": ..}, ...}``."""
    with open(path) as f:
        table = {k.lower(): v for k, v in json.load(f).items()}
    return lambda mac: table.get(mac.hex())


def _psk_lookup_from_file(path):
    """Offline PSK-database source (a 3wifi-style dump): lines of
    ``aabbccddeeff:psk``.  Answers still go through full server-side
    re-verification — the file is never trusted."""
    table = {}
    with open(path, "rb") as f:
        for ln in f:
            mac, _, psk = ln.rstrip(b"\r\n").partition(b":")
            if len(mac) == 12 and psk:
                try:
                    table[bytes.fromhex(mac.decode())] = psk
                except (ValueError, UnicodeDecodeError):
                    pass  # header/junk line, skip like any malformed row
    return lambda macs: {m: table[m] for m in macs if m in table}


def _job_lookups(args):
    """Build the geo/PSK lookup callables — ONCE, and before any
    background thread starts, so a bad path, malformed file, or missing
    API key fails the command loudly instead of silently killing the
    cron layer.  Offline file sources win over live API adapters when
    both are configured (airgapped deployments stay airgapped)."""
    geo = psk = None
    if getattr(args, "wigle_api", None):
        from .external import WIGLE_URL, WigleClient

        geo = WigleClient(args.wigle_api,
                          url=getattr(args, "wigle_url", None) or WIGLE_URL)
    if getattr(args, "wifi3_api", None):
        from .external import WIFI3_URL, ThreeWifiClient

        psk = ThreeWifiClient(args.wifi3_api,
                              url=getattr(args, "wifi3_url", None) or WIFI3_URL)
    if args.geo_file:
        geo = _geo_lookup_from_file(args.geo_file)
    if args.psk_file:
        psk = _psk_lookup_from_file(args.psk_file)
    return geo, psk


def _keygen_gens(args):
    """``extra_generators`` for keygen precompute: the built-in vendor
    families, plus any deployment data pack (``--vendor-data``).  None
    keeps keygen_precompute's default (built-ins only)."""
    path = getattr(args, "vendor_data", None)
    if not path:
        return None
    from ..gen.vendor_data import load_vendor_pack
    from ..gen.vendors import vendor_candidates

    return [vendor_candidates] + load_vendor_pack(path)


def cmd_jobs(args):
    """The cron layer: one shot of maintenance + keygen (+ geolocation /
    PSK lookup when a source is configured) by default, or continuous
    with --loop (maintenance hourly, keygen every 5 min, enrichment every
    10 min — the INSTALL.md:47-52 cadence)."""
    from ..obs import setup_logging
    from .jobs import (geolocate, keygen_precompute, maintenance, precrack,
                       psk_lookup)

    setup_logging()
    core = _core(args)
    geo, psk = _job_lookups(args)
    if not args.loop:
        out = {"maintenance": maintenance(core),
               "keygen": keygen_precompute(
                   core, extra_generators=_keygen_gens(args)),
               "precrack": precrack(
                   core, limit=args.precrack_limit,
                   batch=args.precrack_batch,
                   device=args.precrack_device,
                   dict_limit=args.precrack_dict_limit)}
        if geo:
            out["geolocate"] = geolocate(core, geo)
        if psk:
            out["psk_lookup"] = psk_lookup(core, psk)
        print(json.dumps(out, default=str))
        return
    _jobs_loop(core, args, geo, psk)


def _jobs_loop(core, args, geo, psk):
    """The continuous cron layer (INSTALL.md:47-52 cadence); shared by
    ``jobs --loop`` and ``serve --with-jobs``.  Transient job errors
    (sqlite lock contention, I/O hiccups) are logged and retried next
    tick — one bad pass must not end the cron layer for good."""
    from ..obs import get_logger
    from .jobs import (geolocate, keygen_precompute, maintenance, precrack,
                       psk_lookup)

    log = get_logger("server.jobs")
    gens = _keygen_gens(args)
    last_maint = last_enrich = last_precrack = 0.0
    while True:
        now = time.time()
        try:
            if now - last_maint >= args.maint_interval:
                maintenance(core)
                last_maint = now
            if (geo or psk) and now - last_enrich >= args.enrich_interval:
                if geo:
                    geolocate(core, geo)
                if psk:
                    psk_lookup(core, psk)
                last_enrich = now
            if now - last_precrack >= args.precrack_interval:
                precrack(core, limit=args.precrack_limit,
                         batch=args.precrack_batch,
                         device=args.precrack_device,
                         dict_limit=args.precrack_dict_limit)
                last_precrack = now
            keygen_precompute(core, extra_generators=gens)
        except Exception:
            log.exception("jobs tick failed (will retry)")
        time.sleep(args.keygen_interval)


def cmd_recrack(args):
    from .tools import recrack_verify

    print(json.dumps(recrack_verify(_core(args), limit=args.limit)))


def cmd_pack_dict(args):
    from .tools import pack_dict

    rules = None
    if args.default_rules:
        from ..rules import wpa_rules_text

        rules = wpa_rules_text()
    elif args.rules:
        with open(args.rules) as f:
            rules = f.read()
    print(json.dumps(pack_dict(_core(args), args.source, args.name, rules=rules)))


def cmd_dedup_dicts(args):
    from .tools import dedup_dicts

    core = _core(args) if args.db else None
    print(json.dumps(dedup_dicts(args.paths, core=core)))


def cmd_fill_pr(args):
    from .tools import fill_pr, get_extractor

    ex = get_extractor(native=args.native)
    print(json.dumps(fill_pr(_core(args), limit=args.limit, extractor=ex)))


def cmd_enrich(args):
    from .tools import enrich_message_pair, get_extractor

    ex = get_extractor(native=args.native)
    print(json.dumps(
        enrich_message_pair(_core(args), limit=args.limit, extractor=ex)))


def cmd_ks_add(args):
    from ..keyspace import KeyspaceError

    try:
        row = _core(args).ks_add(args.ssid_re, args.pass_re,
                                 priority=args.priority,
                                 enabled=not args.disabled)
    except KeyspaceError as e:
        # Loud rejection is the dialect's contract: a pattern the
        # compiler can't cover exactly must never be half-scheduled.
        raise SystemExit(f"pass-regex rejected: {e}")
    except re.error as e:
        raise SystemExit(f"bad --ssid-re: {e}")
    print(json.dumps(row))


def cmd_ks_list(args):
    core = _core(args)
    out = []
    for row in core.ks_rows(enabled_only=False):
        d = dict(row)
        d["keyspace"] = core._ks_cache.keyspace(row["pass_regex"])
        out.append(d)
    print(json.dumps(out))


def cmd_reorder_captures(args):
    from .tools import reorder_captures

    print(json.dumps(reorder_captures(_core(args))))


def cmd_pack_client(args):
    from .tools import pack_client

    _load_conf(args)
    if not args.hcdir:
        raise SystemExit("--hcdir (or a conf file with an 'hcdir' key) "
                         "is required")
    print(json.dumps(pack_client(args.hcdir, version=args.version)))


def cmd_migrate(args):
    """Legacy hccapx / 16800-PMKID storage -> m22000 nets rows.

    Input: a file of newline-separated legacy PMKID lines, a single
    hccapx capture file (393-byte records back to back), or both.
    """
    from .tools import HCCAPX_LEN, migrate_legacy

    records = []
    for path in args.sources:
        with open(path, "rb") as f:
            blob = f.read()
        if blob[:4] == b"HCPX":
            records += [blob[i:i + HCCAPX_LEN]
                        for i in range(0, len(blob), HCCAPX_LEN)]
        else:
            records += [ln for ln in blob.splitlines() if ln.strip()]
    print(json.dumps(migrate_legacy(
        _core(args), records, verify=not args.no_verify), default=str))


def main(argv=None):
    p = argparse.ArgumentParser(prog="dwpa_tpu.server")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, db_required=True):
        sp.add_argument("--db", help="sqlite path")
        sp.add_argument("--conf", help="JSON conf file (web/conf.php "
                                       "equivalent); flags override it")
        sp.add_argument("--dictdir")
        sp.add_argument("--capdir")

    def jobs_flags(sp):
        """Cron-layer knobs, shared by `jobs` and `serve --with-jobs`."""
        sp.add_argument("--maint-interval", type=float, default=3600)
        sp.add_argument("--keygen-interval", type=float, default=300)
        sp.add_argument("--enrich-interval", type=float, default=600,
                        help="geolocate/psk-lookup cadence (wigle.php/"
                             "3wifi.php run every 10 min)")
        sp.add_argument("--geo-file", help="offline geolocation JSON "
                                           "{mac_hex: {lat, lon, ...}}")
        sp.add_argument("--psk-file", help="offline PSK database, lines of "
                                           "mac_hex:psk (3wifi-dump style)")
        sp.add_argument("--wigle-api", help="wigle.net Basic-auth API key "
                                            "(live geolocation, wigle.php)")
        sp.add_argument("--wigle-url", help="override the wigle endpoint "
                                            "(stub testing)")
        sp.add_argument("--wifi3-api", help="3wifi API key (live PSK "
                                            "lookups, 3wifi.php)")
        sp.add_argument("--wifi3-url", help="override the 3wifi endpoint "
                                            "(stub testing)")
        sp.add_argument("--vendor-data",
                        help="JSON vendor keygen pack (gen/vendor_data.py "
                             "format): adds data-driven routerkeygen "
                             "families to keygen precompute")
        sp.add_argument("--precrack-interval", type=float, default=300,
                        help="server-side pre-crack sweep cadence in "
                             "seconds (fused mixed-ESSID PMK derivation "
                             "over every unprocessed net's candidates)")
        sp.add_argument("--precrack-batch", type=int, default=2048,
                        help="fused PMK derivation width per pre-crack "
                             "wave (sched/fuse.py static widths)")
        sp.add_argument("--precrack-device", choices=("auto", "on", "off"),
                        default="auto",
                        help="derive pre-crack PMKs on the accelerator: "
                             "auto engages only on a real TPU; the host "
                             "oracle fallback is bit-identical")
        sp.add_argument("--precrack-limit", type=int, default=100,
                        help="max unprocessed nets per pre-crack sweep")
        sp.add_argument("--precrack-dict-limit", type=int, default=64,
                        help="top-N cracked-corpus passwords replayed per "
                             "pre-crack sweep (0 disables the dict source)")
        sp.add_argument("--no-precrack-ingest", action="store_true",
                        help="don't sweep new nets synchronously at "
                             "capture ingestion (the recurring job still "
                             "covers them on --precrack-interval)")

    sp = sub.add_parser("serve", help="run the HTTP API + UI")
    common(sp)
    sp.add_argument("--host", default=None, help="bind address (default 127.0.0.1)")
    sp.add_argument("--port", type=int, default=None,
                    help="port (default 8080; 0 = OS-assigned)")
    sp.add_argument("--base-url", dest="base_url", help="public URL for mailed links")
    sp.add_argument("--bosskey", help="32-hex superuser key (conf.php)")
    sp.add_argument("--hcdir", help="client-distribution dir (web/hc/): "
                                    "dwpa_tpu.version + dwpa_tpu.pyz")
    sp.add_argument("--capture-cap", dest="capture_cap", type=int, default=None,
                    help="capture upload size bound in bytes, raw and "
                         "gzip-decompressed (default 8 MiB — the reference's "
                         "deployment-tunable PHP upload limit)")
    sp.add_argument("--max-inflight", dest="max_inflight", type=int,
                    default=None,
                    help="admission-control cap on live leases; extra "
                         "get_work calls get HTTP 429 + Retry-After "
                         "(default 4096, 0 disables)")
    sp.add_argument("--no-work-queue", dest="no_work_queue",
                    action="store_true",
                    help="disable the precomputed issuable-unit queue and "
                         "fall back to per-request table scans")
    sp.add_argument("--with-jobs", action="store_true",
                    help="run the cron layer as a background thread of "
                         "this process (single-process deployment)")
    sp.add_argument("--recaptcha-secret",
                    help="enable reCAPTCHA siteverify on key issue "
                         "(index.php:16-35)")
    sp.add_argument("--recaptcha-url", help="override the siteverify "
                                            "endpoint (stub testing)")
    sp.add_argument("--mx-check", action="store_true",
                    help="DNS MX probe on e-mail validation "
                         "(validEmail, common.php:981-992)")
    jobs_flags(sp)
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("jobs", help="run maintenance + keygen precompute")
    common(sp)
    sp.add_argument("--loop", action="store_true")
    jobs_flags(sp)
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("recrack", help="re-verify every cracked net")
    common(sp)
    sp.add_argument("--limit", type=int)
    sp.set_defaults(fn=cmd_recrack)

    sp = sub.add_parser("pack-dict", help="package a wordlist for serving")
    common(sp)
    sp.add_argument("source", help="input wordlist (.txt or .txt.gz)")
    sp.add_argument("--name", required=True, help="served dict name")
    sp.add_argument("--rules", help="hashcat rules file to attach")
    sp.add_argument("--default-rules", action="store_true",
                    help="attach the bundled WPA ruleset (rules/wpa.rule)")
    sp.set_defaults(fn=cmd_pack_dict)

    sp = sub.add_parser("dedup-dicts", help="cross-dict dedup, earlier wins")
    sp.add_argument("paths", nargs="+")
    sp.add_argument("--db", help="also refresh dicts rows")
    sp.add_argument("--dictdir")
    sp.add_argument("--capdir")
    sp.set_defaults(fn=cmd_dedup_dicts)

    sp = sub.add_parser("fill-pr", help="backfill probe-request tables")
    common(sp)
    sp.add_argument("--limit", type=int)
    sp.add_argument("--native", action="store_true",
                    help="use the C++ bulk parser (native/capture_fast)")
    sp.set_defaults(fn=cmd_fill_pr)

    sp = sub.add_parser("enrich", help="backfill message_pair from captures")
    common(sp)
    sp.add_argument("--limit", type=int)
    sp.add_argument("--native", action="store_true",
                    help="use the C++ bulk parser (native/capture_fast)")
    sp.set_defaults(fn=cmd_enrich)

    sp = sub.add_parser("ks-add",
                        help="add a smart-keyspace rule: nets whose SSID "
                             "matches --ssid-re get mask shards compiled "
                             "from --pass-re scheduled alongside dicts")
    common(sp)
    sp.add_argument("--ssid-re", required=True,
                    help="SSID filter (re.search semantics; anchor with "
                         "^...$ for an exact match)")
    sp.add_argument("--pass-re", required=True,
                    help="password pattern in the bounded dialect "
                         "(literals, [...], \\d, {n}/{m,n}/?, top-level "
                         "|); anything else is rejected loudly")
    sp.add_argument("--priority", type=int, default=0,
                    help="higher priorities are planned first")
    sp.add_argument("--disabled", action="store_true",
                    help="insert the rule disabled (enable later in SQL)")
    sp.set_defaults(fn=cmd_ks_add)

    sp = sub.add_parser("ks-list",
                        help="list smart-keyspace rules with compiled "
                             "keyspace sizes")
    common(sp)
    sp.set_defaults(fn=cmd_ks_list)

    sp = sub.add_parser("reorder-captures",
                        help="migrate a flat capture archive to the dated "
                             "CAP/Y/m/d layout (misc/reorder_by_date.sh)")
    common(sp)
    sp.set_defaults(fn=cmd_reorder_captures)

    sp = sub.add_parser("pack-client",
                        help="build the hc/ self-update artifacts "
                             "(dwpa_tpu.pyz + version manifest)")
    sp.add_argument("--conf", help="JSON conf file (supplies hcdir)")
    sp.add_argument("--hcdir", help="output dir served at /hc/")
    sp.add_argument("--version", help="override the advertised version")
    sp.set_defaults(fn=cmd_pack_client)

    sp = sub.add_parser("migrate",
                        help="convert legacy hccapx/16800 storage to m22000")
    common(sp)
    sp.add_argument("sources", nargs="+",
                    help="hccapx file(s) and/or legacy PMKID line file(s)")
    sp.add_argument("--no-verify", action="store_true",
                    help="skip the post-migration recrack pass")
    sp.set_defaults(fn=cmd_migrate)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
