"""Server-rendered HTML pages (the reference's web/content/ CMS).

Behavioral equivalents of nets.php / search.php / stats.php / my_nets.php /
dicts.php / home.php / submit.php / get_key.php, rendered straight from the
sqlite core.  The three visibility tiers match the reference exactly
(nets.php:17-53):

- **bosskey** viewer sees every password;
- **anonymous** viewer sees 'Found' placeholders for cracked nets;
- **keyed** viewer additionally sees the real password for nets linked to
  their own user (the n2u join).

Uncracked nets render a per-net PSK input whose POST goes through
``build_cand`` -> put_work (nets.php:6-8) — crowdsourced manual cracking,
verified server-side like every other claim.
"""

import html
import time
from dataclasses import dataclass

from .core import ServerCore

PAGE_LIMIT = 20


@dataclass(frozen=True)
class Viewer:
    """Resolved identity of the requesting browser (cookie key)."""

    key: str = ""
    is_boss: bool = False
    u_id: int = None

    @property
    def tier(self) -> str:
        if self.is_boss:
            return "boss"
        return "keyed" if self.u_id is not None else "anonymous"


def resolve_viewer(core: ServerCore, key: str) -> Viewer:
    from .core import valid_key

    if not key or not valid_key(key):
        return Viewer()
    if core.bosskey and key == core.bosskey:
        return Viewer(key=key, is_boss=True)
    row = core.db.q1("SELECT u_id FROM users WHERE userkey = ?", (key,))
    return Viewer(key=key, u_id=row["u_id"] if row else None)


# ---------------------------------------------------------------------------
# display decoding (common.php:1036-1110)
# ---------------------------------------------------------------------------


def decode_keyver(keyver: int) -> str:
    return {1: "WPA", 2: "WPA2", 3: "WPA2_11w", 100: "PMKID"}.get(keyver, "UNC")


def decode_mp(mp, keyver: int) -> str:
    mp = int(mp or 0)
    if keyver == 100:
        if mp & 0x01:
            res = "AP"
        elif mp & 0x10:
            res = "CL"
        else:
            res = "UNK"
        if mp & 0b10:
            res += " possible FT"
        return res
    low = mp & 0b111
    res = {
        0b000: "M1M2/M2/U", 0b001: "M1M4/M4/A", 0b010: "M2M3/M2/A",
        0b011: "M2M3/M3/A", 0b100: "M3M4/M3/A", 0b101: "M3M4/M4/A",
    }.get(low, "UNK")
    if mp & 0b00010000:
        res += " AP-less"
    if mp & 0b10000000:
        res += " RCnC"
    if mp & 0b00100000:
        res += " LE"
    if mp & 0b01000000:
        res += " BE"
    return res


def decode_keyinfo(n_state, algo, nc, endian) -> str:
    if n_state == 2:
        return "Uncrackable"
    res = ""
    if algo:
        res += algo
    if nc:
        res += f" nc: {nc}"
    if endian:
        res += f" {endian}"
    return res.strip()


def convert_num(n: float) -> str:
    """Human units (common.php:995-1012)."""
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1000:
            return f"{n:.2f}".rstrip("0").rstrip(".") + unit
        n /= 1000
    return f"{n:.2f}P"


def convert_sec(sec: float) -> str:
    sec = int(sec)
    out = []
    for label, span in (("d", 86400), ("h", 3600), ("m", 60), ("s", 1)):
        if sec >= span or (label == "s" and not out):
            out.append(f"{sec // span}{label}")
            sec %= span
    return " ".join(out)


# ---------------------------------------------------------------------------
# nets table renderer (write_nets, common.php:1113-1168)
# ---------------------------------------------------------------------------


_NET_COLS = (
    "n.hash AS hash, n.bssid, n.ssid, n.keyver, n.message_pair, n.algo, "
    "n.nc, n.endian, n.hits, n.ts, n.n_state, b.country"
)


def _pass_select(viewer: Viewer) -> str:
    """The tier-dependent password column (nets.php:17-53)."""
    if viewer.is_boss:
        return "n.pass AS pass"
    if viewer.u_id is not None:
        return (
            "CASE WHEN n2u.u_id IS NOT NULL THEN n.pass "
            "WHEN n.pass IS NOT NULL THEN CAST('Found' AS BLOB) "
            "ELSE NULL END AS pass"
        )
    return (
        "CASE WHEN n.pass IS NOT NULL THEN CAST('Found' AS BLOB) "
        "ELSE NULL END AS pass"
    )


def _viewer_join(viewer: Viewer) -> str:
    if viewer.u_id is not None and not viewer.is_boss:
        return "LEFT JOIN n2u ON n2u.net_id = n.net_id AND n2u.u_id = :uid"
    return ""


def write_nets(rows) -> str:
    out = [
        '<form class="form" method="post">',
        '<table class="nets">',
        "<tr><th>CC</th><th>BSSID</th><th>SSID</th><th>Type</th><th>Feat</th>"
        "<th>WPA key</th><th>Key info</th><th>Get works</th><th>Timestamp</th></tr>",
    ]
    has_input = False
    for r in rows:
        bssid = f"{r['bssid']:012x}"
        ssid = html.escape(r["ssid"].decode("utf-8", "replace"))
        if r["n_state"] == 0:
            has_input = True
            key_cell = f'<input class="input" name="{r["hash"].hex()}">'
        else:
            p = r["pass"]
            key_cell = html.escape((p or b"").decode("utf-8", "replace"))
        cc = (r["country"] or "xx").lower()
        ts = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(r["ts"]))
        out.append(
            f'<tr><td>{cc}</td>'
            f'<td class="bssid"><a href="https://wigle.net/search?netid='
            f'{":".join(bssid[i:i+2] for i in range(0, 12, 2))}">{bssid}</a></td>'
            f"<td>{ssid}</td><td>{decode_keyver(r['keyver'])}</td>"
            f"<td>{decode_mp(r['message_pair'], r['keyver'])}</td>"
            f"<td>{key_cell}</td>"
            f"<td>{decode_keyinfo(r['n_state'], r['algo'], r['nc'], r['endian'])}</td>"
            f"<td>{r['hits']}</td><td>{ts}</td></tr>"
        )
    out.append("</table>")
    if has_input:
        out.append('<br><input class="btn" type="submit" value="Send WPA keys">')
    out.append("</form>")
    return "\n".join(out)


def _query_nets(core: ServerCore, viewer: Viewer, where: str, params: dict,
                order: str = "n.ts DESC", limit: int = PAGE_LIMIT,
                offset: int = 0) -> list:
    params = dict(params, lim=limit, off=offset)
    join = _viewer_join(viewer)
    if join:
        params["uid"] = viewer.u_id
    sql = f"""SELECT {_NET_COLS}, {_pass_select(viewer)}
              FROM nets n LEFT JOIN bssids b ON n.bssid = b.bssid
              {join}
              WHERE {where} ORDER BY {order} LIMIT :lim OFFSET :off"""
    return core.db.q(sql, params)


# ---------------------------------------------------------------------------
# pages
# ---------------------------------------------------------------------------


def page_nets(core: ServerCore, viewer: Viewer) -> str:
    """Last 20 submitted networks (nets.php)."""
    rows = _query_nets(core, viewer, "n.n_state < 2", {})
    return "<h1>Last 20 submitted networks</h1>\n" + write_nets(rows)


def page_search(core: ServerCore, viewer: Viewer, search: str) -> str:
    """BSSID / OUI / client-MAC / SSID search (search.php:12-117)."""
    out = ["<h1>Search networks</h1>"]
    out.append(
        '<form method="get">'
        f'<input class="input" name="search" value="{html.escape(search)}">'
        '<input class="btn" type="submit" value="Search"></form>'
    )
    if len(search) >= 3:
        column = "bssid"
        if search.startswith("client:"):
            search = search[7:].strip()
            column = "mac_sta"
        mac = search.replace(":", "").replace("-", "").lower()
        if len(mac) == 12 and all(c in "0123456789abcdef" for c in mac):
            rows = _query_nets(
                core, viewer, f"n.{column} = :mac AND n.n_state < 2",
                {"mac": int(mac, 16)},
            )
        elif len(mac) == 6 and all(c in "0123456789abcdef" for c in mac):
            # OUI match: top 24 bits (search.php:59-85)
            rows = _query_nets(
                core, viewer, f"(n.{column} >> 24) = :oui AND n.n_state < 2",
                {"oui": int(mac, 16)},
            )
        else:
            like = search if ("_" in search or "%" in search) else search + "%"
            # ssid is a BLOB column; sqlite's LIKE is false for blob
            # operands, so compare through a text cast
            rows = _query_nets(
                core, viewer,
                "CAST(n.ssid AS TEXT) LIKE :ssid AND n.n_state < 2",
                {"ssid": like},
            )
        out.append(write_nets(rows))
    return "\n".join(out)


def page_my_nets(core: ServerCore, viewer: Viewer, page: int = 1) -> str:
    """Paginated per-user nets + potfile download link (my_nets.php)."""
    out = ["<h1>My networks</h1>"]
    if viewer.u_id is None:
        out.append("No user key set.")
        return "\n".join(out)
    offset = (max(1, page) - 1) * PAGE_LIMIT
    rows = core.db.q(
        f"""SELECT {_NET_COLS}, n.pass AS pass
            FROM nets n JOIN n2u ON n.net_id = n2u.net_id
            LEFT JOIN bssids b ON n.bssid = b.bssid
            WHERE n2u.u_id = :uid AND n.n_state < 2
            ORDER BY n.ts DESC, n.bssid ASC LIMIT :lim OFFSET :off""",
        {"uid": viewer.u_id, "lim": PAGE_LIMIT, "off": offset},
    )
    total = core.db.q1(
        "SELECT COUNT(*) c FROM nets n JOIN n2u ON n.net_id = n2u.net_id "
        "WHERE n2u.u_id = ? AND n.n_state < 2",
        (viewer.u_id,),
    )["c"]
    out.append(write_nets(rows))
    out.append('<a href="?api&dl=1" class="btn">Download all founds</a>')
    pages = -(-total // PAGE_LIMIT)
    out.append('<div class="pagination">')
    for i in range(1, pages + 1):
        if i == page:
            out.append(f'<span class="btn active">{i}</span>')
        else:
            out.append(f'<a href="?my_nets&page={i}" class="btn">{i}</a>')
    out.append("</div>")
    return "\n".join(out)


def page_stats(core: ServerCore) -> str:
    """Totals, splits, 24h perf, contributors, round ETA + progress bar
    (stats.php:5-84)."""
    s = {r["name"]: r["value"] for r in core.db.q("SELECT name, value FROM stats")}
    g = lambda k: int(s.get(k, 0))
    out = ["<h1>Statistics</h1>"]
    out.append(f"Total nets: {g('nets')}<br>")
    out.append(f"Cracked nets: {g('cracked')} / Uncracked: {g('uncracked')}<br>")
    if g("nets"):
        out.append(f"Success rate: {g('cracked') / g('nets') * 100:.2f}%<br>")
    out.append(f"PMKID nets: {g('pmkid')} / cracked: {g('pmkid_cracked')}<br>")
    out.append(
        f"Cracked by known algorithm: {g('rkg_cracked')} / {g('rkg')}<br>"
    )
    if g("geo"):
        out.append(f"Geolocated nets: {g('geo')}<br>")
    out.append(f"Last 24h processed nets: {g('24getwork')}<br>")
    out.append(f"Last 24h performance: {convert_num(g('24psk') / 86400)}/s<br>")
    out.append(f"Last 24h submissions: {g('24sub')}<br>")
    out.append(f"Last 24h founds: {g('24founds')}<br>")
    live = core.db.q1(
        "SELECT COUNT(DISTINCT hkey) d, COUNT(hkey) t FROM n2d "
        "WHERE hkey IS NOT NULL"
    )
    out.append(
        f"Current contributors count: {live['d']} working on {live['t']} nets<br>"
    )
    rate = g("24psk") / 86400
    remaining = g("words") - g("triedwords")
    eta = convert_sec(remaining / rate) if rate > 0 else "infinity"
    out.append(f"Current round ends in: {eta}<br>")
    words = g("words") or 1
    pct = round(g("triedwords") / words * 100, 2)
    out.append(
        f'Current keyspace progress: <dl class="progress">'
        f'<dd class="done" style="width: {pct}%">{pct}%</dd></dl>'
    )
    return "\n".join(out)


def page_dicts(core: ServerCore) -> str:
    rows = core.db.q(
        "SELECT dpath, dname, wcount, hits FROM dicts "
        "ORDER BY wcount DESC, dname DESC"
    )
    out = [
        "<h1>Dictionaries</h1>",
        '<table class="dicts">',
        "<tr><th>Dictionary</th><th>Word count</th><th>Hits</th></tr>",
    ]
    for r in rows:
        out.append(
            f'<tr><td><a href="{html.escape(r["dpath"])}">'
            f'{html.escape(r["dname"])}</a></td>'
            f"<td>{r['wcount']}</td><td>{r['hits']}</td></tr>"
        )
    out.append("</table>")
    out.append('Keygen generated dict: <a href="dict/rkg.txt.gz">rkg.txt.gz</a>')
    return "\n".join(out)


def page_home() -> str:
    return (
        "<h1>dwpa_tpu — distributed WPA security audit</h1>\n"
        "<p>Upload a capture (?submit), fetch your key (?get_key), watch "
        "progress (?stats). Volunteer clients crack work units on TPU "
        "meshes and every claimed PSK is independently re-verified.</p>"
    )


def page_submit() -> str:
    return (
        "<h1>Submit capture</h1>\n"
        '<form method="post" enctype="multipart/form-data">'
        '<input type="file" name="file">'
        '<input class="btn" type="submit" value="Upload"></form>'
    )


def page_get_key(message: str = None, has_key: bool = False) -> str:
    out = ["<h1>Get key</h1>"]
    if message:
        out.append(html.escape(message))
    elif has_key:
        out.append("Key already issued.")
    else:
        out.append(
            '<form method="post">'
            '<input class="input" name="mail" placeholder="e-mail">'
            '<input class="btn" type="submit" value="Get key"></form>'
        )
    return "\n".join(out)


def render(body: str, title: str = "dwpa_tpu") -> bytes:
    return (
        f"<!DOCTYPE html><html><head><title>{html.escape(title)}</title></head>"
        "<body>"
        '<nav><a href="?nets">nets</a> <a href="?search">search</a> '
        '<a href="?stats">stats</a> <a href="?my_nets">my nets</a> '
        '<a href="?dicts">dicts</a> <a href="?submit">submit</a> '
        '<a href="?get_key">get key</a></nav><hr>'
        f"{body}</body></html>"
    ).encode()
