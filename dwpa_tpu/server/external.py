"""Outward-facing HTTP/DNS integrations, behind the existing seams.

The reference ships live API clients for wigle geolocation
(web/wigle.php:30-53), the 3wifi PSK database (web/3wifi.php:27-66),
Google reCAPTCHA verification (web/index.php:16-35), and a DNS MX probe
inside validEmail (web/common.php:981-992).  This module provides the
same adapters as urllib-based callables matching the pluggable seam
shapes already used by the jobs/API layers:

- :class:`WigleClient`     -> ``jobs.geolocate``'s ``lookup(mac) -> dict|None``
- :class:`ThreeWifiClient` -> ``jobs.psk_lookup``'s ``lookup(macs) -> {mac: psk}``
- :class:`RecaptchaVerifier` -> ``ServerCore.captcha``'s ``(response, ip) -> bool``
- :func:`mx_email_validator` -> wraps ``core.valid_email`` with an MX probe

Every adapter takes a ``url`` override and an injectable ``opener`` /
``resolver`` / ``sleep`` so the full request/response path is testable
against a local stub server (this build environment has zero egress; the
defaults point at the real services).  Failure semantics mirror the
reference's: a transport/parse error or service refusal raises
``jobs.LookupUnavailable`` so the cron layer leaves the rows unmarked
for retry (wigle.php only stamps ``wiglets`` after a parsed successful
response), while a successful-but-empty answer is a definitive
"not found"; the captcha verifier fails closed.
"""

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request

WIGLE_URL = "https://api.wigle.net/api/v2/network/search"
WIFI3_URL = "https://3wifi.stascorp.com/api/apiquery"
RECAPTCHA_URL = "https://www.google.com/recaptcha/api/siteverify"
USER_AGENT = "wpa-sec"  # the reference identifies itself as wpa-sec


def _fetch(req, opener=None, timeout=30):
    """GET/POST ``req`` and parse the JSON body.

    Transport and parse failures raise :class:`jobs.LookupUnavailable`
    so the cron layer retries the same rows next tick instead of
    marking them attempted — the reference only stamps its
    wiglets/wifi3ts timestamps after a parsed, successful response
    (wigle.php:33-49, 3wifi.php:50-79)."""
    from .jobs import LookupUnavailable

    opener = opener or urllib.request.urlopen
    try:
        with opener(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise LookupUnavailable(str(e))


class _Throttle:
    """Min-interval limiter (wigle.php:53 sleeps 1 s between queries)."""

    def __init__(self, interval_s, sleep=time.sleep, clock=time.monotonic):
        self.interval_s = interval_s
        self._sleep = sleep
        self._clock = clock
        self._last = None

    def wait(self):
        now = self._clock()
        if self._last is not None:
            remaining = self.interval_s - (now - self._last)
            if remaining > 0:
                self._sleep(remaining)
                now = self._clock()
        self._last = now


class WigleClient:
    """wigle.net network-search geolocation (wigle.php:30-53).

    ``__call__(mac: bytes) -> dict | None`` — the ``jobs.geolocate``
    lookup seam.  GET ``?netid=AA:BB:CC:DD:EE:FF`` with Basic auth; a
    unique result (resultCount == 1) maps to the bssids-row fields, any
    other answer is None (the reference then only refreshes the
    attempt timestamp).
    """

    def __init__(self, api_key: str, url: str = WIGLE_URL, *,
                 throttle_s: float = 1.0, opener=None, sleep=time.sleep):
        self.api_key = api_key
        self.url = url
        self.opener = opener
        self.throttle = _Throttle(throttle_s, sleep=sleep)

    def __call__(self, mac: bytes):
        self.throttle.wait()
        netid = ":".join("%02x" % b for b in mac)
        req = urllib.request.Request(
            self.url + "?" + urllib.parse.urlencode({"netid": netid}),
            headers={
                "Content-Type": "application/json",
                "User-Agent": USER_AGENT,
                "Authorization": "Basic " + self.api_key,
            },
        )
        data = _fetch(req, self.opener)
        if not data or not data.get("success"):
            # service-side refusal (quota, auth): retryable, not "no hit"
            from .jobs import LookupUnavailable

            raise LookupUnavailable("wigle answered without success=true")
        if data.get("resultCount") != 1 or not data.get("results"):
            return None
        r = data["results"][0]
        return {
            "lat": r.get("trilat"),
            "lon": r.get("trilong"),
            "country": r.get("country"),
            "region": r.get("region"),
            "city": r.get("city"),
        }


class ThreeWifiClient:
    """3wifi batch PSK lookup (3wifi.php:40-66).

    ``__call__(macs: list[bytes]) -> {mac_bytes: psk_bytes}`` — the
    ``jobs.psk_lookup`` seam; answers flow through the normal put_work
    re-verification, exactly like the reference submits them.
    """

    def __init__(self, api_key: str, url: str = WIFI3_URL, *, opener=None):
        self.api_key = api_key
        self.url = url
        self.opener = opener

    def __call__(self, macs):
        if not macs:
            return {}
        payload = json.dumps({
            "key": self.api_key,
            "bssid": [mac.hex() for mac in macs],
        }).encode()
        req = urllib.request.Request(
            self.url, data=payload,
            headers={"Content-Type": "application/json",
                     "User-Agent": USER_AGENT},
        )
        data = _fetch(req, self.opener)
        if not data or not data.get("result"):
            from .jobs import LookupUnavailable

            raise LookupUnavailable("3wifi answered without result=true")
        out = {}
        entries = data.get("data") or {}
        # the reference iterates data values, each a list of candidate
        # rows, and takes the first row's bssid/key (3wifi.php:52-58)
        if isinstance(entries, dict):
            entries = entries.values()
        for d in entries:
            try:
                row = d[0] if isinstance(d, list) else d
                mac = bytes.fromhex(row["bssid"].replace(":", "").lower())
                key = row["key"]
            except (KeyError, TypeError, ValueError, IndexError,
                    AttributeError):
                continue  # empty list / malformed row (e.g. numeric bssid)
            if len(mac) == 6 and key:
                out[mac] = key.encode() if isinstance(key, str) else key
        return out


class RecaptchaVerifier:
    """Google reCAPTCHA siteverify (index.php:16-35).

    ``__call__(response, ip) -> bool`` — the ``ServerCore.captcha`` seam.
    POSTs the urlencoded secret/response/remoteip form and accepts only
    an explicit ``success: true``.
    """

    def __init__(self, secret: str, url: str = RECAPTCHA_URL, *, opener=None):
        self.secret = secret
        self.url = url
        self.opener = opener

    def __call__(self, response: str, ip: str = "") -> bool:
        body = urllib.parse.urlencode({
            "secret": self.secret,
            "response": response or "",
            "remoteip": ip or "",
        }).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/x-www-form-urlencoded",
                     "User-Agent": USER_AGENT},
        )
        from .jobs import LookupUnavailable

        try:
            data = _fetch(req, self.opener)
        except LookupUnavailable:
            return False  # unreachable verifier: fail closed, like the reference
        return bool(data and data.get("success") is True)


def mx_email_validator(resolver=None):
    """Build a ``valid_email``-shaped callable with the reference's MX
    probe (validEmail, common.php:981-992): format check first, then
    ``checkdnsrr(domain., 'MX')``.

    ``resolver(domain: str) -> bool`` answers "does this domain have an
    MX record".  The stdlib cannot issue MX queries; the default
    resolver shells out to ``getent``-independent ``nslookup -type=MX``
    if available and otherwise accepts the domain (fail-open, so an
    airgapped deployment does not lock every user out).
    """
    from .core import valid_email as format_ok

    if resolver is None:
        resolver = _nslookup_mx

    def check(mail: str) -> bool:
        if not format_ok(mail):
            return False
        domain = mail.rsplit("@", 1)[1]
        try:
            return bool(resolver(domain))
        except Exception:
            return True  # resolver trouble must not block key issuance

    return check


def _nslookup_mx(domain: str) -> bool:
    import shutil
    import subprocess

    exe = shutil.which("nslookup")
    if exe is None:
        return True  # no resolver tooling: fail open
    # LANG/LC_ALL=C: _parse_mx_output matches English resolver strings
    # ("can't find", "non-existent domain") — under a non-English locale
    # the negatives would never match and every probe would fail open.
    out = subprocess.run(
        [exe, "-type=MX", domain + "."],
        capture_output=True, text=True, timeout=10,
        env={**os.environ, "LANG": "C", "LC_ALL": "C"},
    )
    return _parse_mx_output(out.stdout + out.stderr)


def _parse_mx_output(text: str) -> bool:
    """Decide MX presence from resolver output.

    Only an affirmative "domain does not resolve" rejects the address;
    anything else (busybox nslookup without -type support, odd output
    formats) fails open — a present-but-incompatible resolver must not
    silently lock every user out of key issuance.
    """
    text = text.lower()
    if "mail exchanger" in text:
        return True
    negatives = ("nxdomain", "can't find", "no servers could be reached",
                 "server can't", "non-existent domain")
    return not any(n in text for n in negatives)
