"""Pluggable mail delivery for the user-key flows.

The reference bundles PHPMailer (web/mail.php + web/m/, ~5.3k LoC) purely
to send two messages: the initial "here is your key" mail and the 24h
key-reset confirmation link (web/index.php:66-99).  Here that surface is
a two-method seam: production uses SmtpMailer (stdlib smtplib), tests use
CapturingMailer, and a core with ``mailer=None`` simply skips delivery —
the same observable behavior as the reference's swallowed mail exceptions
(index.php:72, 96: ``catch (Exception $e) { }``).
"""

import smtplib
from email.message import EmailMessage


class Mailer:
    """Interface: deliver one plain-text message; errors must not raise
    into the request path (reference swallows them too)."""

    def send(self, to: str, subject: str, body: str) -> bool:
        raise NotImplementedError


class CapturingMailer(Mailer):
    """Test double: records (to, subject, body) tuples."""

    def __init__(self):
        self.sent = []

    def send(self, to: str, subject: str, body: str) -> bool:
        self.sent.append((to, subject, body))
        return True


class SmtpMailer(Mailer):
    def __init__(self, host: str = "localhost", port: int = 25,
                 sender: str = "noreply@localhost",
                 username: str = None, password: str = None,
                 starttls: bool = False):
        self.host, self.port, self.sender = host, port, sender
        self.username, self.password = username, password
        self.starttls = starttls

    def send(self, to: str, subject: str, body: str) -> bool:
        msg = EmailMessage()
        msg["From"] = self.sender
        msg["To"] = to
        msg["Subject"] = subject
        msg.set_content(body)
        try:
            with smtplib.SMTP(self.host, self.port, timeout=30) as s:
                if self.starttls:
                    s.starttls()
                if self.username:
                    s.login(self.username, self.password or "")
                s.send_message(msg)
            return True
        except (OSError, smtplib.SMTPException):
            return False
