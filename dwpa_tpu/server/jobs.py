"""Async maintenance jobs (the reference's cron layer, SURVEY.md §2.7).

- ``maintenance``: hourly stats recompute, stale work-unit lease reaping
  (3 h, the elastic-recovery mechanism — maint.php:36), cracked-dictionary
  regeneration ordered by password frequency (maint.php:41-77);
- ``keygen_precompute``: the rkg.php equivalent — per unprocessed net, run
  default-key generators + the "Single" bssid/ssid mutation generator,
  verify each candidate with the oracle, and finally set ``algo`` (''
  when nothing matched), which *releases* the net to the get_work
  scheduler (get_work only serves algo='' nets, get_work.php:65,101);
- ``precrack``: the batched superset of ``keygen_precompute``
  (server/precrack.py): vendor packs + IMEI sweeps + Single/Pattern +
  cracked-corpus dictionary + cross-net replay, derived as one fused
  mixed-ESSID device batch and demuxed per net;
- ``geolocate``: wigle.php/3wifi.php equivalent, behind a pluggable
  lookup function (this environment has zero egress; the reference calls
  external HTTP APIs with throttles).

Run them from a scheduler loop or one-shot (``python -m dwpa_tpu.server
--jobs`` style); they are plain functions over the Database.
"""

import gzip
import hashlib
import os
import time

from ..gen.dicts import md5_file
from ..gen.psktool import psk_candidates
from ..gen.vendors import vendor_candidates
from ..keyspace.schedule import mask_keyspace_totals
from ..models import hashline as hl
from ..obs import get_logger
from ..oracle import m22000 as oracle
from .core import LEASE_REAP_S, LEASE_RETENTION_S, SERVER_NC, ServerCore
from .db import long2mac
from .precrack import PrecrackEngine

_log = get_logger(__name__)


def _job_timer(core: ServerCore, job: str):
    """Span for one cron job, recorded into the core's registry as
    ``dwpa_span_seconds{span="job:..."}`` — the jobs are pure
    host/sqlite work (plus oracle verify), so the span needs no device
    sync."""
    from ..obs import SpanTracer

    tracer = getattr(core, "_job_tracer", None)
    if tracer is None:
        tracer = core._job_tracer = SpanTracer(core.registry)
    return tracer.span(job)


def maintenance(core: ServerCore, cracked_dict_path: str = None) -> dict:
    """Stats + lease reaping + cracked-dict regen; returns the stats."""
    with _job_timer(core, "job:maintenance"):
        return _maintenance(core, cracked_dict_path)


def _maintenance(core: ServerCore, cracked_dict_path: str = None) -> dict:
    db = core.db
    day_ago = time.time() - 86400
    if cracked_dict_path is None and core.dictdir:
        cracked_dict_path = os.path.join(core.dictdir, "cracked.txt.gz")

    s = {}
    s["nets"] = db.q1("SELECT COUNT(*) c FROM nets")["c"]
    s["cracked"] = db.q1("SELECT COUNT(*) c FROM nets WHERE n_state = 1")["c"]
    s["uncracked"] = db.q1("SELECT COUNT(*) c FROM nets WHERE n_state = 0")["c"]
    s["pmkid"] = db.q1("SELECT COUNT(*) c FROM nets WHERE keyver = 100")["c"]
    s["pmkid_cracked"] = db.q1(
        "SELECT COUNT(*) c FROM nets WHERE keyver = 100 AND n_state = 1"
    )["c"]
    s["rkg"] = db.q1("SELECT COUNT(DISTINCT net_id) c FROM rkg")["c"]
    s["rkg_cracked"] = db.q1(
        "SELECT COUNT(*) c FROM nets WHERE n_state = 1 AND algo != '' AND algo IS NOT NULL"
    )["c"]
    s["geo"] = db.q1("SELECT COUNT(*) c FROM bssids WHERE lat IS NOT NULL")["c"]
    s["submissions"] = db.q1("SELECT COUNT(*) c FROM submissions")["c"]
    s["users"] = db.q1("SELECT COUNT(*) c FROM users")["c"]
    s["24sub"] = db.q1(
        "SELECT COUNT(*) c FROM submissions WHERE ts > ?", (day_ago,)
    )["c"]
    s["24founds"] = db.q1(
        "SELECT COUNT(*) c FROM nets WHERE n_state = 1 AND ts > ?", (day_ago,)
    )["c"]
    s["24getwork"] = db.q1(
        "SELECT COUNT(DISTINCT hkey) c FROM n2d WHERE ts > ?", (day_ago,)
    )["c"]
    # 24 h keyspace throughput: sum of dict wordcounts over last-day
    # leases, plus last-day mask-shard spans (the shard IS its count)
    s["24psk"] = db.q1(
        """SELECT COALESCE(SUM(d.wcount), 0) c FROM n2d
           JOIN dicts d ON d.d_id = n2d.d_id WHERE n2d.ts > ?""",
        (day_ago,),
    )["c"] + db.q1(
        "SELECT COALESCE(SUM(span), 0) c FROM n2m WHERE ts > ?", (day_ago,)
    )["c"]
    # round totals: dict words × uncracked nets plus the scheduled mask
    # keyspace of every matching enabled ks row (smart keyspace — the
    # dicts-only total undercounted the round once mask shards existed)
    total_words = db.q1("SELECT COALESCE(SUM(wcount), 0) c FROM dicts")["c"]
    mask_total, _ = mask_keyspace_totals(db, core._ks_cache)
    s["words"] = s["uncracked"] * total_words + mask_total
    s["triedwords"] = db.q1(
        """SELECT COALESCE(SUM(d.wcount), 0) c FROM n2d
           JOIN dicts d ON d.d_id = n2d.d_id
           JOIN nets n ON n.net_id = n2d.net_id WHERE n.n_state = 0"""
    )["c"] + db.q1(
        """SELECT COALESCE(SUM(m.span), 0) c FROM n2m m
           JOIN nets n ON n.net_id = m.net_id WHERE n.n_state = 0"""
    )["c"]
    s["contributors"] = db.q1(
        "SELECT COUNT(DISTINCT hkey) c FROM n2d WHERE hkey IS NOT NULL"
    )["c"]
    for name, value in s.items():
        db.set_stat(name, value)

    # Reap stale in-flight leases AFTER the stats pass, matching the
    # reference's ordering (maint.php computes its counters at 16-32 and
    # reaps at 36) — reaping first would drop just-expired work units out
    # of 24getwork/contributors for the hour they should still count.
    # One transaction under the scheduler mutex: the coverage-row clear,
    # the lease-state flip (live -> reaped, which is what blocks the
    # stale holder's later release) and the retention prune land
    # together — a kill mid-reap never leaves a reaped lease whose n2d
    # rows still look in-flight, or vice versa.
    cutoff = time.time() - LEASE_REAP_S
    with core._getwork_lock:
        with db.tx():
            reaped = db.x(
                """UPDATE n2d SET hkey = NULL
                   WHERE hkey IS NOT NULL
                     AND (ts < ? OR hkey IN (SELECT hkey FROM leases
                                             WHERE state = 0 AND issued < ?))""",
                (cutoff, cutoff),
            ).rowcount
            # Mask shards are DELETEd, not NULLed: a NULLed n2m row would
            # count as completed coverage, but an abandoned range was
            # never searched — dropping the row reopens the gap so
            # _plan_mask_shards re-issues it under a fresh epoch, while
            # the lease flip below still blocks the stale holder's
            # release (no double-credit).
            reaped += db.x(
                """DELETE FROM n2m
                   WHERE hkey IS NOT NULL
                     AND (ts < ? OR hkey IN (SELECT hkey FROM leases
                                             WHERE state = 0 AND issued < ?))""",
                (cutoff, cutoff),
            ).rowcount
            db.x(
                """UPDATE leases SET state = 2, released = ?
                   WHERE state = 0 AND issued < ?""",
                (time.time(), cutoff),
            )
            # bound the lease ledger: released/reaped records older than
            # the retention window carry no audit value
            db.x(
                """DELETE FROM leases WHERE state != 0
                   AND COALESCE(released, issued) < ?""",
                (time.time() - LEASE_RETENTION_S,),
            )
    if reaped > 0:
        core.registry.counter(
            "dwpa_server_leases_reaped_total",
            "stale work-unit leases reclaimed by maintenance").inc(reaped)

    if cracked_dict_path:
        regen_cracked_dict(core, cracked_dict_path)
    return s


def regen_cracked_dict(core: ServerCore, path: str) -> int:
    """cracked.txt.gz: distinct non-keygen passwords by frequency
    (maint.php:41-64); non-printables emitted as $HEX[...]."""
    rows = core.db.q(
        """SELECT pass, COUNT(*) c FROM nets
           WHERE n_state = 1 AND pass IS NOT NULL AND LENGTH(pass) >= 8
             AND (algo = '' OR algo IS NULL)
           GROUP BY pass ORDER BY c DESC"""
    )
    words = []
    for r in rows:
        p = r["pass"]
        try:
            printable = p.decode("ascii").isprintable()
        except UnicodeDecodeError:
            printable = False
        words.append(p if printable else b"$HEX[%s]" % p.hex().encode())
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    data = b"\n".join(words) + (b"\n" if words else b"")
    with open(path, "wb") as f:
        # mtime=0 -> deterministic bytes, so dhash (and every client's
        # cached copy) only changes when the word list itself changes.
        with gzip.GzipFile(fileobj=f, mode="wb", compresslevel=9, mtime=0) as gz:
            gz.write(data)
    # update/insert the dict row so the scheduler hands it out
    dhash = md5_file(path)
    dname = os.path.basename(path)
    row = core.db.q1("SELECT d_id FROM dicts WHERE dname = ?", (dname,))
    if row:
        core.db.x(
            "UPDATE dicts SET dhash = ?, wcount = ? WHERE d_id = ?",
            (dhash, len(words), row["d_id"]),
        )
    else:
        core.add_dict("dict/" + dname, dname, dhash, len(words))
    return len(words)


def regen_rkg_dict(core: ServerCore, path: str) -> int:
    """rkg.txt.gz: distinct passwords of keygen-cracked nets (algo set
    and non-empty — rkg.php:178-197 regenerates this dict on any keygen
    hit so volunteers try known vendor-default keys everywhere).

    Served as a plain ``/dict/`` artifact, NOT registered in the dicts
    table — exactly the reference's arrangement: clients fetch it in
    their cracked/rkg pass 1, and registering it would double-issue the
    same words through the scheduler.  ORDER BY keeps the bytes (and so
    any cached copy) stable when the word set hasn't changed.

    Skips the gzip -9 rewrite when the word set is unchanged since the
    last regeneration: the content signature (63-bit blake2b of the
    uncompressed blob) is kept in the stats table, so every keygen hit
    on an already-known vendor key stops costing a full recompression.
    """
    rows = core.db.q(
        """SELECT DISTINCT pass FROM nets
           WHERE algo IS NOT NULL AND algo != '' AND pass IS NOT NULL
           ORDER BY pass"""
    )
    words = [r["pass"] for r in rows]
    data = b"\n".join(words) + (b"\n" if words else b"")
    # 63-bit signature: the stats table stores sqlite INTEGERs (signed
    # 64-bit); 0 is reserved for "never generated"
    sig = (int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
                          "big") >> 1) or 1
    if core.db.get_stat("rkg_dict_sig") == sig and os.path.exists(path):
        _log.info("rkg dict unchanged (%d words, sig %x) — skipping "
                  "gzip rewrite of %s", len(words), sig, path)
        return len(words)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        with gzip.GzipFile(fileobj=f, mode="wb", compresslevel=9, mtime=0) as gz:
            gz.write(data)
    core.db.set_stat("rkg_dict_sig", sig)
    return len(words)


def single_mode_candidates(bssid: bytes, ssid: bytes):
    """The "Single" generator: bssid +/-1 in 12/10/8-hex widths and ssid
    case/suffix mutations (rkg.php single_mode_generator, :48-77)."""
    b = int.from_bytes(bssid, "big")
    for delta in (0, 1, -1):
        h = f"{(b + delta) & 0xFFFFFFFFFFFF:012x}"
        for width in (12, 10, 8):
            tail = h[12 - width:]
            yield tail.encode()
            yield tail.upper().encode()
    text = ssid.decode("latin1")
    for base in (text, text.lower(), text.upper()):
        for suffix in ("", "1", "123", "!"):
            cand = (base + suffix).encode("latin1")
            if len(cand) >= 8:
                yield cand


def keygen_precompute(core: ServerCore, limit: int = 100,
                      extra_generators=None) -> dict:
    """Process up to ``limit`` nets with algo IS NULL; returns counts.

    ``extra_generators``: iterable of callables ``(bssid: bytes,
    ssid: bytes) -> iterable[tuple[str, bytes]]`` yielding (algo_name,
    candidate) pairs.  Default (None): the built-in vendor keygen
    families (gen/vendors.py — Thomson, Belkin, EasyBox, MacTail, IMEI),
    the routerkeygen-cli dispatch equivalent; pass ``[]`` to disable.
    """
    if extra_generators is None:
        extra_generators = [vendor_candidates]
    with _job_timer(core, "job:keygen_precompute"):
        return _keygen_precompute(core, limit, extra_generators)


def _keygen_precompute(core: ServerCore, limit, extra_generators) -> dict:
    db = core.db
    nets = db.q(
        "SELECT * FROM nets WHERE algo IS NULL AND n_state = 0 "
        "ORDER BY net_id LIMIT ?", (limit,)
    )
    found = 0
    for net in nets:
        h = hl.parse(net["struct"])
        bssid = long2mac(net["bssid"])
        cands = [("Single", c) for c in single_mode_candidates(bssid, h.essid)]
        cands += [("Pattern", c) for c in psk_candidates(h.essid, bssid)]
        for gen in extra_generators or []:
            cands += list(gen(bssid, h.essid))
        # Oracle verification first (pure compute, no locks held), then
        # ONE transaction per net: the rkg attempt rows, the crack mark
        # and the algo release commit together — a kill mid-net leaves
        # it fully unprocessed (algo still NULL), never half-recorded.
        # ONE oracle call per net: the oracle walks the key list with
        # identical first-match-wins semantics to the old per-candidate
        # loop, and the hit index recovers the tried prefix (the rkg
        # attempt rows the scalar loop would have recorded).
        tried, hit = list(cands), None
        keys = [c for _, c in cands]
        r = oracle.check_key_m22000(h, keys, nc=SERVER_NC) if keys else None
        if r:
            i = next(i for i, k in enumerate(keys)
                     if oracle.hc_unhex(k) == r[0])
            tried = cands[:i + 1]
            hit = (cands[i][0], cands[i][1], r)
        hit_algo = hit[0] if hit else ""
        with core._getwork_lock:
            with db.tx():
                for algo, cand in tried:
                    db.x(
                        "INSERT INTO rkg(net_id, algo, pass) VALUES (?, ?, ?)",
                        (net["net_id"], algo, cand),
                    )
                if hit:
                    _, cand, r = hit
                    core._mark_cracked(
                        net["net_id"], r[0], r[3], r[1] or 0, r[2] or ""
                    )
                    db.x(
                        "UPDATE rkg SET n_state = 1 WHERE net_id = ? AND pass = ?",
                        (net["net_id"], cand),
                    )
                    found += 1
                # setting algo (even '') releases the net to the volunteers
                db.x(
                    "UPDATE nets SET algo = ? WHERE net_id = ?",
                    (hit_algo, net["net_id"]),
                )
    if found and core.dictdir:
        # any keygen hit regenerates the vendor-key dictionary so every
        # volunteer tries known default keys everywhere (rkg.php:178-197)
        regen_rkg_dict(core, os.path.join(core.dictdir, "rkg.txt.gz"))
    return {"processed": len(nets), "cracked": found}


def precrack(core: ServerCore, limit: int = 100, batch: int = 2048,
             device: str = "auto", store=None, dict_limit: int = 64,
             imei_limit: int = None) -> dict:
    """The batched pre-crack sweep (server/precrack.py) as a cron job.

    A superset of ``keygen_precompute``: the same candidate families plus
    the cracked-corpus dictionary and cross-net replay, derived as ONE
    fused mixed-ESSID batch (device when available, host PBKDF2
    otherwise).  The engine is cached on the core (``core.precrack``) so
    the recurring job and the ingestion hook share one PMK memo/store,
    and records its own ``job:precrack`` span.
    """
    eng = core.precrack
    if eng is None:
        eng = core.precrack = PrecrackEngine(
            core, batch=batch, device=device, store=store,
            dict_limit=dict_limit, imei_limit=imei_limit)
    return eng.run(limit=limit)


class LookupUnavailable(Exception):
    """Raised by an enrichment ``lookup`` to signal a *transient* failure
    (network error, service refusal) as opposed to "queried fine, not
    found".  The batch is abandoned and no row is marked as attempted, so
    the same BSSIDs are retried next tick — matching the reference's
    wigle.php, which only stamps ``wiglets`` after a parsed, successful
    response (wigle.php:33-49)."""


def psk_lookup(core: ServerCore, lookup, batch: int = 100) -> dict:
    """External PSK-database sweep (3wifi.php equivalent).

    Batches up to ``batch`` uncracked, not-yet-queried BSSIDs through
    ``lookup(macs: list[bytes]) -> dict[mac_bytes, psk_bytes]`` and
    submits every hit through the normal put_work verification path —
    the external database is never trusted, exactly as the reference
    routes 3wifi answers through full re-verification (3wifi.php:66).
    flags bit 1 marks queried bssids (wpa.sql:16) so each is asked once.
    """
    rows = core.db.q(
        """SELECT DISTINCT n.bssid FROM nets n
           JOIN bssids b ON b.bssid = n.bssid
           WHERE n.n_state = 0 AND b.flags & 1 = 0 LIMIT ?""", (batch,)
    )
    macs = [long2mac(r["bssid"]) for r in rows]
    if not macs:
        return {"queried": 0, "submitted": 0}
    try:
        found = lookup(macs) or {}
    except LookupUnavailable:
        return {"queried": 0, "submitted": 0, "unavailable": True}
    cand = [{"k": mac.hex(), "v": psk.hex()} for mac, psk in found.items()]
    # put_work caps candidates per call (MAX_CANDS_PER_PUT, matching the
    # reference's 200-pair limit) — chunk so no hit is silently dropped.
    from .core import MAX_CANDS_PER_PUT

    for i in range(0, len(cand), MAX_CANDS_PER_PUT):
        core.put_work({"type": "bssid",
                       "cand": cand[i:i + MAX_CANDS_PER_PUT],
                       "ip": "psk_lookup"})
    with core.db.tx():
        for r in rows:
            core.db.x(
                "UPDATE bssids SET flags = flags | 1 WHERE bssid = ?",
                (r["bssid"],),
            )
    return {"queried": len(macs), "submitted": len(cand)}


def geolocate(core: ServerCore, lookup, batch: int = 5) -> int:
    """Enrich bssids rows via ``lookup(mac: bytes) -> dict|None`` with keys
    lat/lon/country/region/city (wigle.php equivalent; the reference
    throttles to 5 BSSIDs per run at 1 rps, wigle.php:37-53)."""
    rows = core.db.q(
        "SELECT bssid FROM bssids WHERE flags & 2 = 0 LIMIT ?", (batch,)
    )
    done = 0
    for r in rows:
        try:
            info = lookup(long2mac(r["bssid"]))
        except LookupUnavailable:
            break  # transient outage: leave the rest unmarked for retry
        info = info or {}
        # One statement covers both the hit and the not-found mark
        # (COALESCE keeps existing values on a miss): each row's update
        # is atomic on its own, and the lookup between rows means a
        # wider transaction would just hold the write lock across
        # network calls.
        core.db.x(
            """UPDATE bssids SET lat = COALESCE(?, lat),
                    lon = COALESCE(?, lon), country = COALESCE(?, country),
                    region = COALESCE(?, region), city = COALESCE(?, city),
                    flags = flags | 2
               WHERE bssid = ?""",
            (info.get("lat"), info.get("lon"), info.get("country"),
             info.get("region"), info.get("city"), r["bssid"]),
        )
        done += 1
    return done
