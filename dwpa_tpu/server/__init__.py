"""dwpa-compatible work server: scheduler, ingestion, verification, jobs.

A from-scratch reimplementation of the reference's PHP/MySQL server stack
(web/common.php, web/content/*, web/maint.php, web/rkg.php, db/wpa.sql) on
sqlite + stdlib WSGI, speaking the same JSON protocol as the reference so
either client works against either server.
"""

from .db import Database  # noqa: F401
from .core import ServerCore  # noqa: F401
from .api import make_wsgi_app  # noqa: F401
