"""Data model on sqlite3.

Mirrors the reference schema's entities and invariants (db/wpa.sql: nets,
submissions, bssids, dicts, n2d, n2u, users, rkg, prs, p2s, ks, stats —
see SURVEY.md §2.6) with idiomatic-sqlite choices instead of a literal DDL
translation:

- MACs stored as INTEGER (the reference packs them into BIGINT too);
- counter maintenance (nets.hits / dicts.hits) done by triggers exactly as
  the reference pushes it into the DB (wpa.sql:107-121), so concurrent
  writers stay consistent;
- the nets.hash / submissions.hash uniqueness + INSERT OR IGNORE give the
  same idempotent-ingestion semantics;
- two tables have no reference twin: ``leases`` (epoch-numbered work-unit
  leases, the crash-safety spine) and ``n2m`` (net x compiled-mask
  shard-range coverage for the smart-keyspace vertical — ``n2d``'s analog
  for the ks table, same hkey/epoch lease discipline; a reaped range is
  DELETEd, never NULLed, because a NULL hkey row MEANS completed
  coverage);
- WAL journal + a statement-level lock on the shared connection make the
  handle thread-safe under the threaded server; the larger critical
  section the reference guards with its SHM lockfile (work-unit issue)
  is ServerCore._getwork_lock.
"""

import contextlib
import sqlite3
import threading
import time

SCHEMA = """
CREATE TABLE IF NOT EXISTS nets (
    net_id   INTEGER PRIMARY KEY,
    s_id     INTEGER REFERENCES submissions(s_id),
    u_id     INTEGER,
    bssid    INTEGER NOT NULL,
    mac_sta  INTEGER NOT NULL,
    ssid     BLOB NOT NULL,
    pass     BLOB,
    pmk      BLOB,
    algo     TEXT,              -- NULL = keygen unprocessed, '' = released
    hash     BLOB NOT NULL UNIQUE,  -- md5 net identity (hashline fields 1-7)
    struct   TEXT NOT NULL,     -- the m22000 hashline
    message_pair INTEGER,
    keyver   INTEGER NOT NULL,  -- 1|2|3|100=PMKID
    nc       INTEGER,
    endian   TEXT,
    sip      TEXT,
    sts      REAL NOT NULL DEFAULT (strftime('%s','now')),
    n_state  INTEGER NOT NULL DEFAULT 0,  -- 0 uncracked, 1 cracked, 2 uncrackable
    hits     INTEGER NOT NULL DEFAULT 0,
    ts       REAL NOT NULL DEFAULT (strftime('%s','now'))
);
CREATE INDEX IF NOT EXISTS idx_nets_sched ON nets(n_state, hits, ts, algo);
CREATE INDEX IF NOT EXISTS idx_nets_bssid ON nets(bssid);
CREATE INDEX IF NOT EXISTS idx_nets_ssid ON nets(ssid);
CREATE INDEX IF NOT EXISTS idx_nets_mac_sta ON nets(mac_sta);

CREATE TABLE IF NOT EXISTS submissions (
    s_id      INTEGER PRIMARY KEY,
    localfile TEXT,
    hash      BLOB NOT NULL UNIQUE,   -- md5 of the capture file
    ip        TEXT,
    ts        REAL NOT NULL DEFAULT (strftime('%s','now'))
);

CREATE TABLE IF NOT EXISTS bssids (
    bssid   INTEGER PRIMARY KEY,
    flags   INTEGER NOT NULL DEFAULT 0,   -- bit1 = 3wifi done, bit2 = wigle done
    lat     REAL, lon REAL,
    country TEXT, region TEXT, city TEXT,
    ts      REAL NOT NULL DEFAULT (strftime('%s','now'))
);

CREATE TABLE IF NOT EXISTS dicts (
    d_id   INTEGER PRIMARY KEY,
    dpath  TEXT NOT NULL,
    dname  TEXT NOT NULL,
    dhash  TEXT NOT NULL,
    rules  TEXT,
    wcount INTEGER NOT NULL DEFAULT 0,
    hits   INTEGER NOT NULL DEFAULT 0
);

CREATE TABLE IF NOT EXISTS n2d (
    net_id INTEGER NOT NULL REFERENCES nets(net_id) ON DELETE CASCADE,
    d_id   INTEGER NOT NULL REFERENCES dicts(d_id),
    hkey   TEXT,                -- non-NULL = in-flight work unit lease
    epoch  INTEGER NOT NULL DEFAULT 0,  -- lease generation (leases.epoch)
    ts     REAL NOT NULL DEFAULT (strftime('%s','now')),
    PRIMARY KEY (net_id, d_id)
);
CREATE INDEX IF NOT EXISTS idx_n2d_hkey ON n2d(hkey);

-- First-class work-unit leases: one row per issued hkey, carrying a
-- globally monotonic epoch (the lease generation).  Release and reap key
-- on (hkey, epoch, state), so a reaped-then-reissued unit cannot be
-- released or double-credited by the stale holder, and duplicate
-- submits are idempotent (state only moves 0 -> 1|2 once).
CREATE TABLE IF NOT EXISTS leases (
    lease_id INTEGER PRIMARY KEY,
    hkey     TEXT NOT NULL UNIQUE,
    epoch    INTEGER NOT NULL,
    issued   REAL NOT NULL DEFAULT (strftime('%s','now')),
    state    INTEGER NOT NULL DEFAULT 0,  -- 0 live, 1 released, 2 reaped
    released REAL
);
CREATE INDEX IF NOT EXISTS idx_leases_state ON leases(state, issued);

CREATE TRIGGER IF NOT EXISTS trg_n2d_ins AFTER INSERT ON n2d BEGIN
    UPDATE nets  SET hits = hits + 1 WHERE net_id = NEW.net_id;
    UPDATE dicts SET hits = hits + 1 WHERE d_id  = NEW.d_id;
END;
CREATE TRIGGER IF NOT EXISTS trg_n2d_del AFTER DELETE ON n2d
WHEN (SELECT n_state FROM nets WHERE net_id = OLD.net_id) = 0 BEGIN
    UPDATE nets  SET hits = MAX(hits - 1, 0) WHERE net_id = OLD.net_id;
    UPDATE dicts SET hits = MAX(hits - 1, 0) WHERE d_id  = OLD.d_id;
END;

CREATE TRIGGER IF NOT EXISTS trg_nets_bssids AFTER INSERT ON nets BEGIN
    INSERT OR IGNORE INTO bssids(bssid) VALUES (NEW.bssid);
END;

CREATE TABLE IF NOT EXISTS n2u (
    net_id INTEGER NOT NULL REFERENCES nets(net_id) ON DELETE CASCADE,
    u_id   INTEGER NOT NULL REFERENCES users(u_id),
    PRIMARY KEY (net_id, u_id)
);

CREATE TABLE IF NOT EXISTS users (
    u_id      INTEGER PRIMARY KEY,
    userkey   TEXT UNIQUE,
    linkkey   TEXT,
    linkkeyts REAL,
    mail      TEXT UNIQUE,
    ip        TEXT,
    ts        REAL NOT NULL DEFAULT (strftime('%s','now'))
);

CREATE TABLE IF NOT EXISTS rkg (
    net_id  INTEGER NOT NULL REFERENCES nets(net_id) ON DELETE CASCADE,
    algo    TEXT NOT NULL,
    pass    BLOB NOT NULL,
    n_state INTEGER NOT NULL DEFAULT 0
);

CREATE TABLE IF NOT EXISTS prs (
    p_id         INTEGER PRIMARY KEY,
    ssid         BLOB NOT NULL UNIQUE,
    default_ssid INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS p2s (
    p_id INTEGER NOT NULL REFERENCES prs(p_id),
    s_id INTEGER NOT NULL REFERENCES submissions(s_id),
    PRIMARY KEY (p_id, s_id)
);

-- Smart keyspace (the reference's dormant ks table, wired for real):
-- ssid_regex selects nets, pass_regex compiles to mask shards
-- (keyspace/compiler.py).  priority orders competing rows; enabled=0
-- parks a row without losing its n2m coverage history.
CREATE TABLE IF NOT EXISTS ks (
    ks_id      INTEGER PRIMARY KEY,
    ssid_regex TEXT NOT NULL,
    pass_regex TEXT NOT NULL,
    priority   INTEGER NOT NULL DEFAULT 0,
    enabled    INTEGER NOT NULL DEFAULT 1
);

-- Mask-shard coverage, mirroring n2d: one row per net x compiled mask x
-- keyspace range.  span counts candidates from offset skip (hashcat
-- -s/-l framing); hkey/epoch carry the same lease semantics as n2d
-- (non-NULL hkey = in flight; release NULLs it = done).  Reap DELETEs
-- stale rows so abandoned ranges reappear as coverage gaps and are
-- re-issued under a fresh epoch.
CREATE TABLE IF NOT EXISTS n2m (
    net_id INTEGER NOT NULL REFERENCES nets(net_id) ON DELETE CASCADE,
    ks_id  INTEGER NOT NULL REFERENCES ks(ks_id),
    mask_i INTEGER NOT NULL,    -- index into the compiled pass_regex masks
    skip   INTEGER NOT NULL,    -- keyspace offset of this shard
    span   INTEGER NOT NULL,    -- candidate count (the wire "limit")
    hkey   TEXT,
    epoch  INTEGER NOT NULL DEFAULT 0,
    ts     REAL NOT NULL DEFAULT (strftime('%s','now')),
    PRIMARY KEY (net_id, ks_id, mask_i, skip)
);
CREATE INDEX IF NOT EXISTS idx_n2m_hkey ON n2m(hkey);

CREATE TABLE IF NOT EXISTS stats (
    name  TEXT PRIMARY KEY,
    value INTEGER NOT NULL DEFAULT 0
);
"""

STAT_NAMES = [
    "nets", "cracked", "uncracked", "pmkid", "pmkid_cracked", "rkg", "rkg_cracked",
    "geo", "submissions", "users", "words", "triedwords", "24getwork", "24psk",
    "24sub", "24founds", "contributors",
]


class Database:
    """One sqlite connection with the dwpa schema applied.

    Thread-safe at statement granularity: a process-wide RLock serializes
    every q/q1/x, so the threaded WSGI server and the --with-jobs cron
    thread can share one handle.  This is the same coarse posture as the
    reference (MySQL serializes statements; the only larger critical
    section it needs is the get_work mutex, which ServerCore provides).
    """

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._lock = threading.RLock()
        self._tx_depth = 0  # mutated only while holding _lock
        # 30 s busy wait (default is 5 s): an ops writer holding a
        # transaction for a few seconds — migration tooling, a manual
        # sqlite session, the jobs process mid-regen — must make API
        # writes wait, not 500 them (the reference's MySQL posture).
        # isolation_level=None: sqlite3's implicit-BEGIN machinery is off;
        # statements autocommit unless tx() has opened an explicit
        # BEGIN IMMEDIATE, so transaction boundaries are exactly where
        # the code says they are.
        self.conn = sqlite3.connect(path, check_same_thread=False,
                                    timeout=30.0, isolation_level=None)
        self.conn.row_factory = sqlite3.Row
        self.conn.execute("PRAGMA journal_mode=WAL")
        self.conn.execute("PRAGMA foreign_keys=ON")
        self.conn.executescript(SCHEMA)
        # Legacy databases predate the lease epoch column; CREATE TABLE
        # IF NOT EXISTS won't touch their n2d, so migrate in place.
        cols = [r[1] for r in self.conn.execute("PRAGMA table_info(n2d)")]
        if "epoch" not in cols:
            self.conn.execute(
                "ALTER TABLE n2d ADD COLUMN epoch INTEGER NOT NULL DEFAULT 0")
        # Legacy ks tables predate ks_id/priority/enabled; ALTER cannot
        # add a PRIMARY KEY column, so rebuild in place (rename, recreate
        # from SCHEMA, copy, drop).
        cols = [r[1] for r in self.conn.execute("PRAGMA table_info(ks)")]
        if cols and "ks_id" not in cols:
            self.conn.execute("ALTER TABLE ks RENAME TO ks_legacy")
            self.conn.executescript(SCHEMA)
            self.conn.execute(
                "INSERT INTO ks(ssid_regex, pass_regex) "
                "SELECT ssid_regex, pass_regex FROM ks_legacy")
            self.conn.execute("DROP TABLE ks_legacy")
        self.conn.executemany(
            "INSERT OR IGNORE INTO stats(name, value) VALUES (?, 0)",
            [(n,) for n in STAT_NAMES],
        )

    def close(self):
        self.conn.close()

    # -- tiny helpers ------------------------------------------------------

    def _exec(self, sql, params=()):
        """Every statement — including tx()'s BEGIN/COMMIT — funnels
        through this one call: the fault-injection seam the chaos
        harness wraps (chaos/dbfault.py)."""
        return self.conn.execute(sql, params)

    def q(self, sql, params=()):
        with self._lock:
            return self._exec(sql, params).fetchall()

    def q1(self, sql, params=()):
        with self._lock:
            return self._exec(sql, params).fetchone()

    def x(self, sql, params=()):
        # Transaction-aware: inside an open tx() the statement joins the
        # transaction and lands (or vanishes) with its COMMIT; outside,
        # autocommit makes it durable immediately — same as before.
        with self._lock:
            return self._exec(sql, params)

    @contextlib.contextmanager
    def tx(self):
        """Explicit transaction seam: ``BEGIN IMMEDIATE`` .. COMMIT, or
        ROLLBACK on any exception.  Reentrant: nested ``tx()`` blocks
        join the outermost transaction (depth-counted), so helper
        methods can declare their own atomicity and still compose into a
        caller's larger transaction.  Holds the statement lock for the
        whole block — within a process a transaction is exclusive, and
        BEGIN IMMEDIATE serializes writers across processes.
        """
        with self._lock:
            if self._tx_depth == 0:
                self._exec("BEGIN IMMEDIATE")
            self._tx_depth += 1
            try:
                yield self
            except BaseException:
                self._tx_depth -= 1
                if self._tx_depth == 0:
                    # A faulted/crashed connection may already be out of
                    # its transaction — the rollback is best-effort, the
                    # raise is not.
                    try:
                        self.conn.rollback()
                    except sqlite3.Error:
                        pass
                raise
            else:
                self._tx_depth -= 1
                if self._tx_depth == 0:
                    try:
                        self._exec("COMMIT")
                    except BaseException:
                        try:
                            self.conn.rollback()
                        except sqlite3.Error:
                            pass
                        raise

    def set_stat(self, name: str, value: int):
        self.x("INSERT OR REPLACE INTO stats(name, value) VALUES (?, ?)", (name, value))

    def get_stat(self, name: str) -> int:
        row = self.q1("SELECT value FROM stats WHERE name = ?", (name,))
        return row["value"] if row else 0


def mac2long(mac: bytes) -> int:
    """6-byte MAC -> int (MACs live as integers, like the reference's BIGINT)."""
    return int.from_bytes(mac, "big")


def long2mac(v: int) -> bytes:
    return int(v).to_bytes(6, "big")


def now() -> float:
    return time.time()
