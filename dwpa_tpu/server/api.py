"""HTTP front controller (WSGI) — the web/index.php + web/content/* layer.

Routes by query-string key exactly like the reference front controller
(web/index.php:146-163), with the four machine interfaces bypassing any
HTML chrome:

- ``?get_work=<ver>``  POST {"dictcount": N} -> work-unit JSON,
  or sentinel bodies ``Version`` / ``No nets`` (get_work.php:25-27,77-81);
- ``?put_work``        POST candidate JSON -> ``OK`` / ``Nope``;
- ``?prdict=<hkey>``   gzip dynamic dictionary stream (prdict.php);
- ``?api``             cookie-keyed potfile export (api.php);
- ``?stats``           JSON stats (the machine-readable face of stats.php);
- POST file upload     capture submission (index.php:4-11 besside path /
  content/submit.php) — accepts m22000 text, gz, or pcap/pcapng captures;
- ``dict/<name>``      static dictionary downloads.

Serve with ``wsgiref.simple_server`` (tests, small sites) or any WSGI
container.
"""

import json
import gzip
import os
import re
import urllib.parse

from .core import ServerCore
from .capture import extract_hashlines

MIN_HC_VER = "2.1.1"  # oldest client protocol accepted (conf.php:29)


def _version_ok(ver: str) -> bool:
    def parts(v):
        return [int(x) for x in re.findall(r"\d+", v)][:3]

    try:
        return parts(ver) >= parts(MIN_HC_VER)
    except ValueError:
        return False


class BodyTooLarge(Exception):
    """Request body exceeds the cap — reject, never silently truncate."""


def make_wsgi_app(core: ServerCore):
    def app(environ, start_response):
        try:
            status, ctype, body = _route(core, environ)
        except BodyTooLarge:
            status, ctype, body = (
                "413 Content Too Large", "text/plain", b"capture too large",
            )
        except ValueError as e:
            status, ctype, body = "400 Bad Request", "text/plain", str(e).encode()
        start_response(status, [("Content-Type", ctype),
                                ("Content-Length", str(len(body)))])
        return [body]

    return app


def _read_body(environ, cap=64 * 1024 * 1024) -> bytes:
    try:
        n = int(environ.get("CONTENT_LENGTH") or 0)
    except ValueError:
        n = 0
    if n < 0:
        n = 0  # a negative length would make read() slurp the stream
    if n > cap:
        raise BodyTooLarge(n)
    return environ["wsgi.input"].read(n) if n else b""


def _route(core: ServerCore, environ):
    qs = urllib.parse.parse_qs(environ.get("QUERY_STRING", ""), keep_blank_values=True)
    path = environ.get("PATH_INFO", "/")

    if path.startswith("/dict/") and core.dictdir:
        name = os.path.basename(path)
        full = os.path.join(core.dictdir, name)
        if os.path.isfile(full):
            with open(full, "rb") as f:
                return "200 OK", "application/octet-stream", f.read()
        return "404 Not Found", "text/plain", b"no such dict"

    if "get_work" in qs:
        ver = qs["get_work"][0]
        if not _version_ok(ver):
            return "200 OK", "text/plain", b"Version"
        try:
            req = json.loads(_read_body(environ) or b"{}")
        except ValueError:
            req = {}
        work = core.get_work(int(req.get("dictcount", 1)))
        if work is None:
            return "200 OK", "text/plain", b"No nets"
        return "200 OK", "application/json", json.dumps(work).encode()

    if "put_work" in qs:
        try:
            data = json.loads(_read_body(environ) or b"{}")
        except ValueError:
            return "200 OK", "text/plain", b"Nope"
        data.setdefault("ip", environ.get("REMOTE_ADDR", ""))
        ok = core.put_work(data)
        return "200 OK", "text/plain", b"OK" if ok else b"Nope"

    if "prdict" in qs:
        words = core.prdict_words(qs["prdict"][0])
        blob = gzip.compress(b"\n".join(words) + b"\n")
        return "200 OK", "application/octet-stream", blob

    if "api" in qs:
        key = qs.get("key", [""])[0] or _cookie_key(environ)
        lines = core.user_potfile(key)
        return "200 OK", "text/plain", ("\n".join(lines) + "\n").encode()

    if "stats" in qs:
        rows = core.db.q("SELECT name, value FROM stats")
        return (
            "200 OK", "application/json",
            json.dumps({r["name"]: r["value"] for r in rows}).encode(),
        )

    if environ["REQUEST_METHOD"] == "POST":
        # capture submission (multipart not required: raw body accepted,
        # like the besside-ng direct upload path)
        blob = _read_body(environ)
        if not blob:
            return "400 Bad Request", "text/plain", b"empty submission"
        report = submit_capture(core, blob,
                                ip=environ.get("REMOTE_ADDR", ""),
                                userkey=qs.get("key", [None])[0])
        return "200 OK", "application/json", json.dumps(report).encode()

    return "200 OK", "text/plain", b"dwpa_tpu server"


def _cookie_key(environ) -> str:
    cookies = environ.get("HTTP_COOKIE", "")
    for part in cookies.split(";"):
        k, _, v = part.strip().partition("=")
        if k == "key":
            return v
    return ""


def submit_capture(core: ServerCore, blob: bytes, ip: str = "",
                   userkey: str = None) -> dict:
    """Ingest one uploaded capture (pcap/pcapng/gz or m22000 text).

    The reference shells out to hcxpcapngtool here (common.php:481); we
    parse captures natively (capture.py) and also accept pre-extracted
    hashline text so converted archives ingest directly.
    """
    if blob[:2] == b"\x1f\x8b":
        try:
            blob = gzip.decompress(blob)
        except OSError:
            raise ValueError("bad gzip")
    s_id = core.add_submission(blob, ip=ip)
    if blob[:4].lstrip()[:3] == b"WPA":
        lines = blob.decode("utf-8", "replace").splitlines()
        probes = []
    else:
        lines, probes = extract_hashlines(blob)
    report = core.add_hashlines(lines, s_id=s_id, ip=ip, userkey=userkey)
    if probes:
        core.add_probe_requests(probes, s_id)
        report["probes"] = len(probes)
    return report
