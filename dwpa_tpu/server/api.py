"""HTTP front controller (WSGI) — the web/index.php + web/content/* layer.

Routes by query-string key exactly like the reference front controller
(web/index.php:146-163), with the four machine interfaces bypassing any
HTML chrome:

- ``?get_work=<ver>``  POST {"dictcount": N} -> work-unit JSON,
  or sentinel bodies ``Version`` / ``No nets`` (get_work.php:25-27,77-81);
- ``?put_work``        POST candidate JSON -> ``OK`` / ``Nope``;
- ``?prdict=<hkey>``   gzip dynamic dictionary stream (prdict.php);
- ``?api``             cookie-keyed potfile export (api.php);
- ``?stats``           JSON stats (the machine-readable face of stats.php);
- ``?metrics``         Prometheus text-format v0.0.4 scrape of the live
  telemetry registry (``?metrics=json`` for the JSON form) — request
  counters + per-endpoint latency histograms recorded by this layer,
  scheduler/claim counters from core.py, cron-job durations from
  jobs.py, and scrape-time lease/net gauges (core.observe_metrics);
- POST file upload     capture submission (index.php:4-11 besside path /
  content/submit.php) — accepts m22000 text, gz, or pcap/pcapng captures;
- ``dict/<name>``      static dictionary downloads.

Serve with ``wsgiref.simple_server`` (tests, small sites) or any WSGI
container.
"""

import json
import gzip
import os
import re
import sqlite3
import time
import urllib.parse

from .core import OVERLOAD_RETRY_AFTER_S, Overloaded, ServerCore
from .capture import extract_hashlines

MIN_HC_VER = "2.1.1"  # oldest client protocol accepted (conf.php:29)

#: machine endpoints + UI pages a request is attributed to in
#: dwpa_http_requests_total{endpoint=...}; query keys win over paths so
#: the label set stays closed (unknown paths all land in "other").
_ENDPOINT_KEYS = ("metrics", "get_work", "put_work", "prdict", "api",
                  "stats", "home", "get_key", "my_nets", "submit", "nets",
                  "dicts", "search")


def _endpoint_label(environ, qs) -> str:
    for key in _ENDPOINT_KEYS:
        if key in qs:
            return key
    path = environ.get("PATH_INFO", "/")
    if path.startswith("/dict/"):
        return "dict"
    if path.startswith("/hc/"):
        return "hc"
    if path in ("", "/"):
        return ("capture" if environ.get("REQUEST_METHOD") == "POST"
                else "home")
    return "other"


def _version_ok(ver: str) -> bool:
    def parts(v):
        return [int(x) for x in re.findall(r"\d+", v)][:3]

    try:
        return parts(ver) >= parts(MIN_HC_VER)
    except ValueError:
        return False


class BodyTooLarge(Exception):
    """Request body exceeds the cap — reject, never silently truncate."""


def make_wsgi_app(core: ServerCore, registry=None):
    """WSGI front; every request lands in the telemetry registry
    (default: the core's — one registry per deployment) as a
    ``dwpa_http_requests_total{endpoint,status}`` count and a
    ``dwpa_http_request_seconds{endpoint}`` latency observation, and
    ``?metrics`` scrapes that same registry."""
    from ..obs import is_emitter

    registry = registry or getattr(core, "registry", None)
    if registry is None:
        from ..obs import default_registry

        registry = default_registry()
    req_count = registry.counter(
        "dwpa_http_requests_total", "HTTP requests, by endpoint and status")
    req_seconds = registry.histogram(
        "dwpa_http_request_seconds", "HTTP request latency, by endpoint")

    def app(environ, start_response):
        t0 = time.perf_counter()
        qs = urllib.parse.parse_qs(environ.get("QUERY_STRING", ""),
                                   keep_blank_values=True)
        try:
            # root-path only, like every other query route: unknown
            # paths must stay 404 even when a ?metrics key rides along
            if "metrics" in qs and environ.get("PATH_INFO", "/") in ("", "/"):
                out = _metrics_response(core, registry, qs)
            else:
                out = _route(core, environ)
        except BodyTooLarge:
            out = ("413 Content Too Large", "text/plain", b"capture too large")
        except ValueError as e:
            out = ("400 Bad Request", "text/plain", str(e).encode())
        except Overloaded as e:
            # Admission control (core.max_inflight): shed with 429 + a
            # Retry-After the client RetryPolicy honors as its backoff
            # floor — overload composes with retries, not against them.
            out = ("429 Too Many Requests", "text/plain", b"overloaded",
                   [("Retry-After", str(max(1, round(e.retry_after))))])
        except sqlite3.OperationalError:
            # Transient DB-layer refusal ("database is locked", disk I/O):
            # the request may retry once the writer drains — a 503, not a
            # crash page, so the client classifies it transient.
            out = ("503 Service Unavailable", "text/plain", b"database busy",
                   [("Retry-After", str(OVERLOAD_RETRY_AFTER_S))])
        status, ctype, body = out[:3]
        extra_headers = list(out[3]) if len(out) > 3 else []
        endpoint = _endpoint_label(environ, qs)
        req_count.labels(endpoint=endpoint, status=status.split()[0]).inc()
        req_seconds.labels(endpoint=endpoint).observe(
            time.perf_counter() - t0)
        start_response(status, [("Content-Type", ctype),
                                ("Content-Length", str(len(body)))]
                       + extra_headers)
        return [body]

    def _metrics_response(core, registry, qs):
        # Multi-host gate: on a multi-host mesh only process 0 owns
        # emission (obs.multihost) — peers answer 404 so a fleet scrape
        # config can point at every host without double counting.
        if not is_emitter():
            return ("404 Not Found", "text/plain",
                    b"metrics served by process 0 only")
        core.observe_metrics()
        if qs["metrics"][0] == "json":
            return ("200 OK", "application/json",
                    registry.render_json().encode())
        return ("200 OK", "text/plain; version=0.0.4; charset=utf-8",
                registry.render_prometheus().encode())

    return app


def _set_key_cookie(key: str):
    return [("Set-Cookie", f"key={key}; Max-Age=2147483647; HttpOnly")]


def _clear_key_cookie():
    return [("Set-Cookie", "key=; Max-Age=0; HttpOnly")]


# Capture uploads get a much tighter body cap than the JSON/form routes:
# the reference runs behind PHP upload limits (typically single-digit MiB),
# and a 64 MiB cap x 16 concurrent workers would bound worst-case hostile
# upload memory at 1 GiB.  8 MiB holds any real-world capture; deployments
# with longer captures raise it per-core (ServerCore(capture_cap=...),
# ``serve --capture-cap``) without patching this default.
CAPTURE_BODY_CAP = 8 * 1024 * 1024


def _capture_cap(core) -> int:
    cap = getattr(core, "capture_cap", None)
    return CAPTURE_BODY_CAP if cap is None else int(cap)


def _parse_multipart(body: bytes, ctype: str):
    """Minimal multipart/form-data parser (RFC 7578 subset) for the
    browser submit form (web/content/submit.php:18-31 accepts $_FILES).

    Returns ``(fields, files)``: text fields as {name: str} and file
    parts as {name: (filename, bytes)}.  Strict on structure (missing
    boundary or malformed part -> ValueError -> 400), tolerant on
    charset (latin1 headers).
    """
    m = re.search(r'boundary="?([^";,\s]+)"?', ctype)
    if not m:
        raise ValueError("multipart body without boundary")
    delim = b"--" + m.group(1).encode("latin1")
    fields, files = {}, {}
    chunks = body.split(delim)
    if len(chunks) < 2:
        raise ValueError("multipart body without parts")
    for chunk in chunks[1:]:
        if chunk[:2] == b"--":
            break  # closing delimiter
        head, sep, content = chunk.partition(b"\r\n\r\n")
        if not sep:
            raise ValueError("malformed multipart part")
        if content.endswith(b"\r\n"):
            content = content[:-2]
        headers = head.decode("latin1")
        # Anchor ``name=`` to a parameter boundary: a bare name="..."
        # search would also match the tail of ``filename="..."``, so a
        # part ordered ``filename= ... name=`` would lose its real name.
        mname = re.search(r'(?:^|[;\s])name="([^"]*)"', headers)
        if not mname:
            continue
        mfile = re.search(r'(?:^|[;\s])filename="([^"]*)"', headers)
        if mfile:
            files[mname.group(1)] = (mfile.group(1), content)
        else:
            fields[mname.group(1)] = content.decode("utf-8", "replace")
    return fields, files


def _read_body(environ, cap=64 * 1024 * 1024) -> bytes:
    # Cached: the UI router may parse the body as a form and fall through
    # to the capture path — re-reading a socket-backed wsgi.input past the
    # request body would block the worker.  The cap still applies to the
    # cached body: the capture path's tighter limit must hold even when
    # an urlencoded route already slurped the body at the default cap.
    if "dwpa.body" in environ:
        body = environ["dwpa.body"]
        if len(body) > cap:
            raise BodyTooLarge(len(body))
        return body
    try:
        n = int(environ.get("CONTENT_LENGTH") or 0)
    except ValueError:
        n = 0
    if n < 0:
        n = 0  # a negative length would make read() slurp the stream
    if n > cap:
        raise BodyTooLarge(n)
    body = environ["wsgi.input"].read(n) if n else b""
    environ["dwpa.body"] = body
    return body


def _route(core: ServerCore, environ):
    qs = urllib.parse.parse_qs(environ.get("QUERY_STRING", ""), keep_blank_values=True)
    path = environ.get("PATH_INFO", "/")

    if path.startswith("/dict/") and core.dictdir:
        name = os.path.basename(path)
        full = os.path.join(core.dictdir, name)
        if os.path.isfile(full):
            with open(full, "rb") as f:
                return "200 OK", "application/octet-stream", f.read()
        return "404 Not Found", "text/plain", b"no such dict"

    if path.startswith("/hc/"):
        # Client-distribution artifacts (version manifest + archive), the
        # web/hc/ static dir of the reference (help_crack.py:162,173).
        name = os.path.basename(path)
        full = os.path.join(getattr(core, "hcdir", None) or "", name)
        if getattr(core, "hcdir", None) and os.path.isfile(full):
            with open(full, "rb") as f:
                return "200 OK", "application/octet-stream", f.read()
        return "404 Not Found", "text/plain", b"no such artifact"

    if path not in ("", "/"):
        # Unknown paths must 404, not render the home page: the client's
        # update probe treats any 200 body as a version manifest.
        return "404 Not Found", "text/plain", b"not found"

    if "get_work" in qs:
        ver = qs["get_work"][0]
        if not _version_ok(ver):
            return "200 OK", "text/plain", b"Version"
        try:
            req = json.loads(_read_body(environ) or b"{}")
        except ValueError:
            req = {}
        raw_dc = req.get("dictcount", 1) if isinstance(req, dict) else 1
        try:
            dictcount = int(raw_dc)
        except (TypeError, ValueError):
            # client-supplied JSON: a non-numeric dictcount (string,
            # list, object) must get a clean 400, not a traceback —
            # int() raises TypeError on containers, which the generic
            # ValueError->400 net would NOT catch.
            return "400 Bad Request", "text/plain", b"bad dictcount"
        work = core.get_work(dictcount)
        if work is None:
            return "200 OK", "text/plain", b"No nets"
        return "200 OK", "application/json", json.dumps(work).encode()

    if "put_work" in qs:
        try:
            data = json.loads(_read_body(environ) or b"{}")
        except ValueError:
            return "200 OK", "text/plain", b"Nope"
        data.setdefault("ip", environ.get("REMOTE_ADDR", ""))
        ok = core.put_work(data)
        return "200 OK", "text/plain", b"OK" if ok else b"Nope"

    if "prdict" in qs:
        words = core.prdict_words(qs["prdict"][0])
        blob = gzip.compress(b"\n".join(words) + b"\n")
        return "200 OK", "application/octet-stream", blob

    if "api" in qs:
        key = qs.get("key", [""])[0] or _cookie_key(environ)
        lines = core.user_potfile(key)
        return "200 OK", "text/plain", ("\n".join(lines) + "\n").encode()

    if "stats" in qs and "text/html" not in environ.get("HTTP_ACCEPT", ""):
        rows = core.db.q("SELECT name, value FROM stats")
        return (
            "200 OK", "application/json",
            json.dumps({r["name"]: r["value"] for r in rows}).encode(),
        )

    # ---- browser surface (HTML CMS + user-key actions) -------------------
    resp = _route_ui(core, environ, qs)
    if resp is not None:
        return resp

    if environ["REQUEST_METHOD"] == "POST":
        # Capture submission.  Two wire shapes, one pipeline:
        # - raw body (the besside-ng direct upload, index.php:4-11);
        # - multipart/form-data from the browser submit form
        #   (content/submit.php:18-31) — the capture is the first file
        #   part (the form names it "file").
        blob = _read_body(environ, cap=_capture_cap(core))
        userkey = qs.get("key", [None])[0]
        ctype = environ.get("CONTENT_TYPE", "")
        if ctype.startswith("multipart/form-data"):
            fields, files = _parse_multipart(blob, ctype)
            part = files.get("file") or next(iter(files.values()), None)
            if part is None:
                return "400 Bad Request", "text/plain", b"no file part"
            blob = part[1]
            userkey = fields.get("key", userkey)
        if not blob:
            return "400 Bad Request", "text/plain", b"empty submission"
        report = submit_capture(core, blob,
                                ip=environ.get("REMOTE_ADDR", ""),
                                userkey=userkey)
        return "200 OK", "application/json", json.dumps(report).encode()

    return "200 OK", "text/plain", b"dwpa_tpu server"


UI_KEYS = ("home", "get_key", "my_nets", "submit", "nets", "dicts", "stats",
           "search")


def _route_ui(core: ServerCore, environ, qs):
    """The human-facing CMS (web/index.php:12-163 + web/content/*.php).

    Returns a response tuple, or None to fall through to the machine
    catch-alls.  POST bodies here are urlencoded forms; raw/multipart
    bodies stay on the capture-upload path.
    """
    from . import ui
    from .core import valid_email, valid_key

    method = environ["REQUEST_METHOD"]
    if method == "POST" and environ.get("CONTENT_TYPE", "").startswith(
        "multipart/form-data"
    ):
        # The ?submit form posts its multipart body back to /?submit
        # (content/submit.php:18-31 handles $_FILES on the same URL);
        # fall through to the capture-upload handler instead of
        # re-rendering the page over the discarded body.
        return None
    form = {}
    if method == "POST" and environ.get("CONTENT_TYPE", "").startswith(
        "application/x-www-form-urlencoded"
    ):
        form = {
            k: v[0]
            for k, v in urllib.parse.parse_qs(
                _read_body(environ).decode("utf-8", "replace"),
                keep_blank_values=True,
            ).items()
        }

    # -- key set / remove (index.php:109-142) --
    if "key" in form:
        k = form["key"].lower()
        if valid_key(k) and (
            (core.bosskey and k == core.bosskey) or core.user_key_exists(k)
        ):
            return ("302 Found", "text/plain", b"",
                    [("Location", "/")] + _set_key_cookie(k))
        return ("302 Found", "text/plain", b"",
                [("Location", "/")] + _clear_key_cookie())
    if "remkey" in form:
        return ("302 Found", "text/plain", b"",
                [("Location", "/")] + _clear_key_cookie())

    # -- key issue (index.php:14-102): optional captcha seam, then mail --
    if "mail" in form:
        ip = environ.get("REMOTE_ADDR", "")
        if core.captcha and not core.captcha(
            form.get("g-recaptcha-response", ""), ip
        ):
            return ("200 OK", "text/html",
                    ui.render(ui.page_get_key("Captcha validation failed.")))
        mail = form["mail"].strip()
        if not (core.email_check or valid_email)(mail):
            return ("200 OK", "text/html",
                    ui.render(ui.page_get_key("No valid e-mail provided!")))
        status, key = core.issue_user_key(mail, ip=ip)
        if status == "issued":
            return ("200 OK", "text/html",
                    ui.render(ui.page_get_key(
                        "User key issued. Make sure you keep it to access "
                        "the results.")),
                    _set_key_cookie(key))
        if status == "reset":
            return ("200 OK", "text/html",
                    ui.render(ui.page_get_key(
                        "New key request was submitted. Please check your "
                        "e-mail to confirm.")))
        return ("200 OK", "text/html",
                ui.render(ui.page_get_key(
                    "User key request was already submitted. Please try "
                    "again tomorrow.")))

    # -- linkkey confirmation (get_key.php:11-31) --
    if "get_key" in qs and valid_key(qs["get_key"][0].lower()):
        lk = qs["get_key"][0].lower()
        if core.confirm_linkkey(lk):
            return ("302 Found", "text/plain", b"",
                    [("Location", "/")] + _set_key_cookie(lk))
        return ("200 OK", "text/html",
                ui.render(ui.page_get_key("User key NOT set.")))

    page = next((k for k in UI_KEYS if k in qs), None)
    if page is None:
        return None

    viewer = ui.resolve_viewer(core, _cookie_key(environ))

    # -- crowdsourced PSK guesses on nets/search/my_nets (build_cand,
    #    common.php:39-53; nets.php:6-8) --
    cand = [{"k": k, "v": v} for k, v in form.items()
            if valid_key(k) and v.strip()]
    if cand:
        core.put_work({"type": "hash", "cand": cand,
                       "ip": environ.get("REMOTE_ADDR", "")})

    if page == "nets":
        body = ui.page_nets(core, viewer)
    elif page == "search":
        # ?search&search=<term>: the page key and the term share the name
        # (PHP keeps the last duplicate, search.php:13-15)
        body = ui.page_search(core, viewer, qs.get("search", [""])[-1])
    elif page == "my_nets":
        try:
            pageno = int(qs.get("page", ["1"])[0])
        except ValueError:
            pageno = 1
        body = ui.page_my_nets(core, viewer, pageno)
    elif page == "stats":
        body = ui.page_stats(core)
    elif page == "dicts":
        body = ui.page_dicts(core)
    elif page == "submit":
        body = ui.page_submit()
    elif page == "get_key":
        body = ui.page_get_key(has_key=bool(viewer.key))
    else:
        body = ui.page_home()
    return "200 OK", "text/html", ui.render(body)


def _cookie_key(environ) -> str:
    cookies = environ.get("HTTP_COOKIE", "")
    for part in cookies.split(";"):
        k, _, v = part.strip().partition("=")
        if k == "key":
            return v
    return ""


def submit_capture(core: ServerCore, blob: bytes, ip: str = "",
                   userkey: str = None) -> dict:
    """Ingest one uploaded capture (pcap/pcapng/gz or m22000 text).

    The reference shells out to hcxpcapngtool here (common.php:481); we
    parse captures natively (capture.py) and also accept pre-extracted
    hashline text so converted archives ingest directly.
    """
    if blob[:2] == b"\x1f\x8b":
        # Bounded decompression: an 8 MiB gzip bomb inflates ~1000x, so
        # an unbounded gzip.decompress would defeat the capture cap's
        # whole point (the hostile-upload memory bound).  The cap applies
        # to the decompressed capture too — no real pcap needs more.
        import io

        cap = _capture_cap(core)
        try:
            with gzip.GzipFile(fileobj=io.BytesIO(blob)) as gf:
                blob = gf.read(cap + 1)
        except (OSError, EOFError):
            raise ValueError("bad gzip")
        if len(blob) > cap:
            raise BodyTooLarge(len(blob))
    s_id = core.add_submission(blob, ip=ip)
    if blob[:4].lstrip()[:3] == b"WPA":
        lines = blob.decode("utf-8", "replace").splitlines()
        probes = []
    else:
        lines, probes = extract_hashlines(blob)
    report = core.add_hashlines(lines, s_id=s_id, ip=ip, userkey=userkey)
    if probes:
        core.add_probe_requests(probes, s_id)
        report["probes"] = len(probes)
    return report
