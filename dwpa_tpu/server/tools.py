"""Ops & migration tooling — the reference's ``misc/`` scripts as library
functions (CLI in server/__main__.py):

- ``recrack_verify``   — re-verify every cracked net from its stored
  pass/pmk/nc and abort on the first mismatch, the safety net the
  reference runs after storage migration (misc/migrate_to_m22000.php:
  121-141, ``die('Recrack failed!')``);
- ``pack_dict``        — package a wordlist into the served ``.txt.gz``
  form: deterministic gzip -9, md5 manifest, dicts-table row
  (misc/create_gz.sh:27-35);
- ``dedup_dicts``      — cross-dictionary dedup, earlier dicts win,
  output ordered shortest-word-first (misc/dedup.sh:4-24);
- ``fill_pr``          — backfill PROBEREQUEST tables by re-parsing
  archived captures (misc/fill_pr.php:33-71);
- ``enrich_message_pair`` — upgrade stored hashlines missing
  message-pair info by re-parsing their original captures
  (misc/enrich_pmkid.php:44-68).

All functions are idempotent (INSERT OR IGNORE / UNIQUE-keyed writes) so
re-running a partially-completed pass is safe — matching the reference's
at-least-once ops posture (SURVEY.md §5.2).
"""

import gzip
import hashlib
import os
import re

from ..models import hashline as hl
from ..obs import get_logger
from .capture import extract_hashlines
from .core import SERVER_NC, ServerCore
from .db import long2mac
from .precrack import verify_batch

# child of the package logger: one setup_logging() config for every
# emitter (obs/logs.py), ops warnings included
_log = get_logger(__name__)


class RecrackError(RuntimeError):
    """A stored crack failed re-verification (data corruption or a
    storage-migration bug); mirrors the reference's hard abort."""


def recrack_verify(core: ServerCore, limit: int = None) -> dict:
    """Re-verify every cracked net; raise RecrackError on any mismatch.

    Nets with a non-empty stored pass are re-cracked from scratch (full
    PBKDF2 — the migrate_to_m22000.php:121-141 semantics) and the derived
    PMK compared against the stored one; empty-pass nets (ZeroPMK) are
    verified by PMK replay.
    """
    q = "SELECT * FROM nets WHERE n_state = 1"
    args = ()
    if limit:
        q += " LIMIT ?"
        args = (limit,)
    nets = core.db.q(q, args)
    # One batched dispatch for the whole table: non-empty passes derive
    # their PBKDF2 in the fused wave, ZeroPMK rows replay the stored PMK
    # — verdicts identical to the old per-net oracle loop.
    items = []
    for net in nets:
        h = hl.parse(net["struct"])
        if net["pass"]:
            items.append((h, [net["pass"]], None))
        else:
            items.append((h, [net["pass"] or b""], net["pmk"]))
    for net, r in zip(nets, verify_batch(items, nc=SERVER_NC,
                                         batcher=core.verifier)):
        if r is None or (net["pmk"] is not None and r[3] != net["pmk"]):
            raise RecrackError(
                f"net {net['net_id']} ({long2mac(net['bssid']).hex()}): "
                f"stored pass/pmk does not re-crack its hashline"
            )
    return {"checked": len(nets)}


def _read_words(path: str):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        return [ln.rstrip(b"\r\n") for ln in f if ln.strip()]


def _write_gz(path: str, words) -> bytes:
    """Deterministic gzip (mtime=0) so the dhash only moves with content."""
    import io

    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb", compresslevel=9, mtime=0) as gz:
        for w in words:
            gz.write(w + b"\n")
    blob = buf.getvalue()
    with open(path, "wb") as f:
        f.write(blob)
    return blob


def pack_dict(core: ServerCore, source, dname: str, rules: str = None) -> dict:
    """Package ``source`` (path or iterable of words) as a served dict.

    Writes ``<dictdir>/<dname>`` (deterministic .txt.gz), registers the
    dicts row with its md5 + wordcount (create_gz.sh emits the same
    INSERT), returns {dpath, dhash, wcount}.
    """
    words = _read_words(source) if isinstance(source, str) else list(source)
    if not dname.endswith(".txt.gz"):
        dname += ".txt.gz"
    os.makedirs(core.dictdir, exist_ok=True)
    path = os.path.join(core.dictdir, dname)
    blob = _write_gz(path, words)
    dhash = hashlib.md5(blob).hexdigest()
    dpath = f"dict/{dname}"
    core.add_dict(dpath, dname, dhash, len(words), rules=rules)
    return {"dpath": dpath, "dhash": dhash, "wcount": len(words)}


def dedup_dicts(paths, core: ServerCore = None) -> dict:
    """Cross-dict dedup: drop words already present in an earlier dict.

    Earlier paths win (the reference pipes successive dicts through
    ``comm -13``, dedup.sh:4-24); each rewritten dict is ordered
    shortest-word-first (cheap candidates first, dedup.sh's final sort).
    When ``core`` is given, matching dicts rows get their dhash/wcount
    refreshed so clients re-download only what changed.
    """
    seen = set()
    stats = {}
    for i, path in enumerate(paths):
        words = _read_words(path)
        kept = []
        local = set()
        for w in words:
            if w not in seen and w not in local:
                kept.append(w)
                local.add(w)
        kept.sort(key=lambda w: (len(w), w))
        seen |= local
        changed = kept != words
        if changed:
            # Rewrite only on real content/order change so dhash — and
            # with it every client's cached copy — stays stable otherwise.
            if path.endswith(".gz"):
                _write_gz(path, kept)
            else:
                with open(path, "wb") as f:
                    f.write(b"\n".join(kept) + (b"\n" if kept else b""))
        stats[path] = {"before": len(words), "after": len(kept)}
        if core is not None and changed:
            dname = os.path.basename(path)
            row = core.db.q1("SELECT d_id FROM dicts WHERE dname = ?", (dname,))
            if row:
                with open(path, "rb") as f:
                    dhash = hashlib.md5(f.read()).hexdigest()
                core.db.x(
                    "UPDATE dicts SET dhash = ?, wcount = ? WHERE d_id = ?",
                    (dhash, len(kept), row["d_id"]),
                )
    return stats


def _archived_captures(core: ServerCore, limit: int = None):
    q = "SELECT s_id, localfile FROM submissions WHERE localfile IS NOT NULL"
    args = ()
    if limit:
        q += " LIMIT ?"
        args = (limit,)
    for row in core.db.q(q, args):
        try:
            with open(row["localfile"], "rb") as f:
                yield row["s_id"], f.read()
        except OSError:
            continue


def get_extractor(native: bool = False):
    """Select the capture extractor: the Python specification parser or
    the C++ fast path (native/capture_fast) for bulk re-parses.  The
    native library is differentially tested against the Python one
    (tests/test_native_capture.py); unavailability falls back silently.
    """
    if native:
        try:
            from ..native import extract_hashlines_fast, load

            if load() is not None:
                return extract_hashlines_fast
        except (ImportError, RuntimeError):
            pass
    return extract_hashlines


def fill_pr(core: ServerCore, limit: int = None, extractor=None) -> dict:
    """Re-parse archived captures into the PROBEREQUEST tables.

    The dynamic-dict source (prs/p2s) for captures ingested before the
    probe-harvest path existed (fill_pr.php:33-71).  INSERT OR IGNORE
    keyed on (ssid) / (p_id, s_id) makes re-runs free.
    """
    extractor = extractor or extract_hashlines
    subs = probes = 0
    for s_id, blob in _archived_captures(core, limit):
        _, prs = extractor(blob)
        if prs:
            core.add_probe_requests(prs, s_id)
            probes += len(prs)
        subs += 1
    return {"submissions": subs, "probes": probes}


def enrich_message_pair(core: ServerCore, limit: int = None,
                        extractor=None) -> dict:
    """Backfill message-pair info on nets whose stored line lacks it.

    Re-parses each archived capture and, for any net matching by m22000
    identity (the hash over fields 1-7, which *excludes* message_pair —
    common.php:310-315), replaces a NULL message_pair with the freshly
    parsed line's value (enrich_pmkid.php:44-68).
    """
    extractor = extractor or extract_hashlines
    updated = 0
    for s_id, blob in _archived_captures(core, limit):
        lines, _ = extractor(blob)
        for line in lines:
            try:
                h = hl.parse(line)
            except ValueError:
                continue
            if h.message_pair is None:
                continue
            row = core.db.q1(
                "SELECT net_id, message_pair FROM nets WHERE hash = ?",
                (h.key_id(),),
            )
            if row and row["message_pair"] is None:
                core.db.x(
                    "UPDATE nets SET message_pair = ?, struct = ? WHERE net_id = ?",
                    (h.message_pair, h.raw, row["net_id"]),
                )
                updated += 1
    return {"updated": updated}


# ---------------------------------------------------------------------------
# Legacy-storage migration (misc/migrate_to_m22000.php)
# ---------------------------------------------------------------------------

HCCAPX_LEN = 393  # fixed struct size (hashcat hccapx v4 format)


def convert_legacy(record) -> str:
    """One legacy stored net -> m22000 hashline string, or None.

    The two pre-m22000 storage forms the reference migrates
    (misc/migrate_to_m22000.php:253-270):

    - a 393-byte hccapx struct ("HCPX" magic): repacked into a TYPE-02
      EAPOL hashline carrying the struct's message_pair verbatim;
    - a legacy PMKID line ``pmkid:mac_ap:mac_sta:essid_hex`` (the
      hcxtools 16800 format): rewritten as a TYPE-01 line with empty
      anonce/eapol/message_pair fields.
    """
    if isinstance(record, str):
        record = record.encode()
    if len(record) == HCCAPX_LEN and record[:4] == b"HCPX":
        mp, essid_len = record[8], record[9]
        essid = record[10 : 10 + min(essid_len, 32)]
        keymic = record[43:59]
        mac_ap = record[59:65]
        nonce_ap = record[65:97]
        mac_sta = record[97:103]
        eapol_len = int.from_bytes(record[135:137], "little")
        eapol = record[137 : 137 + min(eapol_len, 256)]
        return "WPA*02*%s*%s*%s*%s*%s*%s*%02x" % (
            keymic.hex(), mac_ap.hex(), mac_sta.hex(), essid.hex(),
            nonce_ap.hex(), eapol.hex(), mp,
        )
    parts = record.strip().decode("ascii", "replace").split(":")
    if len(parts) == 4 and all(parts):
        return "WPA*01*%s*%s*%s*%s***" % tuple(p.lower() for p in parts)
    return None


def migrate_legacy(core: ServerCore, records, ip: str = "",
                   verify: bool = True) -> dict:
    """Convert legacy records and ingest them through the normal pipeline.

    Mirrors the reference's migration posture: every record goes through
    ``convert_legacy`` then ``add_hashlines`` (hash-identity dedup, zero-
    PMK probe, cross-crack — the same checks fresh captures get), and
    with ``verify`` the migrated DB must pass ``recrack_verify`` before
    the function returns (migrate_to_m22000.php:121-141 aborts the whole
    migration on one recrack failure).
    """
    lines, bad = [], 0
    for rec in records:
        line = convert_legacy(rec)
        if line is None:
            bad += 1
        else:
            lines.append(line)
    res = core.add_hashlines(lines, ip=ip)
    if verify:
        recrack_verify(core)
    return {"converted": len(lines), "unconvertible": bad, **res}


def reorder_captures(core: ServerCore, capdir: str = None) -> dict:
    """Migrate a flat capture archive into the dated CAP/Y/m/d layout.

    The reference stores uploads under CAP/Y/m/d (common.php:492-494)
    and ships misc/reorder_by_date.sh for legacy flat dirs; this is that
    tool: every md5-named file directly under ``capdir`` moves to
    ``Y/m/d`` of its mtime, and matching ``submissions.localfile`` rows
    are rewritten.  Idempotent; files already in dated subdirs are left
    alone.
    """
    import shutil
    import time as _t

    capdir = capdir or core.capdir
    if not capdir or not os.path.isdir(capdir):
        return {"moved": 0, "db_updated": 0}
    moved = updated = 0
    for name in sorted(os.listdir(capdir)):
        src = os.path.join(capdir, name)
        if not os.path.isfile(src) or not re.fullmatch(r"[0-9a-f]{32}", name):
            continue
        day = _t.strftime("%Y/%m/%d", _t.localtime(os.path.getmtime(src)))
        dstdir = os.path.join(capdir, day)
        os.makedirs(dstdir, exist_ok=True)
        dst = os.path.join(dstdir, name)
        shutil.move(src, dst)
        moved += 1
        # Match rows by the md5 basename, not the exact joined path: the
        # server may have stored a different capdir spelling (relative
        # "caps" vs absolute, trailing slash) than this CLI was given,
        # and an exact-match UPDATE would move the file but leave the
        # DB row pointing at the old location.
        updated += core.db.x(
            "UPDATE submissions SET localfile = ? "
            "WHERE localfile = ? OR localfile LIKE ?",
            (dst, src, "%/" + name),
        ).rowcount
    if moved != updated:
        _log.warning(
            "reorder_captures: moved %d files but updated %d submissions "
            "rows — some captures have no (or multiple) DB rows", moved, updated,
        )
    return {"moved": moved, "db_updated": updated}


# ---------------------------------------------------------------------------
# Client distribution (the web/hc/ artifact dir, help_crack.py:158-189)
# ---------------------------------------------------------------------------


def pack_client(hcdir: str, version: str = None) -> dict:
    """Build the self-update artifacts: ``dwpa_tpu.pyz`` + version manifest.

    The reference serves ``hc/help_crack.py`` with a one-line
    ``help_crack.py.version`` next to it; here the client is a package,
    so the artifact is a zipapp (runnable as ``python dwpa_tpu.pyz
    <server-url>``) and the manifest carries ``<version> <archive-md5>``
    so the client can integrity-check the download
    (client/main.py:check_update).
    """
    import re
    import zipfile

    import dwpa_tpu

    version = version or dwpa_tpu.__version__
    # The client's manifest probe only accepts this shape
    # (client/main.py:check_update) — publishing anything else would
    # silently disable updates fleet-wide.
    if not re.fullmatch(r"[0-9]+(\.[0-9]+)*[a-z0-9]*", version):
        raise ValueError(f"version {version!r} would be rejected by the "
                         "client's manifest check")
    pkg_root = os.path.dirname(os.path.abspath(dwpa_tpu.__file__))
    os.makedirs(hcdir, exist_ok=True)
    pyz = os.path.join(hcdir, "dwpa_tpu.pyz")
    count = 0
    with zipfile.ZipFile(pyz, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(pkg_root):
            # sorted: readdir order varies per filesystem, and the md5
            # must be reproducible across hosts serving the same tree
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(files):
                if name.endswith((".pyc", ".so")):
                    continue  # native libs rebuild from the bundled source
                full = os.path.join(root, name)
                rel = "dwpa_tpu/" + os.path.relpath(full, pkg_root).replace(
                    os.sep, "/"
                )  # zipimport requires forward slashes
                # Deterministic archive: fixed timestamp so the md5 (and
                # every client's cached copy) moves only with content.
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                with open(full, "rb") as f:
                    z.writestr(info, f.read())
                count += 1
        # __name__ guard: rule-expansion worker processes (spawn) re-import
        # __main__, which must not re-enter the client
        stub = ("if __name__ == '__main__':\n"
                "    from dwpa_tpu.client.__main__ import main\n"
                "    main()\n")
        info = zipfile.ZipInfo("__main__.py", date_time=(1980, 1, 1, 0, 0, 0))
        z.writestr(info, stub)
    with open(pyz, "rb") as f:
        md5 = hashlib.md5(f.read()).hexdigest()
    with open(os.path.join(hcdir, "dwpa_tpu.version"), "w") as f:
        f.write(f"{version} {md5}\n")
    return {"pyz": pyz, "version": version, "md5": md5, "files": count}
