"""pcap/pcapng -> m22000 hashline extraction (hcxpcapngtool-equivalent).

The reference system depends on the external C tool hcxpcapngtool for all
capture parsing (server ingestion common.php:481, backfills
misc/fill_pr.php:37, misc/enrich_pmkid.php:44).  This module implements the
same extraction natively:

- container parsing: classic pcap (usec/nsec magics, both endiannesses)
  and pcapng (SHB/IDB/EPB blocks);
- link layers: raw IEEE 802.11 (DLT 105), radiotap (DLT 127), PPI (192);
- 802.11: beacon / probe-response / association-request SSIDs (per-BSSID
  ESSID map, "--max-essids=1" semantics: keep the most frequent),
  probe-request SSIDs (the PROBEREQUEST sidecar output used for dynamic
  dictionaries, prdict.php), and EAPOL-Key frames;
- EAPOL-Key classification by key_info flags (M1..M4), PMKID harvesting
  from M1 key-data RSN KDEs, and message pairing by replay counter:
  M1+M2 (pair 0), M2+M3 (pair 2), M1+M4 / M3+M4 (pairs 1/3) when M4
  carries a nonzero SNONCE;
- m22000 serialization via models.hashline (format documented at
  web/common.php:114-155): EAPOL field = the STA message with its MIC
  zeroed, ANONCE from the AP message, message_pair low bits = pairing.

Pure host-side code — parsing throughput is irrelevant next to PBKDF2, so
clarity wins; a C++ fast path is only worth it for bulk archive re-parses.
"""

import struct
from collections import Counter, defaultdict
from dataclasses import dataclass, field

from ..models import hashline as hl

DLT_IEEE802_11 = 105
DLT_RADIOTAP = 127
DLT_PPI = 192

# EAPOL-Key key_information flags
KI_KEYVER = 0x0007
KI_PAIRWISE = 0x0008
KI_INSTALL = 0x0040
KI_ACK = 0x0080
KI_MIC = 0x0100
KI_SECURE = 0x0200


# ---------------------------------------------------------------------------
# Container readers -> iterable of (linktype, frame_bytes)
# ---------------------------------------------------------------------------


def _pcap_frames(data: bytes):
    magic = data[:4]
    if magic in (b"\xd4\xc3\xb2\xa1", b"\x4d\x3c\xb2\xa1"):
        endian = "<"
    elif magic in (b"\xa1\xb2\xc3\xd4", b"\xa1\xb2\x3c\x4d"):
        endian = ">"
    else:
        raise ValueError("not a pcap file")
    # The nanosecond-resolution magics (a1b23c4d and its byte swap).
    frac = 1e-9 if magic in (b"\xa1\xb2\x3c\x4d", b"\x4d\x3c\xb2\xa1") else 1e-6
    if len(data) < 24:
        return  # truncated global header: no frames, not a crash
    linktype = struct.unpack_from(endian + "I", data, 20)[0] & 0xFFFF
    off = 24
    while off + 16 <= len(data):
        sec, sub, caplen, _ = struct.unpack_from(endian + "IIII", data, off)
        off += 16
        if off + caplen > len(data):
            break
        yield linktype, sec + sub * frac, data[off : off + caplen]
        off += caplen


def _if_tsresol(body: bytes, endian: str) -> float:
    """Seconds per timestamp unit from an IDB's if_tsresol option (code 9,
    default 10^-6; high bit set means a power-of-two resolution)."""
    off = 8  # linktype(2) + reserved(2) + snaplen(4)
    while off + 4 <= len(body):
        code, ln = struct.unpack_from(endian + "HH", body, off)
        if code == 0:  # opt_endofopt
            break
        if code == 9 and ln >= 1 and off + 4 < len(body):
            v = body[off + 4]
            return 2.0 ** -(v & 0x7F) if v & 0x80 else 10.0 ** -(v & 0x7F)
        off += 4 + ln + ((-ln) % 4)
    return 1e-6


def _pcapng_frames(data: bytes):
    if data[:4] != b"\x0a\x0d\x0d\x0a":
        raise ValueError("not a pcapng file")
    endian = "<" if data[8:12] == b"\x4d\x3c\x2b\x1a" else ">"
    off = 0
    ifaces = []  # (linktype, seconds-per-ts-unit)
    while off + 12 <= len(data):
        btype, blen = struct.unpack_from(endian + "II", data, off)
        if blen < 12 or off + blen > len(data):
            break
        body = data[off + 8 : off + blen - 4]
        if btype == 0x00000001 and len(body) >= 2:  # IDB
            ifaces.append((struct.unpack_from(endian + "H", body, 0)[0],
                           _if_tsresol(body, endian)))
        elif btype == 0x00000006 and len(body) >= 20:  # EPB
            iface, tsh, tsl, caplen, _ = struct.unpack_from(
                endian + "IIIII", body, 0
            )
            frame = body[20 : 20 + caplen]
            lt, res = (ifaces[iface] if iface < len(ifaces)
                       else (DLT_IEEE802_11, 1e-6))
            yield lt, ((tsh << 32) | tsl) * res, frame
        elif btype == 0x00000003 and len(body) >= 4:  # Simple Packet Block
            lt = ifaces[0][0] if ifaces else DLT_IEEE802_11
            caplen = struct.unpack_from(endian + "I", body, 0)[0]
            yield lt, None, body[4 : 4 + caplen]  # SPB carries no timestamp
        off += blen


def iter_frames(data: bytes):
    """Yield (timestamp-seconds-or-None, 802.11-frame) from a pcap or
    pcapng blob.  The timestamp (epoch seconds, float) feeds the EAPOL
    pairing time gate; pcapng Simple Packet Blocks carry none."""
    if data[:4] == b"\x0a\x0d\x0d\x0a":
        src = _pcapng_frames(data)
    else:
        src = _pcap_frames(data)
    for lt, ts, frame in src:
        if lt == DLT_RADIOTAP:
            if len(frame) < 4:
                continue
            rtlen = struct.unpack_from("<H", frame, 2)[0]
            frame = frame[rtlen:]
        elif lt == DLT_PPI:
            if len(frame) < 4:
                continue
            pplen = struct.unpack_from("<H", frame, 2)[0]
            frame = frame[pplen:]
        elif lt != DLT_IEEE802_11:
            continue
        if frame:
            yield ts, frame


# ---------------------------------------------------------------------------
# 802.11 parsing
# ---------------------------------------------------------------------------


@dataclass
class EapolMsg:
    num: int                 # 1..4
    ap: bytes
    sta: bytes
    replay: int
    nonce: bytes
    key_information: int
    frame: bytes             # full EAPOL frame, MIC zeroed
    mic: bytes
    pmkids: list = field(default_factory=list)
    ts: float = None         # capture timestamp (epoch s), None if unknown


def _tagged_ssid(body: bytes, off: int):
    """Walk tagged parameters; return the SSID tag payload or None."""
    while off + 2 <= len(body):
        tag, ln = body[off], body[off + 1]
        if off + 2 + ln > len(body):
            return None
        if tag == 0:
            ssid = body[off + 2 : off + 2 + ln]
            return ssid if 0 < len(ssid) <= 32 and any(ssid) else None
        off += 2 + ln
    return None


def _parse_eapol_key(ap: bytes, sta: bytes, eapol: bytes):
    # 802.1X: ver(1) type(1) len(2); EAPOL-Key descriptor follows
    if len(eapol) < 95 + 4 or eapol[1] != 3:
        return None
    # Descriptor type must be RSN (2) or WPA (254); other 802.1X type-3
    # packets can carry a coincidental pairwise bit (hcxpcapngtool checks).
    if eapol[4] not in (2, 254):
        return None
    ki = struct.unpack_from(">H", eapol, 5)[0]
    if not ki & KI_PAIRWISE:
        return None
    replay = struct.unpack_from(">Q", eapol, 9)[0]
    nonce = eapol[17:49]
    mic = eapol[81:97]
    kd_len = struct.unpack_from(">H", eapol, 97)[0]
    key_data = eapol[99 : 99 + kd_len]

    ack, has_mic, secure = ki & KI_ACK, ki & KI_MIC, ki & KI_SECURE
    if ack and not has_mic:
        num = 1
    elif ack and has_mic:
        num = 3
    elif has_mic and not secure:
        num = 2
    else:
        num = 4

    pmkids = []
    if num in (1, 3):
        # RSN PMKID KDE: dd <len> 00 0f ac 04 <pmkid>
        off = 0
        while off + 2 <= len(key_data):
            t, ln = key_data[off], key_data[off + 1]
            chunk = key_data[off + 2 : off + 2 + ln]
            if (t == 0xDD and ln >= 20 and len(chunk) >= 20
                    and chunk[:4] == b"\x00\x0f\xac\x04"):
                pmkid = chunk[4:20]
                if any(pmkid) and pmkid != b"\xff" * 16:
                    pmkids.append(pmkid)
            off += 2 + ln

    zeroed = eapol[:81] + b"\x00" * 16 + eapol[97:]
    # truncate to the 802.1X-declared length (body + 4-byte header)
    declared = struct.unpack_from(">H", eapol, 2)[0] + 4
    zeroed = zeroed[: max(95, min(declared, len(zeroed)))]
    return EapolMsg(num, ap, sta, replay, nonce, ki, zeroed, mic, pmkids)


def parse_80211(frame: bytes):
    """One 802.11 frame -> ('essid'|'probe'|'eapol', payload) or None."""
    if len(frame) < 24:
        return None
    fc = struct.unpack_from("<H", frame, 0)[0]
    ftype = (fc >> 2) & 3
    subtype = (fc >> 4) & 0xF
    to_ds, from_ds = fc & 0x100, fc & 0x200
    a1, a2, a3 = frame[4:10], frame[10:16], frame[16:22]

    if ftype == 0:  # management
        body_off = 24
        if subtype in (8, 5):  # beacon / probe response
            ssid = _tagged_ssid(frame, body_off + 12)
            if ssid:
                return "essid", (a3, ssid)
        elif subtype == 4:  # probe request
            ssid = _tagged_ssid(frame, body_off)
            if ssid:
                return "probe", ssid
        elif subtype in (0, 2):  # assoc / reassoc request
            skip = 4 if subtype == 0 else 10
            ssid = _tagged_ssid(frame, body_off + skip)
            if ssid:
                return "essid", (a3, ssid)
        return None

    if ftype == 2:  # data
        hdr = 24
        if to_ds and from_ds:
            hdr += 6
        if subtype & 8:  # QoS
            hdr += 2
        if fc & 0x8000:  # order bit: HT control
            hdr += 4
        llc = frame[hdr : hdr + 8]
        if len(llc) < 8 or llc[:3] != b"\xaa\xaa\x03" or llc[6:8] != b"\x88\x8e":
            return None
        eapol = frame[hdr + 8 :]
        if to_ds:
            ap, sta = a1, a2
        elif from_ds:
            ap, sta = a2, a1
        else:
            ap, sta = a3, a2
        msg = _parse_eapol_key(ap, sta, eapol)
        if msg:
            return "eapol", msg
    return None


# ---------------------------------------------------------------------------
# Handshake assembly
# ---------------------------------------------------------------------------

# (sta_msg_num, ap_msg_num, replay_delta, message_pair) — replay_delta is
# ap.replay - sta.replay for a valid pairing
_PAIRINGS = [
    (2, 1, 0, 0x00),   # M1+M2
    (2, 3, 1, 0x02),   # M2+M3 (M3 carries the authenticated ANONCE)
    (4, 1, -1, 0x01),  # M1+M4
    (4, 3, 0, 0x03),   # M3+M4
]


#: Max inter-frame gap for M1/M2 (and the other pairings) to count as one
#: handshake exchange — the reference's hcxpcapngtool invocation passes
#: --eapoltimeout=30000 ms (web/common.php:481).  Without the gate, a long
#: capture in which replay counters recur across sessions can pair a MIC
#: with an ANONCE from a *different* exchange, emitting uncrackable junk.
EAPOL_TIMEOUT_S = 30.0


def extract_hashlines(blob: bytes, nc_hint: bool = True,
                      eapol_timeout: float = EAPOL_TIMEOUT_S):
    """Capture blob -> ([m22000 hashline str, ...], [probe-request ssid, ...]).

    Deduped: one PMKID line per (ap, sta, pmkid); the best EAPOL pairing
    per (ap, sta) in _PAIRINGS preference order, restricted to message
    pairs captured within ``eapol_timeout`` seconds of each other
    (frames without timestamps — pcapng SPBs — are never gated).
    """
    essids = defaultdict(Counter)       # ap -> Counter[ssid]
    probes = []
    ap_msgs = defaultdict(list)         # (ap, sta) -> [EapolMsg 1/3]
    sta_msgs = defaultdict(list)        # (ap, sta) -> [EapolMsg 2/4]
    ap_nonces = defaultdict(list)       # ap -> [anonce] in capture order
    pmkid_seen = set()
    pmkid_rows = []

    for ts, frame in iter_frames(blob):
        try:
            parsed = parse_80211(frame)
        except (struct.error, IndexError):
            continue
        if not parsed:
            continue
        kind, payload = parsed
        if kind == "essid":
            ap, ssid = payload
            essids[ap][ssid] += 1
        elif kind == "probe":
            if payload not in probes:
                probes.append(payload)
        else:
            msg = payload
            msg.ts = ts
            bucket = ap_msgs if msg.num in (1, 3) else sta_msgs
            bucket[(msg.ap, msg.sta)].append(msg)
            if msg.num in (1, 3):
                ap_nonces[msg.ap].append(msg.nonce)
            for pmkid in msg.pmkids:
                key = (msg.ap, msg.sta, pmkid)
                if key not in pmkid_seen:
                    pmkid_seen.add(key)
                    pmkid_rows.append((msg.ap, msg.sta, pmkid))

    def best_essid(ap):
        c = essids.get(ap)
        return c.most_common(1)[0][0] if c else None

    endian_cache = {}

    def endian_bits(ap):
        """Observed nonce-increment endianness -> MP_LE/MP_BE hint bits.

        hcxpcapngtool behavior: routers that increment the ANONCE between
        retransmissions reveal whether the counter's last 4 bytes step as
        little- or big-endian; the hint halves the verifier's NC search
        (models/m22000._nc_variants honors it).  Ambiguous evidence
        (both/neither) emits no hint — NC search stays two-sided.
        Memoized per AP: ap_nonces is frozen before any line is emitted.
        """
        if ap in endian_cache:
            return endian_cache[ap]
        le = be = False
        nonces = ap_nonces.get(ap, [])
        for a, b in zip(nonces, nonces[1:]):
            if a[:28] != b[:28] or a == b:
                continue
            for fmt, is_le in (("<I", True), (">I", False)):
                d = (struct.unpack(fmt, b[28:])[0]
                     - struct.unpack(fmt, a[28:])[0]) & 0xFFFFFFFF
                if d >= 0x80000000:
                    d -= 0x100000000
                if 0 < abs(d) <= 128:
                    if is_le:
                        le = True
                    else:
                        be = True
                    break
        bits = (hl.MP_LE if le else hl.MP_BE) if le != be else 0
        endian_cache[ap] = bits
        return bits

    lines = []
    for ap, sta, pmkid in pmkid_rows:
        essid = best_essid(ap)
        if essid:
            lines.append(
                hl.serialize(hl.TYPE_PMKID, pmkid, ap, sta, essid, message_pair=1)
            )

    for (ap, sta), stas in sta_msgs.items():
        essid = best_essid(ap)
        if not essid:
            continue
        aps = ap_msgs.get((ap, sta), [])
        done = False
        for sta_num, ap_num, delta, mp in _PAIRINGS:
            if done:
                break
            for sm in stas:
                if sm.num != sta_num or not any(sm.nonce):
                    continue
                for am in aps:
                    if am.num != ap_num or am.replay - sm.replay != delta:
                        continue
                    if (am.ts is not None and sm.ts is not None
                            and abs(am.ts - sm.ts) > eapol_timeout):
                        continue  # different exchanges, not a handshake
                    mp_final = mp | (0x80 if nc_hint else 0) | endian_bits(ap)
                    lines.append(
                        hl.serialize(
                            hl.TYPE_EAPOL, sm.mic, ap, sta, essid,
                            am.nonce, sm.frame, mp_final,
                        )
                    )
                    done = True
                    break
                if done:
                    break
    return lines, probes
