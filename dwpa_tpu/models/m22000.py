"""m22000 (WPA PMKID / EAPOL 4-way) device cracking engine.

The flagship model of the framework: candidate PSKs -> PBKDF2-HMAC-SHA1
-> PMK -> PMKID-HMAC or PRF+MIC verification with nonce-error-correction,
entirely on device as batched uint32-lane JAX ops.

Reference semantics being matched (never copied — see the pure-Python
oracle at dwpa_tpu/oracle/m22000.py for the executable spec):

- server verifier ``check_key_m22000`` (web/common.php:157-307);
- hashcat client invocation ``--nonce-error-corrections=8``
  (help_crack/help_crack.py:773) — the device searches the same +/-NC
  window the GPU cracker does, while wide-NC re-checks stay host-side;
- message_pair gating bits (web/common.php:114-155, and the client's
  BE/LE handling at help_crack/help_crack.py:378-400): bit4 ap-less =>
  exact nonce only; bit5/bit6 restrict the NC search to LE/BE.

TPU-first design:

- The PBKDF2 kernel (ops/pbkdf2.py) takes the ESSID salt blocks as *data*,
  so one XLA compilation serves every ESSID at a given batch size.
- Verification kernels take per-net constants (PRF message variants, padded
  EAPOL blocks, target words) as arrays and ``vmap`` over the NC-variant
  axis, so compilations are shared across nets with the same
  (keyver, n_variants, n_eapol_blocks) signature.
- All byte wrangling happens host-side in numpy; the device only ever sees
  fixed-shape uint32 arrays.
"""

import struct
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import hmac as hm
from ..ops.aes import aes128_cmac_rolled
from ..ops.common import bswap32, u32
from ..ops.md5 import md5_compress_rolled
from ..ops.sha1 import sha1_compress_rolled
from ..ops.sha256 import sha256_compress_rolled
from ..ops.pbkdf2 import pbkdf2_sha1_pmk
from ..ops.pbkdf2_pallas import pbkdf2_sha1_pmk_pallas
from ..oracle import m22000 as oracle
from ..utils import bytesops as bo
from . import hashline as hl

# Minimum/maximum WPA passphrase length (IEEE 802.11i; enforced by the
# reference dict guidance at INSTALL.md:83 and by hashcat itself).
MIN_PSK_LEN = 8
MAX_PSK_LEN = 63

DEFAULT_NC = 8  # client-side hashcat window (help_crack.py:773)


# ---------------------------------------------------------------------------
# Host-side per-net preparation
# ---------------------------------------------------------------------------


def essid_salt_blocks(essid: bytes):
    """The two PBKDF2 single-block salt messages ``essid || INT32_BE(i)``.

    ESSIDs are <= 32 bytes so ``essid + 4`` always fits one padded SHA-1
    block (after the 64-byte HMAC key block).  Returned as uint32[16]
    arrays — *data*, not trace constants, so the PMK kernel compiles once.
    """
    out = []
    for i in (1, 2):
        tail = essid + struct.pack(">I", i)
        blk = bo.padded_blocks(tail, 64 + len(tail))[0]
        out.append(np.asarray(blk, dtype=np.uint32))
    return out[0], out[1]


def essid_salt_lanes(essids):
    """Stacked per-lane salt tables for a mixed-ESSID batch.

    Row ``b`` of each returned uint32[B, 16] array is
    ``essid_salt_blocks(essids[b])`` — the rank-2 salt mode of
    ``pmk_kernel`` (one lane, one ESSID).  Repeated ESSIDs share one
    derivation, so a sibling-heavy server pre-crack wave pays the salt
    padding once per distinct network name.
    """
    cache = {}
    lanes1, lanes2 = [], []
    for essid in essids:
        pair = cache.get(essid)
        if pair is None:
            pair = cache[essid] = essid_salt_blocks(essid)
        lanes1.append(pair[0])
        lanes2.append(pair[1])
    return np.stack(lanes1), np.stack(lanes2)


def _hmac_msg_blocks(data: bytes, little_endian: bool = False) -> np.ndarray:
    """Pad an HMAC inner message (keyed by one 64-byte block) -> [nb, 16]."""
    return np.asarray(
        bo.message_blocks(data, little_endian, prefix_len=64), dtype=np.uint32
    )


def _nc_variants(h: hl.Hashline, nc: int):
    """(last4, delta, endian) list honoring message_pair gating bits."""
    variants = [(h.anonce[28:32], 0, None)]
    if h.message_pair & hl.MP_APLESS:
        return variants  # M1/M2 from the AP's own frame: nonce is exact
    endians = []
    if h.message_pair & hl.MP_LE:
        endians.append("LE")
    if h.message_pair & hl.MP_BE:
        endians.append("BE")
    if not endians:
        endians = ["LE", "BE"]
    last_le = struct.unpack_from("<I", h.anonce, 28)[0]
    last_be = struct.unpack_from(">I", h.anonce, 28)[0]
    for i in range(1, (nc >> 1) + 2):
        for e in endians:
            if e == "LE":
                variants.append((struct.pack("<I", (last_le + i) & 0xFFFFFFFF), i, "LE"))
                variants.append((struct.pack("<I", (last_le - i) & 0xFFFFFFFF), -i, "LE"))
            else:
                variants.append((struct.pack(">I", (last_be + i) & 0xFFFFFFFF), i, "BE"))
                variants.append((struct.pack(">I", (last_be - i) & 0xFFFFFFFF), -i, "BE"))
    return variants


@dataclass
class PreppedNet:
    """Device-ready constants for one hashline."""

    line: hl.Hashline
    keyver: int                      # 1 | 2 | 3 | 100 (PMKID)
    target: np.ndarray               # uint32[4] (PMKID/MIC words; LE for keyver 1)
    # PMKID path
    pmkid_block: np.ndarray = None   # uint32[16]
    # EAPOL path
    variants: tuple = ()             # ((delta, endian), ...) aligned with prf_blocks
    prf_blocks: np.ndarray = None    # uint32[V, 2, 16] PRF inner-message variants
    eapol_blocks: np.ndarray = None  # uint32[E, 16] (keyver 1: LE words, 2: BE)
    # keyver 3 (AES-128-CMAC MIC)
    cmac_full: np.ndarray = None     # uint32[F, 16] byte values
    cmac_last: np.ndarray = None     # uint32[16] byte values (10*-padded)
    cmac_last_complete: bool = False
    cmac_target: np.ndarray = None   # uint32[16] byte values


def prep_net(h: hl.Hashline, nc: int = DEFAULT_NC) -> PreppedNet:
    """Precompute every per-net constant the device kernels need."""
    if h.hash_type == hl.TYPE_PMKID:
        msg = b"PMK Name" + h.mac_ap + h.mac_sta
        return PreppedNet(
            line=h,
            keyver=100,
            target=np.asarray(bo.be_words(h.pmkid_or_mic), dtype=np.uint32),
            pmkid_block=_hmac_msg_blocks(msg)[0],
        )

    keyver = h.keyver
    if keyver not in (1, 2, 3):
        raise ValueError(f"uncrackable key descriptor version {keyver}")
    m, n, ap_off = oracle.nonce_pairs(h)
    variants = _nc_variants(h, nc)
    prf = []
    for last4, _, _ in variants:
        nv = n[: ap_off + 28] + last4 + n[ap_off + 32 :]
        if keyver == 3:
            msg = oracle.PRF_LABEL_V3 + m + nv + b"\x80\x01"
        else:
            msg = oracle.PRF_LABEL_V12 + m + nv + b"\x00"
        prf.append(_hmac_msg_blocks(msg))
    prepped = PreppedNet(
        line=h,
        keyver=keyver,
        target=np.asarray(
            bo.le_words(h.pmkid_or_mic) if keyver == 1 else bo.be_words(h.pmkid_or_mic),
            dtype=np.uint32,
        )[:4],
        variants=tuple((d, e) for _, d, e in variants),
        prf_blocks=np.stack(prf),
    )
    if keyver == 3:
        ep = h.eapol
        nblk = max(1, (len(ep) + 15) // 16)
        complete = len(ep) > 0 and len(ep) % 16 == 0
        last = ep[(nblk - 1) * 16 :]
        if not complete:
            last = last + b"\x80" + b"\x00" * (15 - len(last))
        prepped.cmac_full = np.frombuffer(
            ep[: (nblk - 1) * 16], dtype=np.uint8
        ).reshape(nblk - 1, 16).astype(np.uint32)
        prepped.cmac_last = np.frombuffer(last, dtype=np.uint8).astype(np.uint32)
        prepped.cmac_last_complete = complete
        prepped.cmac_target = np.frombuffer(h.pmkid_or_mic, dtype=np.uint8).astype(
            np.uint32
        )
    else:
        prepped.eapol_blocks = _hmac_msg_blocks(h.eapol, little_endian=(keyver == 1))
    return prepped


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------


def _rows(arr2d, n=None):
    """[R, 16] array -> list of row-lists of traced scalars."""
    r = arr2d.shape[0] if n is None else n
    return [[arr2d[i, j] for j in range(16)] for i in range(r)]


def _use_pallas() -> bool:
    """Pallas PBKDF2 only on real TPU (the CPU fallback is interpret-mode)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _pmk_impl(pw_words, salt1, salt2, use_pallas=None):
    """PBKDF2 batch: Pallas register-resident kernel on TPU (~4.8x the
    pure-XLA fori_loop formulation on v5e), XLA path elsewhere.

    ``pw_words`` may arrive column-trimmed ([B, W<16]): the host ships
    only the uint32 columns real candidates occupy (H2D through the
    axon tunnel costs ~0.24 s/MB, so a 12-byte dict word must not pay
    for a 64-byte row) and the zero tail of the HMAC key block is
    reconstituted here, on device, where padding is a free fusion.

    ``salt1``/``salt2`` are either uint32[16] (one ESSID for the whole
    batch — the scalar-salt fast path every mask/steady dispatch keeps)
    or uint32[B, 16] (PER-LANE salts: lane b hashes its own ESSID — the
    mixed-ESSID fused batch path, ``parallel.step.fused_pmk_step``).
    jit keys on the salt rank, so the two modes never share or thrash a
    cache entry; per-lane widths must come from the static fused-width
    pad table (lint rule DW109) so the 2-D entries stay bounded too.
    """
    if use_pallas is None:
        use_pallas = _use_pallas()
    if pw_words.shape[1] < 16:
        pw_words = jnp.pad(pw_words, ((0, 0), (0, 16 - pw_words.shape[1])))
    if use_pallas:
        return pbkdf2_sha1_pmk_pallas(pw_words, salt1, salt2)
    pw = [pw_words[:, i] for i in range(16)]
    if salt1.ndim == 2:
        s1 = [salt1[:, i] for i in range(16)]
        s2 = [salt2[:, i] for i in range(16)]
    else:
        s1 = [salt1[i] for i in range(16)]
        s2 = [salt2[i] for i in range(16)]
    return jnp.stack(pbkdf2_sha1_pmk(pw, s1, s2))


#: pmk_kernel(pw_words[B,16], salt1[16]|[B,16], salt2 likewise) -> uint32[8, B]
pmk_kernel = jax.jit(_pmk_impl, static_argnames=("use_pallas",))


def _pmk_key_block(pmk):
    return [pmk[i] for i in range(8)] + [0] * 8


def _eq4(out, target):
    m = out[0] == target[0]
    for i in range(1, 4):
        m = m & (out[i] == target[i])
    return m


def _pmkid_impl(pmk, msg_block, target):
    shape = pmk.shape[1:]
    ist, ost = hm.hmac_sha1_precompute(
        _pmk_key_block(pmk), shape, compress=sha1_compress_rolled
    )
    out = hm.hmac_sha1_blocks(
        ist, ost, [[msg_block[i] for i in range(16)]], compress=sha1_compress_rolled
    )
    return _eq4(out, target)




def eapol_match(pmk, prf_blocks, eapol_blocks, target, *, keyver):
    """MIC match for keyver 1/2 over all NC variants.

    ``pmk``: uint32[8, B]; ``prf_blocks``: uint32[V, 2, 16];
    ``eapol_blocks``: uint32[E, 16]; ``target``: uint32[4].
    Returns bool[V, B].
    """
    shape = pmk.shape[1:]
    ist, ost = hm.hmac_sha1_precompute(
        _pmk_key_block(pmk), shape, compress=sha1_compress_rolled
    )
    eap = _rows(eapol_blocks)

    def per_variant(blk2):
        prf = hm.hmac_sha1_blocks(ist, ost, _rows(blk2, 2), compress=sha1_compress_rolled)
        kck = list(prf[:4])
        if keyver == 1:
            kb = [bswap32(w) for w in kck] + [0] * 12
            ii, oo = hm.hmac_md5_precompute(kb, shape, compress=md5_compress_rolled)
            out = hm.hmac_md5_blocks(ii, oo, eap, compress=md5_compress_rolled)
        else:
            kb = kck + [0] * 12
            ii, oo = hm.hmac_sha1_precompute(kb, shape, compress=sha1_compress_rolled)
            out = hm.hmac_sha1_blocks(ii, oo, eap, compress=sha1_compress_rolled)
        return _eq4(out, target)

    return jax.vmap(per_variant)(prf_blocks)




def eapol_cmac_match(pmk, prf_blocks, cmac_full, cmac_last, target, *, last_complete):
    """AES-128-CMAC MIC match (keyver 3, WPA2 802.11w) -> bool[V, B]."""
    shape = pmk.shape[1:]
    ist, ost = hm.hmac_sha256_precompute(
        _pmk_key_block(pmk), shape, compress=sha256_compress_rolled
    )

    def per_variant(blk2):
        prf = hm.hmac_sha256_blocks(
            ist, ost, _rows(blk2, 2), compress=sha256_compress_rolled
        )
        kck_bytes = []
        for w in prf[:4]:
            kck_bytes += [
                (w >> 24) & u32(0xFF),
                (w >> 16) & u32(0xFF),
                (w >> 8) & u32(0xFF),
                w & u32(0xFF),
            ]
        mac = aes128_cmac_rolled(
            jnp.stack(kck_bytes), cmac_full, cmac_last, last_complete
        )
        return jnp.all(mac == target[:, None], axis=0)

    return jax.vmap(per_variant)(prf_blocks)




def net_match(pmk, net: PreppedNet):
    """Trace-time dispatch of one prepped net -> bool[V, B] (composable)."""
    if net.keyver == 100:
        m = _pmkid_impl(pmk, jnp.asarray(net.pmkid_block), jnp.asarray(net.target))
        return m[None, :]
    if net.keyver == 3:
        return eapol_cmac_match(
            pmk,
            jnp.asarray(net.prf_blocks),
            jnp.asarray(net.cmac_full),
            jnp.asarray(net.cmac_last),
            jnp.asarray(net.cmac_target),
            last_complete=net.cmac_last_complete,
        )
    return eapol_match(
        pmk,
        jnp.asarray(net.prf_blocks),
        jnp.asarray(net.eapol_blocks),
        jnp.asarray(net.target),
        keyver=net.keyver,
    )


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Found:
    """One cracked net, shaped like the reference's verifier return value
    ``[PSK, NC, BE/LE, PMK]`` (web/common.php:152-155)."""

    line: hl.Hashline
    psk: bytes
    nc: int            # signed NC delta (0 = exact)
    endian: str        # "LE" | "BE" | "" (exact / PMKID)
    pmk: bytes


def _trim_cols(max_len: int) -> int:
    """uint32 columns to ship for a batch whose longest word is
    ``max_len`` bytes, bucketed to {4, 8, 16} so jit sees at most three
    width signatures.  The device pads back to the full 16-word HMAC
    key block (see _pmk_impl); for typical dicts (words <= 16 chars)
    this cuts candidate H2D traffic 4x — the difference between the
    tunnel hiding behind compute and throttling the whole dict path.

    Multi-process meshes always ship full rows: every host must enter
    the shard_map with identical shapes, and hosts can't agree on a
    width without a collective that would cost more than it saves."""
    if jax.process_count() > 1:
        return 16
    need = -(-max_len // 4)
    for w in (4, 8):
        if need <= w:
            return w
    return 16


class _PackedWords:
    """Lazy pws view over native-packed rows: ``[b]`` reconstructs the
    decoded candidate bytes from its packed key block + length, so the
    word is only materialized for the rare hit columns."""

    __slots__ = ("words", "lens")

    def __init__(self, words, lens):
        self.words = words
        self.lens = lens

    def __getitem__(self, b):
        return bo.words_to_bytes_be(self.words[b])[: int(self.lens[b])]


class _RuleWords:
    """pws view for a device-mangled batch: column ``b`` decodes by
    applying the host rule to the base word — the executable spec — so
    hit decode never trusts the device transform."""

    __slots__ = ("base", "rule")

    def __init__(self, base, rule):
        self.base = base
        self.rule = rule

    def __getitem__(self, b):
        out = self.rule.apply(self.base[b])
        if out is None or not MIN_PSK_LEN <= len(out) <= MAX_PSK_LEN:
            return None  # rejected/out-of-range: column was zeroed on device
        return out


class _ShiftedWords:
    """pws view for one unit's lane window inside a fused batch: batch
    column ``b`` maps to the unit's own candidate list at ``b - lo``;
    columns outside the window (other units' lanes, padding) decode to
    None so ``_decode`` skips them even if a demux mask ever slipped."""

    __slots__ = ("words", "lo")

    def __init__(self, words, lo):
        self.words = words
        self.lo = lo

    def __getitem__(self, b):
        i = b - self.lo
        return self.words[i] if 0 <= i < len(self.words) else None


class _BaseWords:
    """Lazy base-word list over packed rows + lengths (the warm rules
    cache keeps bases in packed device layout; the fallback split
    guarantees they are HEX-free, so a packed row round-trips
    losslessly).  Supports ``len``/indexing/iteration like the raw word
    list it replaces, materializing bytes only on demand."""

    __slots__ = ("rows", "lens", "n")

    def __init__(self, rows, lens, n):
        self.rows = rows
        self.lens = lens
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, b):
        if not 0 <= b < self.n:
            raise IndexError(b)
        return bo.words_to_bytes_be(self.rows[b])[: int(self.lens[b])]


class _MaskWords:
    """pws stand-in for on-device mask generation: index -> word bytes,
    computed on demand from the keyspace position.

    Indexed by GLOBAL batch column (a pure function of the keyspace
    index) — on a multi-process mesh every host can materialize any
    column, so the find decode skips the candidate exchange (see
    ``_gather_find_data``)."""

    __slots__ = ("mask", "custom", "start")

    global_cols = True

    def __init__(self, mask, custom, start):
        self.mask = mask
        self.custom = custom
        self.start = start

    def __getitem__(self, b):
        from ..gen.mask import mask_words

        return next(mask_words(self.mask, self.custom,
                               skip=self.start + b, limit=1))


class _RulesCtx:
    """Shared per-attack context for the device-expansion seam
    (``M22000Engine._rules_flush``): the split rule sets, the expanded
    stream's rule count, and the attack's telemetry.  One ctx serves
    every rules dispatch path — serial ``crack_rules``, block-framed
    ``crack_rules_blocks`` and the per-device stream adapter — so the
    fallback routing, resume accounting and metrics cannot drift
    between executors."""

    def __init__(self, rules, registry=None, tracer=None):
        from ..obs.metrics import default_registry
        from ..obs.spans import SpanTracer, default_tracer
        from ..rules.device import device_supported, encode_rule

        self.rules = list(rules)
        self.dev_rules = [(r, encode_rule(r)) for r in self.rules
                          if device_supported(r)]
        self.host_rules = [r for r in self.rules
                           if not device_supported(r)]
        self.n_rules = len(self.rules)
        reg = registry if registry is not None else default_registry()
        if tracer is None:
            tracer = default_tracer() if registry is None \
                else SpanTracer(registry)
        self.tracer = tracer
        self.m_device = reg.counter(
            "dwpa_rules_device_expanded_total",
            "(word, rule) pairs expanded on device by the rules seam")
        fb = reg.counter(
            "dwpa_rules_host_fallback_total",
            "(word, rule) pairs routed to the host rule interpreter, "
            "by reason (purge = unsupported op, overflow = length/HEX)")
        self.m_purge = fb.labels(reason="purge")
        self.m_overflow = fb.labels(reason="overflow")

    def span(self, name: str):
        return self.tracer.span(name)


class _BlockAgg:
    """Demux per-sub-batch pipeline events back into per-BLOCK reports.

    A rules block expands into several dispatched sub-batches (fused
    rule chunks + the host-expanded tail); ``_Pipeline`` fires its
    callback once per sub-batch, in stream order, but block callers
    (``crack_rules_blocks`` and the client resume checkpoint behind it)
    need exactly one ``on_batch(consumed, founds)`` per base block.
    ``begin``/``emit``/``close`` bracket each block's emissions;
    ``record`` (installed as the pipeline callback) attributes every
    event to the oldest incompletely-fired block — emission order IS
    event order because the pipeline is FIFO — and a block fires once
    closed and fully collected.  A block that emitted nothing (wholly
    inside the resume prefix, or nothing dispatchable) reports
    nothing, matching ``crack_rules``'s skip semantics."""

    def __init__(self, on_batch):
        import collections

        self.on_batch = on_batch
        self.blocks = collections.deque()
        self.cur = None

    def begin(self):
        self.cur = {"emitted": 0, "fired": 0, "got": 0,
                    "founds": [], "closed": False}
        self.blocks.append(self.cur)

    def emit(self):
        self.cur["emitted"] += 1

    def record(self, raw, new):
        for b in self.blocks:
            if b["fired"] < b["emitted"]:
                b["fired"] += 1
                b["got"] += raw
                b["founds"].extend(new)
                break
        self._fire()

    def close(self):
        self.cur["closed"] = True
        self.cur = None
        self._fire()

    def _fire(self):
        while self.blocks:
            b = self.blocks[0]
            if not b["closed"] or b["fired"] < b["emitted"]:
                return
            self.blocks.popleft()
            if b["emitted"] and self.on_batch is not None:
                self.on_batch(b["got"], b["founds"])


class _Pipeline:
    """Shared dispatch/sync pipeline for the engine's crack paths.

    Holds up to ``engine.PIPELINE_DEPTH`` dispatched batches; ``push``
    finishes the oldest once the depth is exceeded, so the hits-gate
    sync always trails the dispatch frontier.  ``on_batch`` fires in
    stream order — crack() and crack_mask() share these semantics by
    construction instead of re-implementing them (they had already
    drifted on effective depth once).
    """

    def __init__(self, engine, on_batch=None):
        import collections

        self.engine = engine
        self.on_batch = on_batch
        self.pending = collections.deque()  # (dispatched, raw), oldest first
        self.founds = []

    @property
    def active(self) -> bool:
        return bool(self.pending)

    def push(self, dispatched, raw: int):
        self.pending.append((dispatched, raw))
        if len(self.pending) > self.engine.PIPELINE_DEPTH:
            self.finish_one()

    def skip(self, raw: int):
        """A consumed-but-undispatchable batch: drain first so the
        report keeps stream order (resume skip-by-count depends on it)."""
        self.drain()
        if self.on_batch is not None:
            self.on_batch(raw, [])

    def finish_one(self):
        dispatched, raw = self.pending.popleft()
        new = self.engine._collect(dispatched)
        self.founds.extend(new)
        if self.on_batch is not None:
            self.on_batch(raw, new)

    def drain(self):
        while self.pending:
            self.finish_one()


class M22000Engine:
    """Crack a set of m22000 hashlines with batches of candidate PSKs.

    ESSID grouping mirrors the reference scheduler's amortization trick
    (web/content/get_work.php:96-109): one PBKDF2 per (candidate, ESSID)
    feeds the PMKID/MIC checks of every net sharing that ESSID.

    The product path is the mesh-sharded crack step (parallel/step.py):
    candidates split over the "dp" axis, PBKDF2+verify per shard, and a
    psum'd scalar hit count fetched as the only per-batch host sync — the
    full match matrix and PMKs cross to the host only on the rare batch
    that actually contains a find.  ``mesh="auto"`` spans every local
    device; a 1-device mesh degenerates to the single-chip path.
    """

    def __init__(self, lines, nc: int = DEFAULT_NC, batch_size: int = 4096,
                 verify_with_oracle: bool = True, mesh="auto",
                 pmk_store=None):
        from ..parallel import default_mesh

        if mesh == "auto":
            mesh = default_mesh()
        self.mesh = mesh
        # Optional persistent PBKDF2 cache (dwpa_tpu.pmkstore): the feed
        # packer splits blocks into cache hits/misses on the producer
        # threads, the mixed dispatch computes only the misses, and
        # _collect writes newly derived PMKs back after the device fetch.
        self.pmk_store = pmk_store
        # Pad batches to a multiple of the mesh size (shard_map needs the
        # candidate axis evenly split).
        n = mesh.size
        self.batch_size = -(-int(batch_size) // n) * n
        self.nc = nc
        self.verify_with_oracle = verify_with_oracle
        self.groups = {}  # essid -> list[PreppedNet] (live/uncracked view)
        self.skipped = []
        # Steps are built once per ESSID group over its FULL original
        # membership and reused for the engine's lifetime: a find masks
        # its net host-side in _collect instead of shrinking the step's
        # shapes, which would move it to a different jit-cache entry.
        # (Compilations themselves are shared process-wide by shape
        # signature — parallel/step.py — so building a step is cheap.)
        self._full = {}   # essid -> original list[PreppedNet]
        self._steps = {}  # essid -> crack step (parallel.build_crack_step)
        self._rules_steps = {}  # essid -> fused rules step (build_rules_step)
        # Per-stage wall-clock accumulators (SURVEY.md §5.1): host pack +
        # H2D enqueue / device dispatch / sync + decode.  "collect" is
        # where device compute surfaces under the async runtime.
        # Keys are API (the client's stage log and tests read them).
        # Since the candidate feed (dwpa_tpu/feed) moved packing onto
        # producer threads, "prepare" counts only the RESIDUAL on-thread
        # work — device staging for prepacked blocks, or the full pack
        # for non-feed callers; producer-side pack time lives in the
        # feed's ``feed:produce`` spans instead, so the two are never
        # double-counted.
        self.stage_times = {"prepare": 0.0, "dispatch": 0.0, "collect": 0.0}
        for line in lines:
            try:
                h = line if isinstance(line, hl.Hashline) else hl.parse(line)
                net = prep_net(h, nc=nc)
            except ValueError:
                self.skipped.append(line)
                continue
            self.groups.setdefault(h.essid, []).append(net)
        self._full = {e: list(g) for e, g in self.groups.items()}
        self._salts = {e: essid_salt_blocks(e) for e in self.groups}

    @property
    def nets(self):
        return [n for group in self.groups.values() for n in group]

    def remove(self, found: Found):
        """Drop a cracked net (and empty groups) from further batches."""
        group = self.groups.get(found.line.essid)
        if not group:
            return
        group[:] = [n for n in group if n.line is not found.line]
        if not group:
            del self.groups[found.line.essid]
            del self._salts[found.line.essid]
            self._steps.pop(found.line.essid, None)
            self._rules_steps.pop(found.line.essid, None)
            self._full.pop(found.line.essid, None)

    def _step_for(self, essid: bytes):
        """The mesh crack step for one ESSID group, built once over the
        group's full original membership (see __init__)."""
        from ..parallel import build_crack_step

        step = self._steps.get(essid)
        if step is None:
            s1, s2 = self._salts[essid]
            step = build_crack_step(self.mesh, list(self._full[essid]), s1, s2)
            self._steps[essid] = step
        return step

    def _rules_step_for(self, essid: bytes):
        """The fused expand+crack step (build_rules_step) for one ESSID
        group — same full-membership / lifetime contract as _step_for."""
        from ..parallel.step import build_rules_step

        step = self._rules_steps.get(essid)
        if step is None:
            s1, s2 = self._salts[essid]
            step = build_rules_step(self.mesh, list(self._full[essid]), s1, s2)
            self._rules_steps[essid] = step
        return step

    def _prepare(self, passwords):
        """Host stage: decode, filter, pad, pack, and start the async H2D.

        Returns ``(pws, nvalid, pw_words)`` or None if nothing valid.  The
        device_put is asynchronous, so calling this while a previous
        batch's steps are still executing overlaps the transfer with
        compute (see ``crack``).
        """
        from ..parallel import shard_candidates

        t0 = time.perf_counter()
        plist = passwords if isinstance(passwords, list) else list(passwords)
        if not plist:
            # Multi-process: an empty local block must still dispatch
            # padding or the peers' shard_map collectives hang (see
            # _padding_prep; returns None single-process).
            return self._padding_prep(t0)
        # Pad to batch_size (or, for an oversize caller-supplied batch, up
        # to the next mesh-size multiple so the shard_map split stays even).
        cap = max(self.batch_size,
                  -(-len(plist) // self.mesh.size) * self.mesh.size)
        # Native fast path: $HEX decode + length filter + pack fused in
        # one C pass (native/pack_fast.cpp) — the host feed must outrun
        # a mesh, not one chip.  Falls back to the Python pipeline when
        # the library is unavailable or the batch isn't plain bytes.
        from ..native import pack_candidates_fast

        fast = pack_candidates_fast(plist, MIN_PSK_LEN, MAX_PSK_LEN,
                                    capacity=cap)
        if fast is not None:
            packed, lens, nvalid = fast
            if nvalid == 0:
                return self._padding_prep(t0)
            # Size the device batch from the post-filter count, exactly
            # like the fallback: an oversize batch full of invalid words
            # must not inflate the shape (extra zero-row PBKDF2s and a
            # fresh jit entry).
            target = max(self.batch_size,
                         -(-nvalid // self.mesh.size) * self.mesh.size)
            w = _trim_cols(int(lens.max()) if nvalid else MIN_PSK_LEN)
            pw_words = shard_candidates(
                self.mesh, np.ascontiguousarray(packed[:target, :w])
            )
            self.stage_times["prepare"] += time.perf_counter() - t0
            return _PackedWords(packed, lens), nvalid, pw_words

        # $HEX[...] notation decodes to raw bytes before hashing, matching
        # the server's candidate handling (hc_unhex, web/common.php:3-25).
        pws = [oracle.hc_unhex(p) for p in plist]
        pws = [p for p in pws if MIN_PSK_LEN <= len(p) <= MAX_PSK_LEN]
        if not pws:
            return self._padding_prep(t0)
        nvalid = len(pws)
        target = max(self.batch_size, -(-nvalid // self.mesh.size) * self.mesh.size)
        w = _trim_cols(max(len(p) for p in pws))
        if nvalid < target:
            pws = pws + [b"\x00" * MIN_PSK_LEN] * (target - nvalid)
        pw_words = shard_candidates(
            self.mesh, np.ascontiguousarray(bo.pack_passwords_be(pws)[:, :w])
        )
        self.stage_times["prepare"] += time.perf_counter() - t0
        return pws, nvalid, pw_words

    def host_packer(self):
        """Pure-host packing closure for feed producer threads.

        Captures the batch geometry as plain ints so the closure touches
        no engine/jax state from the thread (lint rule DW107: producer
        threads may not touch jax device APIs) — decode, filter and pack
        only; the consumer thread stages the result via
        ``_prepare_staged``.  Returns None when the native packer is
        unavailable (the block then takes the full ``_prepare`` path
        on-thread, unchanged semantics).  ``pack(words, pre=...)``
        accepts an already-packed ``(rows, lens, nvalid)`` from the
        dict cache's warm path and skips the packer (the feed detects
        this via ``pack.supports_pre``); the store split below still
        applies, so warm blocks compose with the PMK-store hit/miss
        dispatch.

        With a ``pmk_store`` attached the closure additionally splits the
        packed block into per-ESSID cache hits and misses
        (``pmkstore.stage.split_block`` — store lookups are mmap/dict
        reads, still pure host work) and returns a ``MixedPrep`` the
        engine's mixed dispatch consumes.  Single-process only: on a
        multi-host slice the per-host miss counts would pick different
        static widths and desync the shard_map shapes, so the split
        would need a width-agreement collective the producer thread must
        not run — multi-host engines keep the plain path (each host's
        store still accumulates its own framed slice via write-back).
        """
        from ..native import pack_candidates_fast

        bs, n = self.batch_size, self.mesh.size
        store = self.pmk_store if jax.process_count() == 1 else None
        essids = list(self._salts) if store is not None else None

        def pack(words, pre=None):
            # ``pre``: an already-packed (rows, lens, nvalid) from the
            # dict cache's warm path (feed.dictcache) — identical to
            # what pack_candidates_fast would return for ``words``, so
            # the packer is bypassed entirely and only the PMK-store
            # split (when attached) still runs
            if pre is not None:
                fast = pre
            else:
                cap = max(bs, -(-len(words) // n) * n)
                fast = pack_candidates_fast(words, MIN_PSK_LEN, MAX_PSK_LEN,
                                            capacity=cap)
            if fast is None or store is None:
                return fast
            packed, lens, nvalid = fast
            if nvalid == 0:
                return fast
            from ..pmkstore.stage import split_block

            return split_block(store, essids, packed, lens, nvalid, bs, n)

        pack.supports_pre = True
        return pack

    def _prepare_staged(self, packed, lens, nvalid):
        """Consumer-side residual of ``_prepare`` for a feed-prepacked
        block: only the device staging (column trim + async H2D) — the
        packing already happened on a producer thread and is accounted
        to the feed's ``feed:produce`` spans, so ``stage_times["prepare"]``
        accumulates just this residual (see the stage_times comment).
        """
        from ..parallel import shard_candidates

        t0 = time.perf_counter()
        if nvalid == 0:
            return self._padding_prep(t0)
        target = max(self.batch_size,
                     -(-nvalid // self.mesh.size) * self.mesh.size)
        w = _trim_cols(int(lens.max()))
        pw_words = shard_candidates(
            self.mesh, np.ascontiguousarray(packed[:target, :w])
        )
        self.stage_times["prepare"] += time.perf_counter() - t0
        return _PackedWords(packed, lens), nvalid, pw_words

    def _prepare_block(self, block):
        """Prep one feed block (``dwpa_tpu.feed.framing.Block``):
        store-split mixed path when the producer looked the block up in
        the PMK cache, staged fast path when it merely prepacked it,
        full ``_prepare`` otherwise."""
        prep = getattr(block, "prep", None)
        if prep is None:
            return self._prepare(block.words)
        if hasattr(prep, "mask_gen"):
            # on-device mask generation (gen.mask.MaskPrep): no host
            # bytes at all — generate the block's keyspace slice
            # directly under this engine's mesh sharding (a 1-device
            # stream engine generates exactly its own candidates)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..gen.mask import device_mask_words
            from ..parallel.mesh import DP_AXIS

            n = block.count
            gen = -(-n // self.mesh.size) * self.mesh.size
            t0 = time.perf_counter()
            pw_words = device_mask_words(
                prep.mask, prep.start, gen, prep.custom,
                sharding=NamedSharding(self.mesh, P(DP_AXIS, None)),
            )
            self.stage_times["prepare"] += time.perf_counter() - t0
            return _MaskWords(prep.mask, prep.custom, prep.start), n, pw_words
        if hasattr(prep, "materialize"):
            # a lazy dict-cache prep (framing.PackedSlices) normally
            # materializes on the feed's producer threads; blocks
            # consumed without a feed (direct frame_packed iteration)
            # materialize here instead — pure host array copies, not
            # cache file I/O (the mmap was opened producer-side)
            prep = prep.materialize()
        from ..pmkstore.stage import MixedPrep

        if isinstance(prep, MixedPrep):
            return self._prepare_mixed(prep)
        return self._prepare_staged(*prep)

    def _prepare_mixed(self, mp):
        """Consumer-side staging of a store-split block: start the async
        H2D of each group's compacted miss sub-batch (column-trimmed
        like ``_prepare_staged``); the cached-PMK matrices stay host
        arrays until dispatch.  Same ``stage_times["prepare"]``
        accounting as the staged path — the split itself ran on a
        producer thread and lives in ``feed:produce`` spans."""
        from ..parallel import shard_candidates

        t0 = time.perf_counter()
        for ent in mp.entries.values():
            if ent.nmiss:
                w = _trim_cols(int(ent.miss_lens.max()))
                ent.miss_dev = shard_candidates(
                    self.mesh, np.ascontiguousarray(ent.miss_rows[:, :w]))
        self.stage_times["prepare"] += time.perf_counter() - t0
        return _PackedWords(mp.packed, mp.lens), mp.nvalid, mp

    def _padding_prep(self, t0):
        """All-padding batch for a shard that contributed no valid words.

        On a multi-process mesh every host must enter the shard_map
        collective in lockstep: if this host returned None (skip) while
        its peers dispatched, their devices would wait forever.  A
        batch_size block of zero rows keeps the step shapes identical
        everywhere; nvalid=0 masks every column at decode, so the only
        cost is one batch of wasted PBKDF2 on this host's shard — paid
        on the rare all-invalid shard, never on the common path.
        Single-process engines keep the cheap skip instead.
        """
        from ..parallel import shard_candidates

        if jax.process_count() <= 1:
            return None
        pw_words = shard_candidates(
            self.mesh, np.zeros((self.batch_size, _trim_cols(MIN_PSK_LEN)),
                                np.uint32)
        )
        self.stage_times["prepare"] += time.perf_counter() - t0
        return [], 0, pw_words

    def _dispatch(self, prep):
        """Launch the crack step for every live ESSID group (no host sync).

        The step always runs over the group's full original membership
        (cracked nets included — their extra MIC checks are noise next to
        the shared PBKDF2); _collect masks the dead rows.
        """
        t0 = time.perf_counter()
        pws, nvalid, pw_words = prep
        from ..pmkstore.stage import MixedPrep

        if isinstance(pw_words, MixedPrep):
            return self._dispatch_mixed(pws, nvalid, pw_words, t0)
        outs = []
        for essid in list(self.groups):
            step = self._step_for(essid)
            outs.append((self._full[essid], step(pw_words)))
        self.stage_times["dispatch"] += time.perf_counter() - t0
        return pws, nvalid, outs

    def _dispatch_mixed(self, pws, nvalid, mp, t0):
        """Mixed hit/miss dispatch (PMK store): per group, PBKDF2 runs
        only on the compacted miss sub-batch, cached PMKs are gathered
        around the computed ones into the full ``uint32[8, B]`` matrix
        (``parallel.step.mix_step``), and the group's verify kernels run
        unchanged on that matrix — an all-hit block dispatches ZERO
        PBKDF2 work.  The returned record carries the write-back list
        (miss PMK device arrays + their words) that ``_collect`` flushes
        to the store AFTER its device fetch, on the consumer thread
        (lint rule DW108: write-back never runs in a producer or traced
        region)."""
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel.mesh import DP_AXIS
        from ..parallel.step import mix_step

        pmk_sharding = getattr(self, "_pmk_sharding", None)
        if pmk_sharding is None:
            pmk_sharding = self._pmk_sharding = NamedSharding(
                self.mesh, PartitionSpec(None, DP_AXIS))
        outs, writeback = [], []
        for essid in list(self.groups):
            step = self._step_for(essid)
            ent = mp.entries[essid]
            if ent.nmiss == 0:
                pmk = jax.device_put(ent.cached, pmk_sharding)
            else:
                pmk_miss = step.compute_pmk(ent.miss_dev)
                writeback.append(
                    (essid, pmk_miss, ent.miss_words, ent.nmiss))
                pmk = (pmk_miss if ent.nhit == 0 else
                       mix_step(self.mesh)(pmk_miss, ent.cached, ent.idx))
            outs.append((self._full[essid], step.verify(pmk)))
        self.stage_times["dispatch"] += time.perf_counter() - t0
        return pws, nvalid, outs, None, writeback

    #: Per-host cap on hit columns exchanged in one multi-process batch
    #: (a fixed-size allgather keeps the exchange shape static; real
    #: crack batches see hits at ~1e-6 rates, so 128 is generous).
    MAX_FINDS_PER_BATCH = 128

    #: Merge the hits-gate and find-decode fetches into ONE device_get
    #: when a batch's whole output payload fits under this byte count.
    #: Through the axon tunnel every D2H call costs ~0.12 s latency
    #: regardless of payload up to ~512 KB (measured: 64 KB -> 112 ms,
    #: 512 KB -> 137 ms, 1 MB -> 261 ms), so for small batches the
    #: gate + decode pair was two round trips where one suffices — this
    #: halves the small-work-unit fixed constant (bench unit_overhead).
    #: Big batches keep the scalar gate: their dense matrices are MBs.
    SMALL_FETCH_BYTES = 600_000

    def _replicated(self, x):
        """Reshard a batch-sharded step output to fully replicated.

        On a multi-process mesh the raw outputs live partly on
        non-addressable devices, which ``np.asarray`` rejects; this jitted
        identity with a replicated out-sharding compiles to an all_gather
        that every process enters in lockstep (the psum hits-gate already
        agreed the batch has a hit, so control flow cannot diverge).
        One jit object per engine so only the first find per shape pays a
        compilation."""
        fn = getattr(self, "_replicate_jit", None)
        if fn is None:
            from jax.sharding import NamedSharding, PartitionSpec

            fn = jax.jit(
                lambda a: a,
                out_shardings=NamedSharding(self.mesh, PartitionSpec()),
            )
            self._replicate_jit = fn
        return fn(x)

    def _gather_find_data(self, found_dev, pmk_dev, pws, nvalid):
        """Multi-process hit decode (rare path).

        Returns ``(found, pmk_host, psk_by_col)``: the replicated find
        matrix/PMKs with every host's local padding columns masked, plus
        a global-column -> candidate-bytes map assembled by a fixed-size
        allgather — the candidate bytes exist only on the host that fed
        that shard (shard_candidates' process-local contract), while
        every host must decode identical founds so the engine's pruning
        (and the later compiled-step dispatch) stays in SPMD lockstep.
        """
        from jax.experimental import multihost_utils

        found = np.array(self._replicated(found_dev))
        pmk_host = np.asarray(self._replicated(pmk_dev))
        nproc = jax.process_count()
        pid = jax.process_index()
        tgt = found.shape[2] // nproc  # equal local batches (see _prepare)
        if getattr(pws, "global_cols", False):
            # Mask path: nvalid counts GLOBAL columns (crack_mask's n)
            # and candidates are a pure function of the global keyspace
            # index (_LazyWords), so mask the tail globally and let every
            # host materialize the hit words locally — identical bytes,
            # no exchange needed.  (The per-process masking below would
            # leave wrap/out-of-limit columns live on a partial batch.)
            found[:, :, nvalid:] = False
            hit_cols = [int(b) for b in np.flatnonzero(found.any(axis=(0, 1)))]
            return found, pmk_host, {b: pws[b] for b in hit_cols}
        nvalids = np.asarray(
            multihost_utils.process_allgather(np.array([nvalid]))
        ).reshape(-1)
        for p in range(nproc):
            found[:, :, p * tgt + int(nvalids[p]):(p + 1) * tgt] = False
        hit_cols = [int(b) for b in np.flatnonzero(found.any(axis=(0, 1)))]
        # Dict path: the candidate bytes exist only on the host that fed
        # that shard (shard_candidates' process-local contract), while
        # every host must decode identical founds so the engine's pruning
        # (and the later compiled-step dispatch) stays in SPMD lockstep.
        # Fixed-shape candidate exchange: [used(1) col(4) len(1) psk(63)]
        # rows, MAX_FINDS_PER_BATCH per round.  Every host derives every
        # host's owned-hit count from the (replicated) find matrix, so
        # all agree on the round count with no extra collective — and no
        # hit is ever dropped, however dense the batch.
        owned = {p: [b for b in hit_cols if b // tgt == p]
                 for p in range(nproc)}
        rounds = max(
            1, -(-max(len(c) for c in owned.values()) // self.MAX_FINDS_PER_BATCH)
        )
        mine = owned[pid]
        psk_by_col = {}
        for r in range(rounds):
            ex = np.zeros((self.MAX_FINDS_PER_BATCH, 6 + MAX_PSK_LEN), np.uint8)
            chunk = mine[r * self.MAX_FINDS_PER_BATCH:
                         (r + 1) * self.MAX_FINDS_PER_BATCH]
            for k, b in enumerate(chunk):
                psk = pws[b - pid * tgt]
                ex[k, 0] = 1
                ex[k, 1:5] = np.frombuffer(struct.pack("<I", b), np.uint8)
                ex[k, 5] = len(psk)
                ex[k, 6:6 + len(psk)] = np.frombuffer(psk, np.uint8)
            allex = np.asarray(multihost_utils.process_allgather(ex))
            allex = allex.reshape(-1, ex.shape[1])
            psk_by_col.update({
                int(struct.unpack("<I", row[1:5].tobytes())[0]):
                    row[6:6 + int(row[5])].tobytes()
                for row in allex if row[0]
            })
        return found, pmk_host, psk_by_col

    def _decode(self, group, found, pmk_col, pws, psk_by_col, live) -> list:
        """Decode one found matrix ([N, V_max, B]) into Found records.

        ``pmk_col(b) -> uint32[8]`` resolves a column's PMK words (a
        dense host matrix or the sparse gathered view — see _collect).
        ``live`` is a mutable id-set shared across a batch's decodes (a
        chunked rules dispatch carries several matrices for the same
        group — a net cracked by rule r must not re-report for r+1).
        """
        founds = []
        for ni, net in enumerate(group):
            if id(net.line) not in live:
                continue  # already cracked; the step still computes it
            nf = found[ni]  # [V_max, B]
            hit_cols = np.flatnonzero(nf.any(axis=0))
            for b in hit_cols:
                if psk_by_col is None:
                    psk = pws[b]
                    if psk is None:
                        continue  # zeroed rule column (see _RuleWords)
                else:
                    psk = psk_by_col.get(int(b))
                    if psk is None:
                        continue  # defensive: every hit col is exchanged
                delta, endian = (0, None)
                if net.keyver != 100:
                    delta, endian = net.variants[int(nf[:, b].argmax())]
                pmk_bytes = bo.words_to_bytes_be(pmk_col(int(b)))
                if self.verify_with_oracle:
                    chk = oracle.check_key_m22000(net.line, [psk], nc=self.nc)
                    if chk is None:
                        continue  # device false positive: reject like the server would
                founds.append(
                    Found(
                        line=net.line,
                        psk=psk,
                        nc=delta,
                        endian=endian or "",
                        pmk=pmk_bytes,
                    )
                )
                live.discard(id(net.line))
                break  # one PSK per net is enough
        return founds

    def _decode_rules(self, group, bits_dev, pws, nvalid, b_local, live) -> list:
        """Decode a fused rules chunk's bit-packed found-any mask.

        ``bits_dev``: uint32[R, B/32], bit b of word b>>5 = column b
        matched SOME net (build_rules_step).  The dense per-net matrix
        and PMKs never cross the tunnel (~tens of MB per chunk); for
        each set bit the host re-derives which net, the NC delta/endian
        and the PMK by running the ORACLE on the decoded candidate —
        finds are rare and the oracle is the executable spec, so this
        is both cheap and authoritative (regardless of
        verify_with_oracle, which exists to double-check *device*
        claims; here the claim IS the oracle's).

        ``b_local`` is the dispatch's per-shard column count
        (``cap // mesh.size``), carried through the pipeline record from
        the ONE place that padded the batch — re-deriving it here from
        ``nvalid`` once silently sliced off every hit in a partial batch
        (``nvalid < batch_size`` pads to ``batch_size``, not to
        ``ceil(nvalid/n)*n``).
        """
        founds = []
        if jax.process_count() > 1:
            # Partly non-addressable on a multi-process mesh: the jitted
            # replicate (an all_gather every host enters in lockstep —
            # the hits-gate already agreed this batch has a find) hands
            # every host the identical global mask, and the global plain
            # list (see crack_rules' multi-process contract) lets each
            # decode every column locally — no candidate exchange.
            bits = np.asarray(self._replicated(bits_dev))
        else:
            bits = np.asarray(jax.device_get(bits_dev))
        # bits: [R, shards*ceil(b_local/32)].  Per-shard layout: each
        # device packs its local columns into ceil(b_local/32) words
        # (32-padded), and the dp out-sharding concatenates the shards —
        # undo both to recover global columns.
        n = self.mesh.size
        assert b_local * n >= nvalid, (b_local, n, nvalid)
        wpb = bits.shape[1] // n
        for r in range(bits.shape[0]):
            if pws[r] is None or not bits[r].any():
                continue  # chunk-padding rule, or no hits for this rule
            # ascontiguousarray: the axon plugin's device_get can hand
            # back non-C-contiguous rows, which .view(uint8) rejects.
            hit = np.unpackbits(
                np.ascontiguousarray(bits[r].reshape(n, wpb)).view(np.uint8),
                axis=1, bitorder="little",
            )[:, :b_local].reshape(-1)
            for b in np.flatnonzero(hit[:nvalid]):
                psk = pws[r][int(b)]
                if psk is None:
                    continue  # zeroed column (reject/overflow)
                for net in group:
                    if id(net.line) not in live:
                        continue
                    chk = oracle.check_key_m22000(net.line, [psk], nc=self.nc)
                    if chk is None:
                        continue  # device false positive for this net
                    _, delta, endian, pmk = chk
                    founds.append(
                        Found(line=net.line, psk=psk, nc=delta or 0,
                              endian=endian or "", pmk=pmk)
                    )
                    live.discard(id(net.line))
        return founds

    def _collect(self, dispatched) -> list:
        """Sync stage: gate on hits, decode founds, prune cracked nets."""
        t0 = time.perf_counter()
        pws, nvalid, outs = dispatched[:3]
        # Rules records carry the dispatch's per-shard width as a 4th
        # element (see _decode_rules on why it cannot be re-derived);
        # mixed-block records carry the PMK-store write-back list as a
        # 5th (see _dispatch_mixed).
        b_shard = dispatched[3] if len(dispatched) > 3 else None
        writeback = dispatched[4] if len(dispatched) > 4 else None
        multiproc = jax.process_count() > 1
        founds = []
        live = {id(n.line) for g in self.groups.values() for n in g}
        fetched = None
        if not multiproc and outs:
            payload = sum(int(a.nbytes) for _, out in outs for a in out[1:])
            if payload <= self.SMALL_FETCH_BYTES:
                # Small batch: ONE merged round trip for every group's
                # (hits, find data) — see SMALL_FETCH_BYTES.  The
                # downstream branches are payload-agnostic (device_get
                # on a host array is a no-op).
                fetched = jax.device_get([out for _, out in outs])
        for i, (group, out) in enumerate(outs):
            if fetched is not None:
                out = fetched[i]
            # The psum hits-gate: one replicated scalar is the only
            # device->host sync on the (overwhelmingly common) all-miss
            # batch; the [N, V, B] matrix and PMKs stay on device.
            if int(np.asarray(out[0])) == 0:
                continue
            if len(out) == 2:  # fused rules chunk: (hits, packed found-any)
                founds += self._decode_rules(group, out[1], pws, nvalid,
                                             b_shard, live)
                continue
            hits, found_dev, pmk_dev = out
            if multiproc:
                found, pmk_host, psk_by_col = self._gather_find_data(
                    found_dev, pmk_dev, pws, nvalid
                )
                founds += self._decode(group, found,
                                       lambda b: pmk_host[:, b], pws,
                                       psk_by_col, live)
                continue
            if pmk_dev.nbytes <= (1 << 21):
                # Small batch: one merged fetch of both arrays (each D2H
                # costs ~0.13 s fixed through the tunnel; this path is in
                # every small work unit's constant overhead).
                found, pmk_host = jax.device_get((found_dev, pmk_dev))
                found = np.array(found)
                pmk_col = lambda b: pmk_host[:, b]
            else:
                # Big batch: the dense PMK matrix is MBs (~1 s/4 MB
                # through the tunnel) while real find batches carry a
                # handful of hits.  Fetch the bool matrix alone, then
                # gather ONLY the hit columns' PMKs on device (fixed
                # 128-slot shape, one extra dispatch on find batches).
                found = np.array(jax.device_get(found_dev))
                found[:, :, nvalid:] = False
                cols = np.flatnonzero(found.any(axis=(0, 1)))
                if len(cols) <= self.MAX_FINDS_PER_BATCH:
                    gather = getattr(self, "_pmk_gather_jit", None)
                    if gather is None:
                        gather = self._pmk_gather_jit = jax.jit(
                            lambda p, c: p[..., c])
                    pad = np.zeros(self.MAX_FINDS_PER_BATCH, np.int32)
                    pad[: len(cols)] = cols
                    pmk_cols = np.asarray(gather(pmk_dev, pad))
                    slot = {int(b): i for i, b in enumerate(cols)}
                    pmk_col = lambda b: pmk_cols[:, slot[b]]
                else:  # pathological hit density: dense fallback
                    pmk_host = np.asarray(jax.device_get(pmk_dev))
                    pmk_col = lambda b: pmk_host[:, b]
            found[:, :, nvalid:] = False
            founds += self._decode(group, found, pmk_col, pws, None, live)
        for f in founds:
            self.remove(f)
        if writeback and self.pmk_store is not None:
            # PMK-store write-back: the one place newly derived PMKs
            # leave the device outside a find.  Runs on the consumer
            # thread after the hits-gate fetch (DW108's allowed seam);
            # the [8, width] miss matrix is an intentional per-batch
            # D2H — it is what turns the NEXT unit's repeats into hits.
            for essid, pmk_dev, miss_words, nmiss in writeback:
                pmk_host = jax.device_get(pmk_dev)
                self.pmk_store.put(essid, miss_words, pmk_host[:, :nmiss])
        self.stage_times["collect"] += time.perf_counter() - t0
        return founds

    def crack_batch(self, passwords) -> list:
        """One fixed-size batch of candidate byte-strings -> list[Found]."""
        prep = self._prepare(passwords)
        if prep is None:
            return []
        return self._collect(self._dispatch(prep))

    #: In-flight batches kept queued on the device ahead of the sync
    #: point.  3 = a four-deep pipeline: while batch N is fetched and
    #: decoded, N+1/N+2 are computing and N+3's H2D is in flight, so
    #: both the hits-gate round trip AND the (column-trimmed, ~2 MB)
    #: candidate upload hide behind PBKDF2 compute.  Measured on the
    #: tunnelled v5e at batch 128k: depth 2 -> 244k PMK/s, depth 3 ->
    #: 250k (96% of the mask path's 260k), depth 4 -> flat; the extra
    #: slot costs only one more batch of at-least-once replay after a
    #: crash (see crack()).
    PIPELINE_DEPTH = 3

    def crack(self, candidates, on_batch=None) -> list:
        """Stream candidates in engine-sized batches until exhausted.

        Software pipeline (``_Pipeline``), ``PIPELINE_DEPTH + 1`` deep:
        while the device crunches batch N, the host packs and uploads
        the next ``PIPELINE_DEPTH`` batches, and the hits-gate sync
        always trails the dispatch frontier by ``PIPELINE_DEPTH``
        batches — the double-buffering SURVEY.md §7.3.3 calls for,
        deeper to also hide the device->host gate latency (see the
        PIPELINE_DEPTH comment for the measured depth choice).

        ``on_batch(consumed, founds)`` is invoked after each batch
        completes, in stream order (consumed = raw candidates in that
        batch, founds = its Found list) — the checkpoint seam the
        client's intra-unit resume hangs off (the hashcat ``--session``
        analog, help_crack.py:773).  At-least-once: up to
        ``PIPELINE_DEPTH`` dispatched-but-unreported batches replay
        after a crash.

        Multi-process contract: every host must feed the SAME NUMBER of
        same-sized batches (each host passing its local shard of a
        globally-agreed stream, as the multihost client does) — batch
        COUNT divergence would desync the shard_map collectives.  A
        host whose shard of some batch holds no valid words is safe:
        _prepare dispatches an all-padding block instead of skipping,
        keeping the slice in lockstep.
        """
        pipe = _Pipeline(self, on_batch)
        batch = []

        def submit(b):
            prep = self._prepare(b)        # async H2D starts here
            # A find in an in-flight batch is still honored for the
            # batches behind it at decode time — _collect masks rows by
            # the live-net set, so overshoot costs only the rare find
            # batch's compute.
            if prep is not None and self.groups:
                pipe.push(self._dispatch(prep), len(b))
            else:
                pipe.skip(len(b))

        for pw in candidates:
            if not self.groups and not pipe.active:
                break
            batch.append(pw)
            if len(batch) == self.batch_size:
                submit(batch)
                batch = []
        if batch:
            submit(batch)
        pipe.drain()
        return pipe.founds

    def crack_blocks(self, blocks, on_batch=None) -> list:
        """Crack a framed candidate-block stream (``dwpa_tpu.feed``).

        The feed-era twin of ``crack``: instead of slicing a flat word
        iterable itself, the engine consumes ``Block``s whose
        ``(offset, count)`` framing was fixed by the producer — so
        ``on_batch(consumed, founds)`` reports each block's GLOBAL
        candidate coverage (count, not local shard rows), which is what
        the client's resume checkpoint and the multi-host no-rules
        pass-2 both need (this replaces the ad-hoc global-count closure
        the client used to wrap around ``crack``).

        Staging is double-buffered (``feed.staging.DeviceStager``): the
        next block's candidate H2D is enqueued before this block's
        steps dispatch, and the ``_Pipeline`` trails the hits-gate sync
        ``PIPELINE_DEPTH`` batches behind — packing (producer threads),
        upload (stager) and gate latency (pipeline) all hide behind
        PBKDF2 compute.

        Multi-process contract: identical to ``crack`` — every host
        must consume the same NUMBER of blocks; the feed's sharded
        framing guarantees it (an empty local shard arrives as an
        all-padding block and still dispatches via ``_padding_prep``).
        """
        from ..feed.staging import DeviceStager

        pipe = _Pipeline(self, on_batch)
        for block, prep in DeviceStager(self, blocks):
            if not self.groups and not pipe.active:
                break
            if prep is not None and self.groups:
                pipe.push(self._dispatch(prep), block.count)
            else:
                pipe.skip(block.count)
        pipe.drain()
        return pipe.founds

    def crack_streams(self, blocks, on_batch=None, *, devices=None,
                      registry=None, tracer=None, engine_factory=None,
                      max_attempts=2) -> list:
        """Crack a framed block stream as independent device streams.

        The stream twin of ``crack_blocks`` (``parallel/streams.py``):
        instead of splitting every block 1/ndev across a lockstep
        ``shard_map`` mesh, each local device gets its own single-device
        engine and crunches WHOLE blocks pulled from a shared queue —
        no per-batch collective, no global barrier, so a straggler only
        slows its own stream.  ``on_batch(consumed, founds)`` keeps the
        ``crack_blocks`` contract exactly: one call per block, in
        global stream order, with the block's global count — resume
        framing is unchanged.  Found lists match the lockstep path's
        (ordered demux dedups by net; first block wins).

        Single-process only: a multi-host slice needs the lockstep
        global hits-gate (every host must agree a batch is finished) —
        ``parallel.streams.streams_default()`` is the switch the client
        uses.  ``engine_factory(device)`` overrides the per-stream
        engine for tests/benches; the default builds this engine's twin
        over a 1-device mesh, sharing the SAME hashline objects so a
        find on one stream prunes the net on every other.
        """
        from ..parallel.streams import StreamExecutor

        if jax.process_count() > 1:
            raise RuntimeError(
                "crack_streams is single-process only — multi-host slices "
                "keep the lockstep shard_map path (parallel/streams.py)")
        if devices is None:
            devices = list(self.mesh.devices.flat)
        lines = [n.line for n in self.nets]

        def _default_factory(device):
            from ..parallel import default_mesh

            return type(self)(
                lines, nc=self.nc, batch_size=self.batch_size,
                verify_with_oracle=self.verify_with_oracle,
                mesh=default_mesh(devices=[device]),
                pmk_store=self.pmk_store)

        ex = StreamExecutor(engine_factory or _default_factory, devices,
                            registry=registry, tracer=tracer,
                            max_attempts=max_attempts)
        founds = ex.run(blocks, on_batch=on_batch)
        for f in founds:
            self.remove(f)  # keep this (parent) engine's live view in sync
        return founds

    def crack_fused(self, parts, on_batch=None, max_units=8, tracer=None,
                    on_fused=None) -> list:
        """Crack several small work units as fused mixed-ESSID batches.

        ``parts``: iterable of ``(essid, words[, count])`` — one entry
        per (work unit, ESSID) pair, where ``words`` is the unit's raw
        candidate list for that ESSID and ``count`` its global coverage
        (defaults to ``len(words)``; the resume-framing analog of
        ``feed.framing.Block.count``).  Units are buffered and packed
        into full device batches (``sched.fuse.fuse_units``): up to
        ``max_units`` units per batch, flushed early when the next part
        would overflow ``batch_size`` or reuse a pending ESSID (one
        salt-table row per ESSID per batch).  Oversize parts split into
        engine-sized chunks and ride the same machinery.

        This is the small-unit throughput fix (BENCH unit_overhead):
        serially, every ~1k-word unit pads to the compiled batch width
        and pays the per-dispatch fixed costs alone; fused, eight such
        units share one batch and one set of round trips.

        ``on_batch(essid, consumed, founds)`` fires per PART in stream
        order — same at-least-once checkpoint seam as ``crack_blocks``,
        keyed by ESSID so a multi-unit caller can demux.  ``on_fused``
        (optional) receives each ``FusedBatch`` before dispatch — the
        executor's fill/units-per-batch metrics hook.  ``tracer``
        (optional ``obs.trace.SpanTracer``) wraps packing in
        ``sched:fuse`` and sync/demux in ``sched:demux`` spans.

        Single-process only: fusion exists to fill ONE small slice from
        a thin work-unit stream; a multi-host slice implies work units
        big enough to saturate it, and the lockstep block contract
        (every host, same batch count) would make partial waves hang.
        """
        import collections
        from contextlib import nullcontext
        from ..sched.fuse import fuse_units

        if jax.process_count() > 1:
            raise RuntimeError(
                "crack_fused is single-process only (multi-host slices "
                "take the crack_blocks path; see the method docstring)")

        pipe_founds = []
        inflight = collections.deque()  # (fb, outs, wb), oldest first
        pending = []                    # buffered (essid, words, count)
        raw = 0                         # candidate estimate of pending

        def finish_one():
            fb, outs, wb = inflight.popleft()
            pipe_founds.extend(
                self._collect_fused(fb, outs, wb, on_batch, tracer))

        def flush():
            nonlocal pending, raw
            if not pending:
                return
            parts_now, pending, raw = pending, [], 0
            with (tracer.span("sched:fuse") if tracer else nullcontext()):
                fb = fuse_units(parts_now, self.batch_size, self.mesh.size,
                                max_units, store=self.pmk_store,
                                salts=self._salts)
            if on_fused is not None:
                on_fused(fb)
            if fb.total == 0:
                # Every candidate was invalid: nothing to dispatch, but
                # the units' coverage must still reach the checkpoint.
                if on_batch is not None:
                    for u in fb.units:
                        on_batch(u.key, u.count, [])
                return
            inflight.append(self._dispatch_fused(fb))
            if len(inflight) > self.PIPELINE_DEPTH:
                finish_one()

        for part in parts:
            key, words = part[0], list(part[1])
            count = part[2] if len(part) > 2 else len(words)
            if not self.groups and not inflight:
                break  # everything cracked; stop consuming the stream
            if key not in self.groups:
                # Unit for an already-cracked (or unknown) ESSID: consume
                # it so the caller's checkpoint advances past it.
                if on_batch is not None:
                    on_batch(key, count, [])
                continue
            # Oversize unit: split into engine-sized chunks; each chunk
            # fuses (alone — a full chunk flushes whatever is pending).
            while len(words) > self.batch_size:
                chunk, words = words[:self.batch_size], words[self.batch_size:]
                count -= len(chunk)
                flush()
                pending, raw = [(key, chunk, len(chunk))], len(chunk)
                flush()
            if (raw + len(words) > self.batch_size
                    or any(k == key for k, _, _ in pending)
                    or len(pending) >= max_units):
                flush()
            pending.append((key, words, count))
            raw += len(words)
        flush()
        while inflight:
            finish_one()
        return pipe_founds

    def _dispatch_fused(self, fb):
        """Launch one fused batch (no host sync): ONE per-lane-salt
        PBKDF2 over the compacted miss lanes (``fused_pmk_step`` — the
        unit_id gather resolves each lane's salt on device), the mixed
        ``mix_step`` gather when the PMK store contributed hits, then
        every live unit's verify kernels over the SAME [8, W] PMK
        matrix.  A unit's verify sees other units' lanes too — their
        PMKs were derived under a different ESSID, so they cannot match
        (and ``_collect_fused`` masks the columns anyway)."""
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel import shard_candidates
        from ..parallel.mesh import DP_AXIS, shard_vector
        from ..parallel.step import fused_pmk_step, mix_step

        t0 = time.perf_counter()
        pmk_sharding = getattr(self, "_pmk_sharding", None)
        if pmk_sharding is None:
            pmk_sharding = self._pmk_sharding = NamedSharding(
                self.mesh, PartitionSpec(None, DP_AXIS))
        wb = None
        if fb.nmiss == 0 and fb.cached is not None:
            # Every lane was a store hit: zero PBKDF2 dispatched.
            pmk = jax.device_put(fb.cached, pmk_sharding)
        else:
            w = _trim_cols(int(fb.miss_lens.max()) if fb.nmiss
                           else MIN_PSK_LEN)
            rows_dev = shard_candidates(
                self.mesh, np.ascontiguousarray(fb.miss_rows[:, :w]))
            uid_dev = shard_vector(self.mesh, fb.unit_id)
            repl = NamedSharding(self.mesh, PartitionSpec())
            t1 = jax.device_put(fb.table1, repl)
            t2 = jax.device_put(fb.table2, repl)
            pmk_miss = fused_pmk_step(self.mesh)(rows_dev, uid_dev, t1, t2)
            entries = [(u.key, u.mlo, u.nmiss, u.miss_words)
                       for u in fb.units if u.nmiss]
            wb = (pmk_miss, entries)
            pmk = (pmk_miss if fb.idx is None else
                   mix_step(self.mesh)(pmk_miss, fb.cached, fb.idx))
        outs = []
        for u in fb.units:
            group = self._full.get(u.key)
            if group is None:  # group cracked out from under the stream
                outs.append((u, None, None))
                continue
            outs.append((u, group, self._step_for(u.key).verify(pmk)))
        self.stage_times["dispatch"] += time.perf_counter() - t0
        return fb, outs, wb

    def _collect_fused(self, fb, outs, wb, on_batch, tracer) -> list:
        """Sync + demux one fused batch: gate each unit's verify on its
        hit scalar, mask the found matrix down to the unit's OWN lane
        window ``[lo, lo + nvalid)`` before decode (a hit in unit A must
        never surface as unit B's find — the columns outside the window
        belong to other units), prune cracked nets, write new PMKs back
        to the store, and fire ``on_batch`` per unit in layout order."""
        from contextlib import nullcontext

        t0 = time.perf_counter()
        founds = []
        by_unit = {id(u): [] for u, _, _ in outs}
        live = {id(n.line) for g in self.groups.values() for n in g}
        with (tracer.span("sched:demux") if tracer else nullcontext()):
            real = [(u, g, out) for u, g, out in outs if out is not None]
            fetched = None
            payload = sum(int(a.nbytes) for _, _, out in real
                          for a in out[1:])
            if real and payload <= self.SMALL_FETCH_BYTES:
                # One merged round trip for every unit's (hits, find
                # data) — fused batches exist to amortize exactly this
                # fixed cost (see SMALL_FETCH_BYTES).
                fetched = jax.device_get([out for _, _, out in real])
            for i, (u, group, out) in enumerate(real):
                if fetched is not None:
                    out = fetched[i]
                if int(np.asarray(out[0])) == 0:
                    continue
                hits, found_dev, pmk_dev = out
                found, pmk_host = jax.device_get((found_dev, pmk_dev))
                found = np.array(found)
                # Demux mask: zero every column outside this unit's lane
                # window (other units' candidates + padding).
                found[:, :, :u.lo] = False
                found[:, :, u.lo + u.nvalid:] = False
                new = self._decode(group, found,
                                   lambda b: pmk_host[:, b],
                                   _ShiftedWords(u.words, u.lo), None, live)
                by_unit[id(u)].extend(new)
                founds.extend(new)
            for f in founds:
                self.remove(f)
            if wb is not None and self.pmk_store is not None:
                # Store write-back (consumer thread, post-fetch — lint
                # rule DW108): each unit's slice of the fused miss PMK
                # matrix lands under its own ESSID.
                pmk_miss, entries = wb
                pmk_host = jax.device_get(pmk_miss)
                for key, mlo, nm, miss_words in entries:
                    self.pmk_store.put(key, miss_words,
                                       pmk_host[:, mlo:mlo + nm])
        if on_batch is not None:
            for u in fb.units:
                on_batch(u.key, u.count, by_unit[id(u)])
        self.stage_times["collect"] += time.perf_counter() - t0
        return founds

    def _rules_flush(self, ctx, batch, account, gbatch, nproc, pid,
                     push, skip):
        """One base-word flush through the device-expansion seam.

        The shared body of every rules dispatch path (serial
        ``crack_rules``, block-framed ``crack_rules_blocks``, and the
        per-device stream adapter ``_RulesStreamEngine``): split the
        batch into device-eligible bases vs host-fallback words, plan
        the fused rule chunks (``simulate_lens`` overflow routing +
        per-chunk resume accounting), pack and upload the base block
        ONCE, dispatch every chunk, then host-expand the fallback tail
        through the normal packed path.  ``batch`` is either a raw word
        list or a warm ``feed.framing.RulesPrep`` (pre-split,
        pre-packed bases — the dict cache's base-block layout), in
        which case both the split and the pack are skipped.
        ``push(record, report)`` / ``skip(report)`` receive the
        dispatched sub-batches in stream order; ``account(consumed)``
        owns the caller's resume window.
        """
        from ..native import pack_candidates_fast
        from ..parallel import shard_candidates
        from ..parallel.mesh import shard_vector
        from ..parallel.step import RULES_CHUNK
        from ..rules.device import simulate_lens, stack_rules

        rules = ctx.rules
        dev_rules, host_rules = ctx.dev_rules, ctx.host_rules
        base_dev = lens_dev = None
        cap = 0
        with ctx.span("rules:expand"):
            if hasattr(batch, "rules_base"):
                # Warm base block: the fallback split and the pack
                # already ran (and were cached); bases stay in packed
                # device layout, words materialize lazily on hits.
                pre = batch
                nplain = pre.nplain
                plain = _BaseWords(pre.rows, pre.lens, nplain)
                lens_np = np.asarray(pre.lens[:nplain], dtype=np.int32)
                fallback = [(w, None) for w in pre.fallback]
            else:
                plain, fallback = [], []
                for w in batch:
                    # Host-fallback words: overlong bases, and anything
                    # that could put "$HEX[...]" syntax in front of the
                    # engine's unhex stage (the host paths unhex AFTER
                    # rule application, so the device must not hash such
                    # words literally).  The substring check also catches
                    # bases a rule could extend into a valid wrapper;
                    # synthesizing "HEX[" itself from unrelated
                    # characters via chained inserts remains a
                    # documented, pathological divergence.
                    if len(w) > MAX_PSK_LEN or b"HEX[" in w:
                        fallback.append((w, None))  # None = every rule
                    else:
                        plain.append(w)
                pre = None
                nplain = len(plain)
                lens_np = None
            plan = []  # (chunk, expanded pairs, candidates to report)
            if nplain and self.groups and dev_rules:
                # Per-chunk accounting and host-overflow routing run
                # BEFORE any device work: a resume window covering the
                # whole batch must not pay the H2D upload, and the
                # overflow pairs belong to the host tail regardless.
                # ``consumed`` excludes the overflow pairs deferred to
                # the host tail — each candidate is counted exactly
                # once, or skip-by-count resume would overshoot.
                if lens_np is None:
                    lens_np = np.asarray([len(w) for w in plain], np.int32)
                for c0 in range(0, len(dev_rules), RULES_CHUNK):
                    chunk = dev_rules[c0:c0 + RULES_CHUNK]
                    overflow = 0
                    for rule, _steps in chunk:
                        _, hostneed = simulate_lens(rule, lens_np)
                        if hostneed.any():
                            pairs = [(plain[i], rule)
                                     for i in np.flatnonzero(hostneed)]
                            fallback.extend(pairs)
                            overflow += len(pairs)
                    expanded = nplain * len(chunk) - overflow
                    plan.append((chunk, expanded, account(expanded)))
            if any(rep for _, _, rep in plan):
                t0 = time.perf_counter()
                # Pad to the engine batch size like _prepare: a distinct
                # cap per partial batch would mean a fresh multi-second
                # XLA compile of the fused step per distinct count.
                cap = max(gbatch,
                          -(-nplain // self.mesh.size) * self.mesh.size)
                if pre is not None:
                    rows = pre.padded_rows(cap)
                else:
                    packed = pack_candidates_fast(plain, 0, MAX_PSK_LEN, cap)
                    if packed is None:  # no native lib: plain Python pack
                        rows = np.zeros((cap, 16), np.uint32)
                        rows[:nplain] = bo.pack_passwords_be(plain)
                    else:
                        rows, _, n = packed  # lens_np above is the source
                        assert n == nplain  # min_len=0: no compaction
                lens_pad = np.zeros(cap, np.int32)
                lens_pad[:nplain] = lens_np
                # Every host packed the identical global batch; ship only
                # this host's row slice (shard_* assemble the global
                # array from per-process slices on a multi-process mesh).
                lo, hi = pid * (cap // nproc), (pid + 1) * (cap // nproc)
                base_dev = shard_candidates(self.mesh, rows[lo:hi])
                lens_dev = shard_vector(self.mesh, lens_pad[lo:hi])
                self.stage_times["prepare"] += time.perf_counter() - t0
        if base_dev is not None:
            # Chunked fused dispatch: each chunk of RULES_CHUNK rules
            # runs expand+PBKDF2+verify in ONE device call per group
            # with ONE hits-gate (through the tunnel every dispatch
            # costs ~0.1 s fixed — per-rule dispatch would throttle
            # the attack; see parallel/step.py build_rules_step).
            for chunk, expanded, report in plan:
                if not self.groups:
                    break
                if report == 0:
                    continue  # chunk wholly inside the resume prefix
                stack = stack_rules([s for _, s in chunk], RULES_CHUNK)
                pws = [_RuleWords(plain, r) for r, _ in chunk]
                pws += [None] * (RULES_CHUNK - len(chunk))
                t0 = time.perf_counter()
                outs = []
                for essid in list(self.groups):
                    step = self._rules_step_for(essid)
                    outs.append(
                        (self._full[essid], step(base_dev, lens_dev, stack))
                    )
                self.stage_times["dispatch"] += time.perf_counter() - t0
                ctx.m_device.inc(expanded)
                push((pws, nplain, outs, cap // self.mesh.size), report)
        # Host-expanded tail: unsupported rules over plain words,
        # plus the per-(word, rule) fallbacks collected above.
        # ``consumed`` counts attempted (word, rule) pairs — rejects
        # included, mirroring how the device chunks count them.
        out = []
        pairs_pending = 0

        def submit_host(cands, consumed):
            report = account(consumed)
            if report == 0:
                return  # batch wholly inside the resume prefix
            if nproc > 1:
                # The tail stream is the identical global expansion
                # on every host; each host dispatches its contiguous
                # 1/nproc block (an empty block still dispatches
                # padding via _prepare, keeping SPMD lockstep).
                blk = -(-len(cands) // nproc)
                cands = cands[pid * blk:(pid + 1) * blk]
            prep = self._prepare(cands)
            if prep is not None and self.groups:
                push(self._dispatch(prep), report)
            else:
                skip(report)

        def tail(w, rr):
            nonlocal out, pairs_pending
            pairs_pending += 1
            o = rr.apply(w)
            if o is not None:
                out.append(o)
                if len(out) >= gbatch:
                    submit_host(out, pairs_pending)
                    out, pairs_pending = [], 0

        for w, r in fallback:
            ctx.m_overflow.inc(len(rules) if r is None else 1)
            for rr in (rules if r is None else [r]):
                tail(w, rr)
        if host_rules and nplain:
            ctx.m_purge.inc(nplain * len(host_rules))
            for w in plain:
                for rr in host_rules:
                    tail(w, rr)
        if out or pairs_pending:
            submit_host(out, pairs_pending)

    def crack_rules(self, words, rules, on_batch=None, skip: int = 0, *,
                    registry=None, tracer=None) -> list:
        """Rules attack with ON-DEVICE mangling (rules/device.py).

        The host uploads each base batch ONCE (packed + lengths) and
        every device-eligible rule mangles it on device — candidate H2D
        drops by the rule count, which is what lets a rules attack
        sustain the dict-path rate through the tunnel (hashcat runs its
        rule engine on the GPU for the same reason; BENCH host_feed
        shows host expansion can't feed a mesh).  Per base batch:

        - words a rule can't cover on device ($HEX/overlong bases, the
          rare length-overflow (word, rule) pairs flagged by
          ``simulate_lens``, rules with unsupported ops) are expanded
          by the host interpreter and fed through the normal packed
          path — same pipeline, same stream;
        - hit columns decode by applying the HOST rule to the base word
          (``_RuleWords``), so the device transform is never trusted
          for results; with ``verify_with_oracle`` every find is
          re-checked against the executable spec.

        ``on_batch(consumed, founds)`` fires per dispatched batch with
        ``consumed`` = candidates that batch covered (a fused chunk
        covers base-words x chunk-rules at once).  Stream order is
        fixed (base-batch major, then device rule chunks in order, then
        the batch's host-expanded tail), so skip-by-count resume works
        like ``crack``.

        Multi-process contract — UNLIKE ``crack``'s local-shard feed:
        every host passes the SAME global word stream and the same
        ``skip`` (hosts hold full dict copies anyway — the reference's
        volunteers each download whole dictionaries, get_work.php).
        Each host then packs the global batch but uploads only its
        1/nproc row slice, and the find decode replicates the bit-packed
        mask so every host re-derives identical founds from the global
        column index — the mask path's global-indexing trick
        (``_LazyWords``), with no candidate exchange.  Host-expanded
        tails slice the identical global tail per host, so dispatch
        counts stay in SPMD lockstep with zero extra collectives.

        ``skip``: resume fast-forward — the first ``skip`` candidates
        of the (deterministic) stream are not re-reported.  Sub-batches
        wholly inside the window are not dispatched at all; a sub-batch
        straddling the boundary is re-dispatched in full (at-least-once,
        like ``crack``'s in-flight replay) but reports only its
        unskipped remainder, so the caller's cumulative count stays
        exact.  The client's intra-unit resume hangs off this — pass-2
        candidates never exist host-side, so it cannot islice() them
        the way pass 1 does (help_crack.py:737-763 restart contract).
        """
        nproc = jax.process_count()
        pid = jax.process_index()
        #: global words per flush: each host uploads a batch_size slice
        gbatch = self.batch_size * nproc

        ctx = _RulesCtx(rules, registry=registry, tracer=tracer)
        pipe = _Pipeline(self, on_batch)
        skip_left = int(skip)

        def account(consumed: int) -> int:
            """Consume up to ``consumed`` from the resume window; returns
            how many candidates this sub-batch must REPORT (0 = wholly
            inside the completed prefix: don't dispatch)."""
            nonlocal skip_left
            take = min(skip_left, consumed)
            skip_left -= take
            return consumed - take

        def flush(batch):
            self._rules_flush(ctx, batch, account, gbatch, nproc, pid,
                              pipe.push, pipe.skip)

        batch = []
        for w in words:
            if not self.groups and not pipe.active:
                break
            batch.append(w)
            # Flush at the GLOBAL batch size: each flush pads the packed
            # rows to gbatch and every host uploads a 1/nproc slice, so
            # slicing the stream at batch_size would leave every host
            # beyond the first shipping pure zero padding (N-host rules
            # attacks at 1-host throughput).
            if len(batch) == gbatch:
                flush(batch)
                batch = []
        if batch and (self.groups or pipe.active):
            flush(batch)
        pipe.drain()
        return pipe.founds

    def crack_rules_blocks(self, blocks, rules, on_batch=None,
                           skip: int = 0, *, registry=None,
                           tracer=None) -> list:
        """Rules attack over a framed base-word block stream.

        The block-framed twin of ``crack_rules``: the feed hands
        ``Block``s of BASE words (cold: raw word lists; warm: the dict
        cache's pre-packed ``RulesPrep`` base layout) and every block
        expands on device through the shared ``_rules_flush`` seam, so
        the serial block path, the stream path and the flat-iterable
        path are ONE dispatch regime.  ``on_batch(consumed, founds)``
        fires once per BLOCK in stream order, where ``consumed`` counts
        EXPANDED (word x rule) candidates — the resume domain.  The
        expansion stream is bit-identical to ``crack_rules`` over the
        same words when blocks are framed at ``batch_size x
        process_count`` words (``feed.framing.frame_blocks``), so skip
        offsets are interchangeable between the two entry points.

        ``skip`` counts expanded candidates.  A block wholly inside the
        resume window is dropped in O(1) — its coverage is exactly
        ``count x len(rules)`` because the seam counts every (word,
        rule) pair exactly once (device chunks + host tail, rejects
        included) — without packing or device work; the straddling
        block replays at-least-once and reports only its remainder,
        exactly like ``crack_rules``'s sub-batch accounting.

        Multi-process: pass GLOBAL blocks (every host the same stream),
        the ``crack_rules`` contract.
        """
        ctx = _RulesCtx(rules, registry=registry, tracer=tracer)
        nproc = jax.process_count()
        pid = jax.process_index()
        gbatch = self.batch_size * nproc
        agg = _BlockAgg(on_batch)
        pipe = _Pipeline(self, agg.record)
        skip_left = int(skip)

        def account(consumed: int) -> int:
            nonlocal skip_left
            take = min(skip_left, consumed)
            skip_left -= take
            return consumed - take

        def push(rec, report):
            agg.emit()
            pipe.push(rec, report)

        def skipf(report):
            agg.emit()
            pipe.skip(report)

        for block in blocks:
            if not self.groups and not pipe.active:
                break
            exp = block.count * ctx.n_rules
            if skip_left >= exp:
                # O(1) whole-block drop: the expanded-count invariant
                # makes the block's total coverage count x n_rules
                # without splitting, packing or expanding it.
                skip_left -= exp
                continue
            prep = getattr(block, "prep", None)
            batch = prep if hasattr(prep, "rules_base") else block.words
            agg.begin()
            self._rules_flush(ctx, batch, account, gbatch, nproc, pid,
                              push, skipf)
            agg.close()
        pipe.drain()
        return pipe.founds

    def crack_rules_streams(self, blocks, rules, on_batch=None,
                            skip: int = 0, *, devices=None, registry=None,
                            tracer=None, engine_factory=None,
                            max_attempts=2) -> list:
        """Rules attack as independent per-device streams.

        The stream twin of ``crack_rules_blocks`` (and the rules analog
        of ``crack_streams``): each local device gets its own
        single-device engine wrapped in the rules seam adapter
        (``_RulesStreamEngine``) and pulls WHOLE base blocks from the
        shared queue, expanding rules directly ahead of its own PBKDF2
        dispatch — the host ships compact base blocks only (candidate
        H2D divided by the rule count), there is no cross-device
        candidate traffic, and a straggler or crash affects only its
        own stream (requeue comes free from ``StreamExecutor``).
        ``on_batch(consumed, founds)`` fires once per base block in
        global stream order with the block's EXPANDED coverage —
        identical framing to ``crack_rules_blocks``, so resume offsets
        interop across all three rules entry points.  Blocks wholly
        inside ``skip`` are dropped before they reach the queue (O(1)
        per block); the straddler carries its in-block expanded skip
        immutably, so a crash requeue replays it deterministically.

        Single-process only (``crack_streams``'s contract).
        ``engine_factory(device)`` overrides the per-stream INNER
        engine (the seam adapter still wraps it) for tests/benches.
        """
        from ..parallel.streams import StreamExecutor

        if jax.process_count() > 1:
            raise RuntimeError(
                "crack_rules_streams is single-process only — multi-host "
                "slices keep the lockstep crack_rules path")
        ctx = _RulesCtx(rules, registry=registry, tracer=tracer)
        if devices is None:
            devices = list(self.mesh.devices.flat)
        lines = [n.line for n in self.nets]

        def _default_factory(device):
            from ..parallel import default_mesh

            return type(self)(
                lines, nc=self.nc, batch_size=self.batch_size,
                verify_with_oracle=self.verify_with_oracle,
                mesh=default_mesh(devices=[device]),
                pmk_store=self.pmk_store)

        inner = engine_factory or _default_factory

        def factory(device):
            return _RulesStreamEngine(inner(device), ctx)

        def wrapped():
            pos, skip_left = 0, int(skip)
            for block in blocks:
                exp = block.count * ctx.n_rules
                if skip_left >= exp:
                    skip_left -= exp
                    pos += exp
                    continue
                prep = getattr(block, "prep", None)
                base = prep if hasattr(prep, "rules_base") else block.words
                yield _RulesBlock(pos + skip_left, exp - skip_left,
                                  base, skip_left)
                pos += exp
                skip_left = 0

        ex = StreamExecutor(factory, devices, registry=registry,
                            tracer=tracer, max_attempts=max_attempts)
        founds = ex.run(wrapped(), on_batch=on_batch)
        for f in founds:
            self.remove(f)  # keep this (parent) engine's live view in sync
        return founds

    def crack_mask(self, mask: str, skip: int = 0, limit: int = None,
                   custom: dict = None, on_batch=None) -> list:
        """Mask attack with on-device candidate generation.

        Unlike ``crack``, no candidate bytes ever exist host-side: each
        batch is generated by ``gen.mask.device_mask_words`` (SURVEY §7
        M5 — iota→digits→pack, one fused program) and fed straight to
        the crack steps, so the only host work per batch is an
        O(positions) digit vector and the hits-gate scalar.  Words are
        materialized lazily from their keyspace index only for the rare
        hit columns.  ``skip``/``limit`` slice the keyspace exactly like
        ``gen.mask.mask_words`` (hashcat -s/-l semantics).

        Since the mesh-aggregate refactor this is a thin front over
        ``crack_blocks`` with ``gen.mask.mask_blocks``'s ``MaskPrep``
        stream — generation happens in ``_prepare_block`` under this
        engine's mesh sharding, so the SAME block stream also schedules
        through ``crack_streams`` (each device stream generates its own
        keyspace slices) or the multi-unit executor.
        """
        from ..gen.mask import mask_blocks

        return self.crack_blocks(
            mask_blocks(mask, self.batch_size, skip=skip, limit=limit,
                        custom=custom),
            on_batch=on_batch)


class _RulesBlock:
    """Work item for the per-device rules streams: a base-word block in
    EXPANDED (word x rule) coordinates.

    ``offset``/``count`` frame the block's expanded remainder in the
    global candidate stream (``StreamExecutor`` orders on_batch demux by
    them and reports ``count`` as the consumed amount — identical to
    ``crack_rules_blocks`` framing).  ``base`` is the raw base-word list
    or a warm ``RulesPrep``; ``skip_pairs`` is the immutable in-block
    expanded resume offset — immutable so a crash requeue replays the
    straddling block deterministically on the surviving stream.
    """

    __slots__ = ("offset", "count", "base", "skip_pairs")

    def __init__(self, offset, count, base, skip_pairs=0):
        self.offset = offset
        self.count = count
        self.base = base
        self.skip_pairs = skip_pairs


class _RulesStreamEngine:
    """Adapter giving a single-device engine the block protocol
    ``parallel.streams.DeviceStream`` drives, with rules expansion done
    ON this stream's device via the shared ``_rules_flush`` seam.

    ``_prepare_block`` runs the whole seam for the block (split, pack,
    per-chunk fused dispatch, host tail) and buffers the dispatched
    records; ``_dispatch`` is the identity (device work was issued
    during prepare — the stream still overlaps blocks because results
    are only BLOCKED on in ``_collect``, ``PIPELINE_DEPTH`` blocks
    later).  ``_collect`` drains the block's records in order through
    the inner engine's normal decode path.
    """

    def __init__(self, inner, ctx):
        self.inner = inner
        self.ctx = ctx
        self.PIPELINE_DEPTH = inner.PIPELINE_DEPTH

    @property
    def groups(self):
        return self.inner.groups

    @property
    def nets(self):
        return self.inner.nets

    def remove(self, found):
        self.inner.remove(found)

    def _prepare_block(self, block):
        eng = self.inner
        recs = []
        skip_left = block.skip_pairs

        def account(consumed):
            nonlocal skip_left
            take = min(skip_left, consumed)
            skip_left -= take
            return consumed - take

        eng._rules_flush(self.ctx, block.base, account, eng.batch_size,
                         1, 0, lambda rec, rep: recs.append(rec),
                         lambda rep: None)
        return recs

    def _dispatch(self, recs):
        return recs

    def _collect(self, recs):
        founds = []
        for rec in recs:
            founds.extend(self.inner._collect(rec))
        return founds
