from . import hashline  # noqa: F401
