"""hashcat mode-22000 hashline parsing and serialization.

Authoritative format (documented in the reference at web/common.php:114-155):

    WPA*TYPE*PMKID/MIC*MACAP*MACSTA*ESSID*ANONCE*EAPOL*MESSAGEPAIR

    TYPE        01 = PMKID, 02 = EAPOL
    PMKID/MIC   16 bytes hex
    MACAP/MACSTA 6 bytes hex
    ESSID       hex (<= 32 bytes)
    ANONCE      32 bytes hex (EAPOL only)
    EAPOL       the M2/M3/M4 frame, MIC zeroed (<= ~320 bytes)
    MESSAGEPAIR bitmask (EAPOL): bit4 ap-less (no NC), bit5 LE router,
                bit6 BE router, bit7 replay-count unchecked (NC needed);
                bits 0-2 encode which messages the pair was taken from.
                For PMKID lines this trailing field is the PMKID-info mask.
"""

import hashlib
import struct
from dataclasses import dataclass

TYPE_PMKID = 1
TYPE_EAPOL = 2

MP_APLESS = 0x10
MP_LE = 0x20
MP_BE = 0x40
MP_NC_NEEDED = 0x80


def _unhex(s: str, what: str) -> bytes:
    if len(s) % 2 != 0:
        raise ValueError(f"odd-length hex in {what}: {s!r}")
    try:
        return bytes.fromhex(s)
    except ValueError as e:
        raise ValueError(f"bad hex in {what}: {s!r}") from e


@dataclass(frozen=True)
class Hashline:
    """One parsed m22000 hashline."""

    hash_type: int            # TYPE_PMKID | TYPE_EAPOL
    pmkid_or_mic: bytes       # 16 bytes
    mac_ap: bytes             # 6 bytes
    mac_sta: bytes            # 6 bytes
    essid: bytes              # 1..32 bytes
    anonce: bytes             # 32 bytes (EAPOL) / b""
    eapol: bytes              # the frame (EAPOL) / b""
    message_pair: int         # bitmask; 0 if absent
    raw: str

    @property
    def keyver(self) -> int:
        """EAPOL key descriptor version (key_information & 3); 100 = PMKID.

        Mirrors the nets.keyver column convention (db/wpa.sql:164,
        web/common.php:217).
        """
        if self.hash_type == TYPE_PMKID:
            return 100
        return self.key_information & 3

    @property
    def key_information(self) -> int:
        return struct.unpack_from(">H", self.eapol, 5)[0]

    @property
    def snonce(self) -> bytes:
        return self.eapol[17:49]

    def key_id(self) -> bytes:
        """Net identity: MD5 over fields 1-7 (excludes message_pair).

        Mirrors hash_m22000 (web/common.php:310-315) so our server's dedup
        matches the reference's nets.hash column.
        """
        parts = self.raw.split("*", 8)
        return hashlib.md5("".join(parts[1:8]).encode()).digest()


def parse(line: str) -> Hashline:
    """Parse and validate one m22000 hashline."""
    line = line.strip()
    parts = line.split("*", 8)
    if len(parts) != 9:
        raise ValueError(f"expected 9 *-separated fields, got {len(parts)}")
    if parts[0] != "WPA":
        raise ValueError(f"bad signature {parts[0]!r}")
    if parts[1] not in ("01", "02"):
        raise ValueError(f"unsupported hash type {parts[1]!r}")
    hash_type = int(parts[1])

    pmkid_or_mic = _unhex(parts[2], "pmkid/mic")
    mac_ap = _unhex(parts[3], "mac_ap")
    mac_sta = _unhex(parts[4], "mac_sta")
    essid = _unhex(parts[5], "essid")
    if len(pmkid_or_mic) != 16:
        raise ValueError("pmkid/mic must be 16 bytes")
    if len(mac_ap) != 6 or len(mac_sta) != 6:
        raise ValueError("MACs must be 6 bytes")
    if not 0 < len(essid) <= 32:
        raise ValueError("essid must be 1..32 bytes")

    anonce = eapol = b""
    mp = 0
    if hash_type == TYPE_EAPOL:
        anonce = _unhex(parts[6], "anonce")
        eapol = _unhex(parts[7], "eapol")
        mp_b = _unhex(parts[8], "message_pair")
        mp = mp_b[0] if mp_b else 0
        if len(anonce) != 32:
            raise ValueError("anonce must be 32 bytes")
        if len(eapol) < 95:
            raise ValueError("eapol frame too short")
    else:
        mp_b = _unhex(parts[8], "pmkid info") if parts[8] else b""
        mp = mp_b[0] if mp_b else 0

    return Hashline(
        hash_type=hash_type,
        pmkid_or_mic=pmkid_or_mic,
        mac_ap=mac_ap,
        mac_sta=mac_sta,
        essid=essid,
        anonce=anonce,
        eapol=eapol,
        message_pair=mp,
        raw=line,
    )


def serialize(
    hash_type: int,
    pmkid_or_mic: bytes,
    mac_ap: bytes,
    mac_sta: bytes,
    essid: bytes,
    anonce: bytes = b"",
    eapol: bytes = b"",
    message_pair: int | None = None,
) -> str:
    """Build an m22000 hashline (used by the capture parser / tests)."""
    mp = "" if message_pair is None else f"{message_pair:02x}"
    return "*".join(
        [
            "WPA",
            f"{hash_type:02d}",
            pmkid_or_mic.hex(),
            mac_ap.hex(),
            mac_sta.hex(),
            essid.hex(),
            anonce.hex(),
            eapol.hex(),
            mp,
        ]
    )
