"""Persistent per-ESSID PMK store: the cross-unit PBKDF2 cache.

PBKDF2->PMK is ~99% of all cycles (ops/pbkdf2.py), yet the PMK for a
given ``(ESSID, word)`` pair never changes — popular ESSIDs recur across
uploads, dictionaries overlap heavily, and pass-2 re-runs replay pass-1
words.  This store turns that repeat work into a disk hit, the
airolib-ng / cowpatty ``genpmk`` precomputed-table idea rebuilt around
the TPU engine's framed candidate feed (hashcat-brain dedupes attacked
candidates server-side for the same reason).

On-disk format, designed for crash-safety without fsync:

- one directory per ESSID (``<root>/<essid.hex()>/``), so the cache is
  per-ESSID by construction and an ESSID's working set is one directory;
- fixed-width 40-byte records: ``blake2b(word, digest_size=8)`` (8) +
  PMK (32, big-endian words — ``bo.words_to_bytes_be`` order);
- records are appended in CRC-framed batches:
  ``b"PMKF" | count u32 LE | crc32(payload) u32 LE | payload``.
  A crash can tear only the LAST frame of the newest segment; on open
  the frame walk stops at the first bad magic/length/CRC and the torn
  tail is SKIPPED, not fatal — every record in an intact frame keeps
  serving hits;
- segments (``seg-<pid>-<seq>.pmkseg``, 8-byte ``b"DWPMKS01"`` header)
  rotate at ``segment_bytes``; sealed segments are mmap'd and served
  through an in-memory ``digest -> (seq, offset)`` index, while the open
  segment's records are served from a small in-memory tail until it
  seals.  A reopened store never appends to an old segment (so a sealed
  file is immutable and its mmap can't go stale) — it starts a fresh one;
- eviction is whole-segment: when total on-disk bytes exceed
  ``max_bytes``, the oldest sealed segments (globally, by sequence
  number) are unlinked and their index entries dropped — the
  ``--pmk-cache-max-bytes`` cap, paid in coarse rotation units so the
  hot path never rewrites files.

Multi-host: segment names carry the writing host's process index, and
each host of a slice derives (and therefore writes back) only the PMKs
of its own framed feed slice (feed/framing.py), so a slice's stores
shard the keyspace for free — no coordination, no shared-writer
segments.

Threading: producer threads call ``lookup_digests`` while the consumer
thread calls ``put`` (write-back after device fetch — lint rule DW108
polices both sides); one RLock covers index/tail/segment mutation.
Everything here is pure host work — no jax imports, by design.

Metrics (README "PMK store"): ``dwpa_pmkstore_hits_total`` /
``dwpa_pmkstore_misses_total`` / ``dwpa_pmkstore_writes_total`` /
``dwpa_pmkstore_evictions_total`` counters, ``dwpa_pmkstore_bytes`` and
``dwpa_pmkstore_hit_ratio`` gauges.
"""

import hashlib
import mmap
import os
import re
import struct
import threading
import zlib

SEG_MAGIC = b"DWPMKS01"
FRAME_MAGIC = b"PMKF"
FRAME_HEADER = len(FRAME_MAGIC) + 8   # magic + count u32 + crc32 u32
DIGEST_LEN = 8
PMK_LEN = 32
RECORD = DIGEST_LEN + PMK_LEN         # 40 bytes, fixed width

_SEG_RE = re.compile(r"^seg-(\d+)-(\d+)\.pmkseg$")


def word_digest(word: bytes) -> bytes:
    """8-byte candidate key: blake2b truncated — 64 bits over even a
    billion-word cache keeps accidental collisions ~1e-11, and a
    collision costs one wrong PMK that the MIC/PMKID check rejects."""
    return hashlib.blake2b(word, digest_size=DIGEST_LEN).digest()


class _Segment:
    """One sealed, immutable, mmap-backed segment file."""

    __slots__ = ("path", "essid", "nbytes", "digests", "_mm", "_f")

    def __init__(self, path, essid, nbytes, digests, mm, f):
        self.path = path
        self.essid = essid
        self.nbytes = nbytes
        self.digests = digests  # [(digest, offset-of-pmk)] for eviction
        self._mm = mm
        self._f = f

    def read_pmk(self, off: int) -> bytes:
        return self._mm[off:off + PMK_LEN]

    def close(self):
        if self._mm is not None:
            self._mm.close()
            self._mm = None
        if self._f is not None:
            self._f.close()
            self._f = None


class PMKStore:
    """Crash-safe, size-capped, per-ESSID on-disk PMK cache.

    ``lookup_digests``/``lookup`` are safe from feed producer threads
    (pure host reads under the store lock); ``put`` is the consumer
    thread's write-back seam.  ``pid`` tags this host's segments (default
    0 — passed by the client on a multi-host slice).
    """

    def __init__(self, root: str, max_bytes: int = 256 << 20,
                 segment_bytes: int = None, pid: int = 0, registry=None):
        self.root = root
        self.max_bytes = int(max_bytes)
        self.segment_bytes = int(segment_bytes
                                 or max(1 << 20, self.max_bytes // 8))
        self.pid = int(pid)
        self._lock = threading.RLock()
        self._index = {}   # essid -> {digest: (seq, pmk offset)}
        self._segments = {}  # seq -> _Segment (sealed, mmap-backed)
        self._tail = {}    # essid -> {digest: pmk} (open segment's records)
        self._open = {}    # essid -> (file, seq, nbytes written)
        self._seq = 0
        os.makedirs(root, exist_ok=True)
        if registry is None:
            from ..obs import default_registry

            registry = default_registry()
        self._m_hits = registry.counter(
            "dwpa_pmkstore_hits_total", "PMK cache lookups served from disk")
        self._m_miss = registry.counter(
            "dwpa_pmkstore_misses_total",
            "PMK cache lookups that fell through to PBKDF2")
        self._m_writes = registry.counter(
            "dwpa_pmkstore_writes_total", "PMK records written back")
        self._m_evict = registry.counter(
            "dwpa_pmkstore_evictions_total",
            "segments evicted under the size cap")
        self._m_bytes = registry.gauge(
            "dwpa_pmkstore_bytes", "PMK store on-disk bytes")
        self._m_ratio = registry.gauge(
            "dwpa_pmkstore_hit_ratio", "lifetime hit fraction of lookups")
        self._load()

    # -- open / load --------------------------------------------------------

    def _load(self):
        """Scan every ESSID dir, mmap intact segments, index their
        records.  Torn tails (bad magic/length/CRC) stop the frame walk
        for that segment — the prefix keeps serving.  Runs under the
        store lock like every other index mutation: the load is
        init-time today, but the index guard invariant (rule DW302) is
        cheaper to keep than to reason away."""
        found = []
        for name in sorted(os.listdir(self.root)):
            edir = os.path.join(self.root, name)
            if not os.path.isdir(edir):
                continue
            try:
                essid = bytes.fromhex(name)
            except ValueError:
                continue
            for fn in sorted(os.listdir(edir)):
                m = _SEG_RE.match(fn)
                if m:
                    found.append((int(m.group(2)), essid,
                                  os.path.join(edir, fn)))
        with self._lock:
            for seq, essid, path in sorted(found):
                self._seq = max(self._seq, seq + 1)
                self._load_segment(seq, essid, path)
        self._m_bytes.set(self._total_bytes())

    def _load_segment(self, seq: int, essid: bytes, path: str):
        f = open(path, "rb")
        try:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:  # empty file (torn at creation): drop it
            f.close()
            return
        size = len(mm)
        pos = len(SEG_MAGIC)
        if mm[:pos] != SEG_MAGIC:
            mm.close()
            f.close()
            return
        idx = self._index.setdefault(essid, {})
        digests = []
        while pos + FRAME_HEADER <= size:
            if mm[pos:pos + 4] != FRAME_MAGIC:
                break  # torn tail: skip the rest, keep the prefix
            count, crc = struct.unpack_from("<II", mm, pos + 4)
            payload_off = pos + FRAME_HEADER
            payload_len = count * RECORD
            if payload_off + payload_len > size:
                break  # truncated mid-frame
            payload = mm[payload_off:payload_off + payload_len]
            if zlib.crc32(payload) != crc:
                break  # torn mid-record: CRC catches the partial write
            for i in range(count):
                off = payload_off + i * RECORD
                digest = mm[off:off + DIGEST_LEN]
                idx[digest] = (seq, off + DIGEST_LEN)
                digests.append((digest, off + DIGEST_LEN))
            pos = payload_off + payload_len
        self._segments[seq] = _Segment(path, essid, size, digests, mm, f)

    # -- lookups (producer-thread safe) -------------------------------------

    def lookup_digests(self, essid: bytes, digests) -> list:
        """``[pmk bytes | None, ...]`` aligned with ``digests``.  Counts
        hits/misses and refreshes the hit-ratio gauge."""
        out = []
        hits = 0
        with self._lock:
            tail = self._tail.get(essid)
            idx = self._index.get(essid)
            for d in digests:
                pmk = tail.get(d) if tail else None
                if pmk is None and idx is not None:
                    ref = idx.get(d)
                    if ref is not None:
                        seg = self._segments.get(ref[0])
                        if seg is not None:
                            pmk = seg.read_pmk(ref[1])
                if pmk is not None:
                    hits += 1
                out.append(pmk)
            self._m_hits.inc(hits)
            self._m_miss.inc(len(out) - hits)
        self._update_ratio()
        return out

    def lookup(self, essid: bytes, words) -> list:
        return self.lookup_digests(essid, [word_digest(w) for w in words])

    def _update_ratio(self):
        h = self._m_hits.labels().value
        m = self._m_miss.labels().value
        if h + m:
            self._m_ratio.set(h / (h + m))

    # -- write-back (consumer thread only — lint rule DW108) ----------------

    def put(self, essid: bytes, words, pmks):
        """Append newly derived PMKs for ``words``.

        ``pmks``: a uint32[8, m] column matrix (the engine's device PMK
        layout, fetched host-side first) or an iterable of 32-byte PMK
        strings.  Already-cached digests are skipped, the rest land in
        ONE CRC frame; rotation and eviction run after the append.

        Deliberately flush-only, no fsync (fsync-audit decision, vs the
        found outbox / resume file which DO pay for it): this is a
        recompute cache on the hot crack path — a power loss tearing
        the last frame costs re-deriving those PMKs, never correctness,
        because the load walk stops at the first bad CRC.  An fsync per
        appended frame would serialize the crack loop on disk latency
        for data that is by definition reproducible.
        """
        pmk_list = self._pmk_bytes(pmks, len(words))
        with self._lock:
            tail = self._tail.setdefault(essid, {})
            idx = self._index.setdefault(essid, {})
            payload = bytearray()
            fresh = []
            for w, pmk in zip(words, pmk_list):
                d = word_digest(w)
                if d in tail or d in idx:
                    continue
                payload += d + pmk
                fresh.append((d, pmk))
            if not fresh:
                return
            f, seq, nbytes = self._open_segment(essid)
            frame_off = nbytes
            f.write(FRAME_MAGIC
                    + struct.pack("<II", len(fresh), zlib.crc32(payload))
                    + payload)
            f.flush()
            nbytes = frame_off + FRAME_HEADER + len(payload)
            self._open[essid] = (f, seq, nbytes)
            off = frame_off + FRAME_HEADER
            for d, pmk in fresh:
                tail[d] = pmk
                idx[d] = (seq, off + DIGEST_LEN)
                off += RECORD
            self._m_writes.inc(len(fresh))
            if nbytes >= self.segment_bytes:
                self._rotate(essid)
            self._evict()
            self._m_bytes.set(self._total_bytes())

    def put_many(self, items):
        """Append write-back for a mixed-ESSID wave: ``items`` iterates
        ``(essid, words, pmks)`` triples (one ``put`` each).  The server
        pre-crack sweep derives many ESSIDs in one fused batch and lands
        them here grouped, so every group pays one frame append."""
        for essid, words, pmks in items:
            self.put(essid, words, pmks)

    @staticmethod
    def _pmk_bytes(pmks, n: int) -> list:
        if isinstance(pmks, (list, tuple)):
            return list(pmks)
        import numpy as np

        # uint32[8, m] device layout -> per-word 32-byte big-endian PMKs
        blob = np.ascontiguousarray(
            np.asarray(pmks, dtype=np.uint32)[:, :n].T).astype(">u4").tobytes()
        return [blob[i * PMK_LEN:(i + 1) * PMK_LEN] for i in range(n)]

    # -- segments -----------------------------------------------------------

    def _open_segment(self, essid: bytes):
        ent = self._open.get(essid)
        if ent is not None:
            return ent
        edir = os.path.join(self.root, essid.hex())
        os.makedirs(edir, exist_ok=True)
        seq = self._seq
        self._seq += 1
        path = os.path.join(edir, f"seg-{self.pid}-{seq:010d}.pmkseg")
        f = open(path, "wb")
        f.write(SEG_MAGIC)
        f.flush()
        ent = (f, seq, len(SEG_MAGIC))
        self._open[essid] = ent
        return ent

    def _rotate(self, essid: bytes):
        """Seal the open segment: close, re-open read-only, mmap, move
        its records from the in-memory tail to mmap-served."""
        ent = self._open.pop(essid, None)
        if ent is None:
            return
        f, seq, _ = ent
        f.close()
        self._load_sealed(seq, essid, f.name)
        self._tail.pop(essid, None)

    def _load_sealed(self, seq: int, essid: bytes, path: str):
        f = open(path, "rb")
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        digests = [(d, ref[1]) for d, ref in self._index.get(essid, {}).items()
                   if ref[0] == seq]
        self._segments[seq] = _Segment(path, essid, len(mm), digests, mm, f)

    def _evict(self):
        """Drop the oldest sealed segments until back under the cap."""
        while self._total_bytes() > self.max_bytes and self._segments:
            seq = min(self._segments)
            seg = self._segments.pop(seq)
            idx = self._index.get(seg.essid, {})
            for d, _off in seg.digests:
                if idx.get(d, (None,))[0] == seq:
                    del idx[d]
            seg.close()
            try:
                os.unlink(seg.path)
            except OSError:
                pass
            self._m_evict.inc()

    def _total_bytes(self) -> int:
        sealed = sum(s.nbytes for s in self._segments.values())
        return sealed + sum(n for _f, _s, n in self._open.values())

    # -- lifecycle ----------------------------------------------------------

    def flush(self):
        with self._lock:
            for f, _seq, _n in self._open.values():
                f.flush()

    def close(self):
        with self._lock:
            for essid in list(self._open):
                self._rotate(essid)
            for seg in self._segments.values():
                seg.close()
            self._segments.clear()
            self._index.clear()
            self._tail.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
