"""dwpa_tpu.pmkstore — persistent cross-unit PBKDF2->PMK cache.

- :mod:`.store` — the crash-safe, size-capped, per-ESSID on-disk record
  store (CRC-framed 40-byte records, mmap reads, segment-rotation
  eviction).
- :mod:`.stage` — the producer-thread hit/miss split that feeds the
  engine's mixed-block dispatch (``M22000Engine._dispatch_mixed`` /
  ``parallel.step.mix_step``).

README "PMK store" documents the CLI knobs (``--pmk-cache-dir`` /
``--pmk-cache-max-bytes``), record format, eviction policy and metric
names; lint rule DW108 (analysis/linter.py) polices the I/O discipline.
"""

from .stage import EssidSplit, MixedPrep, miss_width, miss_widths, split_block
from .store import PMKStore, word_digest

__all__ = [
    "PMKStore", "word_digest",
    "MixedPrep", "EssidSplit", "split_block", "miss_width", "miss_widths",
]
