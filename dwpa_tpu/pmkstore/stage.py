"""Producer-side hit/miss split for store-backed candidate blocks.

The lookup runs as a PURE HOST stage on the feed's producer threads
(lint rule DW107: producers touch no jax device API; lint rule DW108:
store I/O never runs under a trace): per framed block, the packed
candidates are split per ESSID into cache hits (their PMKs come back
from the store as host bytes) and misses (only those rows ship to the
PBKDF2 kernel).  The consumer thread stages the result
(``M22000Engine._prepare_mixed``) and the engine's mixed dispatch
scatters the cached PMKs around the computed ones before the verify
kernels — see ``parallel.step.mix_step``.

Shape discipline: the miss sub-batch is padded to one of at most THREE
static widths (``miss_widths``: ~B/4, ~B/2, B, rounded up to mesh
multiples) so the PBKDF2 and mix steps compile a bounded number of
times however the hit ratio wanders block to block — proven by the
``recompile_sentinel`` tests and the ``bench:pmkstore`` warm pass.
"""

from dataclasses import dataclass, field

import numpy as np

from .store import word_digest


def miss_widths(batch: int, n: int) -> tuple:
    """The static miss sub-batch widths for device batch ``batch`` on an
    ``n``-device mesh: at most 3 distinct values, each a positive mesh
    multiple, the largest exactly ``batch``.

    Geometric (~B/8, ~B/2, B) rather than evenly spaced: PBKDF2 cost is
    proportional to the PADDED width (pad rows hash like real ones), so
    the smallest bucket sets the warm-pass speedup ceiling — B/8 keeps a
    high-hit-ratio stream at ~8x while three widths keep the compile
    count bounded (the recompile_sentinel proof)."""
    def up(x):
        return max(n, -(-x // n) * n)

    return tuple(sorted({up(batch // 8), up(batch // 2), batch}))


def miss_width(batch: int, n: int, nmiss: int) -> int:
    """Smallest static width that holds ``nmiss`` miss rows."""
    for w in miss_widths(batch, n):
        if nmiss <= w:
            return w
    return batch


@dataclass
class EssidSplit:
    """One ESSID group's hit/miss view of a packed block.

    ``nmiss == 0``: all-hit — ``cached`` IS the full PMK matrix, no
    PBKDF2 at all.  ``nhit == 0``: all-miss — ``miss_rows`` is the full
    packed batch (width ``batch``), identical shapes to the plain path.
    Otherwise ``miss_rows`` holds the compacted misses padded to a
    static width and ``idx`` maps each batch column to its slot in
    ``concat([pmk_miss, cached], axis=1)`` (``mix_step``).
    ``miss_dev`` is filled on the CONSUMER thread by
    ``M22000Engine._prepare_mixed`` (H2D staging is not producer work).
    """

    nmiss: int
    nhit: int
    miss_rows: np.ndarray = None   # uint32[width, 16]
    miss_lens: np.ndarray = None   # per miss row, for column trimming
    miss_words: list = field(default_factory=list)  # write-back alignment
    idx: np.ndarray = None         # int32[batch] gather map
    cached: np.ndarray = None      # uint32[8, batch], hit cols filled
    miss_dev: object = None        # staged device rows (consumer-side)


@dataclass
class MixedPrep:
    """A store-split block: what the feed's ``Block.prep`` carries when
    the engine's packer is store-aware (``M22000Engine.host_packer``)."""

    packed: np.ndarray    # uint32[cap, 16] full packed batch (hit decode)
    lens: np.ndarray      # uint8[nvalid]
    nvalid: int
    batch: int            # padded device batch width B
    entries: dict         # essid -> EssidSplit


def _decode_words(packed: np.ndarray, lens, nvalid: int) -> list:
    """Recover the candidate bytes from their packed key-block rows (the
    rows are the words, big-endian-packed and zero-padded)."""
    blob = np.ascontiguousarray(packed[:nvalid]).astype(">u4").tobytes()
    return [blob[64 * i:64 * i + int(lens[i])] for i in range(nvalid)]


def split_block(store, essids, packed, lens, nvalid: int, batch_size: int,
                n: int) -> MixedPrep:
    """Split one packed block into per-ESSID hit/miss sub-batches.

    Pure host work (producer-thread safe): word decode, digesting, store
    lookups, numpy shuffling.  ``essids`` is the engine's group snapshot;
    ``n`` the mesh size (pad geometry must match the engine's)."""
    B = max(batch_size, -(-nvalid // n) * n)
    words = _decode_words(packed, lens, nvalid)
    digests = [word_digest(w) for w in words]
    entries = {}
    for essid in essids:
        pmks = store.lookup_digests(essid, digests)
        miss_cols = [i for i, p in enumerate(pmks) if p is None]
        nmiss, nhit = len(miss_cols), nvalid - len(miss_cols)
        if nhit == 0:
            # all-miss: the plain path's exact shapes — full batch rows,
            # no scatter, so a cold store costs nothing but the lookup
            entries[essid] = EssidSplit(
                nmiss=nvalid, nhit=0, miss_rows=packed[:B], miss_lens=lens,
                miss_words=words)
            continue
        cached = np.zeros((8, B), np.uint32)
        for i, p in enumerate(pmks):
            if p is not None:
                cached[:, i] = np.frombuffer(p, dtype=">u4")
        if nmiss == 0:
            entries[essid] = EssidSplit(nmiss=0, nhit=nhit, cached=cached)
            continue
        width = miss_width(B, n, nmiss)
        cols = np.asarray(miss_cols, np.int64)
        miss_rows = np.zeros((width, 16), np.uint32)
        miss_rows[:nmiss] = packed[cols]
        # gather map: miss columns read the computed sub-batch, everything
        # else (hits AND padding) reads the cached matrix at its own column
        idx = width + np.arange(B, dtype=np.int32)
        idx[cols] = np.arange(nmiss, dtype=np.int32)
        entries[essid] = EssidSplit(
            nmiss=nmiss, nhit=nhit, miss_rows=miss_rows,
            miss_lens=np.asarray(lens)[cols],
            miss_words=[words[i] for i in miss_cols], idx=idx, cached=cached)
    return MixedPrep(packed=packed, lens=lens, nvalid=nvalid, batch=B,
                     entries=entries)
