"""Persistent XLA compilation cache wiring.

The first compile of the 4096-iteration PBKDF2 step costs ~20-40 s on
TPU; per process that was paid once per (batch, width) signature, but a
freshly restarted client paid it again before its first work unit — the
dominant term in cold-start latency (the reference client has no analog:
hashcat ships precompiled GPU kernels).  JAX's persistent compilation
cache turns that into a disk hit across restarts.

Separate module (not utils/__init__) so importing it never drags jax in
before ``jax.distributed.initialize`` runs on multi-host clients.
"""

import logging
import os

log = logging.getLogger(__name__)


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True when enabled.  Best-effort: an unwritable directory or
    a jax build without the feature logs and moves on — the cache is a
    cold-start optimization, never a requirement.  The 0.5 s floor keeps
    trivial host-side jits (reshapes, the replicate identity) out of the
    cache while every kernel that matters (all >1 s) persists.
    """
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        return True
    except Exception as e:  # pragma: no cover - depends on jax build
        log.warning("persistent compilation cache unavailable: %s", e)
        return False
