"""Host-side byte <-> uint32-word packing (plain numpy).

All variable-length byte handling in the framework happens here, on the
host, once per net or per candidate batch.  The device kernels only ever
see fixed-shape uint32 word arrays (see ops/common.py design notes) —
this is deliberate: TPU/XLA wants static shapes, so strings are padded
into word lanes before they go anywhere near a jit boundary.
"""

import struct

import numpy as np


def be_words(data: bytes):
    """bytes (len % 4 == 0) -> list of big-endian 32-bit ints."""
    assert len(data) % 4 == 0
    return list(struct.unpack(">%dI" % (len(data) // 4), data))


def le_words(data: bytes):
    """bytes (len % 4 == 0) -> list of little-endian 32-bit ints."""
    assert len(data) % 4 == 0
    return list(struct.unpack("<%dI" % (len(data) // 4), data))


def md_pad(tail: bytes, total_len: int, little_endian: bool = False):
    """Merkle–Damgård padding for a message tail.

    ``tail`` is the remaining message after any prior full 64-byte blocks;
    ``total_len`` is the length in bytes of the *whole* message (including
    bytes already compressed, e.g. an HMAC key block).  Returns the padded
    tail as raw bytes (length a multiple of 64).

    ``little_endian`` selects MD5 conventions (LE 64-bit bit-length),
    otherwise SHA-1/SHA-256 conventions (BE 64-bit bit-length).
    """
    data = tail + b"\x80"
    pad_to = ((len(data) + 8 + 63) // 64) * 64
    data += b"\x00" * (pad_to - len(data) - 8)
    if little_endian:
        data += struct.pack("<Q", total_len * 8)
    else:
        data += struct.pack(">Q", total_len * 8)
    return data


def padded_blocks(msg_tail: bytes, total_len: int, little_endian: bool = False):
    """Pad a message tail and split into 16-word blocks (list of lists)."""
    data = md_pad(msg_tail, total_len, little_endian)
    words = le_words(data) if little_endian else be_words(data)
    return [words[i : i + 16] for i in range(0, len(words), 16)]


def message_blocks(data: bytes, little_endian: bool = False, prefix_len: int = 0):
    """Split a whole message into padded 16-word blocks.

    ``prefix_len`` counts bytes already compressed before ``data`` (e.g. the
    64-byte HMAC key block) toward the length field, without emitting them.
    """
    nfull = len(data) // 64
    blocks = []
    for i in range(nfull):
        chunk = data[i * 64 : (i + 1) * 64]
        blocks.append(le_words(chunk) if little_endian else be_words(chunk))
    blocks += padded_blocks(data[nfull * 64 :], prefix_len + len(data), little_endian)
    return blocks


def pack_passwords_be(passwords, block_words: int = 16) -> np.ndarray:
    """Pack N password byte-strings into a [N, block_words] uint32 array.

    Each password (<= 4*block_words - 1 bytes; WPA PSKs are 8..63 bytes)
    becomes one zero-padded 64-byte HMAC key block in big-endian words.
    Vectorized so the host can keep a TPU fed (millions of rows/s).
    """
    n = len(passwords)
    # One C-level join + a vectorized scatter instead of a Python loop
    # over rows: the pack stage must outrun a device mesh, not one chip.
    flat = np.frombuffer(b"".join(passwords), dtype=np.uint8)
    lens = np.fromiter((len(p) for p in passwords), np.int64, count=n)
    offs = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    buf = np.zeros((n, block_words * 4), dtype=np.uint8)
    row = np.repeat(np.arange(n), lens)
    col = np.arange(flat.size, dtype=np.int64) - np.repeat(offs, lens)
    buf[row, col] = flat
    return buf.view(">u4").astype(np.uint32)


def words_to_bytes_be(words) -> bytes:
    """Iterable of 32-bit ints -> big-endian bytes."""
    ws = [int(w) & 0xFFFFFFFF for w in words]
    return struct.pack(">%dI" % len(ws), *ws)


def words_to_bytes_le(words) -> bytes:
    ws = [int(w) & 0xFFFFFFFF for w in words]
    return struct.pack("<%dI" % len(ws), *ws)
