"""Durable-commit filesystem helpers shared by the persistence layers.

The resume checkpoint, found outbox, dict cache and PMK store all commit
with the same idiom: write a sibling tmp file, fsync it, ``os.replace``
over the final name, then fsync the directory so the rename itself is on
disk.  Without the two fsyncs a power loss can surface an older-but-valid
file after the rename appeared to succeed — for the resume checkpoint
that means double-counting ``skip``.
"""

import os


def fsync_dir(path: str):
    """fsync a directory so a completed rename/create within it is
    durable.  Best-effort: some filesystems (and platforms) refuse
    O_RDONLY directory fds — a refusal downgrades to the pre-fsync
    behavior rather than failing the commit."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_replace(tmp_path: str, final_path: str):
    """Durably commit ``tmp_path`` over ``final_path``.

    The tmp file must already be written and closed; this fsyncs its
    contents, renames it into place, and fsyncs the parent directory.
    """
    fd = os.open(tmp_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp_path, final_path)
    fsync_dir(os.path.dirname(os.path.abspath(final_path)))
