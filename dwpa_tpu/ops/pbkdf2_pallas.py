"""Pallas TPU kernel for the PBKDF2-HMAC-SHA1 hot loop.

Reference semantics: ``PMK = PBKDF2-HMAC-SHA1(psk, essid, 4096, 32)``
(web/common.php:179).  The pure-XLA formulation (ops/pbkdf2.py) expresses
the 4096-iteration loop as a ``lax.fori_loop`` whose carry is ten [2, B]
uint32 arrays; measured on a v5e chip that plateaus near ~48k PMK/s
because the carry round-trips through memory every iteration.  This
kernel instead runs the *entire* loop inside one Pallas program per batch
tile, so the SHA-1 state lives in vector registers for all 4096
iterations and the only HBM traffic is the initial states in and the
final accumulators out.

Layout: the two PBKDF2 output blocks T1/T2 (a 32-byte PMK needs both)
are folded into extra batch *lanes* rather than a leading axis — lane i
computes T1 for candidate i, lane B+i computes T2.  Each Pallas program
owns a (TILE, 128) lane tile; per 32-bit word that is TILE/8 vector
registers, giving the VPU independent work to hide ALU latency across
the serial SHA-1 round dependency chain.

The kernel reuses the generic unrolled ``sha1_compress`` /
``hmac_sha1_20`` ops — inside Pallas they trace to the same straight-line
uint32 arithmetic, just on register-resident (TILE, 128) tiles.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .hmac import (
    hmac_sha1_20,
    hmac_sha1_20_hoisted,
    hmac_sha1_20_prologue,
    hmac_sha1_blocks,
    hmac_sha1_precompute,
)

# Lane-tile sublane count per Pallas program.  (TILE, 128) uint32 words;
# TILE=32 -> 4 vregs per word -> 4-way independent chains per VPU op.
# Swept on hardware (r3): 32 > 64 > 16 > 128 with the hoisted loop body
# (237.8k / 234.0k / 213.1k / 185.2k PMK/s at B=128k).
DEFAULT_TILE = 32


def _loop_kernel(iterations, unroll, hoist, sin_ref, out_ref):
    """One batch tile: run iterations 1..4096 of the PBKDF2 xor-chain.

    ``sin_ref``: uint32[15, TILE, 128] — rows 0-4 the HMAC ipad state,
    5-9 the opad state, 10-14 U1 (= initial accumulator).
    ``out_ref``: uint32[5, TILE, 128] — the final T accumulator words.
    """
    s = sin_ref[:]
    ist = tuple(s[i] for i in range(5))
    ost = tuple(s[5 + i] for i in range(5))
    u1 = tuple(s[10 + i] for i in range(5))
    if hoist:
        # Hoist the loop-invariant prefix of both compressions (rounds
        # 0-4 partials over the fixed pad states) out of the loop: ~48 of
        # ~2,700 vector ops per iteration move here, run once — at the
        # cost of 16 extra live words of register pressure (A/B'd on
        # hardware; see BASELINE.md ceiling table).
        pro = hmac_sha1_20_prologue(ist, ost)

        def body(_, carry):
            u, acc = carry[:5], carry[5:]
            nu = hmac_sha1_20_hoisted(pro, u)
            return tuple(nu) + tuple(a ^ x for a, x in zip(acc, nu))

    else:

        def body(_, carry):
            u, acc = carry[:5], carry[5:]
            nu = hmac_sha1_20(ist, ost, u)
            return tuple(nu) + tuple(a ^ x for a, x in zip(acc, nu))

    fin = jax.lax.fori_loop(1, iterations, body, u1 + u1, unroll=unroll)
    out_ref[:] = jnp.stack(fin[5:])


@functools.partial(
    jax.jit,
    static_argnames=(
        "iterations", "tile", "unroll", "interpret", "prologue_compress", "hoist",
    ),
)
def pbkdf2_sha1_pmk_pallas(
    pw_words,
    salt1,
    salt2,
    *,
    iterations=4096,
    tile=DEFAULT_TILE,
    unroll=1,
    interpret=False,
    prologue_compress=None,
    hoist=True,
):
    """Derive 32-byte PMKs for a packed password batch on TPU via Pallas.

    ``pw_words``: uint32[B, 16] zero-padded 64-byte HMAC key blocks
    (utils/bytesops.pack_passwords_be).  ``salt1``/``salt2``: uint32[16]
    pre-padded single-block salt messages for ``essid || INT32_BE(i)``
    (models/m22000.essid_salt_blocks), or uint32[B, 16] for PER-LANE
    salts (mixed-ESSID fused batches: lane b hashes its own ESSID).  The
    salt only enters the prologue's U1 computation — the first-iteration
    message block changes from broadcast scalars to [B] columns — so the
    register-resident 4096-iteration loop body, and with it the kernel's
    register pressure, is byte-identical in both modes (the hardware
    tile sweep from r3 carries over; re-sweeping is advisable but not
    required).  Returns uint32[8, B] PMK words, bit-identical to
    ops/pbkdf2.pbkdf2_sha1_pmk.
    """
    B = pw_words.shape[0]
    pw = [pw_words[:, i] for i in range(16)]
    # The hoisted loop body is a TPU-only perf feature (+4-6% on chip):
    # under interpret mode its closure-carried prologue makes the
    # XLA:CPU lowering pathologically slow (>400 s vs ~28 s measured),
    # so CPU correctness tests run the generic body; the hoisted math
    # itself is pinned CPU-side at the sha1 level (tests/test_ops.py
    # sha1_compress_20 equivalence) and bit-exact vs hashlib on TPU.
    if interpret:
        hoist = False

    # Cold prologue (5 compressions of the 8192): pad states + U1, XLA-side.
    # ``prologue_compress`` lets CPU callers (tests) use the rolled
    # compression, whose XLA:CPU compile is seconds rather than minutes.
    kw = {}
    if prologue_compress is not None:
        kw = {"compress": prologue_compress}
    ist, ost = hmac_sha1_precompute(pw, **kw)
    if salt1.ndim == 2:
        # Per-lane salts: word i of lane b's first-iteration message is
        # column i of the [B, 16] salt block — same U1 math, broadcast
        # against [B] instead of from a scalar.
        s1 = [[salt1[:, i] for i in range(16)]]
        s2 = [[salt2[:, i] for i in range(16)]]
    else:
        s1 = [[salt1[i] for i in range(16)]]
        s2 = [[salt2[i] for i in range(16)]]
    u1_t1 = hmac_sha1_blocks(ist, ost, s1, **kw)
    u1_t2 = hmac_sha1_blocks(ist, ost, s2, **kw)

    # Fold T into lanes: [2B] = T1 lanes then T2 lanes, padded to the tile.
    # Clamp the tile to the actual lane count (min 8 sublanes — the uint32
    # tiling floor) so small per-device shards don't pad 8x dead work.
    lanes = 2 * B
    tile = max(8, min(tile, -(-lanes // 128)))
    step = tile * 128
    padded = -(-lanes // step) * step
    rows = (
        [jnp.concatenate([w, w]) for w in ist]
        + [jnp.concatenate([w, w]) for w in ost]
        + [jnp.concatenate([a, b]) for a, b in zip(u1_t1, u1_t2)]
    )
    sin = jnp.stack([jnp.pad(r, (0, padded - lanes)) for r in rows])
    sin = sin.reshape(15, padded // 128, 128)

    out = pl.pallas_call(
        functools.partial(_loop_kernel, iterations, unroll, hoist),
        grid=(padded // step,),
        in_specs=[
            pl.BlockSpec((15, tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (5, tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((5, padded // 128, 128), jnp.uint32),
        interpret=interpret,
    )(sin)

    acc = out.reshape(5, padded)[:, :lanes].reshape(5, 2, B)
    # PMK = T1 (20 bytes) || T2[:12] -> 8 big-endian words.
    return jnp.stack(
        [
            acc[0, 0], acc[1, 0], acc[2, 0], acc[3, 0], acc[4, 0],
            acc[0, 1], acc[1, 1], acc[2, 1],
        ]
    )
