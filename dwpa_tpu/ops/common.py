"""Shared helpers for the uint32-lane crypto ops.

Design notes (TPU-first):

Every hash primitive here operates on *batches* laid out as Python lists of
``uint32`` arrays — one array per 32-bit message/state word, each array
holding that word for the whole batch.  Elementwise uint32 adds/xors/rotates
over a batch axis map 1:1 onto the TPU VPU's (8, 128) vector lanes, and the
fully unrolled round structure gives XLA a straight-line dependency chain it
can software-pipeline.  There are no gathers, no dynamic shapes, and no
data-dependent control flow in any compression function.

Host-side packing of byte strings into word lists lives in
``dwpa_tpu.utils.bytesops`` (plain numpy; runs once per net / per batch).
"""

import jax.numpy as jnp

U32 = jnp.uint32


def rotl32(x, n: int):
    """Rotate a uint32 array left by a static amount ``0 < n < 32``."""
    return (x << n) | (x >> (32 - n))


def rotl32_dyn(x, n):
    """Rotate uint32 left by a traced per-element amount ``0 < n < 32``."""
    n = jnp.uint32(n)
    return (x << n) | (x >> (jnp.uint32(32) - n))


def rotr32(x, n: int):
    """Rotate a uint32 array right by a static amount ``0 < n < 32``."""
    return (x >> n) | (x << (32 - n))


def u32(x):
    """Promote a Python int / array to uint32."""
    return jnp.uint32(x)


def bswap32(x):
    """Byte-swap a uint32 array (BE word <-> LE word)."""
    x = u32(x)
    return (
        ((x & u32(0xFF)) << 24)
        | ((x & u32(0xFF00)) << 8)
        | ((x >> 8) & u32(0xFF00))
        | (x >> 24)
    )
