"""PBKDF2-HMAC-SHA1 -> PMK: the WPA hot kernel.

Reference semantics: ``PMK = PBKDF2-HMAC-SHA1(psk, essid, 4096, 32)``
(web/common.php:179).  This is ~99% of all cycles in the system, so the
shape is chosen for the TPU VPU:

- The HMAC ipad/opad states are precomputed once per candidate
  (2 compressions), so each of the 4096 iterations costs exactly two
  SHA-1 compressions over a fixed 20-byte message (ops/hmac.hmac_sha1_20).
- A 32-byte PMK needs two PBKDF2 output blocks T1, T2.  Instead of two
  sequential loops, the T axis is stacked as a leading dim of size 2 so
  both blocks ride the same ``lax.fori_loop`` — the device sees one
  [2, B] batch and the loop body stays two compressions.
- No data-dependent control flow; iteration count is static; everything
  is uint32 elementwise math that XLA vectorizes across lanes.
"""

import jax
import jax.numpy as jnp

from .common import u32
from .hmac import hmac_sha1_20, hmac_sha1_blocks, hmac_sha1_precompute


def _stack2(words):
    """Duplicate each state word along a new leading T axis of size 2."""
    return tuple(jnp.stack([w, w]) for w in words)


def pbkdf2_sha1_pmk(pw_words, salt_block_1, salt_block_2, iterations=4096):
    """Derive 32-byte PMKs for a batch of candidate passwords.

    ``pw_words``: 16 uint32 arrays of shape [B] — zero-padded 64-byte HMAC
    key blocks (utils/bytesops.pack_passwords_be).
    ``salt_block_1/2``: the single pre-padded 16-word message block for
    ``essid || INT32_BE(i)`` (i = 1, 2).  Each word is either a plain int
    (one ESSID for the whole batch — host-prepped via
    ``utils.bytesops.padded_blocks(essid + pack('>I', i), 64 + len(essid) + 4)``)
    or a uint32 array of shape [B] (PER-LANE salts: lane b hashes its own
    ESSID — the mixed-ESSID fused batch path).  ``broadcast_to`` below is
    the whole dispatch: a scalar word fans out across the batch, a [B]
    word passes through unchanged, and the 4096-iteration loop never sees
    the difference (the salt only enters via U1).

    Returns 8 uint32 arrays of shape [B]: the PMK as big-endian words.
    """
    istate, ostate = hmac_sha1_precompute(pw_words)
    ist2, ost2 = _stack2(istate), _stack2(ostate)

    # First iteration: U1 = HMAC(P, salt || INT(i)), distinct per T block.
    shape = istate[0].shape
    salt = [
        jnp.stack(
            [
                jnp.broadcast_to(u32(a), shape),
                jnp.broadcast_to(u32(b), shape),
            ]
        )
        for a, b in zip(salt_block_1, salt_block_2)
    ]
    u1 = hmac_sha1_blocks(ist2, ost2, [salt])

    def body(_, carry):
        u, acc = carry
        u = hmac_sha1_20(ist2, ost2, u)
        acc = tuple(a ^ x for a, x in zip(acc, u))
        return (u, acc)

    _, acc = jax.lax.fori_loop(1, iterations, body, (u1, u1))

    # PMK = T1 (20 bytes) || T2[:12]  -> 8 big-endian words.
    return (
        acc[0][0], acc[1][0], acc[2][0], acc[3][0], acc[4][0],
        acc[0][1], acc[1][1], acc[2][1],
    )
