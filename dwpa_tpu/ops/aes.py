"""AES-128 and AES-128-CMAC (OMAC1) as batched JAX ops.

Needed only for the WPA2 802.11w keyver=3 MIC (AES-128-CMAC over the EAPOL
frame, reference semantics: web/common.php:272 / omac1_aes_128 at
web/common.php:56-112).  keyver=3 nets are rare, so this path favours
clarity over raw speed: the state is 16 per-byte uint32 arrays and SubBytes
is a 256-entry ``jnp.take`` (TPU handles the gather; the cost is dwarfed by
the PBKDF2 loop that precedes it).

The S-box is generated from the GF(2^8) definition at import time rather
than transcribed, and checked by FIPS-197 test vectors in the test suite.
"""

import numpy as np
import jax.numpy as jnp

from .common import u32


def _gf_mul(a: int, b: int) -> int:
    p = 0
    for _ in range(8):
        if b & 1:
            p ^= a
        hi = a & 0x80
        a = (a << 1) & 0xFF
        if hi:
            a ^= 0x1B
        b >>= 1
    return p


def _make_sbox() -> np.ndarray:
    # multiplicative inverse table via exp/log in GF(2^8), generator 3
    exp = [0] * 510
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x = _gf_mul(x, 3)
    for i in range(255, 510):
        exp[i] = exp[i - 255]
    sbox = np.zeros(256, dtype=np.uint32)
    for v in range(256):
        inv = 0 if v == 0 else exp[255 - log[v]]
        s = inv
        for shift in (1, 2, 3, 4):
            s ^= ((inv << shift) | (inv >> (8 - shift))) & 0xFF
        sbox[v] = s ^ 0x63
    return sbox


SBOX = _make_sbox()
RCON = [1, 2, 4, 8, 16, 32, 64, 128, 27, 54]


def _sub(byte_arr):
    # jnp.asarray of a host constant folds to an XLA constant per trace;
    # caching the device array globally would leak tracers across traces.
    return jnp.take(jnp.asarray(SBOX), byte_arr.astype(jnp.int32))


def _xtime(b):
    return ((b << 1) ^ ((b >> 7) * u32(0x1B))) & u32(0xFF)


def aes128_expand_key(key16):
    """key16: list of 16 uint32 byte-value arrays -> list of 11 round keys."""
    rk = [list(key16)]
    for r in range(10):
        prev = rk[-1]
        t = [_sub(prev[13]), _sub(prev[14]), _sub(prev[15]), _sub(prev[12])]
        t[0] = t[0] ^ u32(RCON[r])
        nk = []
        for c in range(4):
            for row in range(4):
                t[row] = u32(prev[4 * c + row]) ^ t[row]
            nk.extend(t)
            t = list(nk[-4:])
        rk.append(nk)
    return rk


def aes128_encrypt_block(round_keys, block16):
    """Encrypt one 16-byte block (per-byte uint32 arrays, index = byte pos).

    Byte order follows FIPS-197: block16[i] is byte i of the input, state
    column c is bytes 4c..4c+3.
    """
    s = [u32(block16[i]) ^ u32(round_keys[0][i]) for i in range(16)]
    for r in range(1, 11):
        s = [_sub(b) for b in s]
        # ShiftRows: state[row + 4c] <- state[row + 4((c + row) % 4)]
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        if r < 10:
            ns = []
            for c in range(4):
                a0, a1, a2, a3 = s[4 * c : 4 * c + 4]
                x0, x1, x2, x3 = _xtime(a0), _xtime(a1), _xtime(a2), _xtime(a3)
                ns.extend(
                    [
                        x0 ^ x1 ^ a1 ^ a2 ^ a3,
                        a0 ^ x1 ^ x2 ^ a2 ^ a3,
                        a0 ^ a1 ^ x2 ^ x3 ^ a3,
                        x0 ^ a0 ^ a1 ^ a2 ^ x3,
                    ]
                )
            s = ns
        s = [s[i] ^ u32(round_keys[r][i]) for i in range(16)]
    return s


# ---------------------------------------------------------------------------
# Rolled array-state variant (cold-path compile-time trade, like
# sha1_compress_rolled): state is ONE uint32[16, ...] array, rounds are a
# fori_loop, SubBytes one gather, ShiftRows a constant permutation.
# ---------------------------------------------------------------------------

import jax

_SHIFT_ROWS = np.array([(i + 4 * (i % 4)) % 16 for i in range(16)])
_ROT_WORD = np.array([13, 14, 15, 12])


def _mix_columns_arr(s):
    a = s.reshape((4, 4) + s.shape[1:])
    x = _xtime(a)
    rows = [
        x[:, 0] ^ x[:, 1] ^ a[:, 1] ^ a[:, 2] ^ a[:, 3],
        a[:, 0] ^ x[:, 1] ^ x[:, 2] ^ a[:, 2] ^ a[:, 3],
        a[:, 0] ^ a[:, 1] ^ x[:, 2] ^ x[:, 3] ^ a[:, 3],
        x[:, 0] ^ a[:, 0] ^ a[:, 1] ^ a[:, 2] ^ x[:, 3],
    ]
    return jnp.stack(rows, axis=1).reshape(s.shape)


def aes128_expand_key_rolled(key16):
    """key16: uint32[16, ...] byte-value array -> uint32[11, 16, ...]."""
    rcon = jnp.asarray(RCON, dtype=jnp.uint32)

    def body(prev, rc):
        t = _sub(prev[_ROT_WORD])
        t = t.at[0].set(t[0] ^ rc)
        words = []
        cur = t
        for c in range(4):
            cur = prev[4 * c : 4 * c + 4] ^ cur
            words.append(cur)
        nk = jnp.concatenate(words)
        return nk, nk

    _, rks = jax.lax.scan(body, key16, rcon)
    return jnp.concatenate([key16[None], rks])


def aes128_encrypt_rolled(rks, block):
    """``rks``: uint32[11, 16, ...]; ``block``: uint32[16, ...]."""
    s = block ^ rks[0]

    def round_body(r, s):
        s = _sub(s)[_SHIFT_ROWS]
        s = _mix_columns_arr(s)
        return s ^ rks[r]

    s = jax.lax.fori_loop(1, 10, round_body, s)
    s = _sub(s)[_SHIFT_ROWS]
    return s ^ rks[10]


def _dbl_arr(b):
    carry = jnp.concatenate([b[1:] >> 7, jnp.zeros_like(b[:1])])
    out = ((b << 1) & u32(0xFF)) | carry
    return out.at[15].set(out[15] ^ (b[0] >> 7) * u32(0x87))


def aes128_cmac_rolled(key16, msg_blocks, last_block, last_complete):
    """AES-128-CMAC with the rolled AES core.

    ``key16``: uint32[16, ...] (batched KCK bytes); ``msg_blocks``:
    uint32[F, 16] constants; ``last_block``: uint32[16] (10*-padded if
    incomplete); ``last_complete``: static bool.  Returns uint32[16, ...].
    """
    rks = aes128_expand_key_rolled(key16)
    shape = key16.shape[1:]
    zero = jnp.zeros((16,) + shape, dtype=jnp.uint32)
    k1 = _dbl_arr(aes128_encrypt_rolled(rks, zero))
    sub = k1 if last_complete else _dbl_arr(k1)

    c = zero
    for i in range(msg_blocks.shape[0]):
        blk = jnp.broadcast_to(msg_blocks[i][(...,) + (None,) * len(shape)], c.shape)
        c = aes128_encrypt_rolled(rks, blk ^ c)
    last = jnp.broadcast_to(last_block[(...,) + (None,) * len(shape)], c.shape)
    return aes128_encrypt_rolled(rks, last ^ sub ^ c)


def _dbl(b16):
    """GF(2^128) doubling for CMAC subkeys (left shift 1, xor 0x87)."""
    out = []
    for i in range(15):
        out.append(((b16[i] << 1) | (b16[i + 1] >> 7)) & u32(0xFF))
    out.append(((b16[15] << 1) & u32(0xFF)) ^ ((b16[0] >> 7) * u32(0x87)))
    return out


def aes128_cmac(key16, msg_blocks, last_block, last_complete):
    """AES-128-CMAC (OMAC1, RFC 4493).

    ``key16``: 16 uint32 byte arrays (the per-candidate KCK).
    ``msg_blocks``: list of full 16-byte blocks *before* the last block
    (each a list of 16 uint32 words/ints).
    ``last_block``: the final block, already 10*-padded if incomplete.
    ``last_complete``: static bool — selects the K1/K2 subkey.

    Returns 16 uint32 byte arrays (the MAC).
    """
    rks = aes128_expand_key(key16)
    zero = [u32(0)] * 16
    l = aes128_encrypt_block(rks, zero)
    k1 = _dbl(l)
    sub = k1 if last_complete else _dbl(k1)

    c = [u32(0)] * 16
    for blk in msg_blocks:
        c = aes128_encrypt_block(rks, [u32(blk[i]) ^ c[i] for i in range(16)])
    final = [u32(last_block[i]) ^ sub[i] ^ c[i] for i in range(16)]
    return aes128_encrypt_block(rks, final)
