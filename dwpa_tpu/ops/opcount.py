"""Census of traced vector ops for the crypto kernels.

The round-2 ceiling analysis estimated ~1,400 uint32 vector ops per SHA-1
compression by hand.  This module replaces the estimate with a measured
count: trace the exact function the Pallas PBKDF2 loop body runs
(``hmac_sha1_20`` + the accumulator xors) and count the integer ALU
primitives in the jaxpr.  Mosaic lowers each elementwise uint32 primitive
on a (TILE, 128) tile to TILE/8 VPU vreg ops, so

    element_ops / PMK = 2 lanes x 4095 iterations x eqn_count

is the exact numerator for the kernel-efficiency ratio against the
measured VPU ceiling (see ops/vpu_probe.py).

Reference cost model: PBKDF2-HMAC-SHA1 x 4096, 32-byte PMK
(web/common.php:179) = 2 output blocks x 4096 iterations x 2 compressions.
"""

from collections import Counter

import jax
import jax.numpy as jnp

# Primitives that lower to one VPU ALU op per element.
ALU_PRIMS = {
    "add",
    "sub",
    "mul",
    "xor",
    "and",
    "or",
    "not",
    "shift_left",
    "shift_right_logical",
    "shift_right_arithmetic",
}
# Shape/dtype plumbing XLA elides or folds; counted separately for audit.
FREE_PRIMS = {"convert_element_type", "broadcast_in_dim", "reshape", "squeeze"}


def _sub_jaxprs(params):
    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            inner = getattr(item, "jaxpr", item)
            if hasattr(inner, "eqns"):
                yield inner


def census(fn, *args):
    """Trace ``fn(*args)`` and return a Counter of primitive names,
    descending into nested jaxprs (pjit/scan/while bodies)."""
    closed = jax.make_jaxpr(fn)(*args)
    counts = Counter()
    stack = [closed.jaxpr]
    while stack:
        jx = stack.pop()
        for eqn in jx.eqns:
            subs = list(_sub_jaxprs(eqn.params))
            if subs:
                stack.extend(subs)
            else:
                counts[eqn.primitive.name] += 1
    return counts


def summarize(counts):
    alu = sum(n for p, n in counts.items() if p in ALU_PRIMS)
    free = sum(n for p, n in counts.items() if p in FREE_PRIMS)
    other = sum(
        n for p, n in counts.items() if p not in ALU_PRIMS and p not in FREE_PRIMS
    )
    return {
        "alu_ops": alu,
        "free_ops": free,
        "other_ops": other,
        "by_prim": dict(sorted(counts.items(), key=lambda kv: -kv[1])),
    }


def pbkdf2_iteration_census(hoisted=True):
    """Op census of one PBKDF2 loop-body iteration (per lane): one
    HMAC-SHA1 of a 20-byte message plus the 5 accumulator xors."""
    from . import hmac as hm
    from . import sha1

    z = jnp.zeros((1,), jnp.uint32)
    st5 = tuple(z for _ in range(5))

    if hoisted:
        pro = sha1.sha1_20_prologue(st5)

        def body(ipro, opro, u, acc):
            nu = hm.hmac_sha1_20_hoisted((ipro, opro), u)
            return tuple(nu) + tuple(a ^ x for a, x in zip(acc, nu))

        counts = census(body, pro, pro, st5, st5)
    else:

        def body(ist, ost, u, acc):
            nu = hm.hmac_sha1_20(ist, ost, u)
            return tuple(nu) + tuple(a ^ x for a, x in zip(acc, nu))

        counts = census(body, st5, st5, st5, st5)
    return summarize(counts)


def sha1_compress_census():
    """Op census of one generic SHA-1 compression (all 16 words traced)."""
    from .sha1 import sha1_compress

    z = jnp.zeros((1,), jnp.uint32)
    st5 = tuple(z for _ in range(5))
    blk = [z] * 16
    return summarize(census(lambda s, b: sha1_compress(s, b), st5, blk))


def main():
    import json

    gen = sha1_compress_census()
    it_plain = pbkdf2_iteration_census(hoisted=False)
    it_hoist = pbkdf2_iteration_census(hoisted=True)
    out = {
        "sha1_compress_generic": gen,
        "pbkdf2_iter_plain": it_plain,
        "pbkdf2_iter_hoisted": it_hoist,
        # 2 lanes (T1/T2) x 4095 loop iterations, plus the 5-compression
        # prologue (~counted separately; <0.1% of total).
        "element_ops_per_pmk_plain": 2 * 4095 * it_plain["alu_ops"],
        "element_ops_per_pmk_hoisted": 2 * 4095 * it_hoist["alu_ops"],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
