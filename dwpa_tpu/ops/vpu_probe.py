"""Measured VPU integer-throughput ceiling (Pallas microbenchmarks).

The round-2 BASELINE defended the PBKDF2 kernel's ~230k PMK/s/chip with an
*estimated* VPU peak (~6.1 Tops/s from lane-count x clock).  This module
measures what the VPU actually sustains on the op mixes the SHA-1 kernel
is made of: long dependent chains of uint32 add/xor/and/or/shift on
register-resident (TILE, 128) tiles — the same shape, tiling, and ILP
profile as ``ops/pbkdf2_pallas``.

Each mix body is a pure function on a tuple of tile-shaped uint32 arrays
with a hand-counted op cost (``NOPS``); the kernel runs it ``iters`` times
in a ``fori_loop`` and writes a reduction of the carry so nothing folds
away.  element_ops/s = iters x nops x elements / seconds.

The ``sha1_round`` mix is one faithful SHA-1 Ch-round (12 ops: two rotls,
xor-select f, three adds) — its measured rate, combined with the exact op
census in ``ops/opcount.py``, gives the attainable PMK/s ceiling:

    ceiling_pmk_s = sha1_round_ops_per_s / element_ops_per_pmk

Run: ``python -m dwpa_tpu.ops.vpu_probe`` (prints one JSON line).
"""

import functools
import json
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import rotl32, u32

K0 = 0x5A827999


def _mix_add(st):
    a, b, c, d, e = st
    return (a + b, b + c, c + d, d + e, e + a)


def _mix_xor(st):
    a, b, c, d, e = st
    return (a ^ b, b ^ c, c ^ d, d ^ e, e ^ a)


def _mix_rotl(st):
    # 5 independent 3-op rotls: measures whether Mosaic lowers
    # (x << n) | (x >> 32-n) to a native rotate (ops/s >> add ceiling)
    # or to three ALU slots (ops/s ~= add ceiling).
    return tuple(rotl32(x, 5 + i) for i, x in enumerate(st))


def _mix_sha1_round(st):
    # One SHA-1 Ch round, exactly as ops/sha1.py emits it.
    a, b, c, d, e = st
    f = d ^ (b & (c ^ d))  # 3 ops
    tmp = rotl32(a, 5) + f + e + u32(K0)  # 3 rotl + 3 add
    return (tmp, a, rotl32(b, 30), c, d)  # 3 rotl


MIXES = {
    # name: (body, element-ops per iteration)
    "add": (_mix_add, 5),
    "xor": (_mix_xor, 5),
    "rotl": (_mix_rotl, 15),
    "sha1_round": (_mix_sha1_round, 12),
}


# Mix applications per loop iteration: big straight-line body so the
# while-loop's scalar bookkeeping vanishes into the vector work, matching
# the real PBKDF2 kernel's ~2,700-op body.
UNROLL = 64


def _probe_kernel(iters, body, x_ref, o_ref):
    st = tuple(x_ref[i] for i in range(x_ref.shape[0]))

    def step(_, s):
        for _ in range(UNROLL):
            s = body(s)
        return s

    fin = jax.lax.fori_loop(0, iters, step, st)
    acc = fin[0]
    for x in fin[1:]:
        acc = acc ^ x
    o_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("mix", "iters", "tile", "grid"))
def _probe(x, *, mix, iters, tile, grid):
    body, _ = MIXES[mix]
    return pl.pallas_call(
        functools.partial(_probe_kernel, iters, body),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((5, tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((tile, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((grid * tile, 128), jnp.uint32),
    )(x)


def _timed(x, mix, iters, tile, grid, reps):
    """Median-of-``reps`` wall seconds, materializing the result on host
    (on the axon-tunnelled TPU, ``block_until_ready`` returns before
    execution completes — same workaround as bench.py)."""
    import statistics

    import numpy as np

    np.asarray(_probe(x, mix=mix, iters=iters, tile=tile, grid=grid))  # warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(_probe(x, mix=mix, iters=iters, tile=tile, grid=grid))
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def measure(mix, *, tile=64, grid=16, iters=20_000, reps=5):
    """Sustained element-ops/s for one mix via differential timing:
    (t(3N) - t(N)) / 2N cancels the fixed dispatch/transfer overhead of
    the tunnelled device."""
    import numpy as np

    _, nops = MIXES[mix]
    rng = np.random.default_rng(7)
    x = jnp.asarray(
        rng.integers(0, 2**32, (5, grid * tile, 128), dtype=np.uint64).astype(
            np.uint32
        )
    )
    t1 = _timed(x, mix, iters, tile, grid, reps)
    t3 = _timed(x, mix, 3 * iters, tile, grid, reps)
    elems = grid * tile * 128
    dt = max(t3 - t1, 1e-9)
    return {
        "mix": mix,
        "tile": tile,
        "ops_per_iter": nops,
        "seconds_1x": round(t1, 6),
        "seconds_3x": round(t3, 6),
        "tera_ops_per_s": round(2 * iters * UNROLL * nops * elems / dt / 1e12, 4),
    }


def main():
    dev = jax.devices()[0]
    out = {"device": str(dev), "mixes": {}, "sha1_round_tiles": {}}
    for mix in MIXES:
        out["mixes"][mix] = measure(mix)
    for tile in (8, 16, 32, 64, 128, 256):
        r = measure("sha1_round", tile=tile, grid=max(1, 1024 // tile))
        out["sha1_round_tiles"][str(tile)] = r["tera_ops_per_s"]
    # Attainable PMK/s ceiling from the measured sha1-shaped rate and the
    # exact per-PMK op census.
    from .opcount import pbkdf2_iteration_census

    ops_pmk = 2 * 4095 * pbkdf2_iteration_census(hoisted=True)["alu_ops"]
    rate = out["mixes"]["sha1_round"]["tera_ops_per_s"] * 1e12
    out["element_ops_per_pmk"] = ops_pmk
    out["ceiling_pmk_per_s"] = round(rate / ops_pmk, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
