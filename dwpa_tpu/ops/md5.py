"""MD5 as batched uint32-lane JAX ops (RFC 1321).

Used only for the WPA keyver=1 MIC (HMAC-MD5 over the EAPOL frame,
reference semantics: web/common.php:264), so it is off the hot path —
still written in the same unrolled word-list style as SHA-1 so one code
shape serves every primitive.

Note MD5 message words are little-endian; host-side packing handles the
byte order (utils/bytesops), the compression here is byte-order agnostic.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from .common import rotl32, rotl32_dyn, u32

IV = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476)

# Per-round constants straight from the RFC 1321 definition
# T[i] = floor(2^32 * |sin(i + 1)|).
T = [int(4294967296 * abs(math.sin(i + 1))) & 0xFFFFFFFF for i in range(64)]

# Rotation amounts per round quartet.
S = (
    [7, 12, 17, 22] * 4
    + [5, 9, 14, 20] * 4
    + [4, 11, 16, 23] * 4
    + [6, 10, 15, 21] * 4
)


def md5_init(shape=()):
    return tuple(jnp.full(shape, v, jnp.uint32) for v in IV)


def md5_compress(state, block):
    """One MD5 compression over a 16-word (little-endian) block."""
    w = list(block)
    a, b, c, d = state

    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        tmp = d
        d = c
        c = b
        b = b + rotl32(a + f + u32(T[i]) + u32(w[g]), S[i])
        a = tmp

    s0, s1, s2, s3 = state
    return (s0 + a, s1 + b, s2 + c, s3 + d)


# Message-word index per round (the g(i) schedule above, as a table).
G = np.array(
    [i for i in range(16)]
    + [(5 * i + 1) % 16 for i in range(16, 32)]
    + [(3 * i + 5) % 16 for i in range(32, 48)]
    + [(7 * i) % 16 for i in range(48, 64)],
    dtype=np.int32,
)


def md5_compress_rolled(state, block):
    """One MD5 compression as a rolled ``fori_loop`` (cold-path variant).

    Same trade as sha1_compress_rolled: tiny graph, fast compile; per-round
    T/S/G constants become table lookups and the rotate amount is dynamic.
    """
    shape = jnp.broadcast_shapes(*(jnp.shape(u32(w)) for w in block), state[0].shape)
    ws = jnp.stack([jnp.broadcast_to(u32(w), shape) for w in block])
    t_arr = jnp.asarray(T, dtype=jnp.uint32)
    s_arr = jnp.asarray(S, dtype=jnp.uint32)
    g_arr = jnp.asarray(G)

    def body(i, st):
        a, b, c, d = st
        f = jax.lax.switch(
            i // 16,
            [
                lambda: (b & c) | (~b & d),
                lambda: (d & b) | (~d & c),
                lambda: b ^ c ^ d,
                lambda: c ^ (b | ~d),
            ],
        )
        nb = b + rotl32_dyn(a + f + t_arr[i] + ws[g_arr[i]], s_arr[i])
        return (d, nb, b, c)

    out = jax.lax.fori_loop(
        0, 64, body, tuple(jnp.broadcast_to(s, shape) for s in state)
    )
    return tuple(s + o for s, o in zip(state, out))


def md5_digest_blocks(blocks, shape=()):
    st = md5_init(shape)
    for blk in blocks:
        st = md5_compress(st, blk)
    return st
