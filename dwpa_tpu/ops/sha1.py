"""SHA-1 as batched uint32-lane JAX ops (FIPS 180-4).

The 80-round compression is fully unrolled at trace time; the message
schedule is kept as a rolling Python list so XLA sees straight-line uint32
arithmetic it can vectorize across the batch axis (each word array carries
the whole candidate batch in its trailing dims).

This is the inner primitive of the WPA hot loop: PBKDF2-HMAC-SHA1 x 4096
(reference semantics: web/common.php:179) costs ~16384 of these
compressions per candidate, so everything else in the framework is designed
around keeping this function's operands in vector registers.
"""

import jax.numpy as jnp

from .common import rotl32, u32

# FIPS 180-4 initial state and stage constants.
IV = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)
K0, K1, K2, K3 = 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6


def sha1_init(shape=()):
    """Initial state as a 5-tuple of uint32 arrays of ``shape``."""
    return tuple(jnp.full(shape, v, jnp.uint32) for v in IV)


def sha1_compress(state, block):
    """One SHA-1 compression.

    ``state``: 5-tuple of uint32 arrays.  ``block``: list of 16 uint32
    arrays (big-endian message words); entries may be Python ints for
    constant words (e.g. padding) — XLA constant-folds them.
    Returns the new 5-tuple state.
    """
    w = list(block)
    a, b, c, d, e = state

    for t in range(80):
        if t >= 16:
            wt = rotl32(
                u32(w[t - 3]) ^ u32(w[t - 8]) ^ u32(w[t - 14]) ^ u32(w[t - 16]), 1
            )
            w.append(wt)
        if t < 20:
            f = (b & c) | (~b & d)
            k = K0
        elif t < 40:
            f = b ^ c ^ d
            k = K1
        elif t < 60:
            f = (b & c) | (b & d) | (c & d)
            k = K2
        else:
            f = b ^ c ^ d
            k = K3
        tmp = rotl32(a, 5) + f + e + u32(k) + u32(w[t])
        e = d
        d = c
        c = rotl32(b, 30)
        b = a
        a = tmp

    s0, s1, s2, s3, s4 = state
    return (s0 + a, s1 + b, s2 + c, s3 + d, s4 + e)


def sha1_digest_blocks(blocks, shape=()):
    """Run the compression over a list of 16-word blocks from the IV.

    ``blocks`` must already contain the 0x80 / length padding.  Returns the
    5-tuple digest words.  Convenience path for tests and host-prepped
    fixed-size messages.
    """
    st = sha1_init(shape)
    for blk in blocks:
        st = sha1_compress(st, blk)
    return st
