"""SHA-1 as batched uint32-lane JAX ops (FIPS 180-4).

The 80-round compression is fully unrolled at trace time; the message
schedule is kept as a rolling Python list so XLA sees straight-line uint32
arithmetic it can vectorize across the batch axis (each word array carries
the whole candidate batch in its trailing dims).

This is the inner primitive of the WPA hot loop: PBKDF2-HMAC-SHA1 x 4096
(reference semantics: web/common.php:179) costs ~16384 of these
compressions per candidate, so everything else in the framework is designed
around keeping this function's operands in vector registers.
"""

import jax
import jax.numpy as jnp

from .common import rotl32, u32

# FIPS 180-4 initial state and stage constants.
IV = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)
K0, K1, K2, K3 = 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6


def sha1_init(shape=()):
    """Initial state as a 5-tuple of uint32 arrays of ``shape``."""
    return tuple(jnp.full(shape, v, jnp.uint32) for v in IV)


def _xor(x, y):
    # Fold xors with integer constants at trace time (the 20-byte HMAC
    # message block is mostly constant padding words).
    if isinstance(x, int) and isinstance(y, int):
        return x ^ y
    if isinstance(x, int) and x == 0:
        return y
    if isinstance(y, int) and y == 0:
        return x
    return u32(x) ^ u32(y)


def _rotl(x, n):
    if isinstance(x, int):
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF
    return rotl32(x, n)


def _rounds(state, w, start=0):
    """SHA-1 rounds ``start``..79 over the (mutated) schedule list ``w``.

    Fully unrolled at trace time; returns the working variables (not yet
    added back into ``state``).  Round ``t`` reads ``w[t]`` and appends the
    expanded schedule word for ``t >= 16``; constant-int words fold away.
    """
    a, b, c, d, e = state
    for t in range(start, 80):
        if t >= 16:
            w.append(_rotl(_xor(_xor(w[t - 3], w[t - 8]), _xor(w[t - 14], w[t - 16])), 1))
        if t < 20:
            f = d ^ (b & (c ^ d))  # Ch via xor-select: 3 ops vs 4
            k = K0
        elif t < 40:
            f = b ^ c ^ d
            k = K1
        elif t < 60:
            f = (b & c) | (d & (b ^ c))  # Maj: 4 ops vs 5
            k = K2
        else:
            f = b ^ c ^ d
            k = K3
        # Group the round constant with constant message words so XLA (or
        # Python, when w[t] is a literal) folds them into one addend.
        kw = u32((k + w[t]) & 0xFFFFFFFF) if isinstance(w[t], int) else u32(k) + u32(w[t])
        tmp = rotl32(a, 5) + f + e + kw
        e = d
        d = c
        c = rotl32(b, 30)
        b = a
        a = tmp
    return a, b, c, d, e


def sha1_compress(state, block):
    """One SHA-1 compression.

    ``state``: 5-tuple of uint32 arrays.  ``block``: list of 16 uint32
    arrays (big-endian message words); entries may be Python ints for
    constant words (e.g. padding) — XLA constant-folds them.
    Returns the new 5-tuple state.
    """
    a, b, c, d, e = _rounds(state, list(block))
    s0, s1, s2, s3, s4 = state
    return (s0 + a, s1 + b, s2 + c, s3 + d, s4 + e)


def sha1_20_prologue(state):
    """Hoist the loop-invariant prefix of a 20-byte-message compression.

    In the PBKDF2 hot loop (web/common.php:179 semantics) the HMAC
    ipad/opad states are fixed per candidate while only the 5 message
    words change each iteration, so every subexpression of rounds 0-4
    that depends solely on ``state`` can be computed once outside the
    4096-iteration loop: f0/f1 in full, the c-rotations of rounds 0-1,
    and the e+K addends of rounds 2-4 (~24 vector ops per compression,
    x2 compressions x 8190 iterations per PMK).  Returns an opaque tuple
    consumed by :func:`sha1_compress_20`.
    """
    a, b, c, d, e = state
    c0r = rotl32(b, 30)  # c after round 0; d at round 2; e at round 3
    a0r = rotl32(a, 30)  # c after round 1; d at round 3; e at round 4
    f0 = d ^ (b & (c ^ d))
    p0 = rotl32(a, 5) + f0 + e + u32(K0)
    f1 = c ^ (a & (c0r ^ c))
    p1 = f1 + d + u32(K0)
    x2 = a0r ^ c0r
    p2 = c + u32(K0)
    p3 = c0r + u32(K0)
    p4 = a0r + u32(K0)
    return (state, c0r, a0r, p0, p1, x2, p2, p3, p4)


def sha1_compress_20(pro, m5):
    """One compression of a 20-byte message from a hoisted prologue.

    Bit-identical to ``sha1_compress(state, m5 + padding)`` for the
    fixed PBKDF2/HMAC message shape (20-byte message, 84 bytes total
    hashed), with rounds 0-4 specialized to reuse the loop-invariant
    values from :func:`sha1_20_prologue`.
    """
    state, c0r, a0r, p0, p1, x2, p2, p3, p4 = pro
    w0, w1, w2, w3, w4 = (u32(x) for x in m5)
    t0 = p0 + w0
    t1 = rotl32(t0, 5) + (p1 + w1)
    f2 = c0r ^ (t0 & x2)
    t2 = rotl32(t1, 5) + f2 + (p2 + w2)
    cv3 = rotl32(t0, 30)
    f3 = a0r ^ (t1 & (cv3 ^ a0r))
    t3 = rotl32(t2, 5) + f3 + (p3 + w3)
    cv4 = rotl32(t1, 30)
    f4 = cv3 ^ (t2 & (cv4 ^ cv3))
    t4 = rotl32(t3, 5) + f4 + (p4 + w4)
    # State entering round 5; schedule words 5..15 are the fixed padding.
    w = [w0, w1, w2, w3, w4, 0x80000000] + [0] * 9 + [84 * 8]
    a, b, c, d, e = _rounds((t4, t3, rotl32(t2, 30), cv4, cv3), w, start=5)
    s0, s1, s2, s3, s4 = state
    return (s0 + a, s1 + b, s2 + c, s3 + d, s4 + e)


def sha1_compress_rolled(state, block):
    """One SHA-1 compression as a rolled ``fori_loop`` (tiny XLA graph).

    Semantically identical to ``sha1_compress`` but trades straight-line
    speed for compile time: the 80 rounds become one loop body and the
    message schedule a 64-step scan.  Used on the *cold* verification path
    (a handful of compressions per candidate), where XLA:CPU's LLVM
    pipeline otherwise spends minutes on the unrolled graph; the PBKDF2
    hot loop keeps the unrolled form.
    """
    shape = jnp.broadcast_shapes(*(jnp.shape(u32(w)) for w in block), state[0].shape)
    ws = jnp.stack([jnp.broadcast_to(u32(w), shape) for w in block])

    def sched(w16, _):
        nw = rotl32(w16[13] ^ w16[8] ^ w16[2] ^ w16[0], 1)
        return jnp.concatenate([w16[1:], nw[None]]), nw

    _, tail = jax.lax.scan(sched, ws, None, length=64)
    sched80 = jnp.concatenate([ws, tail])

    def body(t, st):
        a, b, c, d, e = st
        stage = t // 20
        fk = jax.lax.switch(
            stage,
            [
                lambda: ((b & c) | (~b & d)) + u32(K0),
                lambda: (b ^ c ^ d) + u32(K1),
                lambda: ((b & c) | (b & d) | (c & d)) + u32(K2),
                lambda: (b ^ c ^ d) + u32(K3),
            ],
        )
        tmp = rotl32(a, 5) + fk + e + sched80[t]
        return (tmp, a, rotl32(b, 30), c, d)

    out = jax.lax.fori_loop(0, 80, body, tuple(jnp.broadcast_to(s, shape) for s in state))
    return tuple(s + o for s, o in zip(state, out))


def sha1_digest_blocks(blocks, shape=()):
    """Run the compression over a list of 16-word blocks from the IV.

    ``blocks`` must already contain the 0x80 / length padding.  Returns the
    5-tuple digest words.  Convenience path for tests and host-prepped
    fixed-size messages.
    """
    st = sha1_init(shape)
    for blk in blocks:
        st = sha1_compress(st, blk)
    return st
