"""HMAC over the batched word-list primitives.

The WPA pipeline only ever HMACs with keys <= 64 bytes (PSK <= 63, PMK = 32,
KCK = 16), so the key always fits a single hash block and the ipad/opad
states can be precomputed once per candidate — two compressions — and then
reused for every message.  That precomputation is what makes the
PBKDF2 x 4096 loop cost exactly 2 compressions per iteration
(see ops/pbkdf2.py).

Message blocks arriving here must already be padded (host-side, see
utils/bytesops.padded_blocks) with total length accounting for the 64-byte
key block.  Word entries may be Python ints (constants, folded by XLA) or
uint32 arrays broadcast against the batch.

Every function takes an optional ``compress`` argument selecting the
compression implementation: the default unrolled form (best TPU runtime,
used by the PBKDF2 hot loop) or the ``*_compress_rolled`` variants (tiny
XLA graphs, used by the cold verification kernels where XLA:CPU's compile
time on unrolled straight-line code is prohibitive).
"""

from .common import u32
from .md5 import md5_compress, md5_init
from .sha1 import sha1_compress, sha1_init
from .sha256 import sha256_compress, sha256_init

IPAD = 0x36363636
OPAD = 0x5C5C5C5C


def _xor_block(key_block, pad):
    return [u32(w) ^ u32(pad) for w in key_block]


def hmac_sha1_precompute(key_block, shape=(), compress=sha1_compress):
    """key_block: 16 uint32 words (zero-padded key). -> (istate, ostate)."""
    i = compress(sha1_init(shape), _xor_block(key_block, IPAD))
    o = compress(sha1_init(shape), _xor_block(key_block, OPAD))
    return i, o


def hmac_md5_precompute(key_block, shape=(), compress=md5_compress):
    i = compress(md5_init(shape), _xor_block(key_block, IPAD))
    o = compress(md5_init(shape), _xor_block(key_block, OPAD))
    return i, o


def hmac_sha256_precompute(key_block, shape=(), compress=sha256_compress):
    i = compress(sha256_init(shape), _xor_block(key_block, IPAD))
    o = compress(sha256_init(shape), _xor_block(key_block, OPAD))
    return i, o


def _outer_sha1(ostate, inner_digest, compress=sha1_compress):
    # outer message = 20-byte digest; total hashed = 64 (key) + 20 = 84 bytes
    blk = list(inner_digest) + [0x80000000] + [0] * 9 + [84 * 8]
    return compress(ostate, blk)


def hmac_sha1_20(istate, ostate, m5, compress=sha1_compress):
    """HMAC-SHA1 of a 20-byte message given precomputed pad states.

    The PBKDF2 iteration shape: exactly two compressions.
    ``m5``: 5 uint32 word arrays.
    """
    blk = list(m5) + [0x80000000] + [0] * 9 + [84 * 8]
    inner = compress(istate, blk)
    return _outer_sha1(ostate, inner, compress)


def hmac_sha1_20_prologue(istate, ostate):
    """Hoist the per-candidate loop-invariant work of ``hmac_sha1_20``.

    Run once per candidate outside the PBKDF2 loop; the returned pair
    feeds :func:`hmac_sha1_20_hoisted` for all 4096 iterations.
    """
    from .sha1 import sha1_20_prologue

    return (sha1_20_prologue(istate), sha1_20_prologue(ostate))


def hmac_sha1_20_hoisted(pro, m5):
    """HMAC-SHA1 of a 20-byte message from hoisted pad-state prologues.

    Bit-identical to ``hmac_sha1_20`` (both compressions hash a 20-byte
    message: the PBKDF2 U word and the inner digest are each 5 words).
    """
    from .sha1 import sha1_compress_20

    ipro, opro = pro
    inner = sha1_compress_20(ipro, m5)
    return sha1_compress_20(opro, inner)


def hmac_sha1_blocks(istate, ostate, msg_blocks, compress=sha1_compress):
    """HMAC-SHA1 over pre-padded message blocks (after the key block)."""
    st = istate
    for blk in msg_blocks:
        st = compress(st, blk)
    return _outer_sha1(ostate, st, compress)


def hmac_md5_blocks(istate, ostate, msg_blocks, compress=md5_compress):
    """HMAC-MD5 over pre-padded (little-endian word) message blocks."""
    st = istate
    for blk in msg_blocks:
        st = compress(st, blk)
    # outer message = 16-byte digest (4 LE words); total = 64 + 16 = 80 bytes
    blk = list(st) + [0x80] + [0] * 9 + [80 * 8, 0]
    return compress(ostate, blk)


def hmac_sha256_blocks(istate, ostate, msg_blocks, compress=sha256_compress):
    """HMAC-SHA256 over pre-padded message blocks."""
    st = istate
    for blk in msg_blocks:
        st = compress(st, blk)
    # outer message = 32-byte digest; total = 64 + 32 = 96 bytes
    blk = list(st) + [0x80000000] + [0] * 6 + [96 * 8]
    return compress(ostate, blk)
