"""SHA-256 as batched uint32-lane JAX ops (FIPS 180-4).

Used for the WPA2 802.11w keyver=3 PTK derivation
(HMAC-SHA256 PRF, reference semantics: web/common.php:271).
Same unrolled word-list style as SHA-1.
"""

import jax
import jax.numpy as jnp

from .common import rotr32, u32

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

K = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)


def sha256_init(shape=()):
    return tuple(jnp.full(shape, v, jnp.uint32) for v in IV)


def sha256_compress(state, block):
    """One SHA-256 compression over a 16-word (big-endian) block."""
    w = list(block)
    for t in range(16, 64):
        w15 = u32(w[t - 15])
        w2 = u32(w[t - 2])
        s0 = rotr32(w15, 7) ^ rotr32(w15, 18) ^ (w15 >> 3)
        s1 = rotr32(w2, 17) ^ rotr32(w2, 19) ^ (w2 >> 10)
        w.append(u32(w[t - 16]) + s0 + u32(w[t - 7]) + s1)

    a, b, c, d, e, f, g, h = state
    for t in range(64):
        S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + u32(K[t]) + u32(w[t])
        S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        h = g
        g = f
        f = e
        e = d + t1
        d = c
        c = b
        b = a
        a = t1 + t2

    s = state
    return (s[0] + a, s[1] + b, s[2] + c, s[3] + d,
            s[4] + e, s[5] + f, s[6] + g, s[7] + h)


def sha256_compress_rolled(state, block):
    """One SHA-256 compression as a rolled ``fori_loop`` (cold-path variant;
    same compile-time trade as sha1_compress_rolled)."""
    shape = jnp.broadcast_shapes(*(jnp.shape(u32(w)) for w in block), state[0].shape)
    ws = jnp.stack([jnp.broadcast_to(u32(w), shape) for w in block])
    k_arr = jnp.asarray(K, dtype=jnp.uint32)

    def sched(w16, _):
        w15, w2 = w16[1], w16[14]
        s0 = rotr32(w15, 7) ^ rotr32(w15, 18) ^ (w15 >> 3)
        s1 = rotr32(w2, 17) ^ rotr32(w2, 19) ^ (w2 >> 10)
        nw = w16[0] + s0 + w16[9] + s1
        return jnp.concatenate([w16[1:], nw[None]]), nw

    _, tail = jax.lax.scan(sched, ws, None, length=48)
    sched64 = jnp.concatenate([ws, tail])

    def body(t, st):
        a, b, c, d, e, f, g, h = st
        S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k_arr[t] + sched64[t]
        S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + S0 + maj, a, b, c, d + t1, e, f, g)

    out = jax.lax.fori_loop(
        0, 64, body, tuple(jnp.broadcast_to(s, shape) for s in state)
    )
    return tuple(s + o for s, o in zip(state, out))


def sha256_digest_blocks(blocks, shape=()):
    st = sha256_init(shape)
    for blk in blocks:
        st = sha256_compress(st, blk)
    return st
