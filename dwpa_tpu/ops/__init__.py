from . import aes, common, hmac, md5, sha1, sha256  # noqa: F401
