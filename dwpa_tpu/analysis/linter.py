"""AST linter for the repo's JAX contracts (the rules tier-1 runs).

Every rule here encodes a hazard that has actually bitten this codebase
or its reference lineage: silent XLA recompiles, Python control flow
over tracers, host↔device syncs on the feed path, dtype promotions off
the uint32 crypto lattice, and benchmark timings that stop the clock
before the device finishes.  The type system sees none of these; they
surface as throughput collapses or mid-cron crashes on real hardware.

Rule codes (stable — referenced by baseline.json and the docs):

- **DW101 traced-python-branch** — Python ``if``/``while``/ternary/
  ``assert`` over a traced value, or a ``for`` loop iterating a tracer,
  inside a function handed to a trace entry point (``jax.jit``,
  ``shard_map``, ``vmap``, ``lax.scan``/``cond``/..., or the repo's
  ``_shard`` wrapper).  Branching on a tracer either raises a
  ConcretizationTypeError at runtime or — worse — silently bakes one
  branch into the compiled program.
- **DW102 uncached-jit** — ``jax.jit(...)`` whose compiled artifact
  cannot be reused: immediately invoked (``jax.jit(f)(x)``), or created
  inside a loop without being stored in a cache (subscript/attribute
  target).  Each fresh jit object owns a fresh compile cache, so these
  patterns recompile on every call — the exact failure the repo's
  ``_STEP_CACHE`` idiom exists to prevent.
- **DW103 off-lattice-dtype** — a float/int64/complex dtype reference
  inside ``ops/``.  The crypto kernels are uint32-lane by design
  (SHA/MD5/AES schedules); a float or 64-bit promotion silently
  doubles register pressure or truncates on TPU (where x64 is off).
- **DW104 host-sync-in-hot-path** — ``.item()``, dtype-less
  ``np.asarray(...)``, or ``jax.device_get`` in the engine hot-path
  modules (``parallel/step.py``, ``models/m22000.py``).  Each is a
  device→host sync that serializes the pipeline; intentional ones
  (the hits-gate, the rare-find decode) live in the baseline.
- **DW105 unsynced-timed-section** — a ``time.perf_counter()`` span in
  ``bench.py`` that launches device work but never forces completion
  (``block_until_ready``, ``np.asarray``, or an engine ``crack*`` call,
  which sync internally) before the clock stops.  On the tunnelled TPU
  dispatch returns early, so such a span overstates throughput by
  orders of magnitude (see bench.py's timing notes).
- **DW107 feed-thread-discipline** — the candidate-feed contract
  (``dwpa_tpu/feed``), two shapes: (a) a blocking synchronization call
  (``queue.get``/``queue.put``/``join``/``acquire``/``wait`` on a
  queue/lock/event-named receiver) inside a function under a JAX trace
  — a traced region that blocks on host synchronization either fails
  on a tracer or, worse, bakes a one-time value into the compiled
  program while serializing the pipeline it was supposed to overlap;
  (b) a feed producer function (``*produce*`` in ``dwpa_tpu/feed/``)
  touching a jax/jnp device API other than ``device_put``/
  ``shard_candidates`` — producer threads run pure host stages; any
  other device call from a thread races the consumer's dispatch order
  (fatal on a multi-process mesh, where enqueue order is a collective
  contract).
- **DW108 pmkstore-discipline** — the PMK-store contract
  (``dwpa_tpu/pmkstore``), two shapes: (a) store I/O — a ``lookup``/
  ``put``/``flush``/``close`` call on a store-named receiver, or an
  ``mmap`` segment mapping — inside a function under a JAX
  trace: store reads are host mmap/dict work and a traced region that
  touches them either fails on a tracer or bakes one lookup's result
  into the compiled program; (b) a write-back ``<store>.put(...)``
  outside the consumer thread's allowed set (``pmkstore/`` itself and
  the engine's post-fetch write-back in ``models/m22000.py``) — a
  producer-thread or client-side put would race the consumer's append
  ordering and could serialize a traced region on disk I/O.
- **DW109 fused-pad-width** — a ``np.zeros``/``np.empty`` ``[W, 16]``
  row-buffer allocation in the fused-batch packers (``sched/fuse.py``,
  ``pmkstore/stage.py``) whose width does not come from the static
  fused-width pad table (``fused_width``/``miss_width`` or a value
  derived from them).  Per-lane salt/candidate rows entering
  ``pmk_kernel`` at a data-dependent width would retrace the PBKDF2
  step per unit combination — the compile-per-work-unit failure the
  width tables exist to prevent (recompile-sentinel proof in tests).
- **DW110 stream-isolation** — the device-stream contract
  (``parallel/streams.py``, see ``STREAM_FILES``), three shapes: (a) a
  cross-device collective (``psum``/``all_gather``/...) anywhere in the
  file — a stream owns exactly one device, and a collective would
  barrier it against its siblings, reintroducing the lockstep coupling
  streams exist to remove (and deadlocking outright when streams run
  different block counts); (b) a blocking device→host fetch
  (``jax.device_get``/``block_until_ready``) inside a ``for``/``while``
  loop — the per-stream dispatch loop must stay async, its only sync
  being the engine's own hits-gate inside ``_collect``; (c) a
  ``device_put`` without an explicit device/sharding argument — a bare
  put lands on the default device, silently stacking every stream's
  arrays onto device 0 instead of the stream's own chip.
- **DW106 telemetry-discipline** — the obs-layer contract, two shapes:
  (a) a metric/span emission call (``.inc()``/``.dec()``/``.set()``/
  ``.observe()``, excluding jnp's ``x.at[i].set(v)`` functional update)
  inside a function under a JAX trace — telemetry is host-side by
  design, and an emission in traced code either fails on a tracer or
  silently bakes a stale value into the compiled program; (b) an obs
  span (``with tracer.span(...):`` body, or a ``.start(...)``/
  ``.stop()`` pair) in the instrumented files (``SPAN_FILES``) that
  launches device work without forcing completion before the clock
  stops — DW105's device-sync rule, ported to the span API.
- **DW111 dictcache-discipline** — the packed-dictionary-cache contract
  (``dwpa_tpu/feed/dictcache``), two shapes: (a) a dict-cache I/O call
  (``reader``/``writer``/``add_many``/``commit``/``abort``/``chunks``/
  ``evict`` on a cache-named receiver) inside a function under a JAX
  trace — cache reads are host mmap/file work and a traced region that
  touches them either fails on a tracer or bakes one chunk's bytes into
  the compiled program; (b) the same call anywhere outside the feed
  subsystem (``dwpa_tpu/feed/``) — dict-cache reads/writes belong to
  feed producer threads (``DictFeedSource`` drives them under the
  feed's source lock), the same seam discipline as DW107/DW108; client
  or engine code touching the cache directly would put file I/O on the
  consumer's dispatch path.
- **DW112 client-transport-confinement** — the resilient-transport
  contract (``dwpa_tpu/client/``, every file except ``protocol.py``):
  (a) no ``urllib`` import — a raw HTTP exchange outside ``ServerAPI``
  bypasses error classification, retry backoff, the circuit breaker
  and the outbox-backed submission path; (b) no bare ``time.sleep``
  call (nor ``from time import sleep``) — every nap must go through
  the injected ``api.sleep`` so chaos runs drive a virtual clock and
  the degraded-mode crack loop can never be parked on a hidden
  blocking sleep (``time.perf_counter`` and friends stay fine).
- **DW113 rules-device-expansion** — the mesh-aggregate feed contract
  (``STREAM_FILES`` plus the feed subsystem, ``FEED_DIRS``): no
  ``apply_rules(...)`` call or import, and no ``.apply(...)`` on a
  rule-valued receiver.  Device-eligible rules expand ON DEVICE via
  ``build_rules_step`` out of the engine's ``_rules_flush`` seam; a
  host interpreter call on a stream or feed-producer thread would
  re-serialize the expansion the mesh-aggregate path exists to remove
  (the host ships compact base blocks, not expanded candidates).  The
  engine's own host tail (``@``-purge rules, length-overflow pairs)
  lives in ``models/m22000.py``, outside this scope by design.
- **DW114 server-db-atomicity** — the server persistence contract
  (``dwpa_tpu/server/``): two or more ``db.x(...)`` write sites in one
  function body, outside a ``with db.tx():`` block, are a torn-write
  hazard — a crash (or an injected ``chaos.dbfault``) between them
  leaves the ledger half-updated.  Multi-statement sequences belong
  inside ``Database.tx()``; a SINGLE lexical write site is fine even
  in a loop (per-row autocommit around network calls, e.g. geolocate,
  is a deliberate pattern, not a tear).
- **DW115 precrack-scalar-verify** — a per-candidate
  ``check_key_m22000(h, [single_key], ...)`` call inside a ``for``/
  ``while`` loop in server code (``dwpa_tpu/server/``, excluding the
  sanctioned host-oracle fallback seam, ``server/precrack.py``).  Each
  such call pays a full PBKDF2-HMAC-SHA1 (4096 iterations, ~99% of an
  m22000 verdict) on the request/cron thread, once per candidate.
  Candidate sweeps belong behind ``server.precrack`` (``verify_batch``
  / ``PmkBatcher.prewarm``): PMKs derive once per fused mixed-ESSID
  batch, verdicts still finish through the same oracle call — bit-
  identical results, batch-width fewer PBKDF2 runs per sweep.
- **DW116 mask-block-seam** — the framed-mask dispatch contract
  (``STREAM_FILES`` + ``FEED_DIRS`` + the client crack loop and the
  scheduling layers, ``MASK_SEAM_FILES``/``MASK_SEAM_DIRS``): no
  ``mask_words``/``device_mask_words`` import or call, and no direct
  ``MaskPrep(...)`` construction.  Mask keyspace slices travel ONLY as
  the framed blocks ``gen.mask.mask_blocks`` emits — it derives every
  block's ``(offset, count)`` from the ``mask_keyspace``-bounded total,
  so skip/limit resume stays in hashcat ``-s`` coordinates and a
  hand-rolled enumerator can never silently walk past a shard's
  ``limit`` or host-materialize candidates the device generator exists
  to absorb.  ``models/m22000.py`` (the engine's ``_prepare_block``
  device-generation seam and its scalar probe) and the low-volume
  targeted host generators (``client/targeted.py``) are outside the
  scope by design.

The linter is repo-native, not general-purpose: rules are scoped to the
paths where the hazard matters (see ``HOT_PATH_FILES``/``BENCH_FILES``/
``OPS_DIRS``) so the baseline stays small and every entry is a real,
individually-accepted sync or compile.
"""

import ast
import dataclasses
import os
import re

#: files whose host↔device syncs DW104 polices (repo-relative, posix)
HOT_PATH_FILES = ("dwpa_tpu/parallel/step.py", "dwpa_tpu/models/m22000.py")
#: files whose timed sections DW105 polices
BENCH_FILES = ("bench.py",)
#: directories whose dtype lattice DW103 polices
OPS_DIRS = ("dwpa_tpu/ops",)
#: files whose obs spans DW106 polices for the device-sync rule (the
#: span-instrumented surfaces; the in-trace emission check is global)
SPAN_FILES = ("bench.py", "dwpa_tpu/client/main.py")

#: the package whose transport confinement DW112 polices, and the one
#: file inside it allowed to speak raw HTTP / own the backoff sleeps
CLIENT_DIR = "dwpa_tpu/client/"
CLIENT_TRANSPORT_FILE = "dwpa_tpu/client/protocol.py"

#: the package whose multi-statement write atomicity DW114 polices
SERVER_DIR = "dwpa_tpu/server/"
#: the one server file allowed to run per-candidate oracle calls in a
#: loop (DW115): the pre-crack module's own host fallback — the seam
#: every other server-side candidate sweep is routed through
PRECRACK_FALLBACK_FILES = ("dwpa_tpu/server/precrack.py",)

#: metric-emission methods DW106 bans inside traced functions
OBS_EMIT_METHODS = {"inc", "dec", "observe", "set"}

#: PMK-store method calls DW108(a) bans inside traced regions, and the
#: receiver names that mark the call as store I/O (so ``cfg.lookup``
#: stays clean while ``pmk_store.lookup`` / ``self._store.put`` flag)
PMKSTORE_IO_METHODS = {"lookup", "lookup_digests", "put", "flush", "close"}
_PMKSTORE_RECV = re.compile(r"(?i)(pmk_?store$|^store$|^_store$)")
#: the consumer-thread write-back set: the only files allowed to call a
#: store's ``.put`` (DW108(b)) — the store itself and the engine's
#: post-device-fetch write-back seam
PMKSTORE_WRITEBACK_FILES = ("dwpa_tpu/pmkstore/", "dwpa_tpu/models/m22000.py",
                            "dwpa_tpu/server/precrack.py")

#: directories whose producer-thread discipline DW107(b) polices
FEED_DIRS = ("dwpa_tpu/feed",)
#: dict-cache I/O methods DW111 polices, and the receiver names that
#: mark the call as cache I/O (so ``csv.writer(...)``/``conn.commit()``
#: stay clean while ``dict_cache.reader`` / ``self._dcache.evict`` flag)
DICTCACHE_IO_METHODS = {"reader", "writer", "add_many", "commit",
                        "abort", "chunks", "evict"}
_DICTCACHE_RECV = re.compile(r"(?i)(dict_?cache$|^_?cache$|^_?dcache$)")
#: the only files allowed to perform dict-cache I/O (DW111(b)) — the
#: feed subsystem, whose producer threads own the cache seam
DICTCACHE_FEED_FILES = ("dwpa_tpu/feed/",)
#: jax calls a feed producer thread MAY make (H2D staging only)
FEED_PRODUCER_ALLOWED = {"device_put", "shard_candidates"}
#: blocking-sync methods DW107(a) bans inside traced regions, and the
#: receiver names that mark the call as a queue/lock primitive (so
#: ``cfg.get(...)``/``", ".join(...)``/``os.path.join`` stay clean)
BLOCKING_SYNC_METHODS = {"get", "put", "join", "acquire", "wait"}
_BLOCKING_RECV = re.compile(r"(?i)(queue|lock|sem|cond|cv|event|^q|_q)$")

#: callables that put their function argument under a JAX trace
TRACE_ENTRYPOINTS = {
    "jit", "pjit", "vmap", "pmap", "shard_map", "scan", "fori_loop",
    "while_loop", "cond", "switch", "checkpoint", "remat", "grad",
    "value_and_grad", "custom_jvp", "custom_vjp",
    # repo-specific wrappers (parallel/step.py)
    "_shard",
}

#: dtypes allowed in ops/ — the uint32 crypto lattice plus the small
#: integer types the packers use (int32 only as gather/index dtype)
OPS_DTYPE_LATTICE = {
    "uint8", "uint16", "uint32", "uint64", "int32", "bool_", "bool",
}
_BAD_DTYPES = {
    "float16", "float32", "float64", "bfloat16", "float_",
    "int64", "complex64", "complex128",
}

#: calls that force device completion (or are documented to sync
#: internally, like the engine's crack loop via its hits gate)
SYNC_MARKERS = {
    "block_until_ready", "asarray", "item", "array",
    "crack", "crack_batch", "crack_rules", "crack_mask", "crack_blocks",
    "crack_fused", "crack_streams", "run_blocks",
    # rules device-expansion entries: both drain the collect pipeline
    # (the hits gate) before returning, same as crack_rules
    "crack_rules_blocks", "crack_rules_streams",
}

#: files holding per-device stream executors DW110 polices — a stream
#: owns ONE device, so nothing in it may span devices or barrier
STREAM_FILES = ("dwpa_tpu/parallel/streams.py",)
#: cross-device collectives DW110 bans anywhere in STREAM_FILES
STREAM_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "psum_scatter",
}
#: blocking device→host fetches DW110 bans inside a stream's
#: dispatch/pull loops (the only allowed sync is the engine's own
#: hits-gate inside ``_collect``)
STREAM_BLOCKING_FETCHES = {"device_get", "block_until_ready"}

#: receiver names DW113 treats as rule-valued (so ``rule.apply(w)`` /
#: ``rr.apply(...)`` flag while ``df.apply(...)``/``pool.apply(...)``
#: stay clean); the rules-feed scope is STREAM_FILES + FEED_DIRS
_RULE_RECV = re.compile(r"(?i)(rule|^rr?$)")

#: the framed-mask dispatch scope DW116 polices beyond STREAM_FILES and
#: FEED_DIRS: the client crack loop and the scheduling layers — every
#: surface where a mask shard travels as a work unit rather than as the
#: engine's own device-generation seam
MASK_SEAM_FILES = ("dwpa_tpu/client/main.py",)
MASK_SEAM_DIRS = ("dwpa_tpu/sched", "dwpa_tpu/keyspace")
#: raw enumerators DW116 bans off the mask_blocks seam (import or call)
MASK_ENUM_NAMES = {"mask_words", "device_mask_words"}

#: files whose [W, 16] row-buffer allocations DW109 polices — the
#: fused/mixed batch packers that feed per-lane rows to pmk_kernel
FUSED_PAD_FILES = ("dwpa_tpu/sched/fuse.py", "dwpa_tpu/pmkstore/stage.py")
#: width-producing calls DW109 accepts (the static pad tables)
FUSED_WIDTH_SOURCES = {"fused_width", "miss_width"}
#: table-returning calls whose subscript DW109 also accepts
FUSED_WIDTH_TABLES = {"fused_widths", "miss_widths"}


@dataclasses.dataclass(frozen=True)
class Violation:
    code: str     # DWxxx
    path: str     # repo-relative posix path
    line: int
    detail: str   # human message
    snippet: str  # stripped offending source line (baseline fingerprint)

    def fingerprint(self) -> tuple:
        """Baseline identity: survives line-number drift (code moving
        around a file must not churn the baseline), dies with the code
        itself (editing the offending line forces a baseline decision)."""
        return (self.code, self.path, self.snippet)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.detail}"


def _line(src_lines, node) -> str:
    try:
        return src_lines[node.lineno - 1].strip()
    except IndexError:  # pragma: no cover - malformed lineno
        return ""


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _is_np_attr(node, attr: str) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == attr
            and isinstance(node.value, ast.Name)
            and node.value.id in ("np", "numpy"))


class _TaintScrubber(ast.NodeTransformer):
    """Drop subtrees that are static at trace time (shape/dtype/len of a
    tracer is a Python value), so taint checks don't flag branches on
    them."""

    _STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

    def visit_Attribute(self, node):
        if node.attr in self._STATIC_ATTRS:
            return ast.copy_location(ast.Constant(value=0), node)
        return self.generic_visit(node)

    def visit_Call(self, node):
        if isinstance(node.func, ast.Name) and node.func.id in ("len", "range"):
            return ast.copy_location(ast.Constant(value=0), node)
        return self.generic_visit(node)


def _tainted_names(expr, tainted: set) -> set:
    """Names from ``tainted`` that the expression's value can depend on,
    ignoring trace-static subtrees (shapes, dtypes, len())."""
    try:
        scrubbed = _TaintScrubber().visit(ast.fix_missing_locations(
            ast.parse(ast.unparse(expr), mode="eval")))
    except (SyntaxError, ValueError):  # unparsable fragment: be conservative
        scrubbed = expr
    return _names_in(scrubbed) & tainted


def _is_jaxlike_call(call: ast.Call) -> bool:
    """Strict device-value producer (taint source): a call rooted at the
    jnp/jax/lax namespaces."""
    f = call.func
    root = f
    while isinstance(root, ast.Attribute):
        root = root.value
    return isinstance(root, ast.Name) and root.id in ("jnp", "jax", "lax")


def _is_devicework_call(call: ast.Call) -> bool:
    """Loose device-work launcher (bench timed-section heuristic): jax
    namespaces, engine crack* methods, or kernel-named helpers."""
    if _is_jaxlike_call(call):
        return True
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr.startswith("crack"):
        return True
    if isinstance(f, ast.Name) and ("pallas" in f.id or "pbkdf2" in f.id
                                    or f.id.startswith("crack")):
        return True
    return False


# ---------------------------------------------------------------------------
# traced-function discovery + DW101/DW104-in-trace
# ---------------------------------------------------------------------------


def _static_params(call) -> tuple:
    """(names, nums) declared static on a jit-style call: taint must not
    cover them — branching on a static arg is the supported idiom."""
    names, nums = set(), set()
    if not isinstance(call, ast.Call):
        return names, nums
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = (kw.value.elts if isinstance(kw.value, ast.Tuple)
                    else [kw.value])
            names |= {v.value for v in vals
                      if isinstance(v, ast.Constant)
                      and isinstance(v.value, str)}
        elif kw.arg == "static_argnums":
            vals = (kw.value.elts if isinstance(kw.value, ast.Tuple)
                    else [kw.value])
            nums |= {v.value for v in vals
                     if isinstance(v, ast.Constant)
                     and isinstance(v.value, int)}
    return names, nums


def _traced_functions(tree: ast.Module):
    """Yield (funcdef, how, static_names, static_nums) for every function
    the module demonstrably puts under a JAX trace: decorated with a
    trace entry point, or passed (by name or as an inline lambda) to
    one.  static_* carry the entry's static_argnames/argnums so the
    taint analysis exempts those parameters."""
    by_name = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)

    seen = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = (target.attr if isinstance(target, ast.Attribute)
                        else getattr(target, "id", ""))
                if name in TRACE_ENTRYPOINTS and id(node) not in seen:
                    seen.add(id(node))
                    snames, snums = _static_params(
                        dec if isinstance(dec, ast.Call) else None)
                    yield node, f"@{name}", snames, snums
        elif isinstance(node, ast.Call):
            entry = _call_name(node)
            if entry not in TRACE_ENTRYPOINTS:
                continue
            snames, snums = _static_params(node)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda) and id(arg) not in seen:
                    seen.add(id(arg))
                    yield arg, f"lambda->{entry}", snames, snums
                elif (isinstance(arg, ast.Name) and arg.id in by_name
                      and id(by_name[arg.id]) not in seen):
                    seen.add(id(by_name[arg.id]))
                    yield by_name[arg.id], f"{arg.id}->{entry}", snames, snums


def _is_static_test(test) -> bool:
    """``x is None`` / ``x is not None`` is host-level control flow even
    when x may hold a tracer (a tracer is never None), so it is decided
    at trace time — the accumulate-or-init idiom, not a tracer branch."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops)
            and any(isinstance(c, ast.Constant) and c.value is None
                    for c in [test.left] + test.comparators))


def _check_traced_function(fn, how, static_names, static_nums, path,
                           src_lines, out):
    """DW101 inside one traced function: taint params + jnp/lax results,
    flag Python control flow whose condition depends on the taint."""
    args = fn.args
    positional = args.posonlyargs + args.args
    static = set(static_names)
    static |= {positional[i].arg for i in static_nums
               if i < len(positional)}
    tainted = {a.arg for a in (
        positional + args.kwonlyargs
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ) if a.arg != "self" and a.arg not in static}

    body = fn.body if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
        else [ast.Expr(value=fn.body)]

    for node in [n for stmt in body for n in ast.walk(stmt)]:
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = node.value
            if value is None:
                continue
            dep = bool(_tainted_names(value, tainted)) or any(
                _is_jaxlike_call(c)
                for c in ast.walk(value) if isinstance(c, ast.Call))
            if dep:
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    # a subscript store taints the CONTAINER, never the
                    # index expression (byte_cols[p] = ... must not
                    # taint p)
                    base = t.value if isinstance(t, ast.Subscript) else t
                    for n in ast.walk(base):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        elif isinstance(node, (ast.If, ast.While)):
            if _is_static_test(node.test):
                continue
            hits = _tainted_names(node.test, tainted)
            if hits:
                out.append(Violation(
                    "DW101", path, node.lineno,
                    f"Python {'if' if isinstance(node, ast.If) else 'while'} "
                    f"over traced value(s) {sorted(hits)} inside "
                    f"traced function ({how}) — branch on a tracer",
                    _line(src_lines, node)))
        elif isinstance(node, ast.IfExp):
            if _is_static_test(node.test):
                continue
            hits = _tainted_names(node.test, tainted)
            if hits:
                out.append(Violation(
                    "DW101", path, node.lineno,
                    f"ternary over traced value(s) {sorted(hits)} inside "
                    f"traced function ({how})", _line(src_lines, node)))
        elif isinstance(node, ast.Assert):
            hits = _tainted_names(node.test, tainted)
            if hits:
                out.append(Violation(
                    "DW101", path, node.lineno,
                    f"assert over traced value(s) {sorted(hits)} inside "
                    f"traced function ({how})", _line(src_lines, node)))
        elif isinstance(node, ast.For):
            # iterating the tracer ITSELF (bare name/attribute) unrolls
            # per element; zip/enumerate over python containers of
            # tracers is static and fine.
            it = node.iter
            if isinstance(it, (ast.Name, ast.Attribute)):
                hits = _names_in(it) & tainted
                if hits:
                    out.append(Violation(
                        "DW101", path, node.lineno,
                        f"for loop iterates traced value {sorted(hits)} "
                        f"inside traced function ({how})",
                        _line(src_lines, node)))
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            if name in ("int", "float", "bool") and node.args:
                hits = _tainted_names(node.args[0], tainted)
                if hits:
                    out.append(Violation(
                        "DW104", path, node.lineno,
                        f"{name}() concretizes traced value(s) "
                        f"{sorted(hits)} inside traced function ({how}) — "
                        "host sync / ConcretizationTypeError",
                        _line(src_lines, node)))
            elif (name in OBS_EMIT_METHODS
                    and isinstance(node.func, ast.Attribute)
                    and not _is_at_update(node.func)):
                out.append(Violation(
                    "DW106", path, node.lineno,
                    f"metric/span emission .{name}() inside traced "
                    f"function ({how}) — telemetry is host-side only; "
                    "record after the device call returns",
                    _line(src_lines, node)))
            elif (name in BLOCKING_SYNC_METHODS
                    and isinstance(node.func, ast.Attribute)
                    and _BLOCKING_RECV.search(_recv_name(node.func))):
                out.append(Violation(
                    "DW107", path, node.lineno,
                    f"blocking .{name}() on "
                    f"'{_recv_name(node.func)}' inside traced function "
                    f"({how}) — queue/lock waits are host-side; a trace "
                    "either fails on it or bakes a one-time value in "
                    "while serializing the pipeline",
                    _line(src_lines, node)))
            elif (name == "mmap"
                    or (name in PMKSTORE_IO_METHODS
                        and isinstance(node.func, ast.Attribute)
                        and _PMKSTORE_RECV.search(_recv_name(node.func)))):
                out.append(Violation(
                    "DW108", path, node.lineno,
                    f"pmkstore I/O {name}() inside traced function "
                    f"({how}) — store reads/writes are host mmap/dict "
                    "work; a trace either fails on them or bakes one "
                    "lookup's result into the compiled program",
                    _line(src_lines, node)))
            elif (name in DICTCACHE_IO_METHODS
                    and isinstance(node.func, ast.Attribute)
                    and _DICTCACHE_RECV.search(_recv_name(node.func))):
                out.append(Violation(
                    "DW111", path, node.lineno,
                    f"dictcache I/O {name}() inside traced function "
                    f"({how}) — packed-dict cache reads/writes are "
                    "producer-thread host work (mmap/file I/O); a trace "
                    "either fails on them or bakes one chunk's bytes "
                    "into the compiled program",
                    _line(src_lines, node)))


def _is_at_update(f: ast.Attribute) -> bool:
    """jnp's functional update ``x.at[i].set(v)`` (or any subscripted
    base) is array code, not telemetry — exempt from the DW106
    emission check."""
    return any(isinstance(n, ast.Subscript) for n in ast.walk(f.value))


def _recv_name(f: ast.Attribute) -> str:
    """Last identifier of a method call's receiver (``self._queue.get``
    -> ``_queue``; ``q.get`` -> ``q``; constants/calls -> "")."""
    base = f.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""


# ---------------------------------------------------------------------------
# DW107(b): feed producer thread discipline
# ---------------------------------------------------------------------------


def _check_feed_producers(tree, path, src_lines, out):
    """In ``dwpa_tpu/feed/``: a producer function (name contains
    "produce" — the subsystem's documented naming convention for code
    that runs on producer threads) may touch NO jax/jnp/lax call beyond
    the allowed H2D staging pair."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if "produce" not in fn.name:
            continue
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and _is_jaxlike_call(node)):
                continue
            name = _call_name(node)
            if name in FEED_PRODUCER_ALLOWED:
                continue
            out.append(Violation(
                "DW107", path, node.lineno,
                f"feed producer {fn.name}() calls jax device API "
                f"'{name}' — producer threads are pure host stages; "
                "only device_put/shard_candidates (H2D staging) are "
                "allowed off the consumer thread",
                _line(src_lines, node)))


# ---------------------------------------------------------------------------
# DW108(b): PMK-store write-back outside the consumer thread's allowed set
# ---------------------------------------------------------------------------


def _check_pmkstore_writeback(tree, path, src_lines, out):
    """Outside ``PMKSTORE_WRITEBACK_FILES``: any ``<store>.put(...)`` is
    a write-back from the wrong seam — producer threads and client code
    must only LOOK UP; appends belong to the engine's consumer-thread
    post-fetch write-back (or the store's own internals)."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and _call_name(node) == "put"
                and isinstance(node.func, ast.Attribute)
                and _PMKSTORE_RECV.search(_recv_name(node.func))):
            out.append(Violation(
                "DW108", path, node.lineno,
                f"pmkstore write-back .put() on "
                f"'{_recv_name(node.func)}' outside the consumer-thread "
                f"allowed set ({', '.join(PMKSTORE_WRITEBACK_FILES)}) — "
                "newly derived PMKs are written back only after the "
                "engine's device fetch", _line(src_lines, node)))


# ---------------------------------------------------------------------------
# DW111(b): dict-cache I/O outside the feed subsystem
# ---------------------------------------------------------------------------


def _check_dictcache_io(tree, path, src_lines, out):
    """Outside ``DICTCACHE_FEED_FILES``: any dict-cache I/O call is on
    the wrong seam — the packed-dict cache is read and written by feed
    producer threads (``DictFeedSource``); client/engine code holds a
    ``DictCache`` handle only to pass it INTO the feed."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and _call_name(node) in DICTCACHE_IO_METHODS
                and isinstance(node.func, ast.Attribute)
                and _DICTCACHE_RECV.search(_recv_name(node.func))):
            out.append(Violation(
                "DW111", path, node.lineno,
                f"dictcache I/O .{_call_name(node)}() on "
                f"'{_recv_name(node.func)}' outside the feed subsystem "
                f"({', '.join(DICTCACHE_FEED_FILES)}) — dict-cache "
                "reads/writes belong to feed producer threads",
                _line(src_lines, node)))


# ---------------------------------------------------------------------------
# DW102 uncached jit
# ---------------------------------------------------------------------------


def _is_jit_ref(node) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit")
    if isinstance(node, ast.Name):
        return node.id in ("jit", "pjit")
    return False


def _check_uncached_jit(tree, path, src_lines, out):
    class V(ast.NodeVisitor):
        def __init__(self):
            self.loop_depth = 0
            self.cached_jits = set()  # jit Call nodes stored to a cache

        def _mark_cached(self, value):
            for n in ast.walk(value):
                if isinstance(n, ast.Call) and _is_jit_ref(n.func):
                    self.cached_jits.add(id(n))

        def visit_Assign(self, node):
            if any(isinstance(t, (ast.Subscript, ast.Attribute))
                   for t in node.targets):
                self._mark_cached(node.value)
            self.generic_visit(node)

        def visit_For(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_While = visit_For

        def visit_Call(self, node):
            # jax.jit(f)(x): the jit object dies with the statement, so
            # every execution is a fresh trace + compile
            if (isinstance(node.func, ast.Call)
                    and _is_jit_ref(node.func.func)):
                out.append(Violation(
                    "DW102", path, node.lineno,
                    "jit result invoked immediately — fresh compile cache "
                    "per call (store the jitted fn once and reuse it)",
                    _line(src_lines, node)))
            elif (_is_jit_ref(node.func) and self.loop_depth > 0
                    and id(node) not in self.cached_jits):
                out.append(Violation(
                    "DW102", path, node.lineno,
                    "jax.jit(...) created inside a loop without a cache "
                    "(subscript/attribute store) — recompiles every "
                    "iteration", _line(src_lines, node)))
            self.generic_visit(node)

    V().visit(tree)


# ---------------------------------------------------------------------------
# DW103 ops/ dtype lattice
# ---------------------------------------------------------------------------


def _check_ops_dtypes(tree, path, src_lines, out):
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute) and node.attr in _BAD_DTYPES
                and isinstance(node.value, ast.Name)
                and node.value.id in ("np", "numpy", "jnp")):
            out.append(Violation(
                "DW103", path, node.lineno,
                f"dtype {node.value.id}.{node.attr} is off the uint32 "
                f"crypto lattice (allowed: {sorted(OPS_DTYPE_LATTICE)})",
                _line(src_lines, node)))
        elif (isinstance(node, ast.Call) and _call_name(node) == "astype"
                and node.args and isinstance(node.args[0], ast.Constant)
                and str(node.args[0].value) in _BAD_DTYPES):
            out.append(Violation(
                "DW103", path, node.lineno,
                f"astype({node.args[0].value!r}) is off the uint32 crypto "
                "lattice", _line(src_lines, node)))


# ---------------------------------------------------------------------------
# DW104 host syncs in hot-path modules
# ---------------------------------------------------------------------------


def _check_hot_path_syncs(tree, path, src_lines, out):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
            out.append(Violation(
                "DW104", path, node.lineno,
                ".item() is a device->host sync on the hot path",
                _line(src_lines, node)))
        elif _is_np_attr(f, "asarray") or _is_np_attr(f, "array"):
            # dtype= marks the host-packing idiom (pure host data);
            # a dtype-less np.asarray of a device value is THE implicit
            # transfer+sync this rule exists for.
            if not any(kw.arg == "dtype" for kw in node.keywords):
                out.append(Violation(
                    "DW104", path, node.lineno,
                    f"np.{f.attr}(...) without dtype= in a hot-path module "
                    "— implicit device->host sync if fed a device value",
                    _line(src_lines, node)))
        elif (isinstance(f, ast.Attribute) and f.attr == "device_get"):
            out.append(Violation(
                "DW104", path, node.lineno,
                "jax.device_get is a device->host sync on the hot path",
                _line(src_lines, node)))


# ---------------------------------------------------------------------------
# DW105 bench timed sections
# ---------------------------------------------------------------------------


def _is_clock_call(node) -> bool:
    return (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("perf_counter", "monotonic", "time")
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time")


def _check_timed_sections(tree, path, src_lines, out):
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stmts = fn.body
        for i, stmt in enumerate(stmts):
            if not (isinstance(stmt, ast.Assign) and _is_clock_call(stmt.value)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            t_name = stmt.targets[0].id
            # find the stop: first later statement computing clock() - t_name
            stop = None
            for j in range(i + 1, len(stmts)):
                for n in ast.walk(stmts[j]):
                    if (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub)
                            and _is_clock_call(n.left)
                            and isinstance(n.right, ast.Name)
                            and n.right.id == t_name):
                        stop = j
                        break
                if stop is not None:
                    break
            if stop is None:
                continue
            region = stmts[i + 1:stop]
            calls = [n for s in region for n in ast.walk(s)
                     if isinstance(n, ast.Call)]
            launches = any(_is_devicework_call(c) for c in calls)
            synced = any(_call_name(c) in SYNC_MARKERS for c in calls)
            if launches and not synced:
                out.append(Violation(
                    "DW105", path, stmt.lineno,
                    f"timed section '{t_name}' in {fn.name}() launches "
                    "device work but never forces completion "
                    "(block_until_ready / np.asarray / engine crack*) "
                    "before the clock stops", _line(src_lines, stmt)))


# ---------------------------------------------------------------------------
# DW106 span device-sync discipline (the obs-layer DW105)
# ---------------------------------------------------------------------------


def _is_span_open(call: ast.Call) -> bool:
    """``<tracer>.span(name...)`` / ``<tracer>.start(name...)`` — the obs
    span API.  The name argument requirement keeps zero-arg ``.start()``
    (threads, servers) out of scope."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr in ("span", "start")
            and bool(call.args or call.keywords))


def _has_sync_kwarg(call: ast.Call) -> bool:
    """``span(..., sync=...)`` / ``stop(sync=...)``: the API's built-in
    fetch-before-clock-stop — counts as synced."""
    return any(kw.arg == "sync" and not (isinstance(kw.value, ast.Constant)
                                         and kw.value.value is None)
               for kw in call.keywords)


def _region_sync_violation(region, opener, label, fn_name, path,
                           src_lines, out):
    calls = [n for s in region for n in ast.walk(s)
             if isinstance(n, ast.Call)]
    launches = any(_is_devicework_call(c) for c in calls)
    synced = any(_call_name(c) in SYNC_MARKERS for c in calls)
    if launches and not synced:
        out.append(Violation(
            "DW106", path, opener.lineno,
            f"span '{label}' in {fn_name}() launches device work but "
            "never forces completion (engine crack* / np.asarray / "
            "block_until_ready / sync=) before the clock stops",
            _line(src_lines, opener)))


def _span_label(call: ast.Call) -> str:
    if call.args and isinstance(call.args[0], ast.Constant):
        return str(call.args[0].value)
    return "<dynamic>"


def _check_span_sync(tree, path, src_lines, out):
    seen_withs = set()  # a With in a nested def is walked by both defs
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # with <tracer>.span(...) [as sp]: — the region is the body
        for node in ast.walk(fn):
            if not isinstance(node, ast.With) or id(node) in seen_withs:
                continue
            seen_withs.add(id(node))
            for item in node.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Call) and _is_span_open(ce)
                        and ce.func.attr == "span"
                        and not _has_sync_kwarg(ce)):
                    _region_sync_violation(
                        node.body, node, _span_label(ce), fn.name,
                        path, src_lines, out)
        # sp = <tracer>.start(...) ... sp.stop() — statement-scoped,
        # like DW105's clock pairs
        stmts = fn.body
        for i, stmt in enumerate(stmts):
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    and _is_span_open(stmt.value)
                    and stmt.value.func.attr == "start"
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            sp_name = stmt.targets[0].id
            stop = stop_call = None
            for j in range(i + 1, len(stmts)):
                for n in ast.walk(stmts[j]):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr == "stop"
                            and isinstance(n.func.value, ast.Name)
                            and n.func.value.id == sp_name):
                        stop, stop_call = j, n
                        break
                if stop is not None:
                    break
            if stop is None or _has_sync_kwarg(stop_call):
                continue
            _region_sync_violation(
                stmts[i + 1:stop], stmt, _span_label(stmt.value), fn.name,
                path, src_lines, out)


def _check_fused_pad_widths(tree, path, src_lines, out):
    """DW109: ``[W, 16]`` row buffers in the fused-batch packers must
    take ``W`` from the static fused-width pad table.

    A width expression is accepted when it provably resolves to the
    tables: a constant, a ``fused_width``/``miss_width`` call, a
    subscript of ``fused_widths``/``miss_widths``, a ``max``/``min``
    over accepted values, a conditional whose branches are accepted, or
    a local name every assignment of which is accepted.  Anything else
    (a parameter, ``len(...)``, arithmetic on a count) is a
    data-dependent pad width — each distinct value retraces the fused
    PBKDF2 step, the compile-per-unit-combination failure the tables
    exist to prevent."""
    seen = set()  # nested defs are walked by their enclosing def too
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        assigns = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                assigns.setdefault(node.targets[0].id, []).append(node.value)

        def accepted(expr, trail=()):
            if isinstance(expr, ast.Constant):
                return True
            if isinstance(expr, ast.Call):
                name = _call_name(expr)
                if name in FUSED_WIDTH_SOURCES:
                    return True
                if name in ("max", "min"):
                    return all(accepted(a, trail) for a in expr.args)
                return False
            if isinstance(expr, ast.Subscript):
                return (isinstance(expr.value, ast.Call)
                        and _call_name(expr.value) in FUSED_WIDTH_TABLES)
            if isinstance(expr, ast.IfExp):
                return (accepted(expr.body, trail)
                        and accepted(expr.orelse, trail))
            if isinstance(expr, ast.Name):
                if expr.id in trail:  # assignment cycle: refuse
                    return False
                vals = assigns.get(expr.id)
                return bool(vals) and all(
                    accepted(v, trail + (expr.id,)) for v in vals)
            return False

        for node in ast.walk(fn):
            if (id(node) in seen
                    or not isinstance(node, ast.Call)
                    or not _is_np_attr(node.func, "zeros")
                    and not _is_np_attr(node.func, "empty")):
                continue
            seen.add(id(node))
            if not (node.args and isinstance(node.args[0], ast.Tuple)
                    and len(node.args[0].elts) == 2):
                continue
            w, cols = node.args[0].elts
            if not (isinstance(cols, ast.Constant) and cols.value == 16):
                continue
            if not accepted(w):
                out.append(Violation(
                    "DW109", path, node.lineno,
                    f"[W, 16] row buffer in {fn.name}() has a "
                    "data-dependent width — per-lane rows entering "
                    "pmk_kernel must be padded to the static fused-width "
                    "pad table (fused_width/miss_width)",
                    _line(src_lines, node)))


def _check_stream_discipline(tree, path, src_lines, out):
    """DW110: per-device stream isolation (``STREAM_FILES``).

    (a) no cross-device collective anywhere in the file — a stream owns
    one device, and a ``psum``/``all_gather`` would barrier it against
    its siblings (or deadlock when streams run different block counts);
    (b) no blocking ``jax.device_get``/``block_until_ready`` inside a
    ``for``/``while`` loop — the dispatch/pull loops stay async, the
    only sync being the engine's hits-gate inside ``_collect``; (c)
    every ``device_put`` carries an explicit device/sharding (second
    positional or ``device=``/``sharding=`` kwarg) — a bare put lands
    every stream's arrays on the default device."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in STREAM_COLLECTIVES:
            out.append(Violation(
                "DW110", path, node.lineno,
                f"cross-device collective {name}() in a device-stream "
                "module — a stream owns one device; a collective "
                "barriers it against its siblings (lockstep coupling, "
                "or deadlock on uneven block counts)",
                _line(src_lines, node)))
        elif name == "device_put":
            explicit = len(node.args) >= 2 or any(
                kw.arg in ("device", "sharding") for kw in node.keywords)
            if not explicit:
                out.append(Violation(
                    "DW110", path, node.lineno,
                    "device_put without an explicit device/sharding — "
                    "a bare put lands on the default device, stacking "
                    "every stream's arrays onto device 0",
                    _line(src_lines, node)))
    seen = set()  # nested loops are walked by their enclosing loop too
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for node in ast.walk(loop):
            if (id(node) in seen or not isinstance(node, ast.Call)
                    or _call_name(node) not in STREAM_BLOCKING_FETCHES):
                continue
            seen.add(id(node))
            out.append(Violation(
                "DW110", path, node.lineno,
                f"blocking {_call_name(node)}() inside a stream loop — "
                "the per-stream dispatch loop must stay async; the "
                "only allowed sync is the engine's hits-gate inside "
                "_collect",
                _line(src_lines, node)))


def _check_client_transport(tree, path, src_lines, out):
    """DW112: transport confinement in the client package (every file
    under ``CLIENT_DIR`` except ``CLIENT_TRANSPORT_FILE``).

    (a) any ``urllib`` import — raw HTTP outside ``ServerAPI`` bypasses
    error classification, retry backoff, the circuit breaker and the
    outbox-backed submission path; (b) a bare ``time.sleep(...)`` call
    or ``from time import sleep`` — naps must be the injected
    ``api.sleep`` so chaos runs drive a virtual clock and the degraded
    crack loop is never parked on a hidden blocking sleep."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "urllib" for a in node.names):
                out.append(Violation(
                    "DW112", path, node.lineno,
                    "urllib import outside client/protocol.py — raw HTTP "
                    "here bypasses the retry/classification/circuit-"
                    "breaker stack; route the call through ServerAPI",
                    _line(src_lines, node)))
        elif isinstance(node, ast.ImportFrom):
            root_mod = (node.module or "").split(".")[0]
            if root_mod == "urllib":
                out.append(Violation(
                    "DW112", path, node.lineno,
                    "urllib import outside client/protocol.py — raw HTTP "
                    "here bypasses the retry/classification/circuit-"
                    "breaker stack; route the call through ServerAPI",
                    _line(src_lines, node)))
            elif (root_mod == "time"
                  and any(a.name == "sleep" for a in node.names)):
                out.append(Violation(
                    "DW112", path, node.lineno,
                    "time.sleep imported outside client/protocol.py — "
                    "naps must go through the injected api.sleep so the "
                    "chaos harness can drive them off a virtual clock",
                    _line(src_lines, node)))
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "sleep"
                    and _recv_name(f) == "time"):
                out.append(Violation(
                    "DW112", path, node.lineno,
                    "bare time.sleep() outside client/protocol.py — the "
                    "crack loop must nap through the injected api.sleep "
                    "(virtual-clock testable, and degraded mode is never "
                    "blocked behind a hidden sleep)",
                    _line(src_lines, node)))


def _check_rules_device_expansion(tree, path, src_lines, out):
    """DW113: no host rule interpretation on the mesh-aggregate feed
    path (``STREAM_FILES`` + ``FEED_DIRS``).

    (a) any ``apply_rules(...)`` call or ``apply_rules`` import — the
    host expansion loop re-serializes exactly the work the device
    ``build_rules_step`` path exists to absorb; (b) ``.apply(...)`` on
    a rule-valued receiver (``rule``/``rr``/``*_rule`` names) — a
    single-rule interpreter call is the same hazard one word at a time.
    Purge/overflow fallbacks belong to the engine's ``_rules_flush``
    host tail (``models/m22000.py``), not to streams or feed
    producers."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if any(a.name == "apply_rules" for a in node.names):
                out.append(Violation(
                    "DW113", path, node.lineno,
                    "apply_rules imported on the mesh-aggregate feed "
                    "path — streams and feed producers ship compact "
                    "base-word blocks; rule expansion runs on device "
                    "via the engine's _rules_flush seam",
                    _line(src_lines, node)))
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            if name == "apply_rules":
                out.append(Violation(
                    "DW113", path, node.lineno,
                    "host apply_rules() on the mesh-aggregate feed path "
                    "— device-eligible rules expand on device "
                    "(build_rules_step); host interpretation here "
                    "re-serializes the expansion and re-inflates H2D "
                    "bytes by the rule count",
                    _line(src_lines, node)))
            elif (name == "apply" and isinstance(node.func, ast.Attribute)
                  and _RULE_RECV.search(_recv_name(node.func))):
                out.append(Violation(
                    "DW113", path, node.lineno,
                    f"rule interpreter .apply() on "
                    f"'{_recv_name(node.func)}' in stream/feed-producer "
                    "code — per-word host mangling belongs to the "
                    "engine's purge/overflow tail (models/m22000.py), "
                    "never to the feed path",
                    _line(src_lines, node)))


# ---------------------------------------------------------------------------
# DW114: server db write atomicity
# ---------------------------------------------------------------------------


def _is_db_tx_with(node: ast.With) -> bool:
    """True for ``with <db>.tx():`` (receiver named ``db`` — covers
    ``db``, ``self.db``, ``core.db``)."""
    for item in node.items:
        ctx = item.context_expr
        if (isinstance(ctx, ast.Call) and isinstance(ctx.func, ast.Attribute)
                and ctx.func.attr == "tx"
                and _recv_name(ctx.func) == "db"):
            return True
    return False


def _check_server_db_atomicity(tree, path, src_lines, out):
    """DW114: >=2 lexical ``db.x(...)`` write sites in one function,
    outside any ``with db.tx():`` block.

    Counts call SITES, not executions: one ``db.x`` inside a loop is a
    deliberate per-row-autocommit pattern (safe to tear between rows —
    each row is self-contained); two sites mean two statements whose
    combined effect the caller almost certainly assumed atomic.  Nested
    function bodies are analyzed separately so an inner helper's write
    never inflates its parent's count."""

    def visit(node, in_tx, sites):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scope: counted on its own visit
        if isinstance(node, ast.With) and _is_db_tx_with(node):
            in_tx = True
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr == "x"
                and _recv_name(node.func) == "db" and not in_tx):
            sites.append(node)
        for child in ast.iter_child_nodes(node):
            visit(child, in_tx, sites)

    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sites = []
        for stmt in node.body:
            visit(stmt, False, sites)
        if len(sites) >= 2:
            first = sites[0]
            out.append(Violation(
                "DW114", path, first.lineno,
                f"{len(sites)} db.x() write sites in {node.name}() outside "
                "Database.tx() — a crash between them tears the ledger; "
                "wrap the sequence in 'with db.tx():' (or self.db.tx())",
                _line(src_lines, first)))


# ---------------------------------------------------------------------------
# DW115: server-side scalar candidate verification
# ---------------------------------------------------------------------------


def _check_precrack_scalar_verify(tree, path, src_lines, out):
    """DW115: ``check_key_m22000(h, [one_key], ...)`` — second argument
    a single-element list literal — lexically inside a ``for``/``while``
    loop, in server code outside the pre-crack fallback seam.

    The single-element-list shape is the scalar tell: a batched call
    passes the whole candidate list (a name or comprehension) and lets
    the oracle scan it, while ``[k]`` in a loop means one full PBKDF2
    derivation per iteration on the request/cron thread.  Matching
    call nodes are deduplicated so nested loops flag each site once."""
    flagged = set()
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for node in ast.walk(loop):
            if (isinstance(node, ast.Call)
                    and _call_name(node) == "check_key_m22000"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.List)
                    and len(node.args[1].elts) == 1
                    and id(node) not in flagged):
                flagged.add(id(node))
                out.append(Violation(
                    "DW115", path, node.lineno,
                    "per-candidate check_key_m22000(h, [key]) inside a "
                    "loop — one full PBKDF2 per iteration on the server "
                    "thread; route the sweep through server.precrack "
                    "(verify_batch / PmkBatcher.prewarm), which derives "
                    "PMKs once per fused mixed-ESSID batch and finishes "
                    "verdicts through the same oracle",
                    _line(src_lines, node)))


# ---------------------------------------------------------------------------
# DW116: framed-mask dispatch seam
# ---------------------------------------------------------------------------


def _check_mask_block_seam(tree, path, src_lines, out):
    """DW116: in the mask-dispatch scope, keyspace slices travel only as
    the framed blocks ``gen.mask.mask_blocks`` emits.

    (a) ``mask_words``/``device_mask_words`` import or call — a raw
    enumerator on the dispatch path either host-materializes candidates
    the device generator exists to absorb or re-derives block framing by
    hand; (b) direct ``MaskPrep(...)`` construction (or its import) — a
    hand-built prep carries whatever ``start`` the caller typed, while
    ``mask_blocks`` derives every ``(offset, count)`` from the
    ``mask_keyspace``-bounded total, keeping skip/limit resume exact in
    hashcat ``-s`` coordinates."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name in MASK_ENUM_NAMES or a.name == "MaskPrep":
                    out.append(Violation(
                        "DW116", path, node.lineno,
                        f"{a.name} imported on the mask-dispatch path — "
                        "mask shards travel only as mask_blocks' framed "
                        "MaskPrep blocks (mask_keyspace-derived framing, "
                        "hashcat -s/-l resume coordinates)",
                        _line(src_lines, node)))
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            if name in MASK_ENUM_NAMES:
                out.append(Violation(
                    "DW116", path, node.lineno,
                    f"raw mask enumerator {name}() on the mask-dispatch "
                    "path — frame the slice through gen.mask.mask_blocks "
                    "and let the engine's _prepare_block seam generate "
                    "on device", _line(src_lines, node)))
            elif name == "MaskPrep":
                out.append(Violation(
                    "DW116", path, node.lineno,
                    "direct MaskPrep(...) construction outside "
                    "gen/mask.py — a hand-built prep bypasses "
                    "mask_blocks' keyspace-bounded (offset, count) "
                    "framing; resume offsets drift off hashcat -s "
                    "coordinates", _line(src_lines, node)))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def lint_source(src: str, path: str) -> list:
    """Lint one file's source; ``path`` is the repo-relative posix path
    (rule scoping keys off it).  Returns a list of Violations."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Violation("DW100", path, e.lineno or 0,
                          f"syntax error: {e.msg}", "")]
    src_lines = src.splitlines()
    out = []
    for fn, how, snames, snums in _traced_functions(tree):
        _check_traced_function(fn, how, snames, snums, path, src_lines, out)
    _check_uncached_jit(tree, path, src_lines, out)
    if path.startswith(tuple(d + "/" for d in OPS_DIRS)):
        _check_ops_dtypes(tree, path, src_lines, out)
    if path in HOT_PATH_FILES:
        _check_hot_path_syncs(tree, path, src_lines, out)
    if path in BENCH_FILES:
        _check_timed_sections(tree, path, src_lines, out)
    if path in SPAN_FILES:
        _check_span_sync(tree, path, src_lines, out)
    if path.startswith(tuple(d + "/" for d in FEED_DIRS)):
        _check_feed_producers(tree, path, src_lines, out)
    if not path.startswith(PMKSTORE_WRITEBACK_FILES):
        _check_pmkstore_writeback(tree, path, src_lines, out)
    if not path.startswith(DICTCACHE_FEED_FILES):
        _check_dictcache_io(tree, path, src_lines, out)
    if path in FUSED_PAD_FILES:
        _check_fused_pad_widths(tree, path, src_lines, out)
    if path in STREAM_FILES:
        _check_stream_discipline(tree, path, src_lines, out)
    if (path in STREAM_FILES
            or path.startswith(tuple(d + "/" for d in FEED_DIRS))):
        _check_rules_device_expansion(tree, path, src_lines, out)
    if (path in STREAM_FILES or path in MASK_SEAM_FILES
            or path.startswith(tuple(
                d + "/" for d in FEED_DIRS + MASK_SEAM_DIRS))):
        _check_mask_block_seam(tree, path, src_lines, out)
    if path.startswith(CLIENT_DIR) and path != CLIENT_TRANSPORT_FILE:
        _check_client_transport(tree, path, src_lines, out)
    if path.startswith(SERVER_DIR):
        _check_server_db_atomicity(tree, path, src_lines, out)
        if path not in PRECRACK_FALLBACK_FILES:
            _check_precrack_scalar_verify(tree, path, src_lines, out)
    return out


def lint_file(full_path: str, root: str) -> list:
    rel = os.path.relpath(full_path, root).replace(os.sep, "/")
    with open(full_path, encoding="utf-8") as f:
        return lint_source(f.read(), rel)


def lint_tree(root: str) -> list:
    """Lint every tracked .py file under ``root`` (skipping caches,
    hidden dirs and the test tree — tests intentionally seed
    violations)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if not d.startswith(".") and d not in (
                "__pycache__", "tests", "build", "dist"))
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(lint_file(os.path.join(dirpath, name), root))
    return [v for vs in out for v in vs]
