"""Whole-program concurrency analysis: lock order, shared state, thread
confinement (rule family DW3xx).

The reference dwpa serializes everything behind one global SHM lock
(get_work.php:49); this port is genuinely concurrent — feed producers,
per-device stream workers, the executor's unit producer, the server's
queue materializer and cron thread, plus every WSGI request thread.  The
per-function linter (DW1xx) cannot see a deadlock: a lock-order
inversion needs the *call graph* (who holds what when calling whom).
This pass builds that graph over the package AST — module-level, no
imports executed — and checks four hazards:

- **DW301 lock-order-inversion** — a cycle in the static
  lock-acquisition-order graph.  Nodes are lock identities (a
  ``threading.Lock/RLock/Condition/Semaphore`` assignment site,
  canonicalized as ``Class.attr`` / module-global name, plus the
  synthetic ``Database.tx`` node for ``with db.tx():`` blocks); an edge
  A→B means some thread can acquire B while holding A, found by
  propagating the held-lock set through the call graph.  A cycle is a
  deadlock schedule: two threads entering the cycle from different
  edges block each other forever.  The canonical repo order is
  ``_getwork_lock`` FIRST, then ``tx()`` (server/core.py) — any path
  taking them in reverse is exactly the PR-12 hand-fixed bug this rule
  exists to catch.  Reentrant self-edges (RLock) are ignored.
- **DW302 unguarded-shared-write** — a module global or ``self.``
  attribute written from ≥2 thread roots with no common guarding lock.
  A thread root is every resolved ``threading.Thread(target=...)``
  plus the synthetic *main* root (externally-callable functions).  A
  write's guard set is the locks lexically held at the write plus the
  locks every caller provably holds around the call (must-intersection
  over call sites).  ``__init__`` writes are exempt (``Thread.start()``
  is a happens-before barrier), as are lock/thread-valued attributes.
- **DW303 blocking-while-locked** — a blocking call (``queue.get`` /
  ``<thread>.join`` / ``<lock>.acquire`` / ``<cv>.wait`` without a
  timeout) made while holding a lock: hold-and-wait, half of a
  deadlock, and a liveness cliff even alone (every sibling of that
  lock stalls behind an unbounded wait).  A ``Condition.wait`` whose
  receiver is itself the held lock is exempt — waiting releases it
  (the feed's backpressure wait); any *other* lock still held flags.
- **DW304 db-handle-escape** — a raw sqlite connection (``*.conn``)
  dereference, or a private ``Database`` method call (``db._exec``
  style), reachable from ≥2 thread roots outside the ``_exec``/``tx()``
  funnel in server/db.py.  Every cross-thread statement must go
  through the funnel: it is the single serialization point (one RLock)
  and the chaos harness's fault-injection seam — a handle that escapes
  it bypasses both, and sqlite check_same_thread=False makes the race
  silent until a torn write.

Heuristics and their bias: lock identity is canonicalized by defining
class + attribute name; an attribute assigned a lock in more than one
class merges into a wildcard ``*.attr`` node (guard matching treats the
wildcard as compatible with any class's attr — biased against false
DW302 positives).  Call resolution is name-based with a deny list of
ubiquitous method names and a fan-out cap, biased toward missing exotic
dispatch rather than drowning the baseline.  The runtime half of this
family (:mod:`.lockwatch`) witnesses the *actual* acquisition order
under the chaos soaks, covering what the static pass abstracts away.
"""

import ast
import dataclasses
import os
import re
import time

from .linter import Violation, _line

#: threading constructors whose assignment defines a lock identity
LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
#: of those, the ones whose .wait() releases the lock itself
CONDITION_CTORS = {"Condition"}

#: blocking-sync methods DW303 polices when called without a timeout
BLOCKING_METHODS = {"get", "join", "acquire", "wait"}
#: receiver-name pattern marking a call as queue/lock/thread-primitive
#: (same shape as the linter's DW107 receiver gate)
_BLOCKING_RECV = re.compile(
    r"(?i)(queue|lock|sem|cond|cv|event|thread|feeder|worker|producer"
    r"|^q$|_q$|^t$)")

#: attribute names DW302 never treats as shared data (synchronization
#: objects and thread handles are written once and used via their API)
_SYNC_ATTR = re.compile(
    r"(?i)(lock|mutex|sem$|semaphore|cond|_cv$|event|thread|_tl$)")

#: mutating container methods DW302 counts as writes to the receiver
MUTATOR_METHODS = {"append", "extend", "add", "update", "insert", "remove",
                   "discard", "clear", "pop", "popleft", "appendleft",
                   "setdefault", "push", "push_many"}

#: method names too ubiquitous to resolve by name across the package
_NO_RESOLVE = {"get", "put", "pop", "append", "add", "update", "close",
               "items", "keys", "values", "join", "split", "strip", "read",
               "write", "open", "run", "start", "set", "clear", "copy",
               "encode", "decode", "hex", "acquire", "release", "wait",
               "notify", "notify_all", "sleep", "now", "info", "debug",
               "warning", "error", "exception", "q", "q1", "x", "send"}
#: resolution fan-out cap: a simple name mapping to more distinct
#: functions than this is too ambiguous to follow
_MAX_FANOUT = 4

#: the public Database API (server/db.py) a handle may cross threads on
DB_PUBLIC_API = {"q", "q1", "x", "tx", "close", "path"}
#: methods of Database itself allowed to touch self.conn (the funnel)
DB_FUNNEL_METHODS = {"__init__", "_exec", "close", "tx"}
#: receiver names DW304 treats as a Database handle
_DB_RECV = re.compile(r"(?i)(^db$|^_db$|_db$|^database$|^conn$)")

#: runnable --explain examples for the DW3xx rules
EXAMPLES = {
    "DW301": """\
# BAD: two threads, opposite acquisition order -> deadlock schedule
def refill(self):                     # thread A
    with self.db.tx():                # tx() first ...
        with self._getwork_lock:      # ... then the scheduler mutex
            ...
def get_work(self):                   # thread B (canonical order)
    with self._getwork_lock:          # scheduler mutex FIRST,
        with self.db.tx():            # then tx() -- every path must agree
            ...""",
    "DW302": """\
# BAD: producer and consumer threads both write self.stats bare
def _produce(self):                   # thread root 1
    self.stats["fed"] += 1
def _collect(self):                   # thread root 2
    self.stats["done"] += 1
# GOOD: a common guard (or confine writes to one thread)
def _produce(self):
    with self._lock:
        self.stats["fed"] += 1""",
    "DW303": """\
# BAD: unbounded blocking call while holding a lock (hold-and-wait)
with self._lock:
    item = self.work_queue.get()      # stalls every sibling of _lock
# GOOD: bound the wait, or drop the lock first
with self._lock:
    item = self.work_queue.get(timeout=5.0)""",
    "DW304": """\
# BAD: raw sqlite handle used off the funnel from a worker thread
def _drain(self):                     # thread root
    self.db.conn.execute("DELETE FROM leases")   # bypasses Database._lock
# GOOD: cross threads only through the funnel
def _drain(self):
    self.db.x("DELETE FROM leases")   # serialized + chaos-injectable""",
}


# ---------------------------------------------------------------------------
# module collection
# ---------------------------------------------------------------------------


def _walk_py(root):
    """Yield (relpath, source) for the same file set lint_tree covers."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if not d.startswith(".") and d not in (
                "__pycache__", "tests", "build", "dist"))
        for name in sorted(filenames):
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, encoding="utf-8") as f:
                    yield rel, f.read()


@dataclasses.dataclass
class _Func:
    qname: str          # "path::Class.name" / "path::name" / nested "a.b"
    path: str           # repo-relative posix path
    cls: str            # enclosing class name or ""
    name: str           # bare function name
    node: object        # the ast.FunctionDef
    src_lines: list
    parent: str = ""    # enclosing function qname (nested defs)
    # analysis outputs (filled by _analyze_body)
    acq: set = dataclasses.field(default_factory=set)
    edges: dict = dataclasses.field(default_factory=dict)
    calls: list = dataclasses.field(default_factory=list)
    blocking: list = dataclasses.field(default_factory=list)
    writes: list = dataclasses.field(default_factory=list)
    conn_uses: list = dataclasses.field(default_factory=list)
    spawns: list = dataclasses.field(default_factory=list)
    local_locks: dict = dataclasses.field(default_factory=dict)


class _Program:
    """The package-wide index: functions, locks, and name tables."""

    def __init__(self):
        self.funcs = {}            # qname -> _Func
        self.by_name = {}          # bare name -> [qname]
        self.by_cls = {}           # (path, cls, name) -> qname
        self.by_mod = {}           # (path, name) -> qname (module level)
        self.attr_locks = {}       # attr -> {"Cls.attr", ...}
        self.mod_locks = {}        # (path, name) -> "path:name"
        self.cond_ids = set()      # lock ids built from Condition()
        self.mod_globals = set()   # (path, name) mutable module globals

    def lock_classes(self, attr):
        return self.attr_locks.get(attr, set())


def _is_lock_ctor(value):
    """The lock constructor name if ``value``'s subtree builds a
    threading primitive (covers shard-lock list comprehensions)."""
    for node in ast.walk(value):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in LOCK_CTORS
                and isinstance(f.value, ast.Name)
                and f.value.id == "threading"):
            return f.attr
        if isinstance(f, ast.Name) and f.id in LOCK_CTORS:
            return f.id
    return None


def build_program(root) -> "_Program":
    prog = _Program()
    for rel, src in _walk_py(root):
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # DW100 is the linter's business
        src_lines = src.splitlines()
        _index_module(prog, rel, tree, src_lines)
    return prog


def _index_module(prog, rel, tree, src_lines):
    def add_func(node, cls, parent):
        qname = (f"{rel}::{cls}.{node.name}" if cls
                 else (f"{parent}.{node.name}" if parent
                       else f"{rel}::{node.name}"))
        fn = _Func(qname, rel, cls, node.name, node, src_lines,
                   parent=parent)
        prog.funcs[qname] = fn
        prog.by_name.setdefault(node.name, []).append(qname)
        if cls:
            prog.by_cls[(rel, cls, node.name)] = qname
        elif not parent:
            prog.by_mod[(rel, node.name)] = qname
        for child in node.body:
            index_stmt(child, cls="", parent=qname)

    def index_stmt(node, cls, parent):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add_func(node, cls, parent)
        elif isinstance(node, ast.ClassDef):
            for child in node.body:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    add_func(child, node.name, "")
            _index_class_locks(prog, node)
        elif isinstance(node, ast.Assign) and not parent and not cls:
            ctor = _is_lock_ctor(node.value)
            if ctor:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lid = f"{rel}:{t.id}"
                        prog.mod_locks[(rel, t.id)] = lid
                        if ctor in CONDITION_CTORS:
                            prog.cond_ids.add(lid)

    for node in tree.body:
        index_stmt(node, cls="", parent="")


def _index_class_locks(prog, cls_node):
    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign):
            continue
        ctor = _is_lock_ctor(node.value)
        if not ctor:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                lid = f"{cls_node.name}.{t.attr}"
                prog.attr_locks.setdefault(t.attr, set()).add(lid)
                if ctor in CONDITION_CTORS:
                    prog.cond_ids.add(lid)


# ---------------------------------------------------------------------------
# per-function analysis
# ---------------------------------------------------------------------------


def _recv_root(expr):
    """Innermost Name of an attribute/subscript chain, or None."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _resolve_lock(prog, fn, expr):
    """Lock identity for an acquisition-site expression, or None."""
    if isinstance(expr, ast.Subscript):      # self._locks[i] (shard lists)
        return _resolve_lock(prog, fn, expr.value)
    if isinstance(expr, ast.Name):
        if expr.id in fn.local_locks:
            return fn.local_locks[expr.id]
        return prog.mod_locks.get((fn.path, expr.id))
    if isinstance(expr, ast.Attribute):
        attr = expr.attr
        owners = prog.lock_classes(attr)
        if (isinstance(expr.value, ast.Name) and expr.value.id == "self"
                and fn.cls and f"{fn.cls}.{attr}" in owners):
            return f"{fn.cls}.{attr}"
        if len(owners) == 1:
            return next(iter(owners))
        if len(owners) > 1:
            return f"*.{attr}"       # ambiguous: wildcard-merged identity
    return None


def _tx_lock(expr):
    """The synthetic Database.tx lock for ``with X.tx():`` items."""
    if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "tx" and not expr.args):
        return "Database.tx"
    return None


def _has_timeout(call, method):
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if method in ("join", "wait") and call.args:
        return True                       # join(10) / wait(0.5)
    if method in ("get", "acquire") and len(call.args) >= 2:
        return True                       # get(block, timeout)
    return False


def _analyze_body(prog, fn):
    """Walk one function body tracking the lexically held lock set;
    fill the function's acq/edges/calls/blocking/writes/conn/spawns."""
    src = fn.src_lines

    def note_edge(held, lid, node):
        fn.acq.add(lid)
        if lid in held:
            return          # reentrant re-acquisition orders nothing
        for h in held:
            if h != lid:
                fn.edges.setdefault(
                    (h, lid), (fn.path, node.lineno, _line(src, node)))

    def walk_expr(node, held):
        for call in _own_calls(node):
            handle_call(call, held)

    def handle_call(call, held):
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        # thread spawns
        if name == "Thread":
            target = next((kw.value for kw in call.keywords
                           if kw.arg == "target"), None)
            if target is not None:
                fn.spawns.append((target, call.lineno))
        # blocking-sync sites (DW303 raw material)
        if isinstance(f, ast.Attribute) and name in BLOCKING_METHODS:
            recv_lock = _resolve_lock(prog, fn, f.value)
            recv_name = (f.value.attr if isinstance(f.value, ast.Attribute)
                         else _recv_root(f.value) or "")
            if ((recv_lock or _BLOCKING_RECV.search(recv_name or ""))
                    and not _has_timeout(call, name)):
                fn.blocking.append((name, recv_lock, frozenset(held),
                                    call.lineno, _line(src, call)))
        # explicit lock.acquire() also orders locks
        if isinstance(f, ast.Attribute) and name == "acquire":
            lid = _resolve_lock(prog, fn, f.value)
            if lid:
                note_edge(held, lid, call)
        # mutating container methods = writes (DW302 raw material)
        if (isinstance(f, ast.Attribute) and name in MUTATOR_METHODS
                and isinstance(f.value, (ast.Attribute, ast.Subscript,
                                         ast.Name))):
            note_write_target(f.value, call, held)
        # db-handle escapes (DW304 raw material)
        if (isinstance(f, ast.Attribute) and name.startswith("_")
                and name not in DB_FUNNEL_METHODS
                and isinstance(f.value, (ast.Name, ast.Attribute))):
            recv = (f.value.attr if isinstance(f.value, ast.Attribute)
                    else f.value.id)
            if _DB_RECV.search(recv or "") and recv != "conn":
                fn.conn_uses.append(("private call", call.lineno,
                                     _line(src, call)))
        # call-graph site
        callees = _resolve_call(prog, fn, call)
        if callees:
            fn.calls.append((callees, frozenset(held), call.lineno,
                             _line(src, call)))

    def note_write_target(target, node, held):
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and fn.cls):
            if _SYNC_ATTR.search(base.attr):
                return
            fn.writes.append((f"{fn.cls}.{base.attr}", frozenset(held),
                              node.lineno, _line(src, node)))
        elif isinstance(base, ast.Name):
            if (fn.path, base.id) in prog.mod_globals:
                fn.writes.append((f"{fn.path}:{base.id}", frozenset(held),
                                  node.lineno, _line(src, node)))

    def walk_block(stmts, held):
        held = list(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes analyzed on their own
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for item in stmt.items:
                    lid = (_resolve_lock(prog, fn, item.context_expr)
                           or _tx_lock(item.context_expr))
                    walk_expr(item.context_expr, inner)
                    if lid:
                        note_edge(inner, lid, item.context_expr)
                        inner.append(lid)
                walk_block(stmt.body, inner)
                continue
            # local lock definitions
            if isinstance(stmt, ast.Assign):
                ctor = _is_lock_ctor(stmt.value)
                if ctor:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            lid = f"{fn.qname}:{t.id}"
                            fn.local_locks[t.id] = lid
                            if ctor in CONDITION_CTORS:
                                prog.cond_ids.add(lid)
            # explicit acquire/release pairs widen/narrow the held set
            if (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Attribute)):
                m = stmt.value.func.attr
                lid = _resolve_lock(prog, fn, stmt.value.func.value)
                if lid and m == "acquire":
                    walk_expr(stmt, held)
                    note_edge(held, lid, stmt.value)
                    held.append(lid)
                    continue
                if lid and m == "release" and lid in held:
                    held.remove(lid)
                    continue
            # assignment targets = writes
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    for el in ast.walk(t):
                        if isinstance(el, (ast.Attribute, ast.Name)):
                            note_write_target(el, stmt, held)
                            break
            walk_expr(stmt, held)
            for child_block in _sub_blocks(stmt):
                walk_block(child_block, held)

    walk_block(fn.node.body, [])


def _own_calls(node):
    """Call nodes in ``node``'s own expressions — does NOT descend into
    nested statement blocks (walk_block recurses into those itself, so
    descending here would record every nested site twice) nor into
    nested ``def`` bodies (analyzed as their own functions)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            yield n
        for field, value in ast.iter_fields(n):
            if isinstance(n, ast.stmt) and field in (
                    "body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.AST):
                stack.append(value)
            elif isinstance(value, list):
                stack.extend(v for v in value if isinstance(v, ast.AST))


def _sub_blocks(stmt):
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if block:
            yield block
    for handler in getattr(stmt, "handlers", []) or []:
        yield handler.body


def _resolve_call(prog, fn, call):
    f = call.func
    if isinstance(f, ast.Name):
        # nested def in the enclosing function chain wins
        scope = fn.qname
        while scope:
            q = f"{scope}.{f.id}"
            if q in prog.funcs:
                return [q]
            scope = prog.funcs[scope].parent if scope in prog.funcs else ""
        q = prog.by_mod.get((fn.path, f.id))
        if q:
            return [q]
        cands = [c for c in prog.by_name.get(f.id, ())
                 if not prog.funcs[c].cls]
        return cands if 0 < len(cands) <= _MAX_FANOUT else []
    if isinstance(f, ast.Attribute):
        name = f.attr
        if isinstance(f.value, ast.Name) and f.value.id == "self" and fn.cls:
            q = prog.by_cls.get((fn.path, fn.cls, name))
            if q:
                return [q]
        if name in _NO_RESOLVE:
            return []
        cands = prog.by_name.get(name, ())
        return list(cands) if 0 < len(cands) <= _MAX_FANOUT else []
    return []


def _resolve_target(prog, fn, target):
    """A Thread(target=...) expression -> function qname, or None."""
    if isinstance(target, ast.Name):
        r = _resolve_call(prog, fn, ast.Call(
            func=ast.Name(id=target.id, ctx=ast.Load()), args=[],
            keywords=[]))
        return r[0] if r else None
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self" and fn.cls):
        return prog.by_cls.get((fn.path, fn.cls, target.attr))
    if isinstance(target, ast.Attribute):
        cands = prog.by_name.get(target.attr, ())
        return cands[0] if len(cands) == 1 else None
    return None


# ---------------------------------------------------------------------------
# whole-program propagation
# ---------------------------------------------------------------------------


def _collect_globals(prog, root):
    """Module-level mutable globals (non-lock, non-constant targets):
    the names DW302 tracks writes to."""
    prog.mod_globals = set()
    for rel, src in _walk_py(root):
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Name) and not t.id.isupper()
                            and not _is_lock_ctor(node.value)
                            and not _SYNC_ATTR.search(t.id)):
                        prog.mod_globals.add((rel, t.id))


def _fixpoint_acq(prog):
    """acq*(f) = locks f may acquire, transitively."""
    star = {q: set(fn.acq) for q, fn in prog.funcs.items()}
    changed = True
    while changed:
        changed = False
        for q, fn in prog.funcs.items():
            for callees, _, _, _ in fn.calls:
                for c in callees:
                    extra = star.get(c, set()) - star[q]
                    if extra:
                        star[q] |= extra
                        changed = True
    return star


def _entry_held(prog):
    """Per-function caller-held sets: may (union) and must
    (intersection) over every call site, propagated to fixpoint."""
    callers = {}          # callee -> [(caller, held)]
    for q, fn in prog.funcs.items():
        for callees, held, _, _ in fn.calls:
            for c in callees:
                callers.setdefault(c, []).append((q, held))
    may = {q: set() for q in prog.funcs}
    must = {q: None for q in prog.funcs}     # None = unconstrained (top)
    for _ in range(len(prog.funcs)):
        changed = False
        for q in prog.funcs:
            sites = callers.get(q)
            if not sites:
                if must[q] is None:
                    must[q] = set()
                continue
            new_may = set()
            new_must = None
            for caller, held in sites:
                site_held = set(held) | may[caller]
                new_may |= site_held
                site_must = set(held) | (must[caller] or set())
                new_must = (site_must if new_must is None
                            else new_must & site_must)
            if new_may != may[q] or new_must != (must[q] or set()):
                may[q], must[q] = new_may, new_must
                changed = True
        if not changed:
            break
    return may, {q: (m or set()) for q, m in must.items()}


def _thread_roots(prog):
    """{root label: reachable qname set}; spawned targets plus the
    synthetic 'main' root (uncalled, unspawned functions = the API)."""
    callees_of = {q: set() for q in prog.funcs}
    called = set()
    for q, fn in prog.funcs.items():
        for cs, _, _, _ in fn.calls:
            callees_of[q] |= set(cs)
            called |= set(cs)

    def reach(seeds):
        seen, stack = set(seeds), list(seeds)
        while stack:
            q = stack.pop()
            for c in callees_of.get(q, ()):
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        return seen

    roots = {}
    spawn_targets = set()
    for q, fn in prog.funcs.items():
        for target, _ in fn.spawns:
            t = _resolve_target(prog, fn, target)
            if t:
                spawn_targets.add(t)
                roots[f"thread:{t}"] = None
    for label in list(roots):
        roots[label] = reach([label.split(":", 1)[1]])
    main_entries = [q for q in prog.funcs
                    if q not in called and q not in spawn_targets]
    roots["main"] = reach(main_entries)
    return roots


def _guard_compatible(guard, held):
    """True if ``held`` contains ``guard`` or its wildcard twin."""
    if guard in held:
        return True
    attr = guard.split(".", 1)[-1]
    return any(h == f"*.{attr}" or (guard.startswith("*.")
                                    and h.split(".", 1)[-1] == attr)
               for h in held)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _check_dw301(prog, acq_star, out):
    edges = {}                          # (a, b) -> witness
    for q, fn in prog.funcs.items():
        for e, w in fn.edges.items():
            edges.setdefault(e, w)
        for callees, held, lineno, snippet in fn.calls:
            for c in callees:
                for lid in acq_star.get(c, ()):
                    if lid in held:
                        continue    # reentrant re-acquire: orders nothing
                    for h in held:
                        if h != lid:
                            edges.setdefault((h, lid),
                                             (fn.path, lineno, snippet))
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    seen_cycles = set()
    for start in sorted(graph):
        stack, on_path = [(start, iter(sorted(graph.get(start, ()))))], [start]
        visited = set()
        while stack:
            node, it = stack[-1]
            nxt = next(it, None)
            if nxt is None:
                stack.pop()
                on_path.pop()
                continue
            if nxt == start and len(on_path) > 1:
                cyc = tuple(on_path)
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    _emit_cycle(edges, cyc, out)
                continue
            if nxt in on_path or nxt in visited:
                continue
            visited.add(nxt)
            on_path.append(nxt)
            stack.append((nxt, iter(sorted(graph.get(nxt, ())))))


def _emit_cycle(edges, cyc, out):
    ring = list(cyc) + [cyc[0]]
    legs = []
    witness = None
    for a, b in zip(ring, ring[1:]):
        w = edges.get((a, b))
        if w:
            legs.append(f"{a}->{b} at {w[0]}:{w[1]}")
            witness = witness or w
    if witness is None:                   # pragma: no cover - edges exist
        return
    path, line, snippet = witness
    out.append(Violation(
        "DW301", path, line,
        "lock-order inversion: acquisition-order cycle "
        + " -> ".join(list(cyc) + [cyc[0]]) + " ("
        + "; ".join(legs) + ") — two threads entering from different "
        "edges deadlock; make every path agree on one order",
        snippet))


def _check_dw302(prog, entry_must, roots, out):
    roots_of = {}
    for label, reach in roots.items():
        for q in reach:
            roots_of.setdefault(q, set()).add(label)
    groups = {}          # shared key -> [(qname, guards, line, snippet)]
    for q, fn in prog.funcs.items():
        if fn.name == "__init__":
            continue     # happens-before Thread.start()
        for key, held, lineno, snippet in fn.writes:
            guards = set(held) | entry_must.get(q, set())
            groups.setdefault(key, []).append((q, guards, lineno, snippet))
    for key, sites in sorted(groups.items()):
        writer_roots = set()
        for q, _, _, _ in sites:
            writer_roots |= roots_of.get(q, set())
        if len(writer_roots) < 2:
            continue
        all_guards = set().union(*(g for _, g, _, _ in sites))
        if any(all(_guard_compatible(g, guards)
                   for _, guards, _, _ in sites) for g in all_guards):
            continue
        q, guards, lineno, snippet = next(
            (s for s in sites if not s[1]), sites[0])
        fn = prog.funcs[q]
        out.append(Violation(
            "DW302", fn.path, lineno,
            f"shared state {key!r} written from {len(writer_roots)} thread "
            f"roots ({', '.join(sorted(writer_roots))}) without a common "
            "guarding lock — guard every write site with one lock or "
            "confine writes to a single thread",
            snippet))


def _check_dw303(prog, entry_may, out):
    for q, fn in prog.funcs.items():
        for method, recv_lock, held, lineno, snippet in fn.blocking:
            effective = set(held) | entry_may.get(q, set())
            if recv_lock:
                # waiting on / re-acquiring the lock you hold releases
                # or reenters it (Condition.wait, reentrant RLock)
                effective.discard(recv_lock)
                if recv_lock.startswith("*."):
                    attr = recv_lock[2:]
                    effective = {h for h in effective
                                 if h.split(".", 1)[-1] != attr}
            if effective:
                out.append(Violation(
                    "DW303", fn.path, lineno,
                    f"blocking .{method}() with no timeout while holding "
                    f"{sorted(effective)} — hold-and-wait stalls every "
                    "sibling of the held lock (and is half a deadlock); "
                    "bound the wait or release the lock first",
                    snippet))


def _check_dw304(prog, roots, out):
    roots_of = {}
    for label, reach in roots.items():
        for q in reach:
            roots_of.setdefault(q, set()).add(label)
    for q, fn in prog.funcs.items():
        uses = list(fn.conn_uses)
        for node in ast.walk(fn.node):
            if (isinstance(node, ast.Attribute) and node.attr == "conn"
                    and isinstance(node.value, (ast.Name, ast.Attribute))):
                recv = (node.value.id if isinstance(node.value, ast.Name)
                        else node.value.attr)
                if recv == "self" and fn.cls:
                    recv = fn.cls.lower()
                if _DB_RECV.search(recv or ""):
                    uses.append(("raw .conn access", node.lineno,
                                 _line(fn.src_lines, node)))
        if not uses:
            continue
        if (fn.path.endswith("server/db.py")
                and fn.name in DB_FUNNEL_METHODS):
            continue                      # the funnel itself
        if len(roots_of.get(q, set())) < 2:
            continue                      # confined to one thread root
        for what, lineno, snippet in uses:
            out.append(Violation(
                "DW304", fn.path, lineno,
                f"sqlite handle crosses thread roots "
                f"({', '.join(sorted(roots_of[q]))}) via {what} outside "
                "the Database._exec/tx() funnel — route every cross-"
                "thread statement through db.q/q1/x/tx so one RLock "
                "serializes it (and chaos faults can reach it)",
                snippet))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def check_concurrency(root: str, timings: dict = None) -> list:
    """Run DW301–DW304 against the tree at ``root``.  Returns a list of
    linter.Violation; fills ``timings`` (rule code -> seconds) when a
    dict is passed."""
    t0 = time.perf_counter()
    prog = build_program(root)
    _collect_globals(prog, root)
    for fn in prog.funcs.values():
        _analyze_body(prog, fn)
    acq_star = _fixpoint_acq(prog)
    entry_may, entry_must = _entry_held(prog)
    roots = _thread_roots(prog)
    if timings is not None:
        timings["graph"] = time.perf_counter() - t0

    out = []
    for code, check, args in (
            ("DW301", _check_dw301, (prog, acq_star)),
            ("DW302", _check_dw302, (prog, entry_must, roots)),
            ("DW303", _check_dw303, (prog, entry_may)),
            ("DW304", _check_dw304, (prog, roots))):
        t1 = time.perf_counter()
        check(*args, out)
        if timings is not None:
            timings[code] = time.perf_counter() - t1
    return out
