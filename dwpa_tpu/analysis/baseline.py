"""Violations baseline: land the linter green, then ratchet.

The baseline (``analysis/baseline.json``, checked in next to this
module) records the violations the repo has individually accepted —
e.g. the engine's intentional hits-gate syncs.  A lint run fails only
on violations NOT absorbed by the baseline, so new hazards are caught
while accepted ones don't nag; fixing an accepted violation leaves a
stale baseline entry, which the CLI reports as a ratchet opportunity
(tighten with ``--update-baseline``) without failing the run.

Entries match on ``(code, path, snippet)`` — the stripped offending
source line — NOT on line numbers, so unrelated edits moving code
around a file never churn the baseline, while editing the offending
line itself forces an explicit re-accept.  Duplicate identical lines
are handled by multiplicity: an entry absorbs at most ``count``
matching violations.

Each entry may carry a ``why`` — the one-line justification for
accepting it (JSON has no comments, so the rationale lives in the
entry itself).  ``--update-baseline`` preserves the ``why`` of every
surviving entry, so a re-ratchet never silently drops the reasoning;
new entries land with an empty ``why`` to be filled in by the author.
"""

import json
import os

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str = None) -> dict:
    """{(code, path, snippet): count}; empty when no baseline exists."""
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out = {}
    for entry in data.get("violations", []):
        key = (entry["code"], entry["path"], entry["snippet"])
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def apply_baseline(violations, baseline: dict):
    """Split ``violations`` into (new, absorbed, stale_entries).

    ``new``: violations no baseline entry absorbs (these fail the run).
    ``absorbed``: violations covered by the baseline.
    ``stale_entries``: baseline keys with leftover multiplicity — the
    violation was fixed; the baseline can ratchet down.
    """
    budget = dict(baseline)
    new, absorbed = [], []
    for v in violations:
        key = v.fingerprint()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            absorbed.append(v)
        else:
            new.append(v)
    stale = sorted(k for k, n in budget.items() if n > 0)
    return new, absorbed, stale


def load_whys(path: str = None) -> dict:
    """{(code, path, snippet): why} for entries carrying a rationale."""
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {
        (e["code"], e["path"], e["snippet"]): e["why"]
        for e in data.get("violations", []) if e.get("why")
    }


def write_baseline(violations, path: str = None):
    """Serialize the current violation set as the new baseline,
    carrying over the ``why`` of every entry that survives."""
    path = path or DEFAULT_BASELINE
    whys = load_whys(path)
    counts = {}
    lines = {}
    for v in violations:
        key = v.fingerprint()
        counts[key] = counts.get(key, 0) + 1
        lines.setdefault(key, v.line)
    entries = [
        {"code": code, "path": p, "snippet": snip, "count": n,
         "line_hint": lines[(code, p, snip)],
         "why": whys.get((code, p, snip), "")}
        for (code, p, snip), n in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "violations": entries}, f, indent=2,
                  sort_keys=False)
        f.write("\n")
    return path
