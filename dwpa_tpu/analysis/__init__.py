"""dwpa_tpu.analysis — repo-native static analysis + runtime sentinels.

Three layers of defense against the bug species the type system cannot
see (the round-5 advisor findings were all of this species):

- :mod:`.linter` — AST rules for the JAX hot paths (tracer branches,
  uncached jits, off-lattice dtypes, hot-path host syncs, unsynced
  bench timings).  Rule codes DW10x.
- :mod:`.contracts` — static cross-layer diff of the client protocol
  fields vs the server handlers vs the sqlite schema.  Codes DW20x.
- :mod:`.concurrency` — whole-program lock-order / shared-state /
  thread-confinement analysis over the package call graph (deadlock
  schedules, unguarded cross-thread writes, hold-and-wait, sqlite
  handles escaping the funnel).  Codes DW30x.
- :mod:`.recompile` — runtime recompilation sentinel (context manager
  + pytest fixture) that counts XLA compile-cache misses and fails a
  sweep that recompiles per batch.
- :mod:`.lockwatch` — runtime lock-order witness: instrumented
  Lock/RLock wrappers record the actual acquisition-order graph during
  a test and fail at teardown if it has a cycle (the dynamic half of
  DW301, wired into the chaos soaks).

Run standalone with ``python -m dwpa_tpu.analysis`` (exit 0 = clean
under the checked-in baseline); tier-1 runs the same pass via
``tests/test_analysis.py``.  See INSTALL.md ("Static analysis") for
rule-code interpretation and the baseline-update workflow.
"""

import os
import time

from .baseline import (DEFAULT_BASELINE, apply_baseline, load_baseline,
                       write_baseline)
from .concurrency import check_concurrency
from .contracts import check_contracts
from .linter import Violation, lint_source, lint_tree
from .lockwatch import (LockOrderError, LockWitness, watch_locks,
                        witness_report)
from .recompile import (CompileReport, RecompilationError, no_recompiles,
                        watch_compiles)

__all__ = [
    "Violation", "lint_source", "lint_tree", "check_contracts",
    "check_concurrency", "watch_compiles", "no_recompiles",
    "RecompilationError", "CompileReport", "LockOrderError", "LockWitness",
    "watch_locks", "witness_report", "load_baseline", "apply_baseline",
    "write_baseline", "DEFAULT_BASELINE", "repo_root", "run_analysis",
]


def repo_root() -> str:
    """The tree this package ships in (…/dwpa_tpu/analysis/../..)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_violations(root: str = None, timings: dict = None) -> list:
    """Full pass: lint every source file + the cross-layer contracts +
    the whole-program concurrency analysis.  ``timings`` (when a dict is
    passed) gains per-pass/per-rule wall-clock seconds."""
    root = root or repo_root()
    t0 = time.perf_counter()
    violations = lint_tree(root)
    if timings is not None:
        timings["lint"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    try:
        violations += check_contracts(root)
    except FileNotFoundError:
        # a partial tree (e.g. a fixture dir) has no protocol layers
        pass
    if timings is not None:
        timings["contracts"] = time.perf_counter() - t0
    violations += check_concurrency(root, timings=timings)
    return violations


def run_analysis(root: str = None, baseline_path: str = None,
                 update_baseline: bool = False, log=print) -> int:
    """The CLI/test entry point.  Returns a process exit code:
    0 = clean under the baseline, 1 = new violations."""
    root = root or repo_root()
    timings = {}
    violations = collect_violations(root, timings=timings)
    timed = " ".join(f"{k}={v:.2f}s" for k, v in timings.items())
    if update_baseline:
        path = write_baseline(violations, baseline_path)
        log(f"baseline updated: {len(violations)} accepted violation(s) "
            f"-> {path}")
        return 0
    new, absorbed, stale = apply_baseline(
        violations, load_baseline(baseline_path))
    for v in new:
        log(v.render())
    if absorbed:
        log(f"{len(absorbed)} violation(s) absorbed by baseline")
    if stale:
        log(f"{len(stale)} stale baseline entrie(s) — fixed violations; "
            "ratchet with --update-baseline:")
        for code, path, snippet in stale:
            log(f"  {code} {path}: {snippet}")
    if new:
        log(f"FAILED: {len(new)} new violation(s) [{timed}]")
        return 1
    log(f"OK: {len(violations)} violation(s), all baselined [{timed}]")
    return 0
