"""dwpa_tpu.analysis — repo-native static analysis + runtime sentinels.

Three layers of defense against the bug species the type system cannot
see (the round-5 advisor findings were all of this species):

- :mod:`.linter` — AST rules for the JAX hot paths (tracer branches,
  uncached jits, off-lattice dtypes, hot-path host syncs, unsynced
  bench timings).  Rule codes DW10x.
- :mod:`.contracts` — static cross-layer diff of the client protocol
  fields vs the server handlers vs the sqlite schema.  Codes DW20x.
- :mod:`.recompile` — runtime recompilation sentinel (context manager
  + pytest fixture) that counts XLA compile-cache misses and fails a
  sweep that recompiles per batch.

Run standalone with ``python -m dwpa_tpu.analysis`` (exit 0 = clean
under the checked-in baseline); tier-1 runs the same pass via
``tests/test_analysis.py``.  See INSTALL.md ("Static analysis") for
rule-code interpretation and the baseline-update workflow.
"""

import os

from .baseline import (DEFAULT_BASELINE, apply_baseline, load_baseline,
                       write_baseline)
from .contracts import check_contracts
from .linter import Violation, lint_source, lint_tree
from .recompile import (CompileReport, RecompilationError, no_recompiles,
                        watch_compiles)

__all__ = [
    "Violation", "lint_source", "lint_tree", "check_contracts",
    "watch_compiles", "no_recompiles", "RecompilationError",
    "CompileReport", "load_baseline", "apply_baseline", "write_baseline",
    "DEFAULT_BASELINE", "repo_root", "run_analysis",
]


def repo_root() -> str:
    """The tree this package ships in (…/dwpa_tpu/analysis/../..)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_violations(root: str = None) -> list:
    """Full pass: lint every source file + the cross-layer contracts."""
    root = root or repo_root()
    violations = lint_tree(root)
    try:
        violations += check_contracts(root)
    except FileNotFoundError:
        # a partial tree (e.g. a fixture dir) has no protocol layers
        pass
    return violations


def run_analysis(root: str = None, baseline_path: str = None,
                 update_baseline: bool = False, log=print) -> int:
    """The CLI/test entry point.  Returns a process exit code:
    0 = clean under the baseline, 1 = new violations."""
    root = root or repo_root()
    violations = collect_violations(root)
    if update_baseline:
        path = write_baseline(violations, baseline_path)
        log(f"baseline updated: {len(violations)} accepted violation(s) "
            f"-> {path}")
        return 0
    new, absorbed, stale = apply_baseline(
        violations, load_baseline(baseline_path))
    for v in new:
        log(v.render())
    if absorbed:
        log(f"{len(absorbed)} violation(s) absorbed by baseline")
    if stale:
        log(f"{len(stale)} stale baseline entrie(s) — fixed violations; "
            "ratchet with --update-baseline:")
        for code, path, snippet in stale:
            log(f"  {code} {path}: {snippet}")
    if new:
        log(f"FAILED: {len(new)} new violation(s)")
        return 1
    log(f"OK: {len(violations)} violation(s), all baselined")
    return 0
