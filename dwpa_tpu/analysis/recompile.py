"""Recompilation sentinel: count XLA compile-cache misses at runtime.

An unnoticed recompile costs more throughput than any kernel tweak: one
mid-sweep XLA compile of the PBKDF2 step is ~20-40 s of dead device time
per occurrence, and a shape leak that recompiles *per batch* turns the
crack loop into a compile loop (the hazard the engine's ``_STEP_CACHE``
/ power-of-two net bucketing exists to prevent — parallel/step.py).

Mechanism: JAX logs one "Finished XLA compilation of <name> ..." record
per compile-cache miss (``jax_log_compiles``); cache hits log nothing.
``watch_compiles`` toggles the flag and attaches a scoped logging
handler, so counting needs no private JAX APIs and works on every
platform (the persistent on-disk compilation cache still logs the
in-process miss, so warm-disk runs count identically).

Usage::

    with watch_compiles() as rep:
        engine.crack(words)
    assert rep.count == 0, rep.names

    with no_recompiles(allowed=0, label="autotune sweep"):
        for batch in sweep:
            engine.crack_batch(batch)      # raises on any compile

Pytest: the ``recompile_sentinel`` fixture (analysis/pytest_plugin.py,
re-exported by tests/conftest.py) wraps ``no_recompiles`` per test.
"""

import contextlib
import logging
import re

import jax

#: emitted by jax._src.dispatch once per compile-cache miss
_COMPILE_RE = re.compile(r"Finished XLA compilation of ([^\s]+) in")
#: loggers that carry the compile events across the jax versions we span
#: (pxla only adds "Compiling <name> ..." noise — attached so propagation
#: pausing silences it too; the count regex never matches its messages)
_LOGGER_NAMES = ("jax._src.dispatch", "jax.dispatch",
                 "jax._src.interpreters.pxla")


class RecompilationError(AssertionError):
    """A guarded region compiled more than its budget allows."""


class CompileReport:
    """Names of every XLA compilation observed inside the guarded region."""

    def __init__(self):
        self.names = []

    @property
    def count(self) -> int:
        return len(self.names)

    def __repr__(self):
        return f"CompileReport(count={self.count}, names={self.names!r})"


class _CompileCounter(logging.Handler):
    def __init__(self, report):
        super().__init__(level=logging.DEBUG)
        self.report = report

    def emit(self, record):
        m = _COMPILE_RE.search(record.getMessage())
        if m:
            self.report.names.append(m.group(1))


@contextlib.contextmanager
def watch_compiles():
    """Collect-only sentinel: yields a CompileReport that accumulates the
    name of every XLA compilation (compile-cache miss) in the region."""
    report = CompileReport()
    handler = _CompileCounter(report)
    prev_flag = jax.config.jax_log_compiles
    prev_state = []
    jax.config.update("jax_log_compiles", True)
    for name in _LOGGER_NAMES:
        lg = logging.getLogger(name)
        prev_state.append((lg, lg.level, lg.propagate))
        # jax_log_compiles emits at WARNING; an app that quieted the jax
        # loggers must not blind the sentinel.  Propagation is paused so
        # the sentinel's own instrumentation doesn't spray WARNING lines
        # into the guarded region's output.
        if lg.getEffectiveLevel() > logging.WARNING:
            lg.setLevel(logging.WARNING)
        lg.propagate = False
        lg.addHandler(handler)
    try:
        yield report
    finally:
        for lg, lvl, prop in prev_state:
            lg.removeHandler(handler)
            lg.setLevel(lvl)
            lg.propagate = prop
        jax.config.update("jax_log_compiles", prev_flag)


@contextlib.contextmanager
def no_recompiles(allowed: int = 0, label: str = ""):
    """Fail-on-exit sentinel: raises RecompilationError when the region
    compiled more than ``allowed`` XLA programs.

    ``allowed`` budgets intentional one-time compiles (e.g. the first
    batch of a fresh shape bucket); a steady-state sweep guards with the
    default 0 so a per-batch recompile fails the test, not the cron.
    """
    with watch_compiles() as report:
        yield report
    if report.count > allowed:
        where = f" in {label}" if label else ""
        raise RecompilationError(
            f"{report.count} XLA compilation(s){where} where <= {allowed} "
            f"allowed — a shape/static-arg leak is recompiling the hot "
            f"path: {report.names}")
