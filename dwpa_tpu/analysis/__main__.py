"""CLI: ``python -m dwpa_tpu.analysis [root] [--update-baseline]``.

Exit codes: 0 = tree is clean under the checked-in baseline,
1 = new violations (printed one per line as ``path:line: CODE msg``).
``--explain DWnnn`` prints one rule's documentation and (for the DW3xx
concurrency family) a runnable example.  The summary line carries
per-pass/per-rule wall-clock so a slow rule is visible the day it
regresses.  See INSTALL.md ("Static analysis") for the rule-code
reference.
"""

import argparse
import re
import sys

from . import DEFAULT_BASELINE, repo_root, run_analysis
from .concurrency import EXAMPLES


def _rule_doc(code: str) -> str:
    """The docstring bullet for ``code`` out of the rule modules."""
    from . import concurrency, contracts, linter

    for mod in (linter, contracts, concurrency):
        doc = mod.__doc__ or ""
        m = re.search(
            rf"^- \*\*{code}[^\n]*\n(?:(?!^- \*\*|^[^ \n]).*\n?)*",
            doc, re.M)
        if m:
            return m.group(0).rstrip()
    return ""


def explain(code: str, log=print) -> int:
    code = code.upper()
    doc = _rule_doc(code)
    if not doc:
        log(f"unknown rule {code!r} — rules are documented in "
            "analysis/linter.py (DW1xx), analysis/contracts.py (DW2xx) "
            "and analysis/concurrency.py (DW3xx)")
        return 2
    log(doc)
    if code in EXAMPLES:
        log("\nExample:\n" + EXAMPLES[code])
    return 0


def build_parser():
    p = argparse.ArgumentParser(
        prog="dwpa_tpu.analysis",
        description="repo-native JAX contract linter + cross-layer "
                    "protocol/schema drift checker + whole-program "
                    "concurrency analysis",
    )
    p.add_argument("root", nargs="?", default=None,
                   help="tree to analyze (default: the repo this package "
                        "ships in)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE})")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept the current violation set as the new "
                        "baseline (use when a flagged line is reviewed "
                        "and intentional)")
    p.add_argument("--explain", metavar="DWnnn", default=None,
                   help="print one rule's documentation (+ example for "
                        "the DW3xx concurrency rules) and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.explain:
        return explain(args.explain)
    return run_analysis(root=args.root or repo_root(),
                        baseline_path=args.baseline,
                        update_baseline=args.update_baseline)


if __name__ == "__main__":
    sys.exit(main())
