"""CLI: ``python -m dwpa_tpu.analysis [root] [--update-baseline]``.

Exit codes: 0 = tree is clean under the checked-in baseline,
1 = new violations (printed one per line as ``path:line: CODE msg``).
See INSTALL.md ("Static analysis") for the rule-code reference.
"""

import argparse
import sys

from . import DEFAULT_BASELINE, repo_root, run_analysis


def build_parser():
    p = argparse.ArgumentParser(
        prog="dwpa_tpu.analysis",
        description="repo-native JAX contract linter + cross-layer "
                    "protocol/schema drift checker",
    )
    p.add_argument("root", nargs="?", default=None,
                   help="tree to analyze (default: the repo this package "
                        "ships in)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE})")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept the current violation set as the new "
                        "baseline (use when a flagged line is reviewed "
                        "and intentional)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return run_analysis(root=args.root or repo_root(),
                        baseline_path=args.baseline,
                        update_baseline=args.update_baseline)


if __name__ == "__main__":
    sys.exit(main())
