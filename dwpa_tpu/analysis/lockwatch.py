"""Runtime lock-order witness: the dynamic half of DW301.

The static pass (:mod:`.concurrency`) proves lock-order acyclicity over
an *abstraction* of the program; this module witnesses the real thing.
``watch_locks()`` patches ``threading.Lock``/``threading.RLock`` for the
duration of a block, so every lock **created inside the window** (the
chaos soaks construct their cores, clients, queues and feeds inside it)
records which locks its acquiring thread already held.  Those
observations form the acquisition-order witness graph; at exit the
witness asserts the graph is acyclic and names the offending edges —
mirroring the :mod:`.recompile` sentinel's shape: a context manager that
fails loudly at teardown, plus a pytest fixture
(:mod:`.pytest_plugin` ``lock_witness``).

What is and isn't recorded:

- an acquisition while other locks are held adds one edge per held
  lock (held → acquired);
- reentrant RLock acquisitions (depth > 1) record nothing — reentry
  orders nothing;
- ``Condition`` waits work unmodified: the wrapper implements the
  ``_release_save``/``_acquire_restore``/``_is_owned`` protocol, and
  the re-acquisition after a wait IS recorded (it is a real
  acquisition, and a real deadlock schedule if ordered against a held
  lock);
- lock names default to their creation site (``file.py:lineno``) so a
  violation names real code, not ``object at 0x...``.

A cycle in the witness graph means the run actually exhibited every
edge of a deadlock schedule — only the interleaving saved it.  That is
a bug whether or not the run hung, which is why the chaos soaks assert
it on every seed.
"""

import os
import sys
import threading

_REAL_LOCK = threading.Lock          # bound at import: patch-proof
_REAL_RLOCK = threading.RLock


class LockOrderError(AssertionError):
    """Raised when the witnessed acquisition-order graph has a cycle."""


def _creation_site(skip_module):
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        if os.path.basename(fname) != skip_module:
            return f"{os.path.basename(fname)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"                # pragma: no cover - always has frames


class LockWitness:
    """Thread-aware acquisition-order recorder shared by every watched
    lock of one ``watch_locks`` window."""

    def __init__(self, label: str = ""):
        self.label = label
        self._mu = _REAL_LOCK()       # guards edges/counter (real lock:
        self._edges = {}              # never watches itself)
        self._tls = threading.local()
        self._n = 0

    # -- naming ------------------------------------------------------------

    def next_name(self, kind: str) -> str:
        with self._mu:
            self._n += 1
            n = self._n
        return f"{kind}-{n}@{_creation_site('lockwatch.py')}"

    # -- recording ---------------------------------------------------------

    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def record_acquire(self, name: str):
        held = self._held()
        if held:
            thread = threading.current_thread().name
            with self._mu:
                for h in held:
                    if h != name:
                        self._edges.setdefault((h, name), thread)
        held.append(name)

    def record_release(self, name: str):
        held = self._held()
        if name in held:
            # remove the most recent acquisition (LIFO is the common
            # case; out-of-order release still just drops one entry)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break

    # -- reporting / verdict -----------------------------------------------

    @property
    def edges(self) -> dict:
        """{(held, acquired): acquiring-thread-name} snapshot."""
        with self._mu:
            return dict(self._edges)

    def find_cycle(self):
        """One acquisition-order cycle as [n1, n2, ..., n1], or None."""
        edges = self.edges
        graph = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        parent = {}

        def dfs(n):
            color[n] = GRAY
            for m in sorted(graph.get(n, ())):
                if color.get(m, WHITE) == WHITE:
                    parent[m] = n
                    found = dfs(m)
                    if found:
                        return found
                elif color.get(m) == GRAY:
                    cyc = [m, n]
                    cur = n
                    while cur != m:
                        cur = parent[cur]
                        cyc.append(cur)
                    cyc.reverse()
                    return cyc
            color[n] = BLACK
            return None

        for n in sorted(graph):
            if color.get(n, WHITE) == WHITE:
                found = dfs(n)
                if found:
                    return found
        return None

    def check(self):
        """Raise LockOrderError if the witness graph has a cycle."""
        cyc = self.find_cycle()
        if cyc is None:
            return
        edges = self.edges
        legs = []
        for a, b in zip(cyc, cyc[1:]):
            legs.append(f"  {a} -> {b} (thread {edges.get((a, b), '?')})")
        label = f" [{self.label}]" if self.label else ""
        raise LockOrderError(
            f"lock acquisition-order cycle witnessed{label}:\n"
            + "\n".join(legs)
            + "\nevery edge of this deadlock schedule really executed — "
            "only the interleaving saved this run (static twin: DW301)")


def witness_report(witness: LockWitness) -> str:
    """Human-readable witness-graph dump (for debugging a violation)."""
    edges = witness.edges
    if not edges:
        return "lockwatch: no ordered acquisitions witnessed"
    lines = [f"lockwatch: {len(edges)} ordered acquisition edge(s)"]
    for (a, b), thread in sorted(edges.items()):
        lines.append(f"  {a} -> {b}  [first witnessed on {thread}]")
    return "\n".join(lines)


class WatchedLock:
    """Drop-in ``threading.Lock`` that reports to a LockWitness."""

    _KIND = "Lock"

    def __init__(self, witness: LockWitness, name: str = None):
        self._witness = witness
        self.name = name or witness.next_name(self._KIND)
        self._inner = self._make_inner()

    def _make_inner(self):
        return _REAL_LOCK()

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness.record_acquire(self.name)
        return ok

    def release(self):
        self._inner.release()
        self._witness.record_release(self.name)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class WatchedRLock(WatchedLock):
    """Drop-in ``threading.RLock``: reentrant, Condition-compatible.

    Reentrant acquisitions (depth > 1) record no edges; the depth is
    tracked per-owner exactly like the real RLock.  The
    ``_release_save``/``_acquire_restore``/``_is_owned`` trio lets a
    ``threading.Condition`` built over this lock wait correctly while
    the witness's held-stack stays truthful across the wait.
    """

    _KIND = "RLock"

    def __init__(self, witness, name=None):
        super().__init__(witness, name)
        self._owner = None
        self._depth = 0       # mutated only by the owning thread

    def _make_inner(self):
        return _REAL_RLOCK()

    def acquire(self, blocking=True, timeout=-1):
        me = threading.get_ident()
        reentry = self._owner == me
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._depth += 1
            if not reentry:
                self._witness.record_acquire(self.name)
        return ok

    def release(self):
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._depth -= 1
        last = self._depth == 0
        if last:
            self._owner = None
        self._inner.release()
        if last:
            self._witness.record_release(self.name)

    # -- Condition protocol ------------------------------------------------

    def _is_owned(self):
        return self._owner == threading.get_ident()

    def _release_save(self):
        """Full release for Condition.wait: unwind the depth, pop the
        witness stack (waiting really relinquishes the lock)."""
        depth = self._depth
        self._depth = 0
        self._owner = None
        state = self._inner._release_save()
        self._witness.record_release(self.name)
        return (depth, state)

    def _acquire_restore(self, saved):
        depth, state = saved
        self._inner._acquire_restore(state)
        self._owner = threading.get_ident()
        self._depth = depth
        # the re-acquisition after a wait is a real ordering event
        self._witness.record_acquire(self.name)

    def locked(self):
        return self._owner is not None


class watch_locks:
    """Context manager: patch ``threading.Lock``/``RLock`` so locks
    created inside the window report to a fresh witness; assert the
    witness graph is acyclic on clean exit (mirrors
    ``recompile.no_recompiles``)::

        with watch_locks(label="chaos soak") as witness:
            core = ServerCore(Database(":memory:"))   # locks watched
            ...
        # exiting raises LockOrderError on an acquisition-order cycle

    On an exceptional exit the original exception propagates unmasked
    (the witness is still queryable for post-mortems).  Not reentrant —
    one window at a time per process.
    """

    def __init__(self, label: str = ""):
        self.witness = LockWitness(label)

    def __enter__(self):
        self._saved = (threading.Lock, threading.RLock)
        witness = self.witness

        def make_lock(*a, **k):
            return WatchedLock(witness)

        def make_rlock(*a, **k):
            return WatchedRLock(witness)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        return witness

    def __exit__(self, exc_type, exc, tb):
        threading.Lock, threading.RLock = self._saved
        if exc_type is None:
            self.witness.check()
        return False
