"""Pytest integration for the recompilation sentinel.

Import the fixture from a conftest to make it available suite-wide::

    from dwpa_tpu.analysis.pytest_plugin import recompile_sentinel  # noqa

Usage in a test — guard a steady-state sweep so a shape/static-arg leak
that recompiles per batch fails the test::

    def test_autotune_sweep_stays_compiled(recompile_sentinel):
        engine.crack_batch(words)            # warmup compile, unguarded
        with recompile_sentinel(allowed=0, label="autotune sweep"):
            for batch in sweep:
                engine.crack_batch(batch)    # RecompilationError on miss

Kept separate from :mod:`.recompile` so the analysis package never
imports pytest outside test runs.
"""

import pytest

from .lockwatch import watch_locks
from .recompile import no_recompiles


@pytest.fixture
def recompile_sentinel():
    """Factory fixture: ``recompile_sentinel(allowed=0, label="")``
    returns the fail-on-exit context manager (see recompile.no_recompiles)."""
    return no_recompiles


@pytest.fixture
def lock_witness():
    """Factory fixture: ``lock_witness(label="")`` returns the
    lock-order witness context manager (see lockwatch.watch_locks) —
    locks created inside the block record their acquisition order, and
    exit raises LockOrderError on a witnessed cycle::

        def test_soak_deadlock_free(lock_witness):
            with lock_witness(label="storm") as witness:
                core = ServerCore(Database(":memory:"))
                ...  # every lock the soak creates is watched
    """
    return watch_locks
