"""Cross-layer contract checker: client wire fields vs server handlers
vs sqlite schema.

The dwpa protocol's work-unit and put_work schemas exist in THREE
places that nothing ties together: the client reads fields off the
work-unit JSON (client/main.py, client/protocol.py), the server builds
that JSON from sqlite rows (server/core.py), and the columns those rows
carry live in the DDL string (server/db.py SCHEMA).  A field renamed in
one layer keeps every unit test green (each layer is tested against its
own fixtures) and fails in production as a work unit the volunteer
silently can't process — the exact species of drift ADVICE.md's round-5
findings describe.

This module diffs the three layers **statically** (pure AST + executing
the DDL in an in-memory sqlite), so the check runs at test time with no
server or client instantiated:

- **DW201 work-unit drift** — a field the client reads off the work
  unit that the server never emits.  Client-local annotations are
  exempt by the underscore convention (``_ver``/``_nproc``/
  ``_progress``/...), which this check also enforces: client-only keys
  MUST start with ``_`` or they shadow future server fields.
- **DW202 dict-entry drift** — keys the client reads off
  ``work["dicts"][i]`` must be emitted by the server's per-dict
  literal, and every key either side uses must be a column of the
  ``dicts`` table.
- **DW203 put_work drift** — fields the server's ``put_work`` handler
  reads must be sent by the client (or injected by the WSGI layer,
  e.g. ``ip``), and the candidate-entry keys must agree.
- **DW204 SQL column drift** — column lists in INSERT statements across
  ``server/*.py`` must exist in the SCHEMA's table definitions.
"""

import ast
import os
import re
import sqlite3

from .linter import Violation

#: fields the WSGI layer injects into put_work payloads (server/api.py
#: ``data.setdefault("ip", ...)``) — server reads of these are not drift
WSGI_INJECTED = {"ip"}


def _parse(root, rel):
    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read())


def _const_str(node):
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def dict_read_keys(tree, varnames) -> dict:
    """{key: first line} for every ``v["k"]`` / ``v.get("k", ...)`` /
    ``v.pop("k", ...)`` where ``v`` is a Name in ``varnames``."""
    out = {}
    for node in ast.walk(tree):
        key = None
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in varnames
                and isinstance(node.ctx, ast.Load)):
            key = _const_str(node.slice)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "pop", "setdefault")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in varnames and node.args):
            key = _const_str(node.args[0])
        if key is not None:
            out.setdefault(key, node.lineno)
    return out


def dict_written_keys(tree, varname) -> set:
    """Keys of dict literals assigned to ``varname`` plus later
    ``varname["k"] = ...`` stores."""
    keys = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id == varname
                        and isinstance(node.value, ast.Dict)):
                    for k in node.value.keys:
                        s = _const_str(k)
                        if s is not None:
                            keys.add(s)
                elif (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == varname):
                    s = _const_str(t.slice)
                    if s is not None:
                        keys.add(s)
    return keys


def _dict_entry_vars(tree) -> set:
    """Names bound by iterating/selecting over a work unit's "dicts"
    list (``for d in work.get("dicts", [])``, comprehensions, and
    ``entry = next((d for d in work...), ...)``) — the variables whose
    string subscripts are dict-ENTRY keys."""
    names = set()

    def iter_mentions_dicts(it):
        return any(_const_str(n) == "dicts" for n in ast.walk(it))

    for node in ast.walk(tree):
        if isinstance(node, ast.For) and iter_mentions_dicts(node.iter):
            names |= {n.id for n in ast.walk(node.target)
                      if isinstance(n, ast.Name)}
        elif isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            for gen in node.generators:
                if iter_mentions_dicts(gen.iter):
                    names |= {n.id for n in ast.walk(gen.target)
                              if isinstance(n, ast.Name)}
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            # entry = next((d for d in work.get("dicts", [])...), None)
            if any(isinstance(a, ast.GeneratorExp)
                   and any(iter_mentions_dicts(g.iter)
                           for g in a.generators)
                   for a in node.value.args):
                names |= {n.id for t in node.targets for n in ast.walk(t)
                          if isinstance(n, ast.Name)}
    return names


def _literal_keys_under(tree, outer_key) -> set:
    """Keys of dict literals that appear inside the value expression of
    ``outer_key`` in any dict literal (the server's per-dict entry
    ``{"dhash": ..., "dpath": ...}`` nested under ``"dicts"``)."""
    keys = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if _const_str(k) == outer_key:
                for inner in ast.walk(v):
                    if isinstance(inner, ast.Dict):
                        for ik in inner.keys:
                            s = _const_str(ik)
                            if s is not None:
                                keys.add(s)
    return keys


def _schema_columns(root) -> dict:
    """{table: {column, ...}} by executing the SCHEMA DDL string from
    server/db.py in an in-memory sqlite (no package import: the checker
    must stay runnable against any tree, including test fixtures)."""
    tree = _parse(root, "dwpa_tpu/server/db.py")
    ddl = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "SCHEMA":
                    ddl = _const_str(node.value)
    if ddl is None:
        return {}
    conn = sqlite3.connect(":memory:")
    try:
        conn.executescript(ddl)
        tables = [r[0] for r in conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table'")]
        return {t: {r[1] for r in conn.execute(f"PRAGMA table_info({t})")}
                for t in tables}
    finally:
        conn.close()


_INSERT_RE = re.compile(
    r"INSERT\s+(?:OR\s+\w+\s+)?INTO\s+(\w+)\s*\(([^)]*)\)", re.I)


def _insert_columns(tree):
    """(table, [cols], line) for every INSERT with an explicit column
    list in the module's string constants."""
    out = []
    for node in ast.walk(tree):
        s = _const_str(node)
        if s and "INSERT" in s.upper():
            for m in _INSERT_RE.finditer(s):
                cols = [c.strip() for c in m.group(2).split(",") if c.strip()]
                out.append((m.group(1), cols, node.lineno))
    return out


def check_contracts(root: str) -> list:
    """Run all cross-layer contract checks against the tree at ``root``.
    Returns a list of linter.Violation (codes DW201-DW204)."""
    out = []
    client_main = _parse(root, "dwpa_tpu/client/main.py")
    client_proto = _parse(root, "dwpa_tpu/client/protocol.py")
    server_core = _parse(root, "dwpa_tpu/server/core.py")
    server_api = _parse(root, "dwpa_tpu/server/api.py")

    # ---- DW201: work-unit fields ------------------------------------
    server_emits = dict_written_keys(server_core, "work")
    client_reads = dict_read_keys(client_main, {"work"})
    # protocol.py's required-field gate reads the same schema
    for node in ast.walk(client_proto):
        if isinstance(node, ast.For) and isinstance(node.iter, ast.Tuple):
            fields = [_const_str(e) for e in node.iter.elts]
            if fields and all(f is not None for f in fields):
                for f in fields:
                    client_reads.setdefault(f, node.lineno)
    for key, line in sorted(client_reads.items()):
        if key in server_emits:
            continue
        if key.startswith("_"):
            continue  # client-local annotation by convention
        out.append(Violation(
            "DW201", "dwpa_tpu/client/main.py", line,
            f"client reads work[{key!r}] but server/core.py never emits "
            f"it (server emits: {sorted(server_emits)}); client-local "
            "keys must start with '_'", f"work[{key!r}]"))

    # ---- DW202: dict-entry fields vs dicts table --------------------
    cols = _schema_columns(root)
    dict_cols = cols.get("dicts", set())
    server_entry_keys = _literal_keys_under(server_core, "dicts")
    entry_vars = _dict_entry_vars(client_main)
    client_entry_reads = dict_read_keys(client_main, entry_vars)
    for key, line in sorted(client_entry_reads.items()):
        if key not in server_entry_keys:
            out.append(Violation(
                "DW202", "dwpa_tpu/client/main.py", line,
                f"client reads dict-entry key {key!r} but the server's "
                f"per-dict literal only carries {sorted(server_entry_keys)}",
                f"d[{key!r}]"))
    for key in sorted(server_entry_keys):
        if dict_cols and key not in dict_cols:
            out.append(Violation(
                "DW202", "dwpa_tpu/server/core.py", 0,
                f"server emits dict-entry key {key!r} which is not a "
                f"column of the dicts table ({sorted(dict_cols)})",
                f'"{key}"'))

    # ---- DW203: put_work payload ------------------------------------
    client_sends = set()
    for node in ast.walk(client_proto):
        if isinstance(node, ast.FunctionDef) and node.name == "put_work":
            for d in ast.walk(node):
                if isinstance(d, ast.Dict):
                    client_sends |= {_const_str(k) for k in d.keys
                                     if _const_str(k)}
    server_reads = {}
    for node in ast.walk(server_core):
        if isinstance(node, ast.FunctionDef) and node.name == "put_work":
            server_reads = dict_read_keys(node, {"data"})
    injected = set(dict_read_keys(server_api, {"data"})) | WSGI_INJECTED
    for key, line in sorted(server_reads.items()):
        if key not in client_sends and key not in injected:
            out.append(Violation(
                "DW203", "dwpa_tpu/server/core.py", line,
                f"server put_work reads {key!r} but the client payload "
                f"only carries {sorted(client_sends)} (WSGI injects "
                f"{sorted(injected)})", f"data.get({key!r})"))
    # candidate entry keys: client emits {"k","v"} literals, server
    # reads pair.get(...)
    cand_client = set()
    for node in ast.walk(client_main):
        if isinstance(node, ast.Dict):
            keys = {_const_str(k) for k in node.keys}
            if keys == {"k", "v"}:
                cand_client |= keys
    cand_server = set()
    for node in ast.walk(server_core):
        if isinstance(node, ast.FunctionDef) and node.name == "put_work":
            cand_server = set(dict_read_keys(node, {"pair"}))
    if cand_client:  # no literal found = no evidence, not drift
        for key in sorted(cand_server - cand_client):
            out.append(Violation(
                "DW203", "dwpa_tpu/server/core.py", 0,
                f"server reads candidate key {key!r} the client never "
                f"sends (client sends {sorted(cand_client)})",
                f"pair.get({key!r})"))

    # ---- DW204: INSERT column lists vs schema -----------------------
    for rel in ("dwpa_tpu/server/core.py", "dwpa_tpu/server/jobs.py",
                "dwpa_tpu/server/api.py", "dwpa_tpu/server/db.py"):
        if not os.path.exists(os.path.join(root, rel)):
            continue
        tree = _parse(root, rel)
        for table, insert_cols, line in _insert_columns(tree):
            known = cols.get(table)
            if known is None:
                out.append(Violation(
                    "DW204", rel, line,
                    f"INSERT INTO {table}: table not in SCHEMA "
                    f"({sorted(cols)})", f"INSERT INTO {table}"))
                continue
            for c in insert_cols:
                if c not in known:
                    out.append(Violation(
                        "DW204", rel, line,
                        f"INSERT INTO {table}({c}): no such column "
                        f"(schema has {sorted(known)})",
                        f"INSERT INTO {table}({c})"))
    return out
