"""HTTP/JSON transport for the dwpa volunteer protocol.

Speaks the exact wire protocol of the reference server so this client can
work against an unmodified dwpa deployment (endpoints and schemas per the
reference: ?get_work / ?put_work / ?prdict routing at web/index.php:146-163,
request/response shapes at web/content/get_work.php and
web/content/put_work.php; client-side counterpart help_crack.py:404-426,
727-735):

- ``get_work``: POST ``{"dictcount": N}`` to ``?get_work=<api-ver>`` ->
  ``{hkey, dicts:[{dhash,dpath}...], hashes:[...], rules?, prdict?}``;
  sentinel body ``Version`` (client too old) or ``No nets``.
- ``put_work``: POST ``{"hkey":…, "type":"bssid", "cand":[{k,v}...]}`` to
  ``?put_work`` -> ``OK`` / anything else = rejected.
- ``prdict``: GET ``?prdict=<hkey>`` -> gzip dictionary stream.
- static artifacts (dicts) by URL with md5 manifests.

Retry behavior mirrors the reference client: every network op retries with
a backoff sleep (help_crack.py:80-87,104-126), except ``max_tries`` is
configurable so tests and batch runs can fail fast instead of spinning
forever.
"""

import contextlib
import gzip
import hashlib
import json
import time
import urllib.error
import urllib.parse
import urllib.request

HC_VER = "2.2.0"  # protocol level spoken (server gates on MIN_HC_VER)


class VersionRejected(RuntimeError):
    """Server refused our protocol version."""


class NoNets(RuntimeError):
    """Server has no work to hand out."""


class ServerAPI:
    def __init__(self, base_url: str, hc_ver: str = HC_VER, timeout: float = 120.0,
                 max_tries: int = 0, backoff: float = 123.0, sleep=time.sleep):
        self.base_url = base_url.rstrip("/") + "/"
        self.hc_ver = hc_ver
        self.timeout = timeout
        self.max_tries = max_tries  # 0 = retry forever (reference behavior)
        self.backoff = backoff
        self.sleep = sleep
        # Telemetry binding (bind_obs): every protocol op counts into
        # dwpa_client_requests_total{endpoint=...} and opens a span, so
        # server-conversation time is visible next to crack time.  Unbound
        # (bare ServerAPI uses) stays zero-overhead.
        self._obs_requests = None
        self._obs_tracer = None

    def bind_obs(self, registry, tracer=None):
        """Attach a metrics registry (and optional SpanTracer): done by
        TpuCrackClient so transport ops land in the client's registry."""
        self._obs_requests = registry.counter(
            "dwpa_client_requests_total",
            "client->server protocol operations by endpoint")
        self._obs_tracer = tracer
        return self

    def _observed(self, endpoint: str):
        """Count + span one protocol op (no-op context when unbound)."""
        if self._obs_requests is not None:
            self._obs_requests.labels(endpoint=endpoint).inc()
        if self._obs_tracer is not None:
            return self._obs_tracer.span(endpoint)
        return contextlib.nullcontext()

    # -- low level ---------------------------------------------------------

    def fetch(self, url: str, data: dict = None, max_tries: int = None) -> bytes:
        """GET (or POST json) with retry/backoff.

        ``max_tries`` overrides the instance default for callers that
        must fail fast (e.g. the optional self-update artifacts, which
        must never park the crack loop in the infinite-retry backoff).
        """
        limit = self.max_tries if max_tries is None else max_tries
        tries = 0
        body = None
        headers = {}
        if data is not None:
            body = json.dumps(data).encode()
            headers["Content-Type"] = "application/json"
        while True:
            tries += 1
            try:
                req = urllib.request.Request(url, data=body, headers=headers)
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return r.read()
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                if limit and tries >= limit:
                    raise ConnectionError(f"giving up on {url}: {e}") from e
                self.sleep(self.backoff)

    def _endpoint(self, query: str) -> str:
        return self.base_url + "?" + query

    # -- protocol ops ------------------------------------------------------

    def get_work(self, dictcount: int) -> dict:
        with self._observed("get_work"):
            raw = self.fetch(
                self._endpoint("get_work=" + self.hc_ver),
                {"dictcount": dictcount}
            )
        text = raw.decode("utf-8", "replace").strip()
        if text == "Version":
            raise VersionRejected(f"server requires newer client than {self.hc_ver}")
        if text == "No nets":
            raise NoNets()
        work = json.loads(raw)
        for field in ("hkey", "dicts", "hashes"):
            if field not in work:
                raise ValueError(f"malformed work unit: missing {field}")
        return work

    def put_work(self, hkey: str, candidates: list) -> bool:
        """``candidates``: [{"k": bssid-12hex, "v": psk-hex}, ...]."""
        with self._observed("put_work"):
            raw = self.fetch(
                self._endpoint("put_work"),
                {"hkey": hkey, "type": "bssid", "cand": candidates},
            )
        return raw.decode("utf-8", "replace").strip() == "OK"

    def get_prdict(self, hkey: str) -> list:
        """Fetch + gunzip the dynamic PROBEREQUEST dictionary."""
        with self._observed("prdict"):
            raw = self.fetch(
                self._endpoint("prdict=" + urllib.parse.quote(hkey)))
        if raw[:2] == b"\x1f\x8b":
            raw = gzip.decompress(raw)
        return [w for w in raw.split(b"\n") if w]

    def remote_version(self) -> str:
        """The server-published client version (self-update probe).

        Reference: GET ``hc/help_crack.py.version`` (help_crack.py:162);
        here the artifact is the package archive, so the manifest is
        ``hc/dwpa_tpu.version``.  Returns '' when the server doesn't
        publish one (non-updating deployments) — a single non-retrying
        probe, unlike ``fetch`` (a missing manifest must not spin the
        infinite-retry loop).
        """
        url = urllib.parse.urljoin(self.base_url, "hc/dwpa_tpu.version")
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as r:
                return r.read().decode("utf-8", "replace").strip()
        except (urllib.error.URLError, OSError, TimeoutError):
            return ""

    def download(self, url: str, dest: str, expected_md5: str = None,
                 max_tries: int = None) -> str:
        if not urllib.parse.urlparse(url).scheme:
            url = urllib.parse.urljoin(self.base_url, url)
        with self._observed("dict_download"):
            data = self.fetch(url, max_tries=max_tries)
        if expected_md5 is not None:
            got = hashlib.md5(data).hexdigest()
            if got != expected_md5:
                raise ValueError(f"md5 mismatch for {url}: {got} != {expected_md5}")
        with open(dest, "wb") as f:
            f.write(data)
        return dest
