"""HTTP/JSON transport for the dwpa volunteer protocol.

Speaks the exact wire protocol of the reference server so this client can
work against an unmodified dwpa deployment (endpoints and schemas per the
reference: ?get_work / ?put_work / ?prdict routing at web/index.php:146-163,
request/response shapes at web/content/get_work.php and
web/content/put_work.php; client-side counterpart help_crack.py:404-426,
727-735):

- ``get_work``: POST ``{"dictcount": N}`` to ``?get_work=<api-ver>`` ->
  ``{hkey, dicts:[{dhash,dpath}...], hashes:[...], rules?, prdict?}``;
  sentinel body ``Version`` (client too old) or ``No nets``.
- ``put_work``: POST ``{"hkey":…, "type":"bssid", "cand":[{k,v}...]}`` to
  ``?put_work`` -> ``OK`` / anything else = rejected.
- ``prdict``: GET ``?prdict=<hkey>`` -> gzip dictionary stream.
- static artifacts (dicts) by URL with md5 manifests.

Retry behavior departs from the reference client's flat infinite loop
(help_crack.py:80-87,104-126) in three ways, all knob-compatible with it:

- ``RetryPolicy``: exponential backoff with decorrelated jitter between
  ``backoff`` (base) and ``retry_cap``, optional per-call ``deadline``
  budget.  The defaults (base == cap == 123 s, retry forever) reproduce
  the reference cadence exactly.
- error classification: transient failures (connection refused/reset,
  timeout, HTTP 5xx) retry; permanent ones (HTTP 4xx, the ``Version``
  sentinel, malformed JSON after ``validation_retries`` re-fetches) raise
  immediately instead of spinning forever.
- a circuit breaker: after ``CircuitBreaker.threshold`` consecutive
  transient failures the transport goes OPEN and a down server is probed
  once per ``cooldown`` instead of hammered.  Callers with a bounded
  ``max_tries`` fail fast with ``CircuitOpenError`` while OPEN; unbounded
  callers sleep until the next probe slot (reference parity: they still
  block until the server returns).  ``TpuCrackClient`` keys its degraded
  mode off :attr:`ServerAPI.circuit_open`.

The single raw HTTP hop lives in :meth:`ServerAPI._transport`; everything
above it (retry, classification, breaker, telemetry) is pure host logic.
Tests and the chaos harness (``dwpa_tpu.chaos``) replace ``_transport``
to inject faults underneath the real retry stack.
"""

import contextlib
import gzip
import hashlib
import json
import random
import time
import urllib.error
import urllib.parse
import urllib.request

HC_VER = "2.2.0"  # protocol level spoken (server gates on MIN_HC_VER)


class VersionRejected(RuntimeError):
    """Server refused our protocol version."""


class NoNets(RuntimeError):
    """Server has no work to hand out."""


class PermanentError(ConnectionError):
    """Classified non-retryable failure (HTTP 4xx, persistent bad JSON).

    Subclasses ``ConnectionError`` so existing call sites that catch the
    old give-up error keep working; new code can match it specifically.
    """


class CircuitOpenError(ConnectionError):
    """Transport circuit is OPEN and the probe window hasn't arrived."""


def classify_error(exc) -> tuple:
    """Map a transport exception to ``(kind, reason)``.

    ``kind`` is ``"permanent"`` (fail fast) or ``"transient"`` (retry);
    ``reason`` is the low-cardinality label recorded in
    ``dwpa_client_retries_total{reason=...}``.  Order matters:
    ``HTTPError`` is a ``URLError`` subclass — the very bug this fixes:
    the old flat loop caught ``URLError`` and retried a 404 forever.
    """
    if isinstance(exc, urllib.error.HTTPError):
        if exc.code == 429:
            # Admission control, not rejection: the server is up and
            # explicitly asking us to come back (Retry-After).
            return "transient", "http_429"
        kind = "permanent" if 400 <= exc.code < 500 else "transient"
        return kind, f"http_{exc.code // 100}xx"
    if isinstance(exc, TimeoutError):
        return "transient", "timeout"
    if isinstance(exc, urllib.error.URLError):
        reason = getattr(exc, "reason", None)
        if isinstance(reason, TimeoutError):
            return "transient", "timeout"
        if isinstance(reason, ConnectionRefusedError):
            return "transient", "refused"
        if isinstance(reason, ConnectionResetError):
            return "transient", "reset"
        return "transient", "unreachable"
    if isinstance(exc, ConnectionRefusedError):
        return "transient", "refused"
    if isinstance(exc, ConnectionResetError):
        return "transient", "reset"
    if isinstance(exc, (ConnectionError, OSError)):
        return "transient", "conn"
    return "transient", "error"


def retry_after_floor(exc) -> float:
    """Server-requested minimum backoff from a Retry-After header, or
    0.0 when the response carried none (or carried garbage).  Only the
    delta-seconds form is parsed — HTTP-date Retry-After is not worth a
    date parser here; a malformed value must never break the retry loop.
    """
    headers = getattr(exc, "headers", None)
    if headers is None:
        return 0.0
    try:
        value = headers.get("Retry-After")
    except AttributeError:
        return 0.0
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        return 0.0


class RetryPolicy:
    """Exponential backoff with decorrelated jitter, cap and deadline.

    Each delay is drawn uniformly from ``[base, 3 * previous]`` and
    clamped to ``cap`` ("decorrelated jitter": successive clients don't
    synchronize their retries into thundering herds).  ``base == cap``
    degenerates to the reference client's flat interval.  ``deadline``
    (seconds) bounds the total time a single call may spend retrying;
    ``rng``/``clock`` are injectable so tests replay exact schedules.
    """

    def __init__(self, base: float = 123.0, cap: float = None,
                 deadline: float = None, rng=None, clock=time.monotonic):
        self.base = base
        self.cap = base if cap is None else max(cap, base)
        self.deadline = deadline
        self.rng = rng if rng is not None else random.Random()
        self.clock = clock

    def start(self, max_tries: int) -> "_RetryState":
        return _RetryState(self, max_tries)


class _RetryState:
    """Per-call retry bookkeeping (attempt count, jitter chain, budget)."""

    def __init__(self, policy: RetryPolicy, max_tries: int):
        self.policy = policy
        self.max_tries = max_tries  # 0 = unbounded (reference behavior)
        self.tries = 0
        self._prev = policy.base
        self._t0 = policy.clock()

    def next_delay(self):
        """Delay before the next attempt, or None when the call must
        give up (tries exhausted or deadline budget spent)."""
        p = self.policy
        self.tries += 1
        if self.max_tries and self.tries >= self.max_tries:
            return None
        delay = min(p.cap, p.rng.uniform(p.base, self._prev * 3))
        self._prev = max(delay, p.base)
        if p.deadline is not None:
            left = p.deadline - (p.clock() - self._t0)
            if left <= 0:
                return None
            delay = min(delay, left)
        return delay


class CircuitBreaker:
    """Three-state breaker over consecutive transient transport failures.

    CLOSED (normal) -> OPEN after ``threshold`` consecutive failures;
    while OPEN, ``allow()`` admits exactly one probe per ``cooldown``
    window (HALF_OPEN); a success anywhere resets to CLOSED.  Permanent
    failures (4xx) never trip it — the server answered, it's reachable.
    """

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2

    def __init__(self, threshold: int = 5, cooldown: float = 30.0,
                 clock=time.monotonic):
        self.threshold = threshold
        self.cooldown = cooldown
        self.clock = clock
        self.state = self.CLOSED
        self.failures = 0
        self._opened_at = 0.0

    def allow(self) -> bool:
        if self.state != self.OPEN:
            return True
        if self.clock() - self._opened_at >= self.cooldown:
            self.state = self.HALF_OPEN  # one probe in flight
            return True
        return False

    def remaining(self) -> float:
        """Seconds until the next probe slot (0 when not OPEN)."""
        if self.state != self.OPEN:
            return 0.0
        return max(0.0, self.cooldown - (self.clock() - self._opened_at))

    def record_success(self):
        self.state = self.CLOSED
        self.failures = 0

    def record_failure(self):
        self.failures += 1
        if self.failures >= self.threshold:
            self.state = self.OPEN
            self._opened_at = self.clock()


#: query parameters that name protocol endpoints (metric label values)
_ENDPOINT_PARAMS = ("get_work", "put_work", "prdict")


def _endpoint_label(url: str) -> str:
    """Low-cardinality endpoint label for retry metrics."""
    query = urllib.parse.urlparse(url).query
    for name in _ENDPOINT_PARAMS:
        if name in urllib.parse.parse_qs(query, keep_blank_values=True):
            return name
    return "download"


class ServerAPI:
    def __init__(self, base_url: str, hc_ver: str = HC_VER, timeout: float = 120.0,
                 max_tries: int = 0, backoff: float = 123.0, sleep=time.sleep,
                 retry_cap: float = None, deadline: float = None,
                 rng=None, breaker: CircuitBreaker = None):
        self.base_url = base_url.rstrip("/") + "/"
        self.hc_ver = hc_ver
        self.timeout = timeout
        self.max_tries = max_tries  # 0 = retry forever (reference behavior)
        self.backoff = backoff      # retry base; also the idle (No nets) nap
        self.sleep = sleep
        self.retry = RetryPolicy(base=backoff, cap=retry_cap,
                                 deadline=deadline, rng=rng)
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        # get_work re-fetches a syntactically-bad body this many times
        # before classifying it permanent (a flaky proxy can truncate one
        # response; a server that always returns garbage is down for us).
        self.validation_retries = 2
        # Telemetry binding (bind_obs): every protocol op counts into
        # dwpa_client_requests_total{endpoint=...} and opens a span, so
        # server-conversation time is visible next to crack time.  Unbound
        # (bare ServerAPI uses) stays zero-overhead.
        self._obs_requests = None
        self._obs_retries = None
        self._obs_backoff = None
        self._obs_circuit = None
        self._obs_tracer = None

    def bind_obs(self, registry, tracer=None):
        """Attach a metrics registry (and optional SpanTracer): done by
        TpuCrackClient so transport ops land in the client's registry."""
        self._obs_requests = registry.counter(
            "dwpa_client_requests_total",
            "client->server protocol operations by endpoint")
        self._obs_retries = registry.counter(
            "dwpa_client_retries_total",
            "transport retries by endpoint and classified failure reason")
        self._obs_backoff = registry.histogram(
            "dwpa_client_backoff_seconds",
            "backoff sleeps between transport retries")
        self._obs_circuit = registry.gauge(
            "dwpa_client_circuit_state",
            "transport circuit state (0 closed / 1 half-open / 2 open)")
        self._obs_circuit.set(self.breaker.state)
        self._obs_tracer = tracer
        return self

    def _observed(self, endpoint: str):
        """Count + span one protocol op (no-op context when unbound)."""
        if self._obs_requests is not None:
            self._obs_requests.labels(endpoint=endpoint).inc()
        if self._obs_tracer is not None:
            return self._obs_tracer.span(endpoint)
        return contextlib.nullcontext()

    def _note_retry(self, endpoint: str, reason: str, delay: float):
        if self._obs_retries is not None:
            self._obs_retries.labels(endpoint=endpoint, reason=reason).inc()
        if self._obs_backoff is not None:
            self._obs_backoff.observe(delay)

    def _note_circuit(self):
        if self._obs_circuit is not None:
            self._obs_circuit.set(self.breaker.state)

    @property
    def circuit_open(self) -> bool:
        """True while the breaker is OPEN (degraded-mode signal)."""
        return self.breaker.state == CircuitBreaker.OPEN

    # -- low level ---------------------------------------------------------

    def _transport(self, url: str, body: bytes = None, headers: dict = None) -> bytes:
        """One raw HTTP exchange — the fault-injection seam.

        The chaos harness and loopback tests replace this attribute; the
        retry/classification/breaker stack above it stays the real one.
        """
        req = urllib.request.Request(url, data=body, headers=headers or {})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return r.read()

    def fetch(self, url: str, data: dict = None, max_tries: int = None) -> bytes:
        """GET (or POST json) with classified retry/backoff.

        ``max_tries`` overrides the instance default for callers that
        must fail fast (e.g. the optional self-update artifacts, which
        must never park the crack loop in the infinite-retry backoff).
        Transient failures retry per ``RetryPolicy``; permanent ones
        raise :class:`PermanentError` on the first occurrence; an OPEN
        circuit raises :class:`CircuitOpenError` for bounded callers and
        sleeps until the probe slot for unbounded ones.
        """
        limit = self.max_tries if max_tries is None else max_tries
        body = None
        headers = {}
        if data is not None:
            body = json.dumps(data).encode()
            headers["Content-Type"] = "application/json"
        endpoint = _endpoint_label(url)
        state = self.retry.start(limit)
        while True:
            if not self.breaker.allow():
                if limit:
                    raise CircuitOpenError(
                        f"transport circuit open; next probe of "
                        f"{self.base_url} in {self.breaker.remaining():.1f}s")
                # Unbounded caller: block until the probe slot — the
                # reference client would be asleep here anyway.
                self.sleep(self.breaker.remaining())
                continue
            try:
                out = self._transport(url, body, headers)
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                kind, reason = classify_error(e)
                if kind == "permanent":
                    # The server answered; a reachable server must not
                    # trip the breaker even when it rejects the request.
                    self.breaker.record_success()
                    self._note_circuit()
                    raise PermanentError(f"giving up on {url}: {e}") from e
                self.breaker.record_failure()
                self._note_circuit()
                delay = state.next_delay()
                if delay is None:
                    raise ConnectionError(f"giving up on {url}: {e}") from e
                # A 429/503 Retry-After is a floor, not a replacement:
                # jittered exponential backoff still applies above it.
                delay = max(delay, retry_after_floor(e))
                self._note_retry(endpoint, reason, delay)
                if self._obs_tracer is not None:
                    with self._obs_tracer.span("transport:retry"):
                        self.sleep(delay)
                else:
                    self.sleep(delay)
            else:
                self.breaker.record_success()
                self._note_circuit()
                return out

    def _endpoint(self, query: str) -> str:
        return self.base_url + "?" + query

    # -- protocol ops ------------------------------------------------------

    def get_work(self, dictcount: int, max_tries: int = None) -> dict:
        attempts = 0
        while True:
            with self._observed("get_work"):
                raw = self.fetch(
                    self._endpoint("get_work=" + self.hc_ver),
                    {"dictcount": dictcount},
                    max_tries=max_tries,
                )
            text = raw.decode("utf-8", "replace").strip()
            if text == "Version":
                raise VersionRejected(
                    f"server requires newer client than {self.hc_ver}")
            if text == "No nets":
                raise NoNets()
            try:
                work = json.loads(raw)
                for field in ("hkey", "dicts", "hashes"):
                    if field not in work:
                        raise ValueError(
                            f"malformed work unit: missing {field}")
                # mask shards are optional; when present each entry must
                # carry the full -s/-l frame (a truncated shard would
                # silently shrink the searched keyspace)
                for m in work.get("masks") or []:
                    missing = {"mask", "skip", "limit"} - set(m)
                    if missing:
                        raise ValueError(
                            f"malformed mask shard: missing "
                            f"{sorted(missing)}")
            except ValueError as e:
                # Truncated/garbage body: re-fetch a bounded number of
                # times (a proxy can mangle one response), then classify
                # permanent — an always-garbage server is down for us.
                attempts += 1
                if attempts > self.validation_retries:
                    raise PermanentError(
                        f"malformed get_work response after "
                        f"{attempts} attempts: {e}") from e
                self._note_retry("get_work", "bad_json", 0.0)
                continue
            return work

    def put_work(self, hkey: str, candidates: list, max_tries: int = None,
                 epoch: int = None) -> bool:
        """``candidates``: [{"k": bssid-12hex, "v": psk-hex}, ...].

        ``epoch`` echoes the lease epoch from the issuing get_work; a
        stale holder (its lease reaped and the unit reissued) then fails
        the keyed release instead of double-crediting.  None (drained
        outbox records from before the epoch era, or old servers) lets
        the server resolve the live epoch itself.
        """
        payload = {"hkey": hkey, "type": "bssid", "cand": candidates,
                   "epoch": epoch}
        if epoch is None:
            del payload["epoch"]  # byte-compatible with reference servers
        with self._observed("put_work"):
            raw = self.fetch(
                self._endpoint("put_work"),
                payload,
                max_tries=max_tries,
            )
        return raw.decode("utf-8", "replace").strip() == "OK"

    def get_prdict(self, hkey: str) -> list:
        """Fetch + gunzip the dynamic PROBEREQUEST dictionary."""
        with self._observed("prdict"):
            raw = self.fetch(
                self._endpoint("prdict=" + urllib.parse.quote(hkey)))
        if raw[:2] == b"\x1f\x8b":
            raw = gzip.decompress(raw)
        return [w for w in raw.split(b"\n") if w]

    def remote_version(self) -> str:
        """The server-published client version (self-update probe).

        Reference: GET ``hc/help_crack.py.version`` (help_crack.py:162);
        here the artifact is the package archive, so the manifest is
        ``hc/dwpa_tpu.version``.  Returns '' when the server doesn't
        publish one (non-updating deployments) — a single non-retrying
        probe, unlike ``fetch`` (a missing manifest must not spin the
        infinite-retry loop).
        """
        url = urllib.parse.urljoin(self.base_url, "hc/dwpa_tpu.version")
        try:
            return self._transport(url).decode("utf-8", "replace").strip()
        except (urllib.error.URLError, OSError, TimeoutError):
            return ""

    def download(self, url: str, dest: str, expected_md5: str = None,
                 max_tries: int = None) -> str:
        if not urllib.parse.urlparse(url).scheme:
            url = urllib.parse.urljoin(self.base_url, url)
        with self._observed("dict_download"):
            data = self.fetch(url, max_tries=max_tries)
        if expected_md5 is not None:
            got = hashlib.md5(data).hexdigest()
            if got != expected_md5:
                raise ValueError(f"md5 mismatch for {url}: {got} != {expected_md5}")
        with open(dest, "wb") as f:
            f.write(data)
        return dest
