"""dwpa protocol client: fetch work, crack on TPU, submit founds."""

from .protocol import NoNets, ServerAPI, VersionRejected  # noqa: F401
from .main import ClientConfig, TpuCrackClient  # noqa: F401
