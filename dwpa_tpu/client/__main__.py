"""CLI: ``python -m dwpa_tpu.client <server-url> [options]``.

Flag set mirrors the reference client's argparse surface
(help_crack.py:975-990): ``-ad`` additional dictionary, ``-pot`` potfile
path, plus engine knobs.
"""

import argparse

from .main import ClientConfig, TpuCrackClient


def build_parser():
    p = argparse.ArgumentParser(
        prog="dwpa_tpu.client",
        description="dwpa volunteer client with a JAX/TPU m22000 cracker",
    )
    p.add_argument("base_url", help="dwpa server base URL (e.g. https://wpa-sec.example/)")
    p.add_argument("-ad", "--additional-dict", help="extra local dictionary (pass 1)")
    p.add_argument("-pot", "--potfile", help="potfile path for founds")
    p.add_argument("-w", "--workdir", default="hc_work", help="working directory")
    p.add_argument("-d", "--dictcount", type=int, default=1, help="initial dict count 1..15")
    p.add_argument("-b", "--batch-size", type=int, default=16384, help="device batch size")
    p.add_argument("-n", "--max-work-units", type=int, default=0, help="stop after N units")
    p.add_argument("--nc", type=int, default=8,
                   help="nonce-error-correction budget (reference -co "
                        "--nonce-error-corrections, help_crack.py:773)")
    p.add_argument("--rule-workers", type=int, default=0,
                   help="expand PASS-1 rules (cracked/rkg dicts) in N "
                        "worker processes; pass 2 mangles on device "
                        "(0 = inline)")
    p.add_argument("--feed-depth", type=int, default=2,
                   help="candidate-feed queue depth: blocks framed/packed "
                        "ahead of the engine (README 'Candidate feed')")
    p.add_argument("--feed-workers", type=int, default=None,
                   help="candidate-feed producer threads running the host "
                        "stages off the crack loop (default: one per local "
                        "device, so every device stream keeps a producer; "
                        "0 = inline feed, no threads)")
    p.add_argument("--device-streams", choices=("auto", "on", "off"),
                   default="auto",
                   help="independent per-device crack streams instead of "
                        "lockstep shard_map dispatch (README 'Device "
                        "streams'); auto = on for single-process "
                        "multi-device, lockstep otherwise")
    p.add_argument("--pmk-cache-dir",
                   help="persistent PMK store directory: cross-unit "
                        "PBKDF2->PMK cache with mixed hit/miss crack "
                        "blocks (README 'PMK store')")
    p.add_argument("--pmk-cache-max-bytes", type=int,
                   default=256 * 1024 * 1024,
                   help="PMK store on-disk cap; oldest segments are "
                        "evicted beyond it (default 256 MiB)")
    p.add_argument("--dict-cache-dir",
                   help="packed-dictionary cache directory: first full "
                        "stream of a dict persists its packed device "
                        "blocks; later units mmap them with O(1) seek "
                        "(README 'Dict cache')")
    p.add_argument("--dict-cache-max-bytes", type=int,
                   default=4 * 1024 * 1024 * 1024,
                   help="dict cache on-disk cap; least-recently-used "
                        "entries are evicted beyond it (default 4 GiB)")
    p.add_argument("--unit-queue", type=int, default=4,
                   help="work units prefetched ahead of the device by "
                        "the fused multi-unit executor (README 'Unit "
                        "fusion'; single-host only)")
    p.add_argument("--fuse-max-units", type=int, default=8,
                   help="max work units packed into one fused device "
                        "batch (one salt-table row per ESSID)")
    p.add_argument("--max-tries", type=int, default=0,
                   help="transport attempts per server call before giving "
                        "up (0 = retry forever, reference behavior; "
                        "README 'Resilience')")
    p.add_argument("--backoff", type=float, default=123.0,
                   help="retry base delay in seconds, also the idle "
                        "(No nets) nap (reference interval 123)")
    p.add_argument("--retry-cap", type=float, default=None,
                   help="max retry delay for the decorrelated-jitter "
                        "exponential backoff (default: flat at --backoff, "
                        "reference parity; set higher, e.g. --backoff 2 "
                        "--retry-cap 120, for the ramp)")
    p.add_argument("--outbox-dir",
                   help="durable found-outbox directory: cracked PSKs "
                        "are journaled there before submission and "
                        "drained at startup/between units (default: "
                        "<workdir>/outbox)")
    p.add_argument("--prefetch-units", type=int, default=0,
                   help="extra work units leased ahead while the server "
                        "is reachable and cracked while the transport "
                        "circuit is OPEN (degraded mode; 0 = off, "
                        "single-host only)")
    p.add_argument("--multihost", action="store_true",
                   help="join a jax.distributed slice before any engine "
                        "work (TPU pod environment auto-detected); the "
                        "slice then acts as ONE volunteer — process 0 "
                        "owns the server conversation")
    p.add_argument("--coordinator",
                   help="manual cluster coordinator host:port (implies "
                        "--multihost; pair with --num-processes and "
                        "--process-id)")
    p.add_argument("--num-processes", type=int, help="manual cluster size")
    p.add_argument("--process-id", type=int, help="this host's rank")
    return p


def main(argv=None):
    from ..obs import setup_logging

    setup_logging()  # console format preserved; DWPA_LOG=json for pipelines
    parser = build_parser()
    args = parser.parse_args(argv)
    manual = (args.coordinator, args.num_processes, args.process_id)
    if args.multihost or any(v is not None for v in manual):
        if any(v is not None for v in manual) and None in manual:
            parser.error("--coordinator, --num-processes and --process-id "
                         "must be given together for a manual cluster")
        # Must run before anything touches the XLA backend (engine
        # construction included); multihost_mesh owns the init-ordering
        # contract for both the manual and the auto-detected path.
        from ..parallel.mesh import multihost_mesh

        multihost_mesh(coordinator=args.coordinator,
                       num_processes=args.num_processes,
                       process_id=args.process_id, auto_init=True)
    cfg = ClientConfig(
        base_url=args.base_url,
        workdir=args.workdir,
        dictcount=args.dictcount,
        batch_size=args.batch_size,
        additional_dict=args.additional_dict,
        potfile=args.potfile,
        max_work_units=args.max_work_units,
        nc=args.nc,
        rule_workers=args.rule_workers,
        feed_depth=args.feed_depth,
        feed_workers=args.feed_workers,
        pmk_cache_dir=args.pmk_cache_dir,
        pmk_cache_max_bytes=args.pmk_cache_max_bytes,
        dict_cache_dir=args.dict_cache_dir,
        dict_cache_max_bytes=args.dict_cache_max_bytes,
        unit_queue=args.unit_queue,
        fuse_max_units=args.fuse_max_units,
        device_streams=args.device_streams,
        max_tries=args.max_tries,
        backoff=args.backoff,
        retry_cap=args.retry_cap,
        outbox_dir=args.outbox_dir,
        prefetch_units=args.prefetch_units,
    )
    TpuCrackClient(cfg).run()


if __name__ == "__main__":
    main()
