"""Durable found outbox: founds survive anything between crack and ack.

A cracked PSK used to live only in process memory between the crack and a
successful ``put_work`` — a client crash, server outage, or rejected
submission lost it (the reference client has the same window,
help_crack.py:727-735).  The outbox closes the window with a CRC32-framed
append-only journal, the same framing/commit idioms as the PMK store and
dict cache:

- every found is journaled **and fsynced** before the first ``put_work``
  attempt — the journal, not the socket, is the durability point;
- a server ``OK`` appends an ``ack`` tombstone; acked keys are never
  re-submitted (a resume-replay re-crack of the same bssid would
  otherwise double-submit after a restart);
- replay at open dedups by ``(hkey, k)`` — the key field is the bssid,
  which has exactly one PSK — keeping the latest value;
- a torn tail (power loss mid-append) is truncated at the last valid
  frame and journaling continues: skip, not fatal;
- compaction rewrites pending founds + ack tombstones through
  tmp + fsync + ``os.replace`` + dir-fsync (``utils.fsio``).

``TpuCrackClient`` drains the outbox at startup and between work units;
``drain`` stops at the first transport failure (the server is down — the
next drain retries) but keeps going past per-key rejections.

The submitting crack loop owns the journal in today's wiring, but the
mutators (``record``/``ack``/``close``) and the replay all run under one
mutex anyway: the journal survives power loss, so it should not be
undone by a background drain thread interleaving ``_append`` frames or
double-creating the file — thread-safety is part of the durability
story, not an optimization (concurrency rule DW302).
"""

import binascii
import json
import os
import struct
import threading

from ..utils.fsio import fsync_dir, fsync_replace

FILE_MAGIC = b"DWOB1\n"
FRAME_MAGIC = b"OBXF"
_HDR = struct.Struct("<II")  # payload length, crc32(payload)

JOURNAL_NAME = "found_outbox.jrnl"


def _frame(record: dict) -> bytes:
    payload = json.dumps(record, sort_keys=True).encode()
    return FRAME_MAGIC + _HDR.pack(len(payload), binascii.crc32(payload)) + payload


def _walk_frames(blob: bytes):
    """Yield ``(record, end_offset)`` for every valid frame; stop at the
    first bad magic / short frame / CRC mismatch (torn tail)."""
    off = len(FILE_MAGIC)
    n = len(blob)
    while off < n:
        end = off + len(FRAME_MAGIC) + _HDR.size
        if blob[off:off + len(FRAME_MAGIC)] != FRAME_MAGIC or end > n:
            return
        length, crc = _HDR.unpack(blob[off + len(FRAME_MAGIC):end])
        payload = blob[end:end + length]
        if len(payload) != length or binascii.crc32(payload) != crc:
            return
        try:
            record = json.loads(payload)
        except ValueError:
            return
        off = end + length
        yield record, off


class FoundOutbox:
    def __init__(self, dirpath: str, registry=None):
        os.makedirs(dirpath, exist_ok=True)
        self.path = os.path.join(dirpath, JOURNAL_NAME)
        # One mutex over state + journal handle: record/ack interleaved
        # from two threads must never tear a frame or double-create the
        # file (module doc).
        self._mu = threading.Lock()
        # (hkey, k) -> v, insertion-ordered: drain submits in the order
        # founds were journaled.
        self._pending = {}
        self._acked = set()
        self._m_pending = self._m_acked = None
        if registry is not None:
            self._m_pending = registry.counter(
                "dwpa_outbox_pending_total",
                "founds journaled ahead of submission")
            self._m_acked = registry.counter(
                "dwpa_outbox_acked_total",
                "outbox founds acknowledged by the server")
        self._replay()
        # Journal creation is lazy (first append): a client that never
        # cracks anything never pays the create+fsync ceremony.
        self._f = None
        if os.path.exists(self.path):
            self._f = open(self.path, "r+b")
            self._f.seek(0, os.SEEK_END)

    # -- journal ----------------------------------------------------------

    def _replay(self):
        """Rebuild pending/acked state; truncate any torn tail; compact
        the journal if prior sessions left dead weight behind."""
        blob = b""
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                blob = f.read()
        if not blob.startswith(FILE_MAGIC):
            # Fresh (or unrecognizable) journal: start clean.  An
            # unrecognizable one is preserved next to the new journal
            # rather than silently destroyed.  Creation of the new
            # journal is deferred to the first append.
            if blob:
                os.replace(self.path, self.path + ".corrupt")
            return
        good_end = len(FILE_MAGIC)
        frames = 0
        with self._mu:
            for record, off in _walk_frames(blob):
                good_end = off
                frames += 1
                op = record.get("op")
                key = (record.get("hkey"), record.get("k"))
                if op == "found":
                    if key not in self._acked:
                        self._pending[key] = record.get("v")  # latest wins
                elif op == "ack":
                    self._acked.add(key)
                    self._pending.pop(key, None)
        live = len(self._pending) + len(self._acked)
        if good_end < len(blob) or frames > 2 * live:
            # Torn tail, or mostly superseded/duplicate frames: rewrite
            # the live state through the durable-commit path so appends
            # never chase garbage and the file stays bounded.
            self._commit_snapshot()

    def _commit_snapshot(self):
        tmp = self.path + f".tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(FILE_MAGIC)
            for (hkey, k) in self._acked:
                f.write(_frame({"op": "ack", "hkey": hkey, "k": k}))
            for (hkey, k), v in self._pending.items():
                f.write(_frame({"op": "found", "hkey": hkey, "k": k, "v": v}))
            f.flush()
        fsync_replace(tmp, self.path)

    def _append(self, records: list):
        # Caller holds ``_mu``: the lazy create and the frame writes
        # below must not interleave across threads.
        created = self._f is None
        if created:
            self._f = open(self.path, "w+b")
            self._f.write(FILE_MAGIC)
        for record in records:
            self._f.write(_frame(record))
        self._f.flush()
        os.fsync(self._f.fileno())
        if created:
            # First frame ever: also pin the directory entry so the
            # freshly created journal survives a crash.
            fsync_dir(os.path.dirname(os.path.abspath(self.path)))

    # -- API --------------------------------------------------------------

    def record(self, hkey: str, cand: list) -> list:
        """Journal founds before their first ``put_work`` attempt.

        Returns the sublist that actually needs submitting — entries
        whose ``(hkey, k)`` was already acked are dropped (the server
        has them; re-sending is the duplicate this outbox exists to
        prevent)."""
        fresh = []
        with self._mu:
            for c in cand:
                key = (hkey, c["k"])
                if key in self._acked:
                    continue
                if self._pending.get(key) == c["v"]:
                    fresh.append(c)  # already journaled, still needs sending
                    continue
                self._pending[key] = c["v"]
                fresh.append(c)
                self._append([{"op": "found", "hkey": hkey,
                               "k": c["k"], "v": c["v"]}])
                if self._m_pending is not None:
                    self._m_pending.inc()
        return fresh

    def ack(self, hkey: str, cand: list):
        """Mark founds as accepted by the server.  Idempotent."""
        acks = []
        with self._mu:
            for c in cand:
                key = (hkey, c["k"])
                if key in self._acked:
                    continue
                self._acked.add(key)
                self._pending.pop(key, None)
                acks.append({"op": "ack", "hkey": hkey, "k": c["k"]})
                if self._m_acked is not None:
                    self._m_acked.inc()
            if acks:
                self._append(acks)

    def pending(self) -> dict:
        """``{hkey: [{"k":…, "v":…}, …]}`` in journaled order."""
        out = {}
        with self._mu:
            items = list(self._pending.items())
        for (hkey, k), v in items:
            out.setdefault(hkey, []).append({"k": k, "v": v})
        return out

    def drain(self, put_work) -> int:
        """Submit every pending found through ``put_work(hkey, cand)``.

        Acks on ``True``; a ``False`` (server rejected) leaves the entry
        pending for the next drain; a ``ConnectionError`` stops the
        whole drain (transport is down — later drains retry).  Returns
        the number of founds delivered."""
        delivered = 0
        for hkey, cand in self.pending().items():
            try:
                ok = put_work(hkey, cand)
            except ConnectionError:
                break
            if ok:
                self.ack(hkey, cand)
                delivered += len(cand)
        return delivered

    def pending_count(self) -> int:
        with self._mu:
            return len(self._pending)

    def close(self):
        with self._mu:
            if self._f is not None:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()
                self._f = None
                fsync_dir(os.path.dirname(os.path.abspath(self.path)))
