"""The volunteer client main loop, with the TPU engine as the cracker.

Equivalent of the reference client's fetch->crack->submit loop
(help_crack.py run(), :881-957), redesigned around the on-device engine:

- challenge gate: before any work is fetched, the engine must crack a
  synthesized known-PSK PMKID + EAPOL pair (the reference uses hardcoded
  vectors, help_crack.py:690-725; we generate ours from the oracle, which
  additionally proves oracle/device agreement end-to-end);
- work loop: get_work -> resume snapshot -> dict download (md5-checked,
  cached by dhash) -> two-pass crack (pass 1: targeted candidates from the
  hash material + dynamic PR dict, no rules — mirroring the DAW client's
  testtarget/prdict flow, help_crack.py:615-665; pass 2: server dicts
  expanded through the server-supplied hashcat rules) -> put_work;
- dictcount autotune +/-1 against the 900 s work-unit pacing target,
  clamped 1..15 (help_crack.py:947-952, get_work.php:41-46);
- resume file: a JSON snapshot of the work unit written before cracking
  and replayed on restart (help_crack.py:737-763);
- potfile: founds appended as ``<hashline>:<psk>`` for user tooling.
"""

import base64
import itertools
import json
import os
import re
import time
from dataclasses import dataclass, field

import jax

from ..analysis import watch_compiles
from ..feed import CandidateFeed, DictFeedSource, RulesFeedSource
from ..feed.framing import frame_blocks
from ..gen import DictStream, psk_candidates
from ..gen.mask import mask_blocks
from ..models import hashline as hl
from ..models.m22000 import M22000Engine
from ..obs import (SpanTracer, default_registry, get_logger, is_emitter,
                   merged_slice_snapshot, setup_logging)
from ..rules import apply_rules, parse_rules
from ..utils.fsio import fsync_replace
from .. import __version__
from .. import testing as synth
from ..oracle import m22000 as oracle
from .outbox import FoundOutbox
from .protocol import NoNets, PermanentError, ServerAPI, VersionRejected
from .targeted import targeted_candidates

PACE_TARGET_S = 900.0  # work-unit pacing target (reference autotune threshold)
CHALLENGE_PSK = b"aaaa1234"


def _broadcast_json(obj):
    """Process 0's JSON-serializable ``obj`` (or None) to every host.

    The multi-host client contract (parallel/mesh.py multihost_mesh: a
    slice is "one very large volunteer"): exactly one host talks to the
    server per decision, and every host must then act on IDENTICAL data
    or the first shard_map collective deadlocks.  Two fixed-shape
    broadcasts: the byte length (-1 = None), then the padded payload —
    broadcast_one_to_all requires equal shapes on every host, so the
    length must be agreed before the buffer exists.
    """
    import numpy as np
    from jax.experimental import multihost_utils as mhu

    pid = jax.process_index()
    data = b"" if obj is None else json.dumps(obj).encode()
    n = int(mhu.broadcast_one_to_all(
        np.int64(-1 if pid == 0 and obj is None else len(data))))
    if n < 0:
        return None
    buf = np.zeros(n, np.uint8)
    if pid == 0:
        buf[:n] = np.frombuffer(data, np.uint8)
    buf = np.asarray(mhu.broadcast_one_to_all(buf))
    return json.loads(buf.tobytes().decode())


def _allgather_strs(s: str, width: int = 256):
    """Every host's (truncated) string, in process order.

    The fixed width keeps ``process_allgather``'s equal-shape contract
    without a length negotiation; used for slice-wide agreement checks
    (versions, digests, error flags) where every host MUST reach the
    collective — a raise before it would strand the peers inside it.
    """
    import numpy as np
    from jax.experimental import multihost_utils as mhu

    buf = np.zeros(width, np.uint8)
    b = (s or "").encode()[:width]
    buf[: len(b)] = np.frombuffer(b, np.uint8)
    rows = np.asarray(mhu.process_allgather(buf)).reshape(-1, width)
    return [bytes(r).rstrip(b"\0").decode("utf-8", "replace") for r in rows]


def shard_word_blocks(words, nproc: int, pid: int, batch_size: int,
                      pad_word: bytes = b""):
    """Block-slice a GLOBAL word stream into this host's 1/nproc shard,
    yielding ``(host_words, global_count)`` per block.

    The no-rules pass-2 analog of crack_rules' internal sharding (and of
    the host tail's ``submit_host`` slicing in m22000.crack_rules): every
    host consumes the identical global stream, takes its contiguous
    ``blk = ceil(len(block)/nproc)`` slice of each ``batch_size * nproc``
    block, and pads short slices with an invalid word so EVERY host feeds
    the engine the same number of same-sized batches — the SPMD-lockstep
    contract ``M22000Engine.crack`` requires.  ``global_count`` is the
    number of real global candidates the block covers, so resume
    checkpoints keep counting stream positions, not local shard rows.

    Kept for API compat: the framing itself now lives in
    ``dwpa_tpu.feed.framing.frame_blocks``, which emits the IDENTICAL
    ``(mine, global_count)`` sequence but buffers only the words that
    can land in this host's slice instead of materializing the full
    ``batch_size * nproc`` global block on every host.
    """
    for blk in frame_blocks(words, batch_size, nproc=nproc, pid=pid,
                            pad_word=pad_word):
        yield blk.words, blk.count


def version_tuple(v: str):
    """Order dotted versions with optional alpha suffixes, matching the
    reference's numeric+alpha compare (help_crack.py:128-156)."""
    parts = []
    for piece in v.strip().split("."):
        m = re.match(r"(\d*)(.*)", piece)
        parts.append((int(m.group(1) or 0), m.group(2)))
    return tuple(parts)


@dataclass
class ClientConfig:
    base_url: str
    workdir: str = "hc_work"
    dictcount: int = 1
    batch_size: int = 16384
    additional_dict: str = None     # -ad equivalent
    potfile: str = None             # -pot equivalent (default: workdir/potfile)
    nc: int = 8
    max_work_units: int = 0         # 0 = run forever
    pace_target: float = PACE_TARGET_S
    cracked_refresh: int = 100      # re-download cracked/rkg dicts every
                                    # N work units (DAW dl_count cadence,
                                    # help_crack.py:47,524-529)
    rule_workers: int = 0           # >1: expand PASS-1 rules (cracked/rkg
                                    # dicts) in a process pool; pass 2
                                    # mangles on device (0 = inline)
    feed_depth: int = 2             # candidate-feed queue depth (blocks
                                    # framed ahead of the engine)
    feed_workers: int = None        # candidate-feed producer threads
                                    # (None = one per local device,
                                    # parallel.streams.default_feed_workers;
                                    # 0 = inline/synchronous feed)
    archive: bool = True            # append-only archive.22000/archive.res
                                    # audit logs (DAW, help_crack.py:453-456)
    pmk_cache_dir: str = None       # --pmk-cache-dir: persistent cross-unit
                                    # PBKDF2->PMK cache (dwpa_tpu/pmkstore)
    pmk_cache_max_bytes: int = 256 * 1024 * 1024
                                    # --pmk-cache-max-bytes: store size cap
                                    # (oldest segments evicted beyond it)
    dict_cache_dir: str = None      # --dict-cache-dir: persistent packed
                                    # dictionary cache keyed by dhash
                                    # (dwpa_tpu/feed/dictcache)
    dict_cache_max_bytes: int = 4 * 1024 * 1024 * 1024
                                    # --dict-cache-max-bytes: cache size cap
                                    # (least-recently-used dicts evicted
                                    # beyond it)
    unit_queue: int = 4             # --unit-queue: work units prefetched
                                    # ahead of the device by the fused
                                    # executor (dwpa_tpu/sched)
    fuse_max_units: int = 8         # --fuse-max-units: max work units
                                    # packed into one fused device batch
                                    # (one salt-table row per ESSID)
    device_streams: str = "auto"    # --device-streams: independent
                                    # per-device crack streams vs lockstep
                                    # shard_map dispatch ("auto": streams
                                    # on single-process multi-device,
                                    # lockstep elsewhere; "on"/"off" force)
    max_tries: int = 0              # --max-tries: transport attempts per
                                    # call (0 = retry forever, reference
                                    # behavior)
    backoff: float = 123.0          # --backoff: retry base delay; also
                                    # the idle (No nets) nap
    retry_cap: float = None         # --retry-cap: max retry delay for the
                                    # decorrelated-jitter ramp (None =
                                    # flat at --backoff, reference parity)
    outbox_dir: str = None          # --outbox-dir: durable found outbox
                                    # journal dir (default workdir/outbox)
    prefetch_units: int = 0         # --prefetch-units: extra work units
                                    # leased ahead while the transport is
                                    # healthy, cracked while it is OPEN
                                    # (degraded mode; single-host only)


@dataclass
class WorkResult:
    hkey: str
    founds: list
    elapsed: float
    accepted: bool = False
    candidates_tried: int = 0


class TpuCrackClient:
    def __init__(self, config: ClientConfig, api: ServerAPI = None, log=None,
                 registry=None):
        self.cfg = config
        self.api = api or ServerAPI(
            config.base_url, max_tries=config.max_tries,
            backoff=config.backoff, retry_cap=config.retry_cap)
        if log is None:
            # one logging config for the whole process (obs.setup_logging
            # is idempotent); DWPA_LOG=json switches to structured lines
            setup_logging()
            log = get_logger("client").info
        self.log = log
        # Telemetry: all client metrics/spans land in one registry
        # (injectable for tests; default: the process-wide one).  The
        # transport layer is bound to the same registry so get_work/
        # put_work/dict-download counters + spans appear next to the
        # crack-loop spans.  Recording is pure host-side work — nothing
        # here may touch a device value (lint rule DW106).
        self.registry = registry or default_registry()
        self.tracer = SpanTracer(self.registry)
        bind = getattr(self.api, "bind_obs", None)
        if bind is not None:  # duck-typed test doubles stay unbound
            bind(self.registry, self.tracer)
        reg = self.registry
        self._m_pmks = reg.gauge(
            "dwpa_client_pmk_per_s",
            "candidates/s through the engine, by crack pass")
        self._m_autotune = reg.counter(
            "dwpa_client_autotune_total",
            "dictcount autotune decisions, by direction")
        self._m_dictcount = reg.gauge(
            "dwpa_client_dictcount", "current work-unit dictionary count")
        self._m_resume = reg.counter(
            "dwpa_client_resume_skipped_total",
            "candidates fast-forwarded by resume replay")
        self._m_recompiles = reg.counter(
            "dwpa_client_recompiles_total",
            "XLA compile-cache misses observed inside work units")
        self._m_units = reg.counter(
            "dwpa_client_work_units_total",
            "work units completed, by server verdict")
        self._m_founds = reg.counter(
            "dwpa_client_founds_total", "cracked PSKs recovered")
        self._m_engine_retries = reg.counter(
            "dwpa_client_engine_retries_total",
            "work units retried in-process after an engine error")
        # Fused-executor families are registered up front (idempotent by
        # name — fused_executor() binds the same series) so a metrics
        # scrape shows them at zero before the first fused wave runs.
        from ..sched.executor import UNITS_PER_BATCH_BUCKETS

        reg.histogram(
            "dwpa_fused_units_per_batch",
            "Work units packed into each fused device batch",
            buckets=UNITS_PER_BATCH_BUCKETS)
        reg.gauge("dwpa_fused_fill_fraction",
                  "Real-candidate fraction of the last fused batch")
        reg.gauge("dwpa_unit_queue_depth",
                  "Prefetched work units waiting in the executor queue")
        # Device-stream families (parallel/streams.py) — same up-front
        # registration so the scrape surface is stable; the per-device
        # labeled series appear once the first stream dispatches.
        reg.counter("dwpa_stream_blocks_total",
                    "Feed blocks completed per device stream")
        reg.gauge("dwpa_stream_busy_fraction",
                  "Per-stream fraction of wall time spent in "
                  "prepare/dispatch/collect (1 - shared-queue wait)")
        reg.gauge("dwpa_stream_queue_depth",
                  "Shared work-queue depth at this stream's last pull")
        if config.additional_dict and jax.process_count() > 1:
            # A per-host local file cannot feed a multi-host slice: the
            # pass-1 streams must be byte-identical on every host or the
            # shard_map collectives deadlock (same reason the cracked/rkg
            # snapshots are digest-checked).  Publish it as a server dict.
            raise SystemExit(
                "additional_dict is host-local; on a multi-host mesh "
                "publish it as a server dictionary instead")
        os.makedirs(config.workdir, exist_ok=True)
        self.dictdir = os.path.join(config.workdir, "dicts")
        os.makedirs(self.dictdir, exist_ok=True)
        # Durable found outbox: every found is journaled before its first
        # put_work attempt and drained at startup/between units, so a
        # crash or server outage between crack and ack cannot lose a PSK.
        # All hosts open a journal (cheap); only process 0 — the slice's
        # server voice — ever records or drains.
        self.outbox = FoundOutbox(
            config.outbox_dir or os.path.join(config.workdir, "outbox"),
            registry=self.registry)
        # Degraded-mode unit buffer (_prefetch_units): units leased ahead
        # while the transport is healthy, cracked while it is OPEN.
        self._unit_buffer = []
        # Cold-start: persist XLA compilations under the workdir so a
        # restarted client skips the ~20-40 s PBKDF2 compile (SURVEY §5.4
        # resume latency; tracked by bench.py unit_overhead).
        from ..utils.compcache import enable_compilation_cache

        enable_compilation_cache(os.path.join(config.workdir, "xla_cache"))
        # Persistent PMK store (optional): repeat (ESSID, word) pairs —
        # popular ESSIDs across uploads, overlapping dicts, pass-2
        # replays of pass-1 words — become disk hits instead of PBKDF2.
        self.pmk_store = None
        if config.pmk_cache_dir:
            if jax.process_count() > 1:
                # The mixed hit/miss dispatch needs every host to agree
                # on the miss sub-batch width before the shard_map enters
                # (a collective the producer thread must not run), so the
                # store stays off on a slice until that exists.
                self.log("pmk store: disabled on a multi-host slice "
                         "(miss-width agreement is per-host for now)")
            else:
                from ..pmkstore import PMKStore

                self.pmk_store = PMKStore(
                    config.pmk_cache_dir,
                    max_bytes=config.pmk_cache_max_bytes,
                    registry=self.registry)
        # Persistent packed-dictionary cache (optional): pass-2 server
        # dicts — ~100%-recurring inputs keyed by dhash — are served as
        # mmap'd pre-packed blocks on every unit after the first (zero
        # gunzip/packing, O(1) resume and shard seeks).  Safe on any
        # mesh: per-dict framing derives identical block geometry from
        # the dict word counts whatever each host's cache state, and a
        # changed server dict gets a new dhash (old entries age out of
        # the LRU cap).
        self.dict_cache = None
        if config.dict_cache_dir:
            from ..feed.dictcache import DictCache

            self.dict_cache = DictCache(
                config.dict_cache_dir,
                max_bytes=config.dict_cache_max_bytes,
                registry=self.registry)
        self.resume_path = os.path.join(config.workdir, "resume.json")
        self._digest_cache = {}  # (path, size, mtime_ns) -> md5 hex
        self.potfile = config.potfile or os.path.join(config.workdir, "potfile")
        self.dictcount = max(1, min(15, config.dictcount))
        self._m_dictcount.set(self.dictcount)
        # cracked/rkg refresh countdown: primed to refresh on first use,
        # then every cfg.cracked_refresh units (DAW dl_count semantics).
        self._cracked_countdown = 0
        self._resuming = False

    # -- self-update (help_crack.py:158-189) --------------------------------

    def check_update(self) -> bool:
        """Probe the server-published client version; download on newer.

        The reference overwrites sys.argv[0] and exits; a package can't
        safely self-overwrite mid-import, so the new archive lands in the
        workdir and run() exits for the supervisor to swap it in —
        operationally the same restart-to-update contract.
        """
        manifest = self.api.remote_version().split()
        # Manifest: "<version> [archive-md5]".  It must look like a
        # version — a misconfigured server returning an HTML page for the
        # probe must not trigger updates.
        remote = manifest[0] if manifest else ""
        md5 = manifest[1] if len(manifest) > 1 else None
        if not remote or not re.fullmatch(r"[0-9]+(\.[0-9]+)*[a-z0-9]*", remote):
            return False
        if version_tuple(remote) <= version_tuple(__version__):
            return False
        dest = os.path.join(self.cfg.workdir, f"dwpa_tpu-{remote}.pyz")
        try:
            # Bounded tries: a manifest pointing at a missing archive must
            # not park the crack loop in the infinite-retry backoff.
            self.api.download("hc/dwpa_tpu.pyz", dest, expected_md5=md5,
                              max_tries=2)
        except (ConnectionError, ValueError, OSError) as e:
            self.log(f"update {remote} advertised but download failed: {e}")
            return False
        self.log(f"update {__version__} -> {remote} downloaded to {dest}; restart to apply")
        return True

    # -- challenge gate ----------------------------------------------------

    def challenge(self) -> bool:
        """Known-PSK self-test; any failure disqualifies this cracker."""
        lines = [
            synth.make_pmkid_line(CHALLENGE_PSK, b"dlink", seed="challenge-p"),
            synth.make_eapol_line(CHALLENGE_PSK, b"dlink", keyver=2, seed="challenge-e"),
        ]
        with self.tracer.span("challenge"):
            eng = M22000Engine(lines, nc=self.cfg.nc, batch_size=64)
            words = [b"notit%04d" % i for i in range(63)] + [CHALLENGE_PSK]
            founds = eng.crack(words)
        ok = len(founds) == 2 and all(f.psk == CHALLENGE_PSK for f in founds)
        self.log(f"challenge: {'passed' if ok else 'FAILED'}")
        if ok:
            self.prewarm()
        return ok

    # -- device-stream plumbing (parallel/streams.py) ----------------------

    def _feed_workers(self) -> int:
        """Configured producer count, defaulting to one per local device
        so an N-stream mesh never starves behind a single producer."""
        if self.cfg.feed_workers is not None:
            return self.cfg.feed_workers
        from ..parallel.streams import default_feed_workers

        return default_feed_workers()

    def _use_streams(self) -> bool:
        """Whether bulk passes run as independent device streams
        (``crack_streams``) instead of lockstep dispatch: "on"/"off"
        force it; "auto" follows ``streams_default()`` — streams on
        single-process multi-device, lockstep on multi-host slices
        (where the global hits-gate is genuinely needed) and on a
        single chip (where they are the same thing)."""
        mode = self.cfg.device_streams
        if mode == "on":
            return True
        if mode == "off":
            return False
        from ..parallel.streams import streams_default

        return streams_default()

    def _crack_blocks(self, engine, feed, on_batch=None):
        """Route one framed block stream through streams or lockstep,
        preserving the ``on_batch`` resume contract either way."""
        if self._use_streams():
            return engine.crack_streams(feed, on_batch=on_batch,
                                        registry=self.registry,
                                        tracer=self.tracer)
        return engine.crack_blocks(feed, on_batch=on_batch)

    def prewarm(self):
        """Compile (or cache-load) the work-sized crack steps behind the
        challenge gate, so the first work unit never stalls on XLA.

        Covers the PBKDF2 shapes real units hit — the configured batch
        size at every trimmed candidate width (W=4 for words <= 16
        chars — nearly every dict — W=8 up to 32, W=16 for the 33-63
        passphrase tail) — through a MIXED ESSID group (PMKID + one
        EAPOL per keyver bucket + CMAC), so every verify kind's step and
        the mixed-group assembly compile here, not on the first real
        unit.  A unit can still pay a small verify compile for an
        unusual (V variants, EAPOL blocks) bucket; the dominant PBKDF2
        trace is shared regardless.  With the persistent cache (see
        __init__) the compile happens once per installation; afterwards
        this is ~0.2 s of device work.
        """
        # perf_counter, not time.time(): an NTP step mid-prewarm must not
        # corrupt the logged duration (same rule as the pacing clock)
        sp = self.tracer.start("prewarm")
        eng = M22000Engine(
            [
                synth.make_pmkid_line(CHALLENGE_PSK, b"dlink", seed="challenge-p"),
                synth.make_eapol_line(CHALLENGE_PSK, b"dlink", keyver=1,
                                      seed="warm-k1"),
                synth.make_eapol_line(CHALLENGE_PSK, b"dlink", keyver=2,
                                      seed="challenge-e"),
                synth.make_eapol_line(CHALLENGE_PSK, b"dlink", keyver=3,
                                      seed="warm-k3"),
            ],
            nc=self.cfg.nc, batch_size=self.cfg.batch_size,
        )
        n = eng.batch_size
        # The three width buckets stream through the candidate feed —
        # one block per bucket — so prewarm also exercises (and warms)
        # the exact feed -> stage -> dispatch path real units take.
        warm_words = itertools.chain(
            (b"warm-%08d" % i for i in range(n)),
            (b"warm-long-padding-%08d" % i for i in range(n)),
            (b"warm-full-width-passphrase-padding-%08d" % i
             for i in range(n)),
        )
        feed = CandidateFeed(warm_words, batch_size=n,
                             depth=self.cfg.feed_depth,
                             producers=self._feed_workers(),
                             prepack=eng.host_packer(),
                             registry=self.registry, name="prewarm")
        try:
            # Streams mode warms the per-device single-mesh engines (the
            # shapes real units hit); lockstep warms the shard_map path.
            self._crack_blocks(eng, feed)
        finally:
            feed.close()
        if jax.process_count() == 1:
            # Pass 2 runs through the fused device-rules step now; warm
            # both interpreter step buckets so a first unit carrying
            # server rules doesn't stall on the fused-step compile —
            # through the SAME blocks/streams entry the real pass-2
            # takes, so streams mode warms the 1-device rules step on
            # every chip, not the full-mesh shape it will never run.
            from ..feed.framing import frame_blocks
            from ..rules import parse_rules

            wrules = parse_rules([":", "c $1 $2"])
            wblocks = frame_blocks(
                (b"warm-%08d" % i for i in range(n)), n)
            if self._use_streams():
                eng.crack_rules_streams(wblocks, wrules,
                                        registry=self.registry,
                                        tracer=self.tracer)
            else:
                eng.crack_rules_blocks(wblocks, wrules,
                                       registry=self.registry,
                                       tracer=self.tracer)
        # crack_batch/crack_rules sync internally (hits gate), so the
        # span's clock stops after real device completion
        sp.stop()
        self.log(f"prewarm: work-size steps ready in {sp.seconds:.1f}s")

    # -- work-unit plumbing ------------------------------------------------

    def _write_resume(self, work: dict):
        # Atomic replace: the checkpoint is rewritten mid-unit after every
        # batch, and a crash during the write must never corrupt the only
        # copy (a truncated snapshot would be discarded on restart and the
        # whole work unit lost until the server's lease reap).
        # The version + mesh-topology + batch-size stamps gate replay:
        # skip-by-count is only sound against the exact stream order this
        # client build generates.  An upgrade and a single-/multi-process
        # topology change reorder pass 2 (device crack_rules order vs
        # host apply_rules order), and the batch size changes crack_rules'
        # chunk boundaries (base-batch major order means a different -b
        # interleaves (word, rule) pairs differently) — a mismatched
        # resume could silently skip candidates that were never tried.
        work["_ver"] = __version__
        work["_nproc"] = jax.process_count()
        work["_batch"] = self.cfg.batch_size
        # fsync file AND directory around the replace (utils.fsio): a
        # bare os.replace is atomic against crashes of this process but
        # not against power loss — the rename can reach disk before the
        # tmp file's data, resurrecting an older-but-valid checkpoint
        # whose skip count double-counts candidates never re-tried.
        tmp = self.resume_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(work, f)
            f.flush()
        fsync_replace(tmp, self.resume_path)

    def _clear_resume(self):
        if os.path.exists(self.resume_path):
            os.unlink(self.resume_path)

    def _read_resume(self) -> dict:
        if not os.path.exists(self.resume_path):
            return None
        try:
            with open(self.resume_path) as f:
                work = json.load(f)
            if ("hkey" in work and "hashes" in work and "dicts" in work
                    and work.get("_ver") == __version__
                    and work.get("_nproc") == jax.process_count()
                    and work.get("_batch") == self.cfg.batch_size):
                return work
        except (ValueError, OSError):
            pass
        self._clear_resume()
        return None

    def _file_digest(self, path: str) -> str:
        """md5 of a workdir file, cached by (size, mtime): the cracked/
        rkg snapshots only change on the refresh cadence, and the
        multi-host agreement check runs every unit — re-hashing a
        many-MB file per unit per host would tax the crack loop for no
        information."""
        import hashlib

        st = os.stat(path)
        key = (path, st.st_size, st.st_mtime_ns)
        hit = self._digest_cache.get(key)
        if hit is None:
            h = hashlib.md5()
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            hit = self._digest_cache[key] = h.hexdigest()
        return hit

    def _fetch_dicts(self, work: dict) -> list:
        """Download (or reuse cached) pass-2 work dicts; returns local
        paths.  cracked.txt.gz is excluded — it runs in pass 1 via
        ``_cracked_candidates`` (the DAW client likewise removes it from
        the rules pass, help_crack.py:927-928)."""
        paths = []
        for d in work.get("dicts", []):
            if os.path.basename(d["dpath"]) == "cracked.txt.gz":
                continue
            dest = os.path.join(self.dictdir, d["dhash"] + ".gz")
            if not os.path.exists(dest):
                self.api.download(d["dpath"], dest, expected_md5=d["dhash"])
            paths.append(dest)
        return paths

    @staticmethod
    def _dict_key(path: str) -> str:
        """Dict-cache key for a pass-2 path: server dicts land as
        ``<dictdir>/<dhash>.gz`` (``_fetch_dicts``), so the basename IS
        the md5 the server published — and a regenerated dict gets a
        new dhash, which is the cache's invalidation rule.  Paths not
        named by an md5 (e.g. ``additional_dict``) return None and
        stream cold, uncached."""
        stem = os.path.splitext(os.path.basename(path))[0]
        return stem if re.fullmatch(r"[0-9a-f]{32}", stem) else None

    def _cracked_candidates(self, work: dict, rules):
        """Pass-1 stream of the server's cracked + rkg dictionaries,
        expanded through the work rules (compat wrapper: prefetch +
        stream — ``_process_work`` calls the two halves separately so
        the downloads and the multi-host digest agreement stay on the
        consumer thread while the streaming runs on feed producers)."""
        files = None

        def deferred():
            nonlocal files
            if files is None:  # first pull: fetch, then stream
                files = self._prefetch_cracked(work)
            yield from self._stream_cracked(files, rules)

        return deferred()

    def _prefetch_cracked(self, work: dict) -> list:
        """Download/refresh the cracked + rkg snapshots and agree on
        their digests across the slice; returns the local file list.

        CONSUMER-THREAD ONLY (server calls + a collective): feed
        producer threads stream the returned files via
        ``_stream_cracked`` but must never fetch (lint rule DW107's
        discipline — collectives off the producer threads).

        DAW behavior (help_crack.py:469-509,512-529): when a work unit
        carries cracked.txt.gz, keep a local copy refreshed only every
        ``cracked_refresh`` units, fetch rkg.txt.gz alongside it
        (best-effort — stock servers serve it as a plain artifact), and
        run both through the rule set before everything else: previously
        cracked and vendor-default keys are the highest-yield candidates.
        """
        entry = next(
            (d for d in work.get("dicts", [])
             if os.path.basename(d["dpath"]) == "cracked.txt.gz"),
            None,
        )
        if entry is None:
            return []
        cracked = os.path.join(self.dictdir, "cracked.txt.gz")
        rkg = os.path.join(self.dictdir, "rkg.txt.gz")
        # The cadence refresh is suppressed while replaying a resumed
        # unit (the skip-by-count fast-forward needs the same bytes the
        # crashed run streamed), but a *missing* file is always fetched —
        # yielding nothing would submit the unit with its highest-yield
        # candidates never tried.
        cadence = self._cracked_countdown <= 0 and not self._resuming
        if cadence or not os.path.exists(cracked):
            try:
                self.api.download(entry["dpath"], cracked, max_tries=2,
                                  expected_md5=entry.get("dhash"))
                self._cracked_countdown = self.cfg.cracked_refresh
            except (ConnectionError, ValueError, OSError):
                pass
            try:
                self.api.download("dict/rkg.txt.gz", rkg, max_tries=1)
            except (ConnectionError, ValueError, OSError):
                pass
        self._cracked_countdown -= 1
        files = [p for p in (cracked, rkg) if os.path.exists(p)]
        if jax.process_count() > 1:
            # cracked/rkg are NOT md5-pinned (best-effort artifacts), so
            # a server-side regen between two hosts' downloads could hand
            # the slice different bytes — the pass-1 streams would then
            # diverge in length and the shard_map collectives deadlock.
            # allgather (not a host-0 broadcast: host 0's view always
            # matches itself) so EVERY host sees every digest and all
            # raise together instead of stranding the one that noticed.
            mine = ",".join(
                f"{os.path.basename(p)}:{self._file_digest(p)}" for p in files)
            alld = _allgather_strs(mine)
            if len(set(alld)) != 1:
                raise RuntimeError(
                    "multi-host pass-1 dict snapshot mismatch (cracked/rkg "
                    "raced a server regen) — delete the local copies and "
                    f"restart the unit; digests: {alld}")
        return files

    def _stream_cracked(self, files: list, rules):
        """Stream the prefetched cracked/rkg files through the work
        rules — pure host work, safe on a feed producer thread."""
        for path in files:
            stream = DictStream(path)
            yield from (apply_rules(rules, stream, workers=self.cfg.rule_workers)
                        if rules else stream)

    def _snapshot_prdict(self, work: dict):
        """Snapshot the dynamic PR dict into the work/resume state.

        CONSUMER-THREAD ONLY, hoisted ahead of the pass-1 feed: the
        server query, the multi-host broadcast AND the resume write must
        not run on a producer thread (collectives would race the
        engine's shard_map enqueue order across hosts, and two threads
        must never mutate/serialize the shared ``work`` dict).

        The server-side query is unordered and grows with new
        submissions, so re-fetching after a crash would misalign the
        resume's skip-by-count fast-forward; the snapshot rides every
        checkpoint write, making the stream deterministic.  Multi-host:
        only process 0 queries (the unordered result MUST be
        byte-identical on every host or the pass-1 stream lengths
        diverge and the shard_map collectives desync).
        """
        if not work.get("prdict") or "_prdict_cache" in work:
            return
        hexes = None
        if jax.process_index() == 0:
            try:
                words = self.api.get_prdict(work["hkey"])
            except (ConnectionError, ValueError, OSError):
                # OSError covers gzip.BadGzipFile etc.; a host-0 raise
                # here would strand the peers already parked in the
                # broadcast below
                words = []
            hexes = [w.hex() for w in words]
        if jax.process_count() > 1:
            hexes = _broadcast_json(hexes) or []
        work["_prdict_cache"] = hexes
        self._write_resume(work)

    def _rules(self, work: dict):
        blob = work.get("rules")
        if not blob:
            return []
        try:
            text = base64.b64decode(blob).decode("utf-8", "replace")
        except ValueError:
            return []
        return parse_rules(text.splitlines())

    def _targeted_candidates(self, engine: M22000Engine, work: dict):
        """Pass-1 generator, in the DAW client's priority order
        (help_crack.py:615-687): ESSID-fingerprint family keyspaces
        first, then hash-material candidates, the dynamic PR dict, and
        any local additional dictionary.

        Derived from ``work["hashes"]`` — NOT the live engine view: the
        engine prunes nets on a find, so a stream generated from
        ``engine.groups``/``engine.nets`` after a mid-unit find would be
        shorter than the fresh-engine stream a resume rebuilds, and the
        skip-by-count fast-forward would under-skip.  Parsing the
        checkpointed hash list keeps the stream a pure function of the
        resume snapshot."""
        parsed = []
        for raw in work.get("hashes", []):
            try:
                parsed.append(hl.parse(raw))
            except ValueError:
                continue  # engine skips it too (M22000Engine.skipped)
        essids = list(dict.fromkeys(h.essid for h in parsed))
        yield from targeted_candidates(essids)
        for h in parsed:
            yield from psk_candidates(h.essid, h.mac_ap, h.mac_sta)
        # The dynamic PR dict reads ONLY the snapshot ``_snapshot_prdict``
        # hoisted into the work state before the feed started — this
        # generator runs on a producer thread and must stay pure host
        # work (no server calls, no collectives, no resume writes).
        for wx in work.get("_prdict_cache") or []:
            yield oracle.hc_unhex(bytes.fromhex(wx))
        if self.cfg.additional_dict:
            yield from DictStream(self.cfg.additional_dict)

    def _record_founds(self, founds: list):
        # flush + fsync per found: the PSK is (or is about to be)
        # reported to the server, so a crash between the append and the
        # page cache reaching disk must not lose the operator's only
        # local copy of a cracked key.
        with open(self.potfile, "a") as f:
            for fd in founds:
                f.write(f"{fd.line.raw}:{fd.psk.decode('latin1')}\n")
                f.flush()
                os.fsync(f.fileno())

    def _archive_work(self, work: dict):
        """Append-only audit logs (DAW fork, help_crack.py:453-456,
        741-743): every work unit's hashlines land in archive.22000 and
        its resume snapshot in archive.res, so an operator can replay or
        post-mortem any unit the client ever handled."""
        if not self.cfg.archive:
            return
        with open(os.path.join(self.cfg.workdir, "archive.22000"), "a") as f:
            for line in work.get("hashes", []):
                f.write(line + "\n")
        with open(os.path.join(self.cfg.workdir, "archive.res"), "a") as f:
            f.write(json.dumps({k: v for k, v in work.items()
                                if not k.startswith("_")}) + "\n")

    # -- the loop ----------------------------------------------------------

    def _pass1_candidates(self, work: dict, rules, cracked_files: list):
        """Pass-1 deterministic host-side stream: targeted generators,
        then cracked/rkg through the work rules (highest-yield first,
        help_crack.py:615-687).  Pure host work — runs on the feed's
        producer threads; every server call/collective was hoisted
        (``_snapshot_prdict`` / ``_prefetch_cracked``)."""
        yield from self._targeted_candidates(None, work)
        yield from self._stream_cracked(cracked_files, rules)

    def _fetch_pass2_paths(self, work: dict) -> list:
        """Fetch the pass-2 server dicts; returns local paths.

        CONSUMER-THREAD ONLY, at pass-2 start (a resume that skipped
        pass 1 still fetches here; the feed's producers then stream
        pure file reads).  Multi-host: a download failure on ONE host
        (e.g. the md5 gate tripping because the server regenerated a
        dict between two hosts' fetches) must abort the whole slice
        loudly — every host reaches the allgather below even on
        failure, then all raise together instead of one host crashing
        out of the stream while its peers block in the crack
        collectives."""
        err = None
        try:
            paths = self._fetch_dicts(work)
        except (ConnectionError, ValueError, OSError) as e:
            if jax.process_count() <= 1:
                raise
            err, paths = f"{type(e).__name__}: {e}", []
        if jax.process_count() > 1:
            errs = [e for e in _allgather_strs(err or "") if e]
            if errs:
                raise RuntimeError(
                    f"pass-2 dict fetch failed on the slice: {errs}")
        return paths

    def process_work(self, work: dict) -> WorkResult:
        """One work unit, traced end to end: the ``work_unit`` span
        parents the phase spans (pass1/pass2 here; dict_download and
        put_work via the bound transport), and the pass PMK/s gauges +
        recompile counter record inside."""
        with self.tracer.span("work_unit"):
            return self._process_work(work)

    def _process_work(self, work: dict) -> WorkResult:
        # perf_counter: the elapsed drives the 900 s dictcount autotune
        # and the logged unit time — a wall-clock NTP step must not
        # corrupt either (time.time() did exactly that before)
        t0 = time.perf_counter()
        # Intra-unit resume (the hashcat --session analog): _progress
        # carries completed-candidate count and prior founds; the stream
        # is deterministic, so skipping replays exactly the unfinished
        # tail (at-least-once: a half-done batch is re-tried).
        # Persist the snapshot as-read (progress included) BEFORE popping:
        # a crash during the skip fast-forward below must not regress the
        # checkpoint to zero.
        self._write_resume(work)
        progress = work.pop("_progress", None) or {}
        skip = int(progress.get("done", 0))
        # Mask shards keep their own progress counter: "done" counts the
        # pass-1/2 candidate stream, "mask_done" counts mask-keyspace
        # candidates — mixing them would make the pass-1 fast-forward
        # skip dict candidates that were never tried.
        mask_skip = int(progress.get("mask_done", 0))
        if jax.process_count() > 1:
            # Hosts may have checkpointed different done counts before a
            # crash; the pass-2 device path requires an identical skip
            # everywhere (SPMD lockstep), so all hosts adopt process 0's
            # (at-least-once: a lower value only re-tries candidates).
            import numpy as _np
            from jax.experimental import multihost_utils

            agreed = multihost_utils.broadcast_one_to_all(
                _np.array([skip, mask_skip], _np.int64))
            skip, mask_skip = int(agreed[0]), int(agreed[1])
        self._resuming = skip > 0 or mask_skip > 0
        if skip or mask_skip:
            self._m_resume.inc(skip + mask_skip)
        if not self._resuming:
            # once per unit: a resume replay must not duplicate the entry
            self._archive_work(work)
        prior_cand = list(progress.get("cand", []))
        engine = M22000Engine(
            work["hashes"], nc=self.cfg.nc, batch_size=self.cfg.batch_size,
            pmk_store=self.pmk_store,
        )
        founds = []
        done = skip
        mask_done = mask_skip

        def _checkpoint():
            work["_progress"] = {
                "done": done,
                "mask_done": mask_done,
                "cand": prior_cand
                + [{"k": f.line.mac_ap.hex(), "v": f.psk.hex()} for f in founds],
            }
            self._write_resume(work)

        def on_batch(consumed, new_founds):
            nonlocal done
            done += consumed
            founds.extend(new_founds)
            _checkpoint()

        def on_mask_batch(consumed, new_founds):
            nonlocal mask_done
            mask_done += consumed
            founds.extend(new_founds)
            _checkpoint()

        # Pass 1 materializes host-side, so its resume fast-forward is
        # the feed's producer-side skip; whatever the window doesn't
        # cover carries into pass 2.  Pass-2 rules run ON DEVICE
        # (crack_rules: one base-word upload mangled by every rule — the
        # hashcat-on-GPU analog of help_crack.py:773's ``-S -r``), where
        # candidates never exist host-side; crack_rules' own skip honors
        # the same count contract.
        #
        # Both passes consume from the candidate feed (dwpa_tpu/feed):
        # producer threads run the host stages (streaming, rule
        # expansion, $HEX decode + packing) behind a bounded block
        # queue, so the mesh never idles on host work — every server
        # call, collective and resume write is hoisted onto this
        # (consumer) thread first, the producer-thread discipline lint
        # rule DW107 documents.
        rules = self._rules(work)
        cfg_feed = dict(depth=self.cfg.feed_depth,
                        producers=self._feed_workers(),
                        registry=self.registry)
        self._snapshot_prdict(work)
        # The compile sentinel wraps both passes: a steady-state unit
        # must not pay XLA time (prewarm covered the shapes), and when
        # one does, the counter makes it visible fleet-wide instead of
        # showing up only as a mysteriously slow unit.
        with watch_compiles() as comp:
            with self.tracer.span("pass1") as sp1:
                cracked_files = self._prefetch_cracked(work)
                if skip:
                    self.log(f"resuming work unit at candidate {skip}")
                feed1 = CandidateFeed(
                    self._pass1_candidates(work, rules, cracked_files),
                    batch_size=self.cfg.batch_size, skip=skip, nproc=1,
                    pid=0, prepack=engine.host_packer(), name="pass1",
                    **cfg_feed)
                try:
                    self._crack_blocks(engine, feed1, on_batch=on_batch)
                    # actually-skipped count (< skip on a short stream);
                    # the remainder of the resume window carries into
                    # pass 2.  The skip ran before any framing, so this
                    # never blocks on device work.
                    skipped = feed1.skipped
                finally:
                    feed1.close()
            # engine crack_blocks syncs internally (hits gate), so sp1's
            # clock stopped after real device completion; the gauge
            # counts candidates/s — PMKs computed per candidate per
            # essid group
            tried1 = done - skip
            if tried1 and sp1.seconds > 0:
                self._m_pmks.labels(**{"pass": "1"}).set(tried1 / sp1.seconds)
            skip2 = skip - skipped
            with self.tracer.span("pass2") as sp2:
                paths = self._fetch_pass2_paths(work)
                words = (w for p in paths for w in DictStream(p))
                if rules and jax.process_count() > 1:
                    # Multi-process: crack_rules takes the full global
                    # dict stream (every host downloads whole dicts
                    # anyway) and shards internally — each host uploads
                    # only its 1/nproc row slice and decodes finds from
                    # the replicated bitmask, so no host ever feeds
                    # expanded candidates.  The feed supplies the base
                    # words (``words()`` flat view): dict read + gunzip
                    # move to the producer threads while crack_rules
                    # owns framing, packing and skip.
                    feed2 = CandidateFeed(
                        words, nproc=1, pid=0, prepack=None, name="pass2",
                        batch_size=self.cfg.batch_size * jax.process_count(),
                        **cfg_feed)
                    try:
                        engine.crack_rules(feed2.words(), rules,
                                           on_batch=on_batch, skip=skip2)
                    finally:
                        feed2.close()
                elif rules:
                    # Single-process mesh-aggregate pass 2: the feed
                    # serves compact BASE-WORD blocks (warm ``.rbase``
                    # entries skip the split + pack; cold dicts stream
                    # once and write the entry back) and every device
                    # expands rules on itself directly ahead of its own
                    # PBKDF2 dispatch — ÷rule-count H2D bytes, zero host
                    # expansion CPU in steady state, `@`-purge and
                    # overflow pairs still host-interpreted by the seam.
                    # The expansion stream is bit-identical to
                    # crack_rules' (blocks framed at batch_size), so
                    # skip2 and the checkpoint counts carry over.
                    src = RulesFeedSource(
                        [(p, self._dict_key(p)) for p in paths],
                        batch_size=self.cfg.batch_size,
                        cache=self.dict_cache, name="pass2", log=self.log)
                    feed2 = CandidateFeed(
                        None, batch_size=self.cfg.batch_size, frames=src,
                        prepack=None, name="pass2", **cfg_feed)
                    try:
                        if self._use_streams():
                            engine.crack_rules_streams(
                                feed2, rules, on_batch=on_batch,
                                skip=skip2, registry=self.registry,
                                tracer=self.tracer)
                        else:
                            engine.crack_rules_blocks(
                                feed2, rules, on_batch=on_batch,
                                skip=skip2, registry=self.registry,
                                tracer=self.tracer)
                    finally:
                        feed2.close()
                else:
                    # No-rules pass 2 shards across hosts (it used to
                    # run replicated — nproc× redundant PBKDF2 on the
                    # bulk of the unit): the feed's sharded framing
                    # hands each host its padded 1/nproc block slice of
                    # the global stream (an empty shard arrives as an
                    # all-padding block, keeping SPMD lockstep), the
                    # resume skip applies to the GLOBAL stream on the
                    # producer, and crack_blocks reports each block's
                    # global count so the checkpoint keeps counting
                    # stream positions.  Single-process degenerates to
                    # nproc=1 framing — one code path for both.
                    if self.dict_cache is not None:
                        # Packed-dict cache path: per-dict framing
                        # (identical geometry on every host whatever
                        # its cache state), warm dicts served as
                        # pre-packed mmap blocks, cold dicts streamed
                        # once and written back.  The source owns the
                        # resume skip — warm skips are index seeks.
                        src = DictFeedSource(
                            [(p, self._dict_key(p)) for p in paths],
                            batch_size=self.cfg.batch_size,
                            cache=self.dict_cache, skip=skip2,
                            name="pass2", log=self.log)
                        feed2 = CandidateFeed(
                            None, batch_size=self.cfg.batch_size,
                            frames=src, prepack=engine.host_packer(),
                            name="pass2", **cfg_feed)
                    else:
                        feed2 = CandidateFeed(
                            words, batch_size=self.cfg.batch_size,
                            skip=skip2, prepack=engine.host_packer(),
                            name="pass2", **cfg_feed)
                    try:
                        self._crack_blocks(engine, feed2, on_batch=on_batch)
                    finally:
                        feed2.close()
            # Mask pass: server-issued keyspace shards, generated ON
            # DEVICE from (mask, custom, skip, limit) alone — zero
            # candidate bytes arrived on the wire.  mask_blocks frames
            # each shard as MaskPrep blocks in hashcat -s/-l coordinates
            # (absolute keyspace offsets), so the mask_done fast-forward
            # resumes mid-shard bit-identically: a restart replays
            # exactly ``limit - done`` candidates of the lease's range.
            mask_entries = work.get("masks") or []
            if mask_entries:
                with self.tracer.span("mask") as spm:
                    mrem = mask_skip
                    for shard in mask_entries:
                        mlimit = int(shard["limit"])
                        if mrem >= mlimit:
                            mrem -= mlimit  # shard finished pre-restart
                            continue
                        custom = {k: v.encode("latin1") for k, v in
                                  (shard.get("custom") or {}).items()}
                        blocks = mask_blocks(
                            shard["mask"], self.cfg.batch_size,
                            skip=int(shard["skip"]) + mrem,
                            limit=mlimit - mrem, custom=custom)
                        mrem = 0
                        self._crack_blocks(engine, blocks,
                                           on_batch=on_mask_batch)
                triedm = mask_done - mask_skip
                if triedm and spm.seconds > 0:
                    self._m_pmks.labels(**{"pass": "mask"}).set(
                        triedm / spm.seconds)
        tried = (done - skip) + (mask_done - mask_skip)
        tried2 = done - skip - tried1
        if tried2 and sp2.seconds > 0:
            self._m_pmks.labels(**{"pass": "2"}).set(tried2 / sp2.seconds)
        if comp.count:
            self._m_recompiles.inc(comp.count)

        elapsed = time.perf_counter() - t0
        st = engine.stage_times
        crack_s = sum(st.values())
        # "prepare" is the RESIDUAL on-thread stage time (device staging
        # for feed-prepacked blocks): packing itself runs on the feed's
        # producer threads and is accounted to the feed:produce spans —
        # the dict keys stay as-is for API compat (M22000Engine
        # stage_times comment).
        self.log(
            "stages: stage+h2d=%.1fs dispatch=%.1fs device+sync=%.1fs "
            "other=%.1fs (tried %d)"
            % (st["prepare"], st["dispatch"], st["collect"],
               max(0.0, elapsed - crack_s), tried)
        )
        result = WorkResult(
            hkey=work["hkey"], founds=founds, elapsed=elapsed,
            candidates_tried=tried,
        )
        if founds:
            self._record_founds(founds)
            self._m_founds.inc(len(founds))
        # prior founds from a resumed session are re-submitted: put_work
        # is idempotent server-side and the claim may not have landed
        cand = prior_cand + [
            {"k": f.line.mac_ap.hex(), "v": f.psk.hex()} for f in founds
        ]
        cand = [dict(t) for t in {tuple(sorted(c.items())) for c in cand}]
        if jax.process_count() > 1:
            # One submission per slice: process 0 talks to the server,
            # every host adopts its verdict (all hosts decoded identical
            # founds, so the payload would be identical anyway).  A
            # host-0 exception must broadcast as an error sentinel — the
            # peers are already parked in the broadcast and would hang
            # forever if host 0 just raised.
            acc = err = None
            if jax.process_index() == 0:
                try:
                    acc = self._submit(work["hkey"], cand,
                                       epoch=work.get("epoch"))
                except ConnectionError:
                    acc = False  # journaled; the outbox drain retries
                except Exception as e:
                    err = f"{type(e).__name__}: {e}"
            payload = _broadcast_json({"acc": acc, "err": err})
            if payload["err"]:
                raise ConnectionError(
                    f"put_work failed on host 0: {payload['err']}")
            result.accepted = bool(payload["acc"])
        else:
            try:
                result.accepted = self._submit(work["hkey"], cand,
                                               epoch=work.get("epoch"))
            except ConnectionError as e:
                # Degraded mode: the founds were journaled before the
                # attempt — delivery now belongs to the outbox drain, so
                # a dead server costs this unit an "accepted" flag, not
                # the PSKs and not a parked crack loop.
                if cand:
                    self.log(f"put_work failed ({e}); "
                             f"{len(cand)} found(s) wait in the outbox")
                result.accepted = False
        self._m_units.labels(
            accepted="true" if result.accepted else "false").inc()
        self._clear_resume()
        self._autotune(elapsed)
        return result

    def _submit_tries(self) -> int:
        """Transport attempts per submission call.  With the outbox
        guaranteeing delivery, an unbounded (reference-style) retry would
        only park the crack loop — bound it; an explicit --max-tries is
        honored as-is."""
        return self.api.max_tries or 2

    def _submit(self, hkey: str, cand: list, epoch: int = None) -> bool:
        """Journal-then-send one unit's founds; acks on server OK.

        The outbox ``record`` is the durability point — it fsyncs before
        the first ``put_work`` attempt and drops any (hkey, bssid) the
        server already acked, so a resume-replay re-crack after a
        restart cannot double-submit.  ``epoch`` (from the work unit)
        keys the lease release server-side; outbox drains pass None and
        the server resolves the live epoch."""
        to_send = self.outbox.record(hkey, cand)
        if not to_send:
            # Nothing the server doesn't already have (all acked, or an
            # empty unit): an empty submission still reports the unit.
            if cand:
                return True
            return self.api.put_work(hkey, cand,
                                     max_tries=self._submit_tries(),
                                     epoch=epoch)
        accepted = self.api.put_work(hkey, to_send,
                                     max_tries=self._submit_tries(),
                                     epoch=epoch)
        if accepted:
            self.outbox.ack(hkey, to_send)
        return accepted

    def _drain_outbox(self):
        """Deliver journaled founds left over from crashes/outages —
        called at startup and between units; stops (and stays pending)
        on the first transport failure."""
        if jax.process_index() != 0 or not self.outbox.pending_count():
            return
        delivered = self.outbox.drain(
            lambda hkey, cand: self.api.put_work(
                hkey, cand, max_tries=self._submit_tries()))
        if delivered:
            self.log(f"outbox: delivered {delivered} journaled found(s)")
        left = self.outbox.pending_count()
        if left:
            self.log(f"outbox: {left} found(s) still pending delivery")

    def _prefetch_units(self):
        """Top the degraded-mode buffer up to ``prefetch_units`` extra
        leased units while the transport is healthy, so an OPEN circuit
        still has queued work to crack (single-host only: a slice's
        lockstep collectives need one agreed unit at a time)."""
        if jax.process_count() > 1 or self.cfg.prefetch_units <= 0:
            return
        while (len(self._unit_buffer) < self.cfg.prefetch_units
               and not self.api.circuit_open):
            try:
                self._unit_buffer.append(
                    self.api.get_work(self.dictcount, max_tries=1))
            except (NoNets, VersionRejected, ConnectionError, ValueError,
                    OSError):
                break  # best-effort: the serial path needs no buffer

    def _autotune(self, elapsed: float):
        if elapsed < self.cfg.pace_target and self.dictcount < 15:
            self.dictcount += 1
            self._m_autotune.labels(direction="up").inc()
        elif elapsed > self.cfg.pace_target and self.dictcount > 1:
            self.dictcount -= 1
            self._m_autotune.labels(direction="down").inc()
        self._m_dictcount.set(self.dictcount)

    def fused_executor(self, units):
        """A ``sched.MultiUnitExecutor`` bound to this client's config,
        telemetry and PMK store — the multi-unit fused crack path
        (``--unit-queue`` / ``--fuse-max-units``).

        Single-host only, for the same reason as the PMK store above:
        fused waves are assembled from whatever units the queue holds,
        so different hosts would enter the shard_map collectives with
        different batch shapes.  A multi-host slice doesn't need fusion
        anyway — it exists to fill one SMALL slice from a thin stream
        of small units.
        """
        if jax.process_count() > 1:
            raise RuntimeError(
                "unit fusion is single-host only (a multi-host slice "
                "takes the serial per-unit path; see fused_executor)")
        from ..sched import MultiUnitExecutor

        return MultiUnitExecutor(
            units, batch_size=self.cfg.batch_size,
            unit_queue=self.cfg.unit_queue,
            fuse_max_units=self.cfg.fuse_max_units,
            nc=self.cfg.nc, pmk_store=self.pmk_store,
            registry=self.registry, tracer=self.tracer,
            streams="auto" if self.cfg.device_streams == "auto"
            else self._use_streams())

    #: In-process crack attempts per work unit before the unit is
    #: abandoned (attempt 1 at the configured batch, each retry attempt
    #: at half — see _process_with_recovery).
    ENGINE_RETRY_LIMIT = 3

    def _process_with_recovery(self, work: dict):
        """One work unit with in-process engine recovery (single-host).

        A crack dispatch that raises — a device falling off the bus, an
        XLA OOM at the configured batch — used to kill the whole client
        and lose the unit.  Instead: retry ONCE at half the batch size
        (an OOM at B usually fits at B/2; a transient device error just
        needs the re-dispatch), dropping the ``_progress`` checkpoint
        first because skip-by-count is only sound against the stream
        order of the batch size that wrote it (see _write_resume).  A
        second failure requeues the unit with backoff via the resume
        file; ``ENGINE_RETRY_LIMIT`` total attempts abandon it rather
        than wedge the loop.  Returns None when no result was produced.
        """
        try:
            return self.process_work(work)
        except (NoNets, SystemExit, KeyboardInterrupt):
            raise
        except RuntimeError as e:
            self._m_engine_retries.inc()
            full = self.cfg.batch_size
            self.log(f"engine error: {e}; retrying unit at batch {full // 2}")
            work.pop("_progress", None)  # unsound across a batch change
            try:
                self.cfg.batch_size = max(1, full // 2)
                return self.process_work(work)
            except RuntimeError as e2:
                work.pop("_progress", None)
                attempts = int(work.get("_attempts", 0)) + 1
                work["_attempts"] = attempts
                self.cfg.batch_size = full  # restore BEFORE stamping resume
                if attempts >= self.ENGINE_RETRY_LIMIT:
                    self._clear_resume()
                    self.log(f"engine error persisted after {attempts} "
                             f"attempts; abandoning unit: {e2}")
                else:
                    self._write_resume(work)
                    self.log(f"engine error persisted: {e2}; unit requeued "
                             f"with backoff (attempt {attempts})")
                    self.api.sleep(self.api.backoff)
                return None
            finally:
                self.cfg.batch_size = full

    def run(self) -> int:
        """Update-check + challenge-gate, then loop work units.

        Multi-host mode (``jax.process_count() > 1`` — a
        ``multihost_mesh`` slice acting as ONE very large volunteer):
        process 0 owns every server decision (update probe, resume read,
        get_work, put_work) and broadcasts the outcome, so all hosts
        crack the SAME unit in SPMD lockstep; dict downloads stay
        per-host (md5-pinned, so the bytes are identical).  The engines
        span the global mesh automatically (parallel/mesh.default_mesh).
        Pass 1 runs replicated — every host feeds the identical targeted
        stream as its local shard, costing nproc× redundant PBKDF2 on
        the (small) pass-1 candidate set; pass 2, where the volume is,
        shards for real: with rules via crack_rules' global-stream
        contract, without rules via ``shard_word_blocks`` (each host
        feeds its padded 1/nproc block slice of the global dict stream,
        so the slice covers the unit once, not nproc times).
        """
        multiproc = jax.process_count() > 1
        pid = jax.process_index()
        if multiproc:
            # A mixed-version slice is fatal-by-design (stream order is
            # version-dependent — see _write_resume), so agreement is
            # checked BEFORE any work, where the failure is a clear exit
            # rather than a mid-unit collective deadlock.
            vers = _allgather_strs(__version__)
            if len(set(vers)) != 1:
                raise SystemExit(
                    f"mixed client versions across the slice: {vers}; "
                    "upgrade every host to the same build")
        # Every host probes/downloads (HTTP only, no collectives), so an
        # update lands on all of them; process 0's verdict alone decides
        # the restart, and the version check above catches any host whose
        # download failed once the supervisor swaps the archives in.
        upd = self.check_update()
        if multiproc:
            upd = bool(_broadcast_json(upd if pid == 0 else None))
        if upd:
            raise SystemExit("client update downloaded; restart to apply")
        if not self.challenge():
            raise SystemExit("challenge failed: cracker output untrusted")
        done = 0
        while not self.cfg.max_work_units or done < self.cfg.max_work_units:
            # Founds journaled by a previous crash/outage go first: the
            # outbox drains at startup and between units, and a drain
            # stopped by a transport failure just retries next round.
            try:
                self._drain_outbox()
            except (ConnectionError, ValueError):
                pass
            if not multiproc:
                work = self._read_resume()
                if work is None and self._unit_buffer:
                    work = self._unit_buffer.pop(0)
                if work is None:
                    try:
                        work = self.api.get_work(self.dictcount)
                    except NoNets:
                        self.log("no nets available; sleeping")
                        self.api.sleep(self.api.backoff)
                        continue
                self._prefetch_units()
            else:
                # Host-0 server errors (version gate, malformed work)
                # must reach every host as a sentinel: the peers are
                # already parked in the broadcast, and a bare raise on
                # host 0 would strand them without a message.
                payload = {"work": None, "err": None}
                if pid == 0:
                    try:
                        payload["work"] = (self._read_resume()
                                           or self.api.get_work(self.dictcount))
                    except NoNets:
                        pass
                    except Exception as e:
                        payload["err"] = f"{type(e).__name__}: {e}"
                payload = _broadcast_json(payload)
                if payload["err"]:
                    raise SystemExit(
                        f"get_work failed on host 0: {payload['err']}")
                work = payload["work"]
                if work is None:
                    self.log("no nets available; sleeping")
                    self.api.sleep(self.api.backoff)
                    continue
            if multiproc:
                res = self.process_work(work)
            else:
                try:
                    res = self._process_with_recovery(work)
                except PermanentError as e:
                    # A 4xx mid-unit (a dict the server no longer serves,
                    # say) will not heal on replay: abandon the unit —
                    # the server's lease reap reassigns it — instead of
                    # resuming into the same rejection forever.
                    self._clear_resume()
                    self.log(f"permanent transport failure mid-unit: {e}; "
                             "abandoning unit")
                    continue
                except ConnectionError as e:
                    # Transport died mid-unit (say, a dict fetch against
                    # a cold cache while the server is down).  The unit
                    # is checkpointed in the resume file — nap until the
                    # circuit's next probe slot, then replay it; any
                    # founds already cracked sit safely in the outbox.
                    nap = self.api.backoff
                    breaker = getattr(self.api, "breaker", None)
                    if breaker is not None and breaker.remaining() > 0:
                        nap = breaker.remaining()
                    self.log(f"transport failure mid-unit: {e}; "
                             f"resuming in {nap:.0f}s")
                    self.api.sleep(nap)
                    continue
                if res is None:
                    continue  # unit requeued (resume file) or abandoned
            done += 1
            self.log(
                f"work {res.hkey[:8]}: {len(res.founds)} founds / "
                f"{res.candidates_tried} candidates in {res.elapsed:.0f}s "
                f"(accepted={res.accepted}, dictcount->{self.dictcount})"
            )
            if multiproc:
                self._slice_report()
        return done

    def _slice_report(self):
        """COLLECTIVE (multi-host only): merge every host's registry and
        report slice-wide throughput ONCE — the slice is one volunteer,
        so its PMK/s must not appear nproc times.  Every host must reach
        this call (it sits on the per-unit path after put_work, which
        every host completes) or the allgather would strand the peers."""
        merged = merged_slice_snapshot(self.registry)
        if is_emitter():
            p1 = merged.value("dwpa_client_pmk_per_s", **{"pass": "1"}) or 0.0
            p2 = merged.value("dwpa_client_pmk_per_s", **{"pass": "2"}) or 0.0
            self.log(
                f"slice PMK/s: pass1={p1:.0f} pass2={p2:.0f} "
                f"(summed over {jax.process_count()} hosts)")
