"""ESSID-fingerprint targeted attacks — the DAW client's ``testtarget`` /
``imeigentest`` pass-1 logic (help_crack.py:615-687), redesigned for the
TPU engine.

The reference selects pre-built *dictionaries* per ESSID regex (netgear /
MySpectrum / digit10 / phome / tenda / EE / altice, help_crack.py:622-637)
because a GPU hashcat run wants files; here the same families are
*generators* feeding the device engine directly — an ISP default-key
scheme is a tiny grammar (word-word-digits, digit mask, IMEI tail), and
the engine's throughput makes materializing it to disk pointless.

Each table entry is ``(compiled_regex, family_name, factory)`` where
``factory(match, essid) -> iterable[bytes]``.  Generators are bounded by
``budget`` so pass 1 stays a fixed slice of the work-unit pacing window
(the reference caps the same families by shipping fixed-size dicts).
"""

import itertools
import re

from ..gen.imei import imei_candidates
from ..gen.mask import mask_words
from ..gen.vendors import HOTSPOT_SSID_RE, MAC_TAIL_SSID_RE

# Compact word pools for the word-word-digits ISP schemes (NETGEAR's
# "adjective-noun-number" and Spectrum's similar scheme).  64x64x1000
# ~= 4M candidates — seconds on the engine.
ADJECTIVES = (
    "ancient breezy bright bumpy calm chilly classy cloudy crazy curly "
    "daily dizzy dusty fancy fast fluffy fresh fuzzy gentle giant happy "
    "heavy hungry icy jolly kind large lazy little lively lucky melodic "
    "mighty misty modern narrow noisy odd orange polite proud quaint "
    "quick quiet rapid rocky rough round royal shiny silent silky silly "
    "slow small smooth snowy strong sunny sweet swift tiny vast warm "
    "wild witty young"
).split()
NOUNS = (
    "apple balloon banana bird boat bolt breeze brook butter canoe cloud "
    "comet coral creek daisy deer desert diamond eagle fern field flower "
    "fog forest fox garden gate hill kayak koala lake leaf lion lotus "
    "meadow moon mountain nest ocean onion owl panda peach pearl pine "
    "planet pond prairie rabbit raven river road rose sea shoe sky snake "
    "squash star stream sun tiger trail tree unicorn valley wave zebra"
).split()


def word_word_digits(digits: int = 3, sep: str = ""):
    """NETGEAR/Spectrum-style adjective+noun+number candidates."""
    for a, n in itertools.product(ADJECTIVES, NOUNS):
        base = f"{a}{sep}{n}"
        for d in range(10 ** digits):
            yield f"{base}{d:0{digits}d}".encode()


def _hotspot_imeis(match, essid):
    """IMEI-derived keys for tethering SSIDs (imeigentest equivalent,
    help_crack.py:667-687): sweep common TACs' serial space."""
    from ..gen.vendors import HOTSPOT_TACS

    for tac in HOTSPOT_TACS:
        yield from imei_candidates(tac)


def _word_word_3(m, e):
    return word_word_digits(3)


#: (regex, family, factory) — first match wins, mirroring the reference's
#: if/elif chain (help_crack.py:622-637).  The Tenda/hotspot fingerprints
#: are shared with the server-side keygen dispatch (gen/vendors.py) so
#: client and server target the same SSIDs.
TARGET_TABLE = (
    (re.compile(rb"^NETGEAR\d\d$"), "netgear", _word_word_3),
    (re.compile(rb"^(MySpectrumWiFi|SpectrumSetup)"), "spectrum", _word_word_3),
    (re.compile(rb"^(2WIRE\d+|ATT\w+|CenturyLink\d+)$"), "digit10",
     lambda m, e: mask_words("?d" * 10, limit=10 ** 7)),
    (re.compile(rb"^PLDTHOME"), "phome",
     lambda m, e: (b"PLDTWIFI" + w for w in mask_words("?d" * 5))),
    (MAC_TAIL_SSID_RE, "digit8",
     lambda m, e: mask_words("?d" * 8, limit=10 ** 7)),
    (re.compile(rb"^EE-\w+"), "ee",
     lambda m, e: word_word_digits(2, sep="-")),
    (re.compile(rb"^(MyAltice|altice)"), "altice",
     lambda m, e: (f"{a}{d:04d}".encode()
                   for a, d in itertools.product(ADJECTIVES, range(10000)))),
    (HOTSPOT_SSID_RE, "imei", _hotspot_imeis),
)


def targeted_for_essid(essid: bytes, budget: int = 5_000_000):
    """-> (family_name, bounded candidate iterator) or (None, None)."""
    for rx, family, factory in TARGET_TABLE:
        m = rx.match(essid)
        if m:
            return family, itertools.islice(factory(m, essid), budget)
    return None, None


def targeted_candidates(essids, budget: int = 5_000_000):
    """Yield candidate bytes for every matched ESSID in a work unit.

    Dedup is by *factory* (the keyspace), not family label, so two
    families sharing a scheme (netgear/spectrum) stream it once — the
    PBKDF2 is per (candidate, essid) anyway, so one pass of a keyspace
    serves every matching net in the hash file."""
    from ..obs import default_registry

    matches = default_registry().counter(
        "dwpa_client_targeted_matches_total",
        "ESSID-fingerprint family matches streamed in pass 1")
    seen = set()
    for essid in essids:
        for rx, family, factory in TARGET_TABLE:
            m = rx.match(essid)
            if m and factory not in seen:
                seen.add(factory)
                matches.labels(family=family).inc()
                yield from itertools.islice(factory(m, essid), budget)
                break
