"""Process-local metrics registry: counters, gauges, histograms.

Zero dependencies beyond the stdlib, thread-safe (one lock per
registry — the server's threaded WSGI handlers and the client's main
loop both record through here), and deliberately tiny: the repo needs
numbers it can trust on the hot path, not a metrics framework.

Design constraints, in priority order:

- **No host syncs.** Recording a metric is a few dict/float ops under a
  lock; nothing here may touch a device value.  Callers compute rates
  (PMK/s) from counts they already hold host-side — the DW106 lint rule
  (analysis/linter.py) enforces that no emission call ever lands inside
  a jit-traced region.
- **Mergeable.** ``snapshot()`` emits a plain-JSON form and
  ``merge_snapshot()`` folds another host's snapshot in (counters and
  histograms add; gauges sum — the slice-wide reading for additive
  gauges like PMK/s).  The multi-host client rides this through the
  same fixed-shape collective discipline as ``_broadcast_json``
  (obs/multihost.py).
- **Prometheus text-format v0.0.4** (``render_prometheus``) for the
  server's ``?metrics`` scrape, plus ``render_json`` for tests and the
  ``?metrics=json`` wire form.

Naming conventions (documented in README "Telemetry"): metric names are
``dwpa_<subsystem>_<what>[_<unit>][_total]``; labels are lowercase
snake-case with low cardinality (endpoint, pass, direction, job, span).
"""

import json
import threading

#: default histogram buckets, in seconds — spans 1 ms kernel dispatches
#: to the 900 s work-unit pacing target.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 900.0,
)

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    # integral values render without the trailing .0 (Prometheus style)
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Child:
    """One labeled series of a family; all mutation under the registry
    lock (metric ops are a few float adds — contention is negligible
    next to the device work they time)."""

    __slots__ = ("_family", "_key", "value", "sum", "buckets")

    def __init__(self, family, key):
        self._family = family
        self._key = key
        self.value = 0.0
        if family.type == HISTOGRAM:
            self.sum = 0.0
            # one count per bound + the +Inf overflow slot
            self.buckets = [0] * (len(family.bucket_bounds) + 1)

    # -- counter / gauge ---------------------------------------------------

    def inc(self, amount: float = 1.0):
        with self._family._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        if self._family.type != GAUGE:
            raise TypeError(f"{self._family.name}: dec() is gauge-only")
        self.inc(-amount)

    def set(self, value: float):
        if self._family.type != GAUGE:
            raise TypeError(f"{self._family.name}: set() is gauge-only")
        with self._family._lock:
            self.value = float(value)

    # -- histogram ---------------------------------------------------------

    def observe(self, value: float):
        fam = self._family
        if fam.type != HISTOGRAM:
            raise TypeError(f"{fam.name}: observe() is histogram-only")
        with fam._lock:
            self.value += 1          # observation count
            self.sum += float(value)
            for i, bound in enumerate(fam.bucket_bounds):
                if value <= bound:
                    self.buckets[i] += 1
                    return
            self.buckets[-1] += 1


class _Family:
    """A named metric and its labeled children."""

    def __init__(self, registry, name: str, mtype: str, help: str = "",
                 buckets=None):
        self.name = name
        self.type = mtype
        self.help = help
        self.bucket_bounds = tuple(buckets or DEFAULT_BUCKETS) \
            if mtype == HISTOGRAM else ()
        self._lock = registry._lock
        self._children = {}

    def labels(self, **labels) -> _Child:
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _Child(self, key)
            return child

    # un-labeled convenience: family.inc() == family.labels().inc()
    def inc(self, amount: float = 1.0):
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0):
        self.labels().dec(amount)

    def set(self, value: float):
        self.labels().set(value)

    def observe(self, value: float):
        self.labels().observe(value)


class MetricsRegistry:
    """Create/look up metric families and render/merge the whole set.

    ``counter``/``gauge``/``histogram`` are idempotent by name: the
    first registration wins (help text included) and later calls return
    the same family, so any module can cheaply re-declare the metric it
    records to.  Re-registering a name as a different *type* is a bug
    and raises.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families = {}

    # -- registration ------------------------------------------------------

    def _family(self, name: str, mtype: str, help: str, buckets=None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    self, name, mtype, help, buckets)
            elif fam.type != mtype:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.type}, "
                    f"not {mtype}")
            return fam

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, COUNTER, help)

    def gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, GAUGE, help)

    def histogram(self, name: str, help: str = "", buckets=None) -> _Family:
        return self._family(name, HISTOGRAM, help, buckets)

    # -- test/introspection helpers ---------------------------------------

    def value(self, name: str, **labels):
        """Current value of one series (histograms: observation count),
        or None when the series was never recorded."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            child = fam._children.get(_label_key(labels))
            return None if child is None else child.value

    def series(self, name: str) -> dict:
        """{label-tuple: value} for every child of ``name``."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return {}
            return {k: c.value for k, c in fam._children.items()}

    # -- snapshot / merge (the multi-host agreement form) ------------------

    def snapshot(self) -> dict:
        """JSON-serializable full state, the unit ``merge_snapshot``
        folds; also the ``?metrics=json`` wire form."""
        out = {}
        with self._lock:
            for name, fam in sorted(self._families.items()):
                samples = []
                for key, c in sorted(fam._children.items()):
                    labels = {k: v for k, v in key}
                    if fam.type == HISTOGRAM:
                        samples.append({"labels": labels, "count": c.value,
                                        "sum": c.sum,
                                        "buckets": list(c.buckets)})
                    else:
                        samples.append({"labels": labels, "value": c.value})
                entry = {"type": fam.type, "help": fam.help,
                         "samples": samples}
                if fam.type == HISTOGRAM:
                    entry["bucket_bounds"] = list(fam.bucket_bounds)
                out[name] = entry
        return out

    def merge_snapshot(self, snap: dict):
        """Fold another registry's ``snapshot()`` into this one.

        Counters and histograms add; gauges SUM — the slice-wide
        reading for additive gauges (per-host PMK/s sums to slice
        PMK/s).  A gauge that must not be summed across hosts should be
        recorded only by the emitting host (process 0).
        """
        for name, entry in snap.items():
            fam = self._family(name, entry["type"], entry.get("help", ""),
                               entry.get("bucket_bounds"))
            for s in entry.get("samples", []):
                child = fam.labels(**s.get("labels", {}))
                with self._lock:
                    if fam.type == HISTOGRAM:
                        if tuple(entry.get("bucket_bounds", ())) != \
                                fam.bucket_bounds:
                            raise ValueError(
                                f"{name}: bucket bounds differ across "
                                "registries — cannot merge")
                        child.value += s["count"]
                        child.sum += s["sum"]
                        for i, b in enumerate(s["buckets"]):
                            child.buckets[i] += b
                    else:
                        child.value += s["value"]

    # -- rendering ---------------------------------------------------------

    def render_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def render_prometheus(self) -> str:
        """Prometheus exposition text-format v0.0.4."""
        lines = []
        snap = self.snapshot()
        for name, entry in snap.items():
            if entry["help"]:
                lines.append(f"# HELP {name} {_escape_help(entry['help'])}")
            lines.append(f"# TYPE {name} {entry['type']}")
            for s in entry["samples"]:
                labels = s["labels"]
                if entry["type"] == HISTOGRAM:
                    cum = 0
                    bounds = entry["bucket_bounds"]
                    for i, b in enumerate(s["buckets"]):
                        cum += b
                        le = _fmt(bounds[i]) if i < len(bounds) else "+Inf"
                        lines.append("%s_bucket%s %s" % (
                            name, _label_str(labels, le=le), _fmt(cum)))
                    lines.append("%s_sum%s %s" % (
                        name, _label_str(labels), _fmt(s["sum"])))
                    lines.append("%s_count%s %s" % (
                        name, _label_str(labels), _fmt(s["count"])))
                else:
                    lines.append("%s%s %s" % (
                        name, _label_str(labels), _fmt(s["value"])))
        return "\n".join(lines) + "\n"


def _label_str(labels: dict, **extra) -> str:
    items = list(labels.items()) + list(extra.items())
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in items)
    return "{%s}" % body


#: the process-wide default registry — what every subsystem records to
#: unless handed an explicit one (tests inject fresh registries).
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
