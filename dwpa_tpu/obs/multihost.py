"""Slice-wide telemetry agreement for multi-host meshes.

The multi-host client contract (client/main.py ``_broadcast_json``): a
slice is ONE very large volunteer, so its telemetry must be reported
once, not ``nproc`` times.  Every host records into its own process-
local registry (recording never needs a collective); at report points
the hosts run ``merged_slice_snapshot`` TOGETHER — a fixed-shape
allgather of JSON snapshots — and each host folds the others' counts
into a merged view.  Only process 0 then *emits* (logs, serves
``?metrics``): ``is_emitter()`` is the gate.

Collective discipline, same as the client's other agreement helpers:
two fixed-shape allgathers (lengths first, then max-padded payloads),
so every host reaches every collective with identical shapes — a raise
before either would strand the peers inside it, so callers must invoke
this from a point every host reaches.
"""

import json


def is_emitter() -> bool:
    """True on the host that owns external emission (process 0; always
    true single-process or before jax initializes a backend)."""
    import jax

    try:
        return jax.process_index() == 0
    except RuntimeError:  # no backend yet: single-host by definition
        return True


def allgather_json(obj):
    """Every host's JSON-serializable ``obj``, in process order.

    Single-process: ``[obj]`` with no jax involvement.  Multi-host: two
    fixed-shape ``process_allgather`` rounds (lengths, then padded
    payload bytes) — the equal-shape contract every host must honor."""
    import jax

    if jax.process_count() == 1:
        return [obj]
    import numpy as np
    from jax.experimental import multihost_utils as mhu

    data = json.dumps(obj).encode()
    lens = np.asarray(mhu.process_allgather(
        np.asarray([len(data)], np.int64))).reshape(-1)
    width = int(lens.max())
    buf = np.zeros(width, np.uint8)
    buf[: len(data)] = np.frombuffer(data, np.uint8)
    rows = np.asarray(mhu.process_allgather(buf)).reshape(-1, width)
    return [json.loads(bytes(r[: int(n)]).decode()) for r, n in zip(rows, lens)]


def merged_slice_snapshot(registry):
    """COLLECTIVE: every host contributes ``registry.snapshot()``; each
    returns the slice-wide merge (counters/histograms summed, additive
    gauges summed — see MetricsRegistry.merge_snapshot).  The merge is
    identical on every host; emit it only where ``is_emitter()``."""
    from .metrics import MetricsRegistry

    snaps = allgather_json(registry.snapshot())
    merged = MetricsRegistry()
    for snap in snaps:
        merged.merge_snapshot(snap)
    return merged
