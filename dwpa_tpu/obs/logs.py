"""One-shot logging config for every dwpa_tpu process.

``setup_logging()`` configures the package root logger (``dwpa_tpu``)
exactly once; the client loop, the server CLI, and library modules that
already log via ``logging.getLogger(__name__)`` (server/tools.py,
rules/engine.py) all inherit it — one config, every emitter.

Console format is the historical one the client printed (the bare
message), so operator muscle memory and log scrapers keep working.
``DWPA_LOG=json`` switches every line to structured JSON
(``{"ts", "level", "logger", "msg"}``) for ingestion pipelines;
``DWPA_LOG_LEVEL`` overrides the level (default INFO).
"""

import json
import logging
import os
import sys
import time

ROOT_LOGGER = "dwpa_tpu"


class JsonFormatter(logging.Formatter):
    def format(self, record):
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
            + ".%03dZ" % (record.msecs,),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def setup_logging(level=None, stream=None, force: bool = False):
    """Configure and return the ``dwpa_tpu`` logger.  Idempotent: a
    second call is a no-op unless ``force`` (tests) — so the client
    entry point, the server CLI, and embedding code can all call it
    without stacking handlers."""
    logger = logging.getLogger(ROOT_LOGGER)
    if logger.handlers and not force:
        return logger
    for h in list(logger.handlers):
        logger.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    if os.environ.get("DWPA_LOG", "").lower() == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter("%(message)s"))
    logger.addHandler(handler)
    if level is None:
        level = os.environ.get("DWPA_LOG_LEVEL", "INFO").upper()
    logger.setLevel(level)
    # Propagation stays ON (the library convention): the bare root
    # logger has no handlers, so CLI output is emitted once by the
    # handler above, while root-attached observers — pytest's caplog,
    # an embedding app's aggregation handler — still see every record.
    return logger


def get_logger(name: str = None) -> logging.Logger:
    """A child of the package logger (``dwpa_tpu.<name>``)."""
    return logging.getLogger(
        ROOT_LOGGER if not name else
        name if name.startswith(ROOT_LOGGER) else f"{ROOT_LOGGER}.{name}")
