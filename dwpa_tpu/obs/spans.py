"""Span tracer: named, nested wall-clock timings over ``perf_counter``.

The crack loop's phases (challenge, get_work, dict download, pass 1,
pass 2, put_work) and bench.py's timed regions all publish through one
span API, so the benchmark JSON and live telemetry can never disagree
about what a region took.

**The device-sync rule.** ``time.perf_counter()`` reads the HOST clock;
on TPU, dispatch returns long before execution completes (bench.py's
timing notes), so a span that stops its clock while device work is
still in flight lies by orders of magnitude.  Every span that covers
device work must force a device→host fetch before the clock stops:

- the engine's ``crack*`` methods sync internally (their hits-gate
  fetches the result), so a span wrapping a whole crack call is sound;
- raw device launches need an explicit ``np.asarray(...)`` /
  ``jax.block_until_ready(...)`` inside the span, or a ``sync=`` value
  passed to ``stop()``/the context manager, which is fetched *before*
  the clock is read.

The DW106 lint rule (analysis/linter.py) enforces this statically on
the instrumented files, exactly as DW105 does for bench's legacy
``perf_counter`` spans.

Timings are recorded twice: into the owning registry as a
``dwpa_span_seconds{span=...}`` histogram (scrapeable), and into a
bounded in-memory ring of finished-span records (name, parent, start,
stop, depth) that tests use to assert well-nestedness.
"""

import contextlib
import threading
import time


def _force_fetch(sync):
    """Materialize ``sync`` on the host: callables are invoked, anything
    else goes through ``np.asarray`` (the same fetch bench.py uses)."""
    if sync is None:
        return
    if callable(sync):
        sync()
        return
    import numpy as np

    np.asarray(sync)


class Span:
    """One live timing region.  Created by ``SpanTracer.start``/``span``;
    ``seconds`` is valid after ``stop()``."""

    __slots__ = ("tracer", "name", "parent", "depth", "t0", "t1")

    def __init__(self, tracer, name, parent, depth):
        self.tracer = tracer
        self.name = name
        self.parent = parent
        self.depth = depth
        self.t0 = time.perf_counter()
        self.t1 = None

    @property
    def seconds(self) -> float:
        """Duration; live reading while the span is still open."""
        return (self.t1 if self.t1 is not None
                else time.perf_counter()) - self.t0

    def elapsed(self) -> float:
        return self.seconds

    def stop(self, sync=None) -> float:
        """Close the span; ``sync`` (device value or callable) is
        fetched/invoked BEFORE the clock is read — the device-sync rule
        above.  Idempotent: a second stop returns the recorded time."""
        if self.t1 is not None:
            return self.seconds
        _force_fetch(sync)
        self.t1 = time.perf_counter()
        self.tracer._finish(self)
        return self.seconds


class SpanTracer:
    """Per-subsystem tracer; records into ``registry`` (default: the
    process-wide one) and keeps the last ``keep`` finished spans."""

    def __init__(self, registry=None, keep: int = 1024):
        from .metrics import default_registry

        self.registry = registry or default_registry()
        self._hist = self.registry.histogram(
            "dwpa_span_seconds", "span durations by name")
        self._lock = threading.Lock()
        self._keep = keep
        self.finished = []  # ring of record dicts, oldest first
        self._local = threading.local()

    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def start(self, name: str) -> Span:
        st = self._stack()
        parent = st[-1].name if st else None
        sp = Span(self, name, parent, len(st))
        st.append(sp)
        return sp

    def _finish(self, sp: Span):
        st = self._stack()
        # pop sp and anything abandoned above it (an exception may have
        # skipped a child's stop; the stack must never wedge)
        if sp in st:
            del st[st.index(sp):]
        self._hist.labels(span=sp.name).observe(sp.seconds)
        with self._lock:
            self.finished.append({
                "name": sp.name, "parent": sp.parent, "depth": sp.depth,
                "t0": sp.t0, "t1": sp.t1,
            })
            if len(self.finished) > self._keep:
                del self.finished[: len(self.finished) - self._keep]

    @contextlib.contextmanager
    def span(self, name: str, sync=None):
        """Context-managed span.  The body must sync its own device work
        (engine ``crack*`` calls do) or pass ``sync=`` to be fetched at
        exit — see the module docstring."""
        sp = self.start(name)
        try:
            yield sp
        finally:
            sp.stop(sync=sync)

    def records(self, name: str = None) -> list:
        """Finished-span records, optionally filtered by name."""
        with self._lock:
            recs = list(self.finished)
        return [r for r in recs if name is None or r["name"] == name]


_DEFAULT_TRACER = None
_DEFAULT_TRACER_LOCK = threading.Lock()


def default_tracer() -> SpanTracer:
    """Lazy singleton bound to the default registry."""
    global _DEFAULT_TRACER
    with _DEFAULT_TRACER_LOCK:
        if _DEFAULT_TRACER is None:
            _DEFAULT_TRACER = SpanTracer()
        return _DEFAULT_TRACER
