"""dwpa_tpu.obs — unified telemetry: metrics, spans, logging.

One observability layer for every process in the system:

- :mod:`.metrics` — process-local registry (counters, gauges, fixed-
  bucket histograms; thread-safe, zero deps) with Prometheus text-format
  v0.0.4 and JSON rendering, plus snapshot/merge for multi-host slices.
- :mod:`.spans` — nested wall-clock spans over ``perf_counter`` with
  the repo's device-sync rule baked into the API (a span covering
  device work must force a device→host fetch before its clock stops —
  lint rule DW106 enforces it statically).
- :mod:`.logs` — ``setup_logging()``: the one logging config
  (``DWPA_LOG=json`` for structured lines) every emitter inherits.
- :mod:`.multihost` — slice-wide snapshot merging and the process-0
  emission gate, following ``_broadcast_json``'s fixed-shape collective
  discipline.

Scrape surface: the server's ``?metrics`` endpoint (server/api.py)
renders the registry; README "Telemetry" documents metric names and
label conventions.
"""

from .logs import get_logger, setup_logging
from .metrics import (DEFAULT_BUCKETS, MetricsRegistry, default_registry)
from .multihost import allgather_json, is_emitter, merged_slice_snapshot
from .spans import Span, SpanTracer, default_tracer

__all__ = [
    "DEFAULT_BUCKETS", "MetricsRegistry", "default_registry",
    "Span", "SpanTracer", "default_tracer",
    "setup_logging", "get_logger",
    "allgather_json", "is_emitter", "merged_slice_snapshot",
]
