"""Pure-Python m22000 verification oracle (hashlib only, no JAX).

This is a behavioral port of the reference server's independent
re-verification kernel ``check_key_m22000`` (web/common.php:157-307) — the
executable spec the device kernels are differentially tested against, and
the host-side wide-NC re-check the server runs on every submitted PSK
before accepting it.

Semantics preserved exactly:

- PMKID path: PMK = PBKDF2-HMAC-SHA1(psk, essid, 4096, 32);
  candidate PMKID = HMAC-SHA1(PMK, "PMK Name" || mac_ap || mac_sta)[:16].
- EAPOL path: key_information parsed at offset 5 (big-endian), snonce at
  17:49, keyver = key_information & 3; MAC pair and nonce pair are
  concatenated in min-order (memcmp of the first 6 bytes);
  keyver 1/2: PTK = HMAC-SHA1(PMK, "Pairwise key expansion\\0" m n "\\0"),
  MIC = HMAC-MD5 / HMAC-SHA1 of the EAPOL frame with KCK = PTK[:16];
  keyver 3: PTK = HMAC-SHA256(PMK, "\\1\\0Pairwise key expansion" m n
  "\\x80\\1"), MIC = AES-128-CMAC.
- Nonce-error correction: the last 4 bytes of the AP nonce are replaced by
  (last +/- i) re-packed little-endian ('V' -> "LE") and big-endian
  ('N' -> "BE") for i = 1 .. nc/2+1, after trying the exact nonce; the
  search order (exact; then +1 LE, -1 LE, +1 BE, -1 BE; then +/-2 ...)
  and the returned (psk, nc, endian, pmk) tuple match the reference,
  including that the server-side check ignores the message_pair gating
  bits (the client-side device kernel does use them).
- hashcat ``$HEX[...]`` password notation is decoded first
  (web/common.php:3-25).
"""

import hashlib
import hmac
import struct

from ..models import hashline as hl

PRF_LABEL_V12 = b"Pairwise key expansion\x00"
PRF_LABEL_V3 = b"\x01\x00Pairwise key expansion"


_XDIGITS = frozenset(b"0123456789abcdefABCDEF")


def hc_unhex(key):
    """Decode hashcat $HEX[...] candidate notation to raw bytes.

    Strict per the reference (web/common.php:3-25): the payload must be
    even-length pure xdigits (``ctype_xdigit`` — no whitespace, which
    ``bytes.fromhex`` would forgive); anything else is taken literally.
    ``$HEX[]`` decodes to the empty string, as the reference's second
    branch does.
    """
    if isinstance(key, str):
        key = key.encode("utf-8", errors="ignore")
    if key.startswith(b"$HEX[") and key.endswith(b"]"):
        k = key[5:-1]
        if k == b"":
            return b""
        if len(k) % 2 == 0 and all(c in _XDIGITS for c in k):
            return bytes.fromhex(k.decode())
    return key


# ---------------------------------------------------------------------------
# Minimal pure-Python AES-128 (encrypt-only) for the CMAC MIC.  Kept free of
# the JAX implementation on purpose: the oracle must be an independent
# implementation for differential testing to mean anything.
# ---------------------------------------------------------------------------


def _aes_tables():
    def gf_mul(a, b):
        p = 0
        for _ in range(8):
            if b & 1:
                p ^= a
            hi = a & 0x80
            a = (a << 1) & 0xFF
            if hi:
                a ^= 0x1B
            b >>= 1
        return p

    exp, log = [0] * 510, [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x = gf_mul(x, 3)
    for i in range(255, 510):
        exp[i] = exp[i - 255]
    sbox = [0] * 256
    for v in range(256):
        inv = 0 if v == 0 else exp[255 - log[v]]
        s = inv
        for sh in (1, 2, 3, 4):
            s ^= ((inv << sh) | (inv >> (8 - sh))) & 0xFF
        sbox[v] = s ^ 0x63
    return sbox


_SBOX = _aes_tables()
_RCON = [1, 2, 4, 8, 16, 32, 64, 128, 27, 54]


def _aes128_round_keys(key: bytes):
    w = [list(key[i : i + 4]) for i in range(0, 16, 4)]
    for i in range(4, 44):
        t = list(w[i - 1])
        if i % 4 == 0:
            t = [_SBOX[t[1]], _SBOX[t[2]], _SBOX[t[3]], _SBOX[t[0]]]
            t[0] ^= _RCON[i // 4 - 1]
        w.append([w[i - 4][j] ^ t[j] for j in range(4)])
    return [sum(w[4 * r : 4 * r + 4], []) for r in range(11)]


def _xt(b):
    return ((b << 1) ^ (0x1B if b & 0x80 else 0)) & 0xFF


def _aes128_encrypt(rks, block: bytes) -> bytes:
    s = [block[i] ^ rks[0][i] for i in range(16)]
    for r in range(1, 11):
        s = [_SBOX[b] for b in s]
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        if r < 10:
            ns = []
            for c in range(4):
                a0, a1, a2, a3 = s[4 * c : 4 * c + 4]
                ns += [
                    _xt(a0) ^ _xt(a1) ^ a1 ^ a2 ^ a3,
                    a0 ^ _xt(a1) ^ _xt(a2) ^ a2 ^ a3,
                    a0 ^ a1 ^ _xt(a2) ^ _xt(a3) ^ a3,
                    _xt(a0) ^ a0 ^ a1 ^ a2 ^ _xt(a3),
                ]
            s = ns
        s = [s[i] ^ rks[r][i] for i in range(16)]
    return bytes(s)


def omac1_aes_128(msg: bytes, key: bytes) -> bytes:
    """AES-128-CMAC, matching the reference helper (web/common.php:56-112)."""

    def dbl(b: bytes) -> bytes:
        v = int.from_bytes(b, "big") << 1
        if b[0] & 0x80:
            v ^= 0x87
        return (v & (1 << 128) - 1).to_bytes(16, "big")

    rks = _aes128_round_keys(key)
    k1 = dbl(_aes128_encrypt(rks, b"\x00" * 16))
    k2 = dbl(k1)

    n = max(1, (len(msg) + 15) // 16)
    complete = len(msg) > 0 and len(msg) % 16 == 0
    last = msg[(n - 1) * 16 :]
    if complete:
        last = bytes(a ^ b for a, b in zip(last, k1))
    else:
        last = last + b"\x80" + b"\x00" * (15 - len(last))
        last = bytes(a ^ b for a, b in zip(last, k2))

    c = b"\x00" * 16
    for i in range(n - 1):
        c = _aes128_encrypt(rks, bytes(a ^ b for a, b in zip(c, msg[i * 16 : i * 16 + 16])))
    return _aes128_encrypt(rks, bytes(a ^ b for a, b in zip(c, last)))


# ---------------------------------------------------------------------------
# The verification kernel.
# ---------------------------------------------------------------------------


def pmk_from_psk(psk: bytes, essid: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha1", psk, essid, 4096, 32)


def compute_pmkid(pmk: bytes, mac_ap: bytes, mac_sta: bytes) -> bytes:
    return hmac.new(pmk, b"PMK Name" + mac_ap + mac_sta, hashlib.sha1).digest()[:16]


def compute_mic(pmk: bytes, keyver: int, m: bytes, n: bytes, eapol: bytes) -> bytes:
    """PTK derivation + MIC for one (pmk, nonce-variant)."""
    if keyver in (1, 2):
        ptk = hmac.new(pmk, PRF_LABEL_V12 + m + n + b"\x00", hashlib.sha1).digest()
        kck = ptk[:16]
        alg = hashlib.md5 if keyver == 1 else hashlib.sha1
        return hmac.new(kck, eapol, alg).digest()[:16]
    if keyver == 3:
        ptk = hmac.new(
            pmk, PRF_LABEL_V3 + m + n + b"\x80\x01", hashlib.sha256
        ).digest()
        return omac1_aes_128(eapol, ptk[:16])
    raise ValueError(f"unknown keyver {keyver}")


def nonce_pairs(h: "hl.Hashline"):
    """Min-order MAC/nonce concatenation + AP-nonce patch offset."""
    if h.mac_ap < h.mac_sta:
        m = h.mac_ap + h.mac_sta
    else:
        m = h.mac_sta + h.mac_ap
    snonce = h.snonce
    if snonce[:6] < h.anonce[:6]:
        n, ap_off = snonce + h.anonce, 32
    else:
        n, ap_off = h.anonce + snonce, 0
    return m, n, ap_off


def nc_variants(anonce: bytes, nc: int):
    """Yield (last4_bytes, delta, endian) in reference search order."""
    last_le = struct.unpack_from("<I", anonce, 28)[0]
    last_be = struct.unpack_from(">I", anonce, 28)[0]
    yield anonce[28:32], 0, None
    halfnc = (nc >> 1) + 1
    for i in range(1, halfnc + 1):
        yield struct.pack("<I", (last_le + i) & 0xFFFFFFFF), i, "LE"
        yield struct.pack("<I", (last_le - i) & 0xFFFFFFFF), -i, "LE"
        yield struct.pack(">I", (last_be + i) & 0xFFFFFFFF), i, "BE"
        yield struct.pack(">I", (last_be - i) & 0xFFFFFFFF), -i, "BE"


def check_key_m22000(line, keys, pmk=None, nc=128):
    """Verify candidate PSKs against one hashline.

    Returns ``(psk_bytes, nc_delta, endian, pmk)`` for the first match
    (``nc_delta``/``endian`` are ``None`` for PMKID; 0/None for an exact
    EAPOL match), or ``None``.  A provided ``pmk`` skips PBKDF2 for the
    first key only — the PMK-reuse path (web/common.php:919).
    """
    h = line if isinstance(line, hl.Hashline) else hl.parse(line)

    if h.hash_type == hl.TYPE_PMKID:
        for key in keys:
            if key is None:
                continue
            key = hc_unhex(key)
            this_pmk = pmk if pmk else pmk_from_psk(key, h.essid)
            pmk = None
            if compute_pmkid(this_pmk, h.mac_ap, h.mac_sta) == h.pmkid_or_mic:
                return key, None, None, this_pmk
        return None

    keyver = h.keyver
    if keyver not in (1, 2, 3):
        # unknown key descriptor version -> not crackable (common.php:274-276)
        return None
    m, n, ap_off = nonce_pairs(h)
    for key in keys:
        if key is None:
            continue
        key = hc_unhex(key)
        this_pmk = pmk if pmk else pmk_from_psk(key, h.essid)
        pmk = None
        for last4, delta, endian in nc_variants(h.anonce, nc):
            nv = n[: ap_off + 28] + last4 + n[ap_off + 32 :]
            if compute_mic(this_pmk, keyver, m, nv, h.eapol) == h.pmkid_or_mic:
                return key, delta, endian, this_pmk
    return None
