from .m22000 import check_key_m22000  # noqa: F401
